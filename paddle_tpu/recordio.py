"""RecordIO files + native prefetching readers (ctypes over
native/recordio.cc — see its header for format & reference citations).

Records are arbitrary byte strings; the convenience layer (de)serialises
numpy sample tuples with pickle, giving readers interchangeable with the
pure-Python reader decorators. Chunk descriptors ("path:offset:count")
plug straight into the master's task queue, reproducing the go/master
RecordIO-sharding data plane end to end:

    write_records("train.rec", sample_iter)
    tasks = chunk_tasks("train.rec", records_per_chunk=512)
    client.set_dataset(tasks)
    reader = client.task_reader(chunk_reader)   # native prefetch per chunk
"""
from __future__ import annotations

import ctypes
import os
import pickle
from typing import Iterable, Iterator, List, Optional, Tuple

from .native import load_library

_MAX_RECORD = 64 << 20  # refuse records over 64 MiB


def _lib():
    lib = load_library("recordio")
    if lib is None:
        raise RuntimeError("no C++ toolchain; recordio unavailable")
    if not getattr(lib, "_configured", False):
        lib.ptrec_writer_open.restype = ctypes.c_void_p
        lib.ptrec_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ptrec_write.restype = ctypes.c_int64
        lib.ptrec_write.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint8),
                                    ctypes.c_uint32]
        lib.ptrec_writer_close.restype = ctypes.c_int64
        lib.ptrec_writer_close.argtypes = [ctypes.c_void_p]
        lib.ptrec_reader_open.restype = ctypes.c_void_p
        lib.ptrec_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.ptrec_read.restype = ctypes.c_int64
        lib.ptrec_read.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_uint32]
        lib.ptrec_reader_close.argtypes = [ctypes.c_void_p]
        lib.ptrec_prefetch_open.restype = ctypes.c_void_p
        lib.ptrec_prefetch_open.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                            ctypes.c_int64, ctypes.c_int]
        lib.ptrec_prefetch_next.restype = ctypes.c_int64
        lib.ptrec_prefetch_next.argtypes = [ctypes.c_void_p,
                                            ctypes.POINTER(ctypes.c_uint8),
                                            ctypes.c_uint32]
        lib.ptrec_prefetch_close.argtypes = [ctypes.c_void_p]
        lib._configured = True
    return lib


class RecordWriter:
    """Append raw byte records; .write returns each record's offset."""

    def __init__(self, path: str, append: bool = False):
        self._lib = _lib()
        self._h = self._lib.ptrec_writer_open(path.encode(),
                                              1 if append else 0)
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, data: bytes) -> int:
        if len(data) > _MAX_RECORD:
            raise ValueError(
                f"record of {len(data)} bytes exceeds _MAX_RECORD "
                f"({_MAX_RECORD}); readers could never consume it")
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        off = self._lib.ptrec_write(self._h, buf, len(data))
        if off < 0:
            raise IOError("record write failed")
        return off

    def close(self) -> int:
        if self._h:
            n = self._lib.ptrec_writer_close(self._h)
            self._h = None
            return n
        return 0

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def read_records(path: str, offset: int = 0,
                 count: int = -1) -> Iterator[bytes]:
    """Sequential raw-record iterator (no prefetch thread). Buffers grow on
    demand up to _MAX_RECORD (the native reader rewinds past the header on
    a too-small buffer, so retry is clean)."""
    lib = _lib()
    h = lib.ptrec_reader_open(path.encode(), offset)
    if not h:
        raise IOError(f"cannot open {path}")
    cap = 1 << 20
    buf = (ctypes.c_uint8 * cap)()
    try:
        n = 0
        while count < 0 or n < count:
            ln = lib.ptrec_read(h, buf, cap)
            if ln == -3:
                if cap >= _MAX_RECORD:
                    raise IOError(f"record exceeds {_MAX_RECORD} bytes")
                cap = min(cap * 4, _MAX_RECORD)
                buf = (ctypes.c_uint8 * cap)()
                continue
            if ln == -1:
                return
            if ln < 0:
                raise IOError(f"corrupt record in {path} (code {ln})")
            yield bytes(bytearray(buf[: ln]))
            n += 1
    finally:
        lib.ptrec_reader_close(h)


def prefetch_records(path: str, offset: int = 0, count: int = -1,
                     queue_cap: int = 64,
                     buf_size: int = 1 << 20) -> Iterator[bytes]:
    """Raw records via the native background-thread prefetcher
    (DoubleBuffer semantics: IO runs ahead of the consumer)."""
    lib = _lib()
    h = lib.ptrec_prefetch_open(path.encode(), offset, count, queue_cap)
    if not h:
        raise IOError(f"cannot open {path}")
    cap = buf_size
    buf = (ctypes.c_uint8 * cap)()
    try:
        while True:
            ln = lib.ptrec_prefetch_next(h, buf, cap)
            if ln == -3:  # record stays queued; grow and retry
                if cap >= _MAX_RECORD:
                    raise IOError(f"record exceeds {_MAX_RECORD} bytes")
                cap = min(cap * 4, _MAX_RECORD)
                buf = (ctypes.c_uint8 * cap)()
                continue
            if ln == -1:
                return
            if ln < 0:
                raise IOError(f"prefetch error in {path} (code {ln})")
            yield bytes(bytearray(buf[: ln]))
    finally:
        lib.ptrec_prefetch_close(h)


# ---------------------------------------------------------------------------
# Sample-level conveniences (pickle payloads) + master integration
# ---------------------------------------------------------------------------
def write_records(path: str, samples: Iterable) -> List[int]:
    """Pickle each sample into a record. Returns record offsets."""
    offsets = []
    with RecordWriter(path) as w:
        for s in samples:
            offsets.append(w.write(pickle.dumps(s, protocol=4)))
    return offsets


def sample_reader(path: str, offset: int = 0, count: int = -1,
                  prefetch: bool = True):
    """A reader() callable yielding unpickled samples."""

    def reader():
        it = (prefetch_records(path, offset, count) if prefetch
              else read_records(path, offset, count))
        for raw in it:
            yield pickle.loads(raw)

    return reader


def chunk_tasks(path: str, records_per_chunk: int = 1024) -> List[str]:
    """Partition a record file into master task descriptors
    ("path:offset:count"), the go/master RecordIO sharding."""
    lib = _lib()
    h = lib.ptrec_reader_open(path.encode(), 0)
    if not h:
        raise IOError(f"cannot open {path}")
    # walk record headers to find chunk offsets
    tasks = []
    buf = (ctypes.c_uint8 * _MAX_RECORD)()
    try:
        pos = 0
        n_in_chunk = 0
        chunk_start = 0
        while True:
            ln = lib.ptrec_read(h, buf, _MAX_RECORD)
            if ln == -1:
                break
            if ln < 0:  # corruption is an error, not a short task list
                raise IOError(f"corrupt record in {path} (code {ln})")
            n_in_chunk += 1
            pos += 12 + ln
            if n_in_chunk == records_per_chunk:
                tasks.append(f"{path}:{chunk_start}:{n_in_chunk}")
                chunk_start = pos
                n_in_chunk = 0
        if n_in_chunk:
            tasks.append(f"{path}:{chunk_start}:{n_in_chunk}")
    finally:
        lib.ptrec_reader_close(h)
    return tasks


def chunk_reader(desc: str):
    """make_reader for MasterClient.task_reader over chunk descriptors."""
    path, offset, count = desc.rsplit(":", 2)
    return sample_reader(path, int(offset), int(count))()

"""Vocab-sharded embedding islands: shard_map gather + row-exchange.

The manual-SPMD half of the Wide&Deep CTR plan
(:func:`paddle_tpu.parallel.vocab_sharded_plan`): the [V, D] table lives
row-sharded over the mesh's vocab axis — each device holds its
contiguous [V/n, D] block, the in-graph form of the reference's sparse
parameter server owning embedding rows by parameter block
(/root/reference/paddle/pserver/ParameterServer2.h:94-100,
/root/reference/paddle/math/SparseRowMatrix.h). Three islands:

- :func:`vp_lookup` — the forward gather. Every shard gathers the rows
  it owns (foreign ids contribute zeros) and one psum over the vocab
  axis exchanges the rows — the "pserver -> trainer" pull as ICI
  all-reduce traffic. Batch stays sharded on the data axis when it
  divides, so dp parallelism survives the island.
- :func:`vp_scatter_add` — the row-granular optimizer write: global
  (rows, values) broadcast to every shard; each shard applies only the
  rows in its block (out-of-range ids — including the SelectedRows
  height sentinel — drop). The "trainer -> pserver" push.
- :func:`vp_rows_pull` — gather a row-subset of sharded per-row state
  (adagrad moments) back to every device for the update formula.

All three are exact: each global row is owned by exactly one shard, so
the psum adds one real value to zeros — bitwise identical to the
unsharded gather/scatter (pinned by the sparse-vs-dense parity tests).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .compat import shard_map


def rows_per_shard(vocab: int, mesh, vocab_axis: str) -> int:
    """Rows per device block, or 0 when the table cannot shard (axis
    absent / size 1 / vocab not divisible) — callers fall back to the
    serial path."""
    if mesh is None or vocab_axis not in mesh.axis_names:
        return 0
    n = mesh.shape[vocab_axis]
    if n <= 1 or vocab % n:
        return 0
    return vocab // n


def _data_spec(n_rows: int, mesh, data_axis):
    """Shard the id/value stream on the data axis when it divides;
    replicated otherwise (shard_map blocks must tile exactly)."""
    if (data_axis and data_axis in mesh.axis_names
            and n_rows % mesh.shape[data_axis] == 0):
        return data_axis
    return None


def vp_lookup(w, flat_ids, mesh, vocab_axis: str = "mp",
              data_axis: str = "dp"):
    """Gather ``w[flat_ids]`` with ``w`` row-sharded over ``vocab_axis``.

    w: [V, D] (annotated P(vocab_axis, None) by the plan); flat_ids: [n]
    int. Returns [n, D] sharded over ``data_axis`` when n divides.
    """
    vl = rows_per_shard(w.shape[0], mesh, vocab_axis)
    if not vl:
        return w[flat_ids]
    da = _data_spec(flat_ids.shape[0], mesh, data_axis)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(vocab_axis, None), P(da)),
                       out_specs=P(da, None))
    def run(wl, ids):
        base = jax.lax.axis_index(vocab_axis) * vl
        local = ids - base
        owned = (local >= 0) & (local < vl)
        rows = jnp.where(owned[:, None],
                         wl[jnp.clip(local, 0, vl - 1)],
                         jnp.zeros((), wl.dtype))
        # the row exchange: each id is owned by exactly ONE shard, so
        # the all-reduce adds its row to zeros — exact, and it IS the
        # ICI traffic replacing the pserver round-trip
        return jax.lax.psum(rows, vocab_axis)

    return run(w, flat_ids)


def vp_scatter_add(p, rows, values, mesh, vocab_axis: str = "mp",
                   mode: str = "add"):
    """``p.at[rows].add(values)`` (or ``.set`` with ``mode='set'`` —
    rows must then be deduplicated) with ``p`` row-sharded over
    ``vocab_axis``. rows may carry the SelectedRows height sentinel
    (== p.shape[0]) — it lands outside every shard's block and drops.
    rows/values are broadcast to all shards (in_specs P()): with dp in
    the mesh each data group carries a distinct slice of the global row
    stream, so the implied all-gather is the cross-replica gradient
    exchange."""
    vl = rows_per_shard(p.shape[0], mesh, vocab_axis)
    if not vl:
        upd = p.at[rows]
        return (upd.set(values, mode="drop") if mode == "set"
                else upd.add(values, mode="drop"))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(vocab_axis, None), P(), P()),
                       out_specs=P(vocab_axis, None))
    def run(pl, rows_g, vals_g):
        base = jax.lax.axis_index(vocab_axis) * vl
        local = rows_g - base
        owned = (local >= 0) & (local < vl)
        # disowned rows point past the block; mode='drop' ignores them
        idx = jnp.where(owned, local, vl)
        upd = pl.at[idx]
        if mode == "set":
            # deduped rows: each local slot is set at most once; foreign
            # rows all alias index vl and drop
            return upd.set(jnp.where(owned[:, None], vals_g,
                                     jnp.zeros((), vals_g.dtype)),
                           mode="drop")
        return upd.add(
            jnp.where(owned[:, None], vals_g,
                      jnp.zeros((), vals_g.dtype)), mode="drop")

    return run(p, rows, values)


def vp_rows_pull(state, rows, mesh, vocab_axis: str = "mp"):
    """``state[rows]`` with ``state`` row-sharded over ``vocab_axis``:
    every device gets the full [n, D] row subset (psum-exchange, exactly
    like :func:`vp_lookup` but replicated — optimizer formulas need the
    same values on every shard). Sentinel rows read as zero."""
    vl = rows_per_shard(state.shape[0], mesh, vocab_axis)
    if not vl:
        # mode='fill' semantics by hand: sentinel rows read zero
        n = state.shape[0]
        safe = jnp.clip(rows, 0, n - 1)
        return jnp.where((rows < n)[:, None] if state.ndim > 1
                         else (rows < n), state[safe],
                         jnp.zeros((), state.dtype))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(vocab_axis, None), P()),
                       out_specs=P())
    def run(sl, rows_g):
        base = jax.lax.axis_index(vocab_axis) * vl
        local = rows_g - base
        owned = (local >= 0) & (local < vl)
        vals = jnp.where(owned[:, None] if sl.ndim > 1 else owned,
                         sl[jnp.clip(local, 0, vl - 1)],
                         jnp.zeros((), sl.dtype))
        return jax.lax.psum(vals, vocab_axis)

    return run(state, rows)

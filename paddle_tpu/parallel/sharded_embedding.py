"""Vocab-sharded embedding islands: shard_map gather + row-exchange.

The manual-SPMD half of the Wide&Deep CTR plan
(:func:`paddle_tpu.parallel.vocab_sharded_plan`): the [V, D] table lives
row-sharded over the mesh's vocab axis — each device holds its
contiguous [V/n, D] block, the in-graph form of the reference's sparse
parameter server owning embedding rows by parameter block
(/root/reference/paddle/pserver/ParameterServer2.h:94-100,
/root/reference/paddle/math/SparseRowMatrix.h). Three islands:

- :func:`vp_lookup` — the forward gather. Every shard gathers the rows
  it owns (foreign ids contribute zeros) and one psum over the vocab
  axis exchanges the rows — the "pserver -> trainer" pull as ICI
  all-reduce traffic. Batch stays sharded on the data axis when it
  divides, so dp parallelism survives the island.
- :func:`vp_scatter_add` — the row-granular optimizer write: each shard
  applies only the rows in its block (out-of-range ids — including the
  SelectedRows height sentinel — drop). The "trainer -> pserver" push.
  Two exchange strategies: the legacy ``gather`` path broadcasts the
  whole (rows, values) stream to every shard; the ``a2a`` path (the
  default for deduplicated ``add`` scatters) splits the stream across
  the vocab axis and ships each row ONLY to its owner shard through a
  capacity-bounded ``all_to_all`` — exchange bytes drop ~n_shards-fold.
  A skewed stream that overflows the per-destination capacity falls
  back in-graph (uniform ``lax.cond`` predicate via psum) to the full
  gather, so the result is bitwise identical on every input.
- :func:`vp_rows_pull` — gather a row-subset of sharded per-row state
  (adagrad moments) back to every device for the update formula.

All three are exact: each global row is owned by exactly one shard, so
the psum adds one real value to zeros — bitwise identical to the
unsharded gather/scatter (pinned by the sparse-vs-dense parity tests).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .compat import shard_map


def rows_per_shard(vocab: int, mesh, vocab_axis: str) -> int:
    """Rows per device block, or 0 when the table cannot shard (axis
    absent / size 1 / vocab not divisible) — callers fall back to the
    serial path."""
    if mesh is None or vocab_axis not in mesh.axis_names:
        return 0
    n = mesh.shape[vocab_axis]
    if n <= 1 or vocab % n:
        return 0
    return vocab // n


def _data_spec(n_rows: int, mesh, data_axis):
    """Shard the id/value stream on the data axis when it divides;
    replicated otherwise (shard_map blocks must tile exactly)."""
    if (data_axis and data_axis in mesh.axis_names
            and n_rows % mesh.shape[data_axis] == 0):
        return data_axis
    return None


def vp_lookup(w, flat_ids, mesh, vocab_axis: str = "mp",
              data_axis: str = "dp"):
    """Gather ``w[flat_ids]`` with ``w`` row-sharded over ``vocab_axis``.

    w: [V, D] (annotated P(vocab_axis, None) by the plan); flat_ids: [n]
    int. Returns [n, D] sharded over ``data_axis`` when n divides.
    """
    vl = rows_per_shard(w.shape[0], mesh, vocab_axis)
    if not vl:
        return w[flat_ids]
    da = _data_spec(flat_ids.shape[0], mesh, data_axis)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(vocab_axis, None), P(da)),
                       out_specs=P(da, None))
    def run(wl, ids):
        base = jax.lax.axis_index(vocab_axis) * vl
        local = ids - base
        owned = (local >= 0) & (local < vl)
        rows = jnp.where(owned[:, None],
                         wl[jnp.clip(local, 0, vl - 1)],
                         jnp.zeros((), wl.dtype))
        # the row exchange: each id is owned by exactly ONE shard, so
        # the all-reduce adds its row to zeros — exact, and it IS the
        # ICI traffic replacing the pserver round-trip
        return jax.lax.psum(rows, vocab_axis)

    return run(w, flat_ids)


def vp_scatter_add(p, rows, values, mesh, vocab_axis: str = "mp",
                   mode: str = "add", exchange: str = "auto",
                   capacity_factor: float = 2.0):
    """``p.at[rows].add(values)`` (or ``.set`` with ``mode='set'`` —
    rows must then be deduplicated) with ``p`` row-sharded over
    ``vocab_axis``. rows may carry the SelectedRows height sentinel
    (== p.shape[0]) — it lands outside every shard's block and drops.

    exchange:
      'gather' — rows/values broadcast to all shards (in_specs P());
                 every shard scans the full stream and keeps its rows.
      'a2a'    — the stream splits over ``vocab_axis`` and each row
                 ships only to its owner shard via a capacity-bounded
                 ``all_to_all`` (:func:`_scatter_add_a2a`); requires
                 unique rows (``SelectedRows.merged`` output) so the
                 single add per table row is order-free — bitwise equal
                 to 'gather'.
      'auto'   — 'a2a' when legal (add mode, stream divides the vocab
                 axis), else 'gather'.
    """
    vl = rows_per_shard(p.shape[0], mesh, vocab_axis)
    if not vl:
        upd = p.at[rows]
        return (upd.set(values, mode="drop") if mode == "set"
                else upd.add(values, mode="drop"))
    nmp = mesh.shape[vocab_axis]
    n = rows.shape[0]
    if exchange == "a2a" or (exchange == "auto" and mode == "add"
                             and nmp > 1 and n % nmp == 0):
        return _scatter_add_a2a(p, rows, values, mesh, vocab_axis, vl,
                                capacity_factor)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(vocab_axis, None), P(), P()),
                       out_specs=P(vocab_axis, None))
    def run(pl, rows_g, vals_g):
        base = jax.lax.axis_index(vocab_axis) * vl
        local = rows_g - base
        owned = (local >= 0) & (local < vl)
        # disowned rows point past the block; mode='drop' ignores them
        idx = jnp.where(owned, local, vl)
        upd = pl.at[idx]
        if mode == "set":
            # deduped rows: each local slot is set at most once; foreign
            # rows all alias index vl and drop
            return upd.set(jnp.where(owned[:, None], vals_g,
                                     jnp.zeros((), vals_g.dtype)),
                           mode="drop")
        return upd.add(
            jnp.where(owned[:, None], vals_g,
                      jnp.zeros((), vals_g.dtype)), mode="drop")

    return run(p, rows, values)


def a2a_capacity(n: int, nmp: int, capacity_factor: float = 2.0) -> int:
    """Per-(source, destination) bucket depth of the a2a exchange: the
    stream slice on each shard is n/nmp rows; a uniform owner spread
    puts n/nmp² in each bucket, head-roomed by ``capacity_factor``."""
    import math

    nl = max(1, n // nmp)
    return max(1, min(nl, int(math.ceil(nl / nmp * capacity_factor))))


def exchange_bytes(n: int, nmp: int, width: int,
                   capacity_factor: float = 2.0) -> dict:
    """Modeled interconnect bytes per dp group for one scatter of an
    n-row stream of ``width``-byte rows (id + value lanes) — what the
    PERF.md witness reports. gather replicates the stream to every
    vocab shard; a2a ships each (capacity-padded) row once."""
    cap = a2a_capacity(n, nmp, capacity_factor)
    return {"gather": n * width * nmp,
            "a2a": nmp * cap * width * nmp,  # nmp shards x [nmp, cap]
            "capacity": cap}


def _scatter_add_a2a(p, rows, values, mesh, vocab_axis, vl,
                     capacity_factor):
    """Owner-targeted row exchange: the (rows, values) stream splits
    over ``vocab_axis`` (each shard holds n/nmp rows of it, replicated
    across dp); every row is packed into a per-owner capacity bucket and
    ONE ``all_to_all`` lands it on the shard whose [V/n, D] block owns
    it. Rows must be unique (merged SelectedRows) so each table row
    receives at most one add — arrival order cannot change the sum, and
    the result is bitwise equal to the gather path. A stream skewed
    enough to overflow a bucket flips a psum'd (hence mesh-uniform)
    predicate and the whole scatter falls back to the gather exchange
    in-graph: capacity bounds bytes, never correctness."""
    nmp = mesh.shape[vocab_axis]
    cap = a2a_capacity(rows.shape[0], nmp, capacity_factor)
    height = p.shape[0]

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(vocab_axis, None), P(vocab_axis),
                                 P(vocab_axis, None)),
                       out_specs=P(vocab_axis, None))
    def run(pl, ids, vals):
        base = jax.lax.axis_index(vocab_axis) * vl
        valid = ids < height  # sentinel padding never ships
        owner = jnp.clip(ids // vl, 0, nmp - 1)
        onehot = ((owner[:, None] == jnp.arange(nmp)[None, :])
                  & valid[:, None])
        # position of each row inside its owner's bucket (cumsum trick)
        pos = jnp.sum(jnp.where(
            onehot, jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1, 0),
            axis=1)
        fits = valid & (pos < cap)
        spilled = jax.lax.psum(
            jnp.any(valid & ~fits).astype(jnp.int32), vocab_axis)

        def apply(pl, ids_g, vals_g):
            local = ids_g - base
            owned = (local >= 0) & (local < vl)
            return pl.at[jnp.where(owned, local, vl)].add(
                jnp.where(owned[:, None], vals_g,
                          jnp.zeros((), vals_g.dtype)), mode="drop")

        def a2a_path(_):
            # slot [owner, pos] in the send buffer; non-fitting rows
            # alias the drop column ``cap``
            o = jnp.where(fits, owner, 0)
            s = jnp.where(fits, pos, cap)
            idb = jnp.full((nmp, cap + 1), height, ids.dtype)
            idb = idb.at[o, s].set(jnp.where(fits, ids, height))
            vb = jnp.zeros((nmp, cap + 1) + vals.shape[1:], vals.dtype)
            vb = vb.at[o, s].set(
                jnp.where(fits[:, None], vals,
                          jnp.zeros((), vals.dtype)))
            rid = jax.lax.all_to_all(idb[:, :cap], vocab_axis, 0, 0,
                                     tiled=True)
            rva = jax.lax.all_to_all(vb[:, :cap], vocab_axis, 0, 0,
                                     tiled=True)
            return apply(pl, rid.reshape(-1),
                         rva.reshape((-1,) + vals.shape[1:]))

        def gather_path(_):
            ids_g = jax.lax.all_gather(ids, vocab_axis, tiled=True)
            vals_g = jax.lax.all_gather(vals, vocab_axis, tiled=True)
            return apply(pl, ids_g, vals_g)

        return jax.lax.cond(spilled > 0, gather_path, a2a_path, None)

    return run(p, rows, values)


def vp_rows_pull(state, rows, mesh, vocab_axis: str = "mp"):
    """``state[rows]`` with ``state`` row-sharded over ``vocab_axis``:
    every device gets the full [n, D] row subset (psum-exchange, exactly
    like :func:`vp_lookup` but replicated — optimizer formulas need the
    same values on every shard). Sentinel rows read as zero."""
    vl = rows_per_shard(state.shape[0], mesh, vocab_axis)
    if not vl:
        # mode='fill' semantics by hand: sentinel rows read zero
        n = state.shape[0]
        safe = jnp.clip(rows, 0, n - 1)
        return jnp.where((rows < n)[:, None] if state.ndim > 1
                         else (rows < n), state[safe],
                         jnp.zeros((), state.dtype))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(vocab_axis, None), P()),
                       out_specs=P())
    def run(sl, rows_g):
        base = jax.lax.axis_index(vocab_axis) * vl
        local = rows_g - base
        owned = (local >= 0) & (local < vl)
        vals = jnp.where(owned[:, None] if sl.ndim > 1 else owned,
                         sl[jnp.clip(local, 0, vl - 1)],
                         jnp.zeros((), sl.dtype))
        return jax.lax.psum(vals, vocab_axis)

    return run(state, rows)

"""Sharding plans: declarative variable-name -> PartitionSpec mapping.

The reference distributes parameters by slicing them into blocks and
round-robining blocks across parameter servers
(/root/reference/paddle/pserver/ParameterServer2.h:94-100) or by name-hash
(/root/reference/go/pserver/client/client.go partition), and distributes data
by splitting the batch across trainer threads
(/root/reference/paddle/gserver/gradientmachines/MultiGradientMachine.h:43-105).
Here both are the same mechanism: a PartitionSpec per variable over a named
mesh. XLA GSPMD propagates the specs through the whole-block computation and
inserts the collectives (psum for data-parallel grad reduction, all-gather /
reduce-scatter for tensor-parallel layers) in-graph.

Optimizer accumulators (named ``<param>_<kind>_acc``) automatically inherit
their parameter's spec because rules match on name substrings — the analogue
of the pserver keeping momentum state sharded exactly like its parameter
blocks (ParameterServer2.h:57-72).
"""
from __future__ import annotations

import hashlib
import re
from typing import Callable, List, Optional, Sequence, Tuple, Union

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SpecLike = Union[P, Callable[[str, int], P]]


class ShardingPlanError(ValueError):
    """Nothing in the plan fits a variable: every matching rule's spec
    rank exceeds the variable's ndim AND the plan default is
    rank-incompatible too. Located at plan-application time
    (ShardProgram / executor lowering) naming the variable and the
    rules tried — not as a GSPMD shape error deep inside jit."""


def _spec_rank_fits(spec: P, ndim: int) -> bool:
    return len(spec) <= ndim


def spec_axes(spec: P) -> List[str]:
    """The mesh axis names a PartitionSpec references (flattened)."""
    axes: List[str] = []
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, tuple):
            axes.extend(entry)
        else:
            axes.append(entry)
    return axes


def _spec_shape_fits(spec: P, shape, axis_sizes) -> bool:
    """Divisibility check: every sharded dim must divide by the product
    of its mesh axes. ``-1`` (the symbolic batch dim) is exempt — its
    concrete size is validated by GSPMD at lowering."""
    if shape is None:
        return True
    for dim, entry in zip(shape, tuple(spec)):
        if entry is None or int(dim) == -1:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        div = 1
        for ax in axes:
            div *= axis_sizes.get(ax, 1)
        if div > 1 and int(dim) % div:
            return False
    return True


class ShardingPlan:
    """Ordered rule list mapping variable names to PartitionSpecs.

    rules: sequence of (regex, spec) — first match wins. ``spec`` is either a
    PartitionSpec or a callable (name, ndim) -> PartitionSpec. A matched
    rule whose spec rank exceeds the variable's ndim falls through to the
    next rule — low-rank optimizer scalars that match their parameter's
    rule by substring land on the (replicated) default this way; when the
    default itself is rank-incompatible, a located
    :class:`ShardingPlanError` names the variable and the rules tried.
    A spec whose sharded dims do not divide the variable's concrete shape
    (``shape=`` given) also falls through quietly: that is the
    (1,)-shaped beta-pow-accumulator case every Megatron-style bias rule
    hits.
    data_axis: mesh axis the leading (batch) dim of feed variables shards on.
    """

    def __init__(self, mesh: Mesh,
                 rules: Optional[Sequence[Tuple[str, SpecLike]]] = None,
                 data_axis: Optional[str] = "dp",
                 default: P = P()):
        self.mesh = mesh
        self.rules: List[Tuple[re.Pattern, SpecLike]] = [
            (re.compile(pat), spec) for pat, spec in (rules or [])
        ]
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.default = default
        self._digest: Optional[str] = None

    # ------------------------------------------------------------------
    def _axis_sizes(self):
        return dict(zip(self.mesh.axis_names,
                        tuple(self.mesh.shape[a]
                              for a in self.mesh.axis_names)))

    def spec_for_state(self, name: str, ndim: int,
                       shape: Optional[Sequence[int]] = None) -> P:
        axis_sizes = self._axis_sizes()
        rank_misfits: List[Tuple[str, P]] = []
        for pat, spec in self.rules:
            if not pat.search(name):
                continue
            cand = spec(name, ndim) if callable(spec) else spec
            if cand is None:
                continue
            if not _spec_rank_fits(cand, ndim):
                # rank misfit: fall through to the next rule instead of
                # returning a spec that only errors at lowering
                rank_misfits.append((pat.pattern, cand))
                continue
            if not _spec_shape_fits(cand, shape, axis_sizes):
                continue  # non-divisible dim (e.g. a (1,) accumulator)
            return cand
        if _spec_rank_fits(self.default, ndim):
            # a rank-misfit rule falls through all the way to the
            # default: low-rank optimizer scalars matching their
            # parameter's rule by substring replicate silently
            return self.default
        tried = "; ".join(f"rule {pat!r} -> {tuple(s)}"
                          for pat, s in rank_misfits) or "(no rule matched)"
        raise ShardingPlanError(
            f"nothing in the plan fits variable {name!r} (ndim={ndim}"
            + (f", shape={tuple(shape)}" if shape is not None else "")
            + f"): {tried}; default {tuple(self.default)} also exceeds "
            f"the variable's rank. Make the rule a callable (name, ndim) "
            f"-> PartitionSpec that degrades for low-rank variables, or "
            f"use a rank-compatible default.")

    def spec_for_feed(self, name: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                cand = spec(name, ndim) if callable(spec) else spec
                if cand is not None and _spec_rank_fits(cand, ndim):
                    return cand
        if self.data_axis is None or ndim == 0:
            return P()
        return P(self.data_axis, *([None] * (ndim - 1)))

    def state_sharding(self, name: str, ndim: int,
                       shape: Optional[Sequence[int]] = None
                       ) -> NamedSharding:
        return NamedSharding(self.mesh,
                             self.spec_for_state(name, ndim, shape=shape))

    def feed_sharding(self, name: str, ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec_for_feed(name, ndim))

    # ------------------------------------------------------------------
    def mesh_axes(self) -> dict:
        """{axis name: size} of the plan's mesh (works for AbstractMesh
        too — the analysis plane prices plans without real devices)."""
        return self._axis_sizes()

    def digest(self) -> str:
        """Stable content digest of (mesh shape, rules, data_axis): two
        independently constructed but equivalent plans — e.g. a fresh
        ``megatron_plan(mesh)`` per serving request — digest identically,
        so the executor compile-cache key they feed stays warm. Callable
        specs hash by qualname + bytecode + closure reprs (two different
        lambdas never collide; the same factory's closure always
        matches)."""
        if self._digest is not None:
            return self._digest
        h = hashlib.sha256()
        h.update(repr(sorted(self._axis_sizes().items())).encode())
        h.update(repr(self.data_axis).encode())
        h.update(repr(tuple(self.default)).encode())
        for pat, spec in self.rules:
            h.update(pat.pattern.encode())
            if callable(spec):
                h.update(getattr(spec, "__module__", "?").encode())
                h.update(getattr(spec, "__qualname__", repr(spec)).encode())
                code = getattr(spec, "__code__", None)
                if code is not None:
                    h.update(code.co_code)
                for cell in (getattr(spec, "__closure__", None) or ()):
                    try:
                        h.update(repr(cell.cell_contents).encode())
                    except ValueError:  # pragma: no cover - empty cell
                        pass
            else:
                h.update(repr(tuple(spec)).encode())
        self._digest = h.hexdigest()[:16]
        return self._digest


# ----------------------------------------------------------------------
# Canned plans
# ----------------------------------------------------------------------

def data_parallel_plan(mesh: Mesh, data_axis: str = "dp") -> ShardingPlan:
    """Pure data parallelism: batch sharded, every parameter replicated.

    The in-graph analogue of MultiGradientMachine / the sync pserver path:
    GSPMD turns the grad contractions into psum over ``data_axis``.
    """
    return ShardingPlan(mesh, rules=[], data_axis=data_axis)


def megatron_plan(mesh: Mesh, data_axis: str = "dp",
                  model_axis: str = "mp") -> ShardingPlan:
    """Hybrid data + tensor parallelism (Megatron-style).

    FC weights (in, out) and conv kernels (kh, kw, cin, cout) shard their
    output dim over ``model_axis``; matching biases shard too. GSPMD inserts
    the all-reduce where a following layer contracts over the sharded dim.
    """
    def fc_w(name: str, ndim: int) -> P:
        if ndim >= 2:
            return P(*([None] * (ndim - 1)), model_axis)
        return P(model_axis)

    return ShardingPlan(
        mesh,
        rules=[
            (r"\.w", fc_w),      # fc/conv weights + their optimizer accs
            (r"\.b", P(model_axis)),
        ],
        data_axis=data_axis,
    )


def zero_plan(mesh: Mesh, data_axis: str = "dp") -> ShardingPlan:
    """ZeRO-style: optimizer accumulators sharded over the data axis.

    The TPU answer to the pserver owning optimizer state in shards
    (/root/reference/go/pserver/optimizer.go:51): accumulator tensors shard
    their leading dim across data-parallel workers; parameters stay
    replicated for the forward pass.
    """
    def acc_spec(name: str, ndim: int) -> P:
        if ndim >= 1:
            return P(data_axis, *([None] * (ndim - 1)))
        return P()

    return ShardingPlan(
        mesh,
        rules=[(r"_acc$", acc_spec)],
        data_axis=data_axis,
    )


def vocab_sharded_plan(mesh: Mesh, data_axis: str = "dp",
                       vocab_axis: str = "mp") -> ShardingPlan:
    """Vocabulary-sharded large embeddings (the CTR / Wide&Deep plan).

    Embedding tables ([V, D], named ``embedding*.w*`` by layers.embedding)
    shard their vocab dim over ``vocab_axis`` — the in-graph ICI analogue of
    the reference's sparse parameter server, which sharded embedding rows
    across pservers by parameter block
    (/root/reference/paddle/pserver/ParameterServer2.h:94-100,
    /root/reference/paddle/math/SparseRowMatrix.h). GSPMD partitions the
    lookup gather and the row-sparse optimizer scatter across the axis; the
    optimizer's row accumulators inherit the spec by the ``_acc`` naming
    convention. Dense-tower parameters stay replicated; batch shards on
    ``data_axis``.
    """
    def emb_spec(name: str, ndim: int) -> P:
        if ndim >= 2:
            return P(vocab_axis, *([None] * (ndim - 1)))
        return P()

    return ShardingPlan(
        mesh,
        rules=[(r"embedding.*\.w", emb_spec)],
        data_axis=data_axis,
    )


def expert_parallel_plan(mesh: Mesh, data_axis: str = "dp",
                         expert_axis: str = "ep",
                         model_axis: Optional[str] = None) -> ShardingPlan:
    """Expert parallelism (+ optional tensor parallelism).

    MoE expert-major tensors (named ``*.expert_*`` by layers.switch_moe,
    shaped [E, ...]) shard dim 0 over ``expert_axis`` — each device holds
    E/n experts and GSPMD turns the dispatch/combine einsums into
    all-to-alls. Gates stay replicated. With ``model_axis`` set, dense fc
    weights also shard Megatron-style.
    """
    def expert_spec(name: str, ndim: int) -> P:
        # rank >= 2 only: expert tensors are [E, ...]; rank-1 matches are
        # optimizer scalars (beta-pow accumulators etc.), not expert-major
        if ndim >= 2:
            return P(expert_axis, *([None] * (ndim - 1)))
        return P()

    rules: List[Tuple[str, SpecLike]] = [
        (r"\.expert_", expert_spec),
        (r"\.gate", P()),
    ]
    if model_axis:
        def fc_w(name: str, ndim: int) -> P:
            if ndim >= 2:
                return P(*([None] * (ndim - 1)), model_axis)
            return P(model_axis)

        rules += [(r"\.w", fc_w), (r"\.b", P(model_axis))]
    return ShardingPlan(mesh, rules=[(p, s) for p, s in rules],
                        data_axis=data_axis)


def pipeline_plan(mesh: Mesh, data_axis: str = "dp",
                  pipe_axis: str = "pp") -> ShardingPlan:
    """Pipeline (+ data) parallelism for stacked layer stacks.

    Tensors created by ``layers.pipelined_transformer_stack`` carry a
    ``.stack_`` name marker and a leading [L, ...] layer axis; sharding
    that axis over ``pipe_axis`` gives each device a contiguous block of
    layers (its pipeline stage) — placement-by-spec where the reference's
    ParallelNeuralNetwork placed layer ranges by config
    (/root/reference/paddle/gserver/gradientmachines/
    ParallelNeuralNetwork.cpp). Optimizer accumulators inherit the spec by
    the usual name-substring rule. Everything else (embeddings, heads)
    stays replicated; feeds shard on ``data_axis``.
    """
    def stage_spec(name: str, ndim: int) -> P:
        # rank >= 2 only: every stacked tensor is [L, d, ...]; rank-1
        # matches are optimizer scalars (beta-pow accumulators etc.)
        if ndim >= 2:
            return P(pipe_axis, *([None] * (ndim - 1)))
        return P()

    return ShardingPlan(mesh, rules=[(r"\.stack_", stage_spec)],
                        data_axis=data_axis)

"""Ring attention: exact attention over sequences sharded across devices.

The long-context scaling path (SURVEY.md §5.7: a NEW capability — the
reference has no sequence parallelism of any kind; its long-sequence story
is LoD batching). Sequences are sharded on the time axis over a mesh axis;
each device keeps its local Q shard resident and the K/V shards rotate
around the ring via ``jax.lax.ppermute`` (XLA lowers this to ICI
neighbour-exchange, overlapping the transfer with the local blockwise
attention compute). The online-softmax accumulators (running max m,
denominator l, weighted sum acc) make the result exact — identical to full
attention — while per-device memory stays O(T/n * T/n) per block pair and
peak activation is O(T/n * d).

This is the in-graph-collective replacement for what a CUDA framework would
build from NCCL send/recv (the reference's closest machinery:
/root/reference/paddle/operators/nccl_op.cc, send_op.cc) — here it is one
``shard_map``-ped function XLA can schedule and fuse.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .compat import pvary, shard_map


def _block_attn(q, k, v, m, l, acc, q_off, k_off, causal, sm_scale):
    """One blockwise-attention accumulation step (online softmax).

    q [b, h, tq, d]; k/v [b, h, tk, d]; m/l [b, h, tq, 1]; acc like q (f32).
    q_off/k_off are the GLOBAL positions of the local shards — causality is
    decided in global coordinates.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        qi = q_off + jnp.arange(q.shape[2])[:, None]
        kj = k_off + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= kj, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(q, k, v, mesh, seq_axis="sp", causal=False, sm_scale=None):
    """Exact attention with q/k/v sharded on the time axis of ``mesh``.

    q, k, v: [B, H, T, D] global tensors (or already-sharded arrays).
    Returns [B, H, T, D] with the same sequence sharding as q.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[seq_axis]
    T = q.shape[2]
    assert T % n == 0, f"seq len {T} not divisible by ring size {n}"
    shard_t = T // n
    spec = P(None, None, seq_axis, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec)
    def ring(ql, kl, vl):
        idx = jax.lax.axis_index(seq_axis)
        q_off = idx * shard_t
        m = jnp.full(ql.shape[:2] + (ql.shape[2], 1), -jnp.inf, jnp.float32)
        l = jnp.zeros_like(m)
        acc = jnp.zeros(ql.shape, jnp.float32)
        # type the carries as device-varying so the fori_loop carry types
        # stay fixed once ppermuted K/V mix in (shard_map vma typing)
        m, l, acc = (pvary(a, (seq_axis,)) for a in (m, l, acc))
        perm = [(i, (i + 1) % n) for i in range(n)]

        def attend(c, kc, vc, m, l, acc):
            # K/V chunk currently held arrived from device (idx - c) % n
            src = (idx - c) % n
            return _block_attn(ql, kc, vc, m, l, acc, q_off, src * shard_t,
                               causal, sm_scale)

        def step(c, carry):
            kc, vc, m, l, acc = carry
            m, l, acc = attend(c, kc, vc, m, l, acc)
            # rotate K/V around the ring (ICI neighbour exchange)
            kc = jax.lax.ppermute(kc, seq_axis, perm)
            vc = jax.lax.ppermute(vc, seq_axis, perm)
            return (kc, vc, m, l, acc)

        # last chunk attends outside the loop — no wasted final rotation
        kc, vc, m, l, acc = jax.lax.fori_loop(
            0, n - 1, step, (kl, vl, m, l, acc))
        m, l, acc = attend(n - 1, kc, vc, m, l, acc)
        out = acc / jnp.maximum(l, 1e-30)
        return out.astype(ql.dtype)

    if isinstance(q, jax.core.Tracer):
        # inside a jit trace (the executor's whole-block compile): shard_map
        # in_specs tell GSPMD how to reshard; no explicit placement possible
        return ring(q, k, v)
    qs = jax.device_put(q, NamedSharding(mesh, spec)) \
        if not _is_sharded(q) else q
    ks = jax.device_put(k, NamedSharding(mesh, spec)) \
        if not _is_sharded(k) else k
    vs = jax.device_put(v, NamedSharding(mesh, spec)) \
        if not _is_sharded(v) else v
    return ring(qs, ks, vs)


def _is_sharded(x):
    sh = getattr(x, "sharding", None)
    return sh is not None and not getattr(sh, "is_fully_replicated", True)

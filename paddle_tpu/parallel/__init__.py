"""Distributed/parallel execution: meshes, sharding plans, SPMD helpers.

This package replaces the reference's entire distributed plane — the
multi-threaded ring gather/scatter of MultiGradientMachine
(/root/reference/paddle/gserver/gradientmachines/MultiGradientMachine.h:43-105),
the C++ parameter server (/root/reference/paddle/pserver/ParameterServer2.h:73),
the Go pserver/master control plane (/root/reference/go/pserver/service.go:134),
the Fluid gRPC send/recv ops (/root/reference/paddle/operators/send_op.cc:30)
and the NCCL ops (/root/reference/paddle/operators/nccl_op.cc:68) — with
in-graph XLA collectives over ICI/DCN, driven by jax.sharding annotations.

The user picks a Mesh and a ShardingPlan; the executor jits the whole program
block with those shardings and XLA GSPMD inserts all-reduce / all-gather /
reduce-scatter where the data flow demands them. There is no parameter-server
process, no gradient RPC, and no explicit communication op in user programs.
"""
from .mesh import make_abstract_mesh, make_mesh, mesh_axis_size
from .multihost import (initialize as initialize_multihost,
                        local_batch_slice, make_hybrid_mesh, process_info)
from .ring_attention import ring_attention
from .plan import (ShardingPlan, ShardingPlanError, data_parallel_plan,
                   expert_parallel_plan, megatron_plan, pipeline_plan,
                   vocab_sharded_plan, zero_plan)

__all__ = [
    "make_mesh", "make_abstract_mesh", "mesh_axis_size", "ring_attention",
    "ShardingPlan", "ShardingPlanError", "data_parallel_plan",
    "expert_parallel_plan", "megatron_plan", "pipeline_plan",
    "vocab_sharded_plan", "zero_plan",
    "initialize_multihost", "make_hybrid_mesh", "process_info",
    "local_batch_slice",
]

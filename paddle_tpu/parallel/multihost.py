"""Multi-host (multi-slice) bring-up: the DCN plane.

The reference scales across hosts with an etcd-discovered pserver fleet and
trainer processes wired by flags (--trainer_id, --pservers,
--num_gradient_servers; /root/reference/doc/design/cluster_train/README.md).
The TPU-native equivalent is radically smaller: every host runs the SAME
SPMD program, jax.distributed provides the rendezvous, and the global
device mesh spans all slices — gradient exchange is the same in-graph
all-reduce, now routed over ICI within a slice and DCN across slices by
XLA. No parameter server exists to fail over; the data plane's master
(paddle_tpu.master) remains the only stateful coordinator.

Axis placement follows the scaling-book recipe: put the
communication-light axis (dp, or ZeRO's data axis) on DCN and the
communication-heavy axes (mp/sp/ep) on ICI — ``make_hybrid_mesh`` encodes
exactly that split.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host rendezvous (idempotent).

    Arguments default from the standard env (COORDINATOR_ADDRESS,
    NUM_PROCESSES, PROCESS_ID) — the analogue of the reference's etcd
    discovery (/root/reference/go/pserver/etcd_client.go), with the
    rendezvous service standing in for etcd. Without a coordinator the
    call is a single-process no-op, so the same training script runs
    unchanged on one host. (Launchers relying on cloud auto-detection can
    call jax.distributed.initialize() directly before importing models.)
    """
    global _initialized
    if _initialized:
        return
    kwargs = {}
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr:
        kwargs["coordinator_address"] = addr
        kwargs["num_processes"] = int(
            num_processes if num_processes is not None
            else os.environ.get("NUM_PROCESSES", 1))
        kwargs["process_id"] = int(
            process_id if process_id is not None
            else os.environ.get("PROCESS_ID", 0))
    if not kwargs:
        # Single-process no-op — deliberately NOT latched: a later call
        # that does carry a coordinator (e.g. after flag parsing) must
        # still be able to join the rendezvous.
        return
    # jax.distributed must run before ANY backend use; detect via the
    # same probe xla_env uses rather than calling jax.process_count()
    # (which would itself initialise the backend).
    from ..xla_env import backend_initialized

    if backend_initialized() is True:
        raise RuntimeError(
            "initialize_multihost() must run before any JAX computation "
            "(the XLA backend is already initialised in this process)")
    jax.distributed.initialize(**kwargs)
    _initialized = True


def process_info() -> Dict[str, int]:
    """(process_id, process_count, local/global device counts) — the
    --trainer_id / --num_gradient_servers analogue."""
    return {
        "process_id": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }


def make_hybrid_mesh(dcn_axes: Dict[str, int],
                     ici_axes: Dict[str, int],
                     devices: Optional[Sequence] = None) -> Mesh:
    """Mesh spanning slices: ``dcn_axes`` (major) are laid out ACROSS
    slices (host/DCN boundaries), ``ici_axes`` (minor) within a slice.

    Example — 4 slices of 8 chips, data parallel across slices, tensor x
    sequence parallel within: ``make_hybrid_mesh({"dp": 4}, {"mp": 4,
    "sp": 2})``. Uses mesh_utils.create_hybrid_device_mesh on real
    multi-slice topologies; on a single host/slice (including the virtual
    CPU mesh) it degrades to the plain ICI-ordered mesh with the same axis
    names, so programs written against the hybrid mesh run anywhere.
    """
    from .mesh import make_mesh

    devices = list(devices if devices is not None else jax.devices())
    axes = dict(dcn_axes)
    axes.update(ici_axes)
    n_slices = 1
    try:  # devices expose slice_index on real multi-slice systems
        n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    except Exception:
        pass
    if n_slices > 1:
        from jax.experimental import mesh_utils

        # create_hybrid_device_mesh takes SAME-LENGTH per-axis shapes whose
        # elementwise product is the result shape: dcn axes get size 1 in
        # the ICI shape and vice versa, so the returned array is already
        # (dcn..., ici...)-ordered — no reshape (one would scramble which
        # axis crosses slices).
        nd, ni = len(dcn_axes), len(ici_axes)
        ici_shape = (1,) * nd + tuple(ici_axes.values())
        dcn_shape = tuple(dcn_axes.values()) + (1,) * ni
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
        return Mesh(dev_array, tuple(axes.keys()))
    return make_mesh(axes, devices=devices)


def local_batch_slice(global_batch: int) -> slice:
    """Each process feeds its shard of the global batch (the analogue of
    the reference's per-trainer data sharding): rows
    [process_id * per_host, (process_id + 1) * per_host). The global
    batch must divide evenly — silently dropping remainder rows would
    corrupt loss averaging."""
    n = max(jax.process_count(), 1)
    if global_batch % n:
        raise ValueError(
            f"global batch {global_batch} is not divisible by the "
            f"{n} processes; pad or resize the batch")
    per_host = global_batch // n
    start = jax.process_index() * per_host
    return slice(start, start + per_host)

"""jax-version compatibility for the manual-SPMD escape hatches.

The ring-attention / GPipe / vocab-parallel islands are written against
the modern surface (``jax.shard_map`` + the varying-manual-axes type
system's ``jax.lax.pcast``). Older jaxlibs (the 0.4.x line this tree
pins while the TPU tunnel is down) ship shard_map under
``jax.experimental.shard_map`` and have no vma typing at all — there the
pcast calls are identity and the per-eqn replication checker predates
the loop shapes these kernels use, so it is disabled. One import site
(`from ..parallel.compat import shard_map, pvary`) keeps every island
running on both lines instead of five copies of the same try/except.
"""
from __future__ import annotations

import jax

try:  # modern surface: vma typing, check_vma semantics
    from jax import shard_map as _shard_map

    _MODERN = True
except ImportError:  # 0.4.x: experimental namespace, rep checker off
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    _MODERN = False

    def _shard_map(f=None, /, *, mesh, in_specs, out_specs, **kw):
        kw.pop("check_vma", None)
        kw.setdefault("check_rep", False)
        if f is None:  # pragma: no cover - decorator-without-fn form
            return lambda g: _exp_shard_map(g, mesh=mesh, in_specs=in_specs,
                                            out_specs=out_specs, **kw)
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)


shard_map = _shard_map


def pvary(x, axes):
    """Type a shard_map carry as device-varying over ``axes`` where the
    vma type system exists; identity on jaxlibs that predate it."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, tuple(axes), to="varying")

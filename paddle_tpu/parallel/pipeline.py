"""Pipeline parallelism: GPipe microbatch rotation over a mesh axis.

The TPU-native pipeline (beyond-reference tier, like ring attention — the
reference's closest machinery is the multi-machine ParallelNeuralNetwork
config split, /root/reference/paddle/gserver/gradientmachines/
ParallelNeuralNetwork.cpp, which places layers on devices and moves
activations by explicit memcpy). Here the schedule is one ``shard_map``-ped
function: the layer stack's parameters carry a leading stage axis sharded
over ``pp``, every device runs its local stage slice, and activations hop
stage-to-stage with ``jax.lax.ppermute`` (ICI neighbour exchange). The
M-microbatch loop runs M + S - 1 steps (the classic GPipe bubble); reverse
AD through the scan gives the backward pipeline for free, and XLA overlaps
each hop with the next microbatch's compute.

Works composed with data parallelism: the microbatch dim can shard over
``dp`` while stages shard over ``pp`` on the same mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .compat import pvary, shard_map


def gpipe(stage_fn, stage_params, x, mesh, axis="pp", n_microbatches=None,
          data_axis=None, remat=False):
    """Run a pipelined layer stack over the ``axis`` dim of ``mesh``.

    stage_fn: (local_params, activation [mb, ...]) -> activation; applied by
        every pipeline rank to its resident stage slice.
    stage_params: pytree whose leaves lead with the stage-stackable axis
        (size divisible by mesh.shape[axis]); each rank sees the local
        [leading/S, ...] slice — typically layers-per-stage to scan over.
    x: [B, ...] batch; split into ``n_microbatches`` (default S) microbatches.
    data_axis: optional mesh axis the microbatch dim additionally shards on
        (dp x pp composition).
    remat: checkpoint each stage application — the backward pipeline then
        recomputes a stage's activations from its input instead of keeping
        every (step, stage) intermediate live, cutting peak activation
        memory from O(M·layers) to O(M) per stage at ~1/3 extra FLOPs.

    Returns [B, ...] outputs, replicated over ``axis`` (the last stage's
    results are broadcast with one masked psum).
    """
    S = mesh.shape[axis]
    M = n_microbatches or S
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    xm = x.reshape((M, B // M) + x.shape[1:])

    xspec = P(None, data_axis, *([None] * (x.ndim - 1)))
    pspec = jax.tree.map(lambda _: P(axis), stage_params)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(pspec, xspec), out_specs=xspec)
    def run(params, xl):
        r = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]
        state = jnp.zeros_like(xl[0])
        outbuf = jnp.zeros_like(xl)
        # device-varying carries so the loop types stay fixed once
        # ppermuted activations mix in (shard_map vma typing)
        state, outbuf = (pvary(a, (axis,)) for a in (state, outbuf))

        def step(t, carry):
            state, outbuf = carry
            # stage 0 injects microbatch t (zeros once the feed is drained,
            # keeping the bubble lanes finite for the backward pass)
            inj = jax.lax.dynamic_index_in_dim(
                xl, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inj = jnp.where(t < M, inj, jnp.zeros_like(inj))
            state = jnp.where(r == 0, inj, state)
            y = stage_fn(params, state)
            # the last stage finished microbatch t - (S - 1)
            m_idx = t - (S - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                outbuf, y, jnp.clip(m_idx, 0, M - 1), 0)
            outbuf = jnp.where((r == S - 1) & (m_idx >= 0), upd, outbuf)
            state = jax.lax.ppermute(y, axis, perm)
            return state, outbuf

        state, outbuf = jax.lax.fori_loop(0, M + S - 1, step,
                                          (state, outbuf))
        # broadcast the last stage's outputs to every pipeline rank
        return jax.lax.psum(jnp.where(r == S - 1, outbuf, 0.0), axis)

    ym = run(stage_params, xm)
    return ym.reshape((B,) + ym.shape[2:])

"""Current-mesh context: lets op kernels opt into mesh-aware lowering.

Op kernels are pure functions; they cannot take a Mesh argument through the
Program IR. The executor publishes its mesh here while tracing/compiling a
block, so ops with a distributed formulation (sequence-parallel attention,
expert-parallel MoE) can pick it up — the analogue of the reference's
global DeviceContextPool (/root/reference/paddle/platform/
device_context.h:161) giving kernels their device handles.
"""
from __future__ import annotations

import contextlib
from typing import Optional

_CURRENT_MESH = None


@contextlib.contextmanager
def mesh_context(mesh):
    global _CURRENT_MESH
    prev = _CURRENT_MESH
    _CURRENT_MESH = mesh
    try:
        yield
    finally:
        _CURRENT_MESH = prev


def current_mesh():
    return _CURRENT_MESH


def mesh_axis(name: str) -> int:
    """Size of axis ``name`` on the current mesh (1 if absent/no mesh)."""
    m = _CURRENT_MESH
    if m is None or name not in m.axis_names:
        return 1
    return m.shape[name]

"""Vocabulary-parallel fused head + cross-entropy (Megatron-style).

The tensor-parallel sibling of ops/loss_ops.fused_head_cross_entropy:
the head weight [d, vocab] shards its vocab dim over the model axis,
every device runs the chunked online-logsumexp over ITS shard only, and
three tiny per-row collectives (pmax + two psums over [tokens]-sized
vectors) combine the shard statistics — the [tokens, vocab] logits never
materialize on any device AND no device ever holds the whole head.
Backward psums the partial dX over the vocab axis and the shard-local
dW over the data axis. Both directions reuse the serial op's per-chunk
bodies (_fhce_lse_chunk/_fhce_grad_chunk), so the two paths cannot
drift numerically.

The reference's closest analogue is the pserver owning sharded softmax
parameters (/root/reference/paddle/pserver/ParameterServer2.h:94-100);
here the collectives ride ICI in-graph via shard_map.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .compat import pvary, shard_map


def _axes(mesh, data_axis, vp_axis):
    """(x2 spec, w spec, per-row spec, fori-carry varying axes)."""
    d = data_axis if data_axis in mesh.axis_names else None
    varying = (vp_axis,) + ((d,) if d else ())
    return P(d, None), P(None, vp_axis), P(d), varying


def _shard_local_labels(labl, base, vl):
    """Global labels -> shard-local ids; labels owned by OTHER shards map
    to -1 (never gathered). A bare ``labl - base`` would let a foreign
    label land in the zero-padded tail chunk window [vl, n_chunks*chunk)
    and gather a -inf masked logit, poisoning the psummed loss."""
    return jnp.where((labl >= base) & (labl < base + vl), labl - base, -1)


def vp_fused_head_lse(x2, w, lab, chunk, mesh, vp_axis, data_axis):
    """(global lse [n], global label-logit [n], global row logit-sum [n])
    over a vocab-sharded w."""
    from ..ops.loss_ops import _fhce_chunks, _fhce_lse_chunk, _fhce_w3

    nshard = mesh.shape[vp_axis]
    vocab = w.shape[1]
    if vocab % nshard:
        raise ValueError(
            f"vocab_parallel fused head needs vocab ({vocab}) divisible "
            f"by the {vp_axis!r} axis size ({nshard})")
    vl = vocab // nshard
    chunk_l, n_chunks_l = _fhce_chunks(vl, chunk)
    xs, ws, vs, varying = _axes(mesh, data_axis, vp_axis)

    @functools.partial(shard_map, mesh=mesh, in_specs=(xs, ws, vs),
                       out_specs=(vs, vs, vs))
    def run(x2l, wl, labl):
        base = jax.lax.axis_index(vp_axis) * vl
        lab_l = _shard_local_labels(labl, base, vl)
        w3 = _fhce_w3(wl, chunk_l, n_chunks_l, vl)
        n = x2l.shape[0]
        # carries become device-varying once shard data mixes in
        # (shard_map vma typing) — pcast them up front
        zeros = jnp.zeros((n,), jnp.float32)
        carry = tuple(
            pvary(a, varying)
            for a in (jnp.full((n,), -jnp.inf, jnp.float32),
                      zeros, zeros, zeros))
        m, s, ll, rs = jax.lax.fori_loop(
            0, n_chunks_l,
            lambda i, c: _fhce_lse_chunk(x2l, w3, i, chunk_l, vl,
                                         lab_l, c),
            carry)
        lse_l = m + jnp.log(s)
        m_g = jax.lax.pmax(lse_l, vp_axis)
        lse_g = m_g + jnp.log(jax.lax.psum(jnp.exp(lse_l - m_g), vp_axis))
        ll_g = jax.lax.psum(ll, vp_axis)
        rs_g = jax.lax.psum(rs, vp_axis)
        return lse_g, ll_g, rs_g

    return run(x2, w, lab)


def vp_fused_head_grad(x2, w, lab, dl, lse, chunk, mesh, vp_axis,
                       data_axis, smoothing=0.0):
    """(dX [n, d] psummed over vocab shards, dW [d, vocab] shard-local,
    psummed over the data axis)."""
    from ..ops.loss_ops import _fhce_chunks, _fhce_grad_chunk, _fhce_w3

    nshard = mesh.shape[vp_axis]
    vocab = w.shape[1]
    vl = vocab // nshard
    chunk_l, n_chunks_l = _fhce_chunks(vl, chunk)
    xs, ws, vs, varying = _axes(mesh, data_axis, vp_axis)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(xs, ws, vs, vs, vs),
                       out_specs=(xs, ws))
    def run(x2l, wl, labl, dll, lseg):
        base = jax.lax.axis_index(vp_axis) * vl
        lab_l = _shard_local_labels(labl, base, vl)
        w3 = _fhce_w3(wl, chunk_l, n_chunks_l, vl)
        lse2 = lseg[:, None]
        dl2 = dll[:, None]
        d = x2l.shape[1]
        n = x2l.shape[0]

        def body(i, carry):
            dx_acc, dw_acc = carry
            dx_c, dw_c = _fhce_grad_chunk(x2l, w3, i, chunk_l, vl,
                                          lab_l, lse2, dl2,
                                          smoothing=smoothing,
                                          full_vocab=vocab)
            return (dx_acc + dx_c,
                    jax.lax.dynamic_update_index_in_dim(dw_acc, dw_c, i,
                                                        axis=1))

        carry = tuple(
            pvary(a, varying)
            for a in (jnp.zeros((n, d), jnp.float32),
                      jnp.zeros((d, n_chunks_l, chunk_l), jnp.float32)))
        dx, dw = jax.lax.fori_loop(0, n_chunks_l, body, carry)
        # dX sums each row's contributions across vocab shards; dW sums
        # each shard's rows across the DATA axis (every dp group saw only
        # its slice of the batch)
        dx = jax.lax.psum(dx, vp_axis)
        if data_axis in mesh.axis_names:
            dw = jax.lax.psum(dw, data_axis)
        dw = dw.reshape(d, n_chunks_l * chunk_l)[:, :vl]
        return dx, dw

    return run(x2, w, lab, dl, lse)

"""Device-mesh construction helpers.

Replaces the reference's device-topology knobs (--trainer_count,
--num_gradient_servers, --ports_num; /root/reference/paddle/utils/Flags.h:19-44)
with a single declarative object: a jax.sharding.Mesh whose named axes are the
parallelism dimensions (dp = data, mp = tensor/model, pp = pipeline,
sp = sequence, ep = expert). Collectives ride ICI within a slice and DCN
across slices; XLA picks the routing from the mesh's device order.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a named device mesh.

    ``axes`` maps axis name -> size, in major-to-minor order; a size of -1
    means "all remaining devices". Defaults to a pure data-parallel mesh over
    every visible device.

    For multi-dim TPU topologies prefer jax.experimental.mesh_utils ordering;
    on a single host (or the virtual CPU mesh used in tests) a plain reshape
    of jax.devices() is correct.
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": len(devices)}
    axes = dict(axes)
    known = 1
    wild = None
    for name, size in axes.items():
        if size == -1:
            if wild is not None:
                raise ValueError("only one mesh axis may be -1")
            wild = name
        else:
            known *= size
    if wild is not None:
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed axes {axes}")
        axes[wild] = len(devices) // known
        known *= axes[wild]
    if known != len(devices):
        raise ValueError(
            f"mesh axes {axes} require {known} devices, have {len(devices)}")
    if len(devices) > 1:
        try:
            from jax.experimental import mesh_utils
            dev_array = mesh_utils.create_device_mesh(
                tuple(axes.values()), devices=devices)
        except Exception:
            dev_array = np.array(devices).reshape(tuple(axes.values()))
    else:
        dev_array = np.array(devices).reshape(tuple(axes.values()))
    return Mesh(dev_array, tuple(axes.keys()))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    """Size of a named axis, 1 if the axis is absent."""
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)


def make_abstract_mesh(axes: Dict[str, int]):
    """A devices-free mesh skeleton (jax.sharding.AbstractMesh) for the
    analysis plane: ShardingPlans built over it resolve specs, divide
    per-device bytes, and price collectives without the process owning
    ``dp*mp*...`` real devices — how ``tools/proglint.py --mesh dp=4,mp=2``
    lints a sharded program on a 1-device box. Not executable: hand the
    executor a plan over a real :func:`make_mesh` mesh instead."""
    from jax.sharding import AbstractMesh

    pairs = tuple((str(k), int(v)) for k, v in axes.items())
    try:
        return AbstractMesh(pairs)
    except TypeError:  # newer signature: (axis_sizes, axis_names)
        return AbstractMesh(tuple(v for _, v in pairs),
                            tuple(k for k, _ in pairs))

"""Dtype and variable-type vocabulary for the program IR.

Mirrors the role of the reference's ``framework.proto`` dtype/var-type enums
(/root/reference/paddle/framework/framework.proto) but maps directly onto JAX
dtypes — the TPU-native compute substrate — instead of a C++ enum.
"""
from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class VarType(enum.Enum):
    """Variable kinds, analogous to VarDesc.VarType in the reference."""

    DENSE_TENSOR = "dense_tensor"  # reference: LOD_TENSOR (lod_tensor.h:84)
    SELECTED_ROWS = "selected_rows"  # sparse row-subset gradient (selected_rows.h)
    TENSOR_ARRAY = "tensor_array"  # LoDTensorArray for dynamic RNN
    STEP_SCOPES = "step_scopes"
    RAW = "raw"


_DTYPE_ALIASES = {
    "float32": jnp.float32,
    "fp32": jnp.float32,
    "float64": jnp.float64,
    "fp64": jnp.float64,
    "float16": jnp.float16,
    "fp16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "bool": jnp.bool_,
}


def to_dtype(dtype) -> np.dtype:
    """Normalise a user-supplied dtype spec to a numpy dtype object."""
    if isinstance(dtype, str):
        if dtype in _DTYPE_ALIASES:
            return np.dtype(_DTYPE_ALIASES[dtype])
        return np.dtype(dtype)
    return np.dtype(dtype)


def is_floating(dtype) -> bool:
    dt = to_dtype(dtype)
    return jnp.issubdtype(dt, jnp.floating)

"""Program IR: Program / Block / Operator / Variable.

The TPU-native analogue of the reference's ProgramDesc graph capture
(/root/reference/paddle/framework/framework.proto,
 /root/reference/python/paddle/v2/fluid/framework.py:105,322,591,747).

Unlike the reference — where the Python classes mirror C++ protobuf descs that
a per-op interpreter walks (/root/reference/paddle/framework/executor.cc:73) —
this IR is the *source* of truth and is lowered wholesale to a single XLA
computation by :mod:`paddle_tpu.core.executor`. Ops therefore carry no device
kernels of their own; each op type names a pure JAX function in the registry.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .types import VarType, to_dtype

# Sentinel used in build-time shape inference wherever the user wrote -1
# (unknown batch dim). Shapes are concretised at executor compile time from the
# actual feeds, so the sentinel only ever flows through jax.eval_shape.
BATCH_DIM_SENTINEL = 1297

# Name of the implicit PRNG-state variable threaded through compiled programs.
RNG_VAR = "@RNG_STATE@"

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


class Variable:
    """A named tensor slot in a Block.

    Mirrors fluid.framework.Variable (framework.py:105): build-time shape and
    dtype metadata only; values live in a Scope at run time. ``shape`` may use
    -1 for the batch dimension.
    """

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        persistable: bool = False,
        stop_gradient: bool = False,
        var_type: VarType = VarType.DENSE_TENSOR,
        lod_level: int = 0,
        is_data: bool = False,
        trainable: bool = True,
        initializer: Optional[dict] = None,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(d) for d in shape) if shape is not None else None
        self.dtype = to_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.type = var_type
        self.lod_level = lod_level
        self.is_data = is_data
        self.trainable = trainable
        self.initializer = initializer  # used by startup-program generation
        self.is_parameter = False

    # -- helpers -----------------------------------------------------------
    def concrete_shape(self, batch: int = BATCH_DIM_SENTINEL) -> Tuple[int, ...]:
        """Shape with -1 dims substituted (for abstract evaluation)."""
        return tuple(batch if d == -1 else d for d in self.shape)

    def __repr__(self):
        return (
            f"Variable(name={self.name!r}, shape={self.shape}, "
            f"dtype={self.dtype.name}, persistable={self.persistable})"
        )


class Parameter(Variable):
    """A trainable persistable variable (fluid framework.py:887)."""

    def __init__(self, block, name, **kw):
        kw.setdefault("persistable", True)
        super().__init__(block, name, **kw)
        self.is_parameter = True


class Operator:
    """One operation: type + named input/output slots + attrs.

    Matches the reference's OpDesc structure (framework.proto): inputs and
    outputs are ``slot -> [var names]`` multimaps (some ops, e.g. ``sum``,
    take a variable number of inputs in one slot).
    """

    def __init__(
        self,
        block: "Block",
        op_type: str,
        inputs: Dict[str, List[str]],
        outputs: Dict[str, List[str]],
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = op_type
        self.inputs = {k: list(v) for k, v in inputs.items()}
        self.outputs = {k: list(v) for k, v in outputs.items()}
        self.attrs = dict(attrs or {})
        if "_callsite" not in self.attrs:
            from ..flags import FLAGS

            if FLAGS.op_callsite:
                from .enforce import user_callsite

                site = user_callsite()
                if site:
                    self.attrs["_callsite"] = site

    def input_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    # -- single-name slot accessors (pattern matching sugar) ---------------
    def input(self, slot: str) -> Optional[str]:
        """The single var name in an input slot, or None when the slot is
        absent/empty. Raises if the slot holds more than one name (a
        pattern matcher that assumed single-arity would silently mismatch
        multi-input slots like ``sum``'s otherwise)."""
        names = self.inputs.get(slot) or []
        if len(names) > 1:
            raise ValueError(
                f"op {self.type!r} input slot {slot!r} has {len(names)} "
                f"names; use .inputs[{slot!r}] for multi-arity slots")
        return names[0] if names else None

    def output(self, slot: str) -> Optional[str]:
        """Single-name accessor for an output slot (see ``input``)."""
        names = self.outputs.get(slot) or []
        if len(names) > 1:
            raise ValueError(
                f"op {self.type!r} output slot {slot!r} has {len(names)} "
                f"names; use .outputs[{slot!r}] for multi-arity slots")
        return names[0] if names else None

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Op({self.type}, inputs={ins}, outputs={outs}, attrs={self.attrs})"


class Block:
    """An ordered list of ops plus a symbol table of variables.

    Mirrors fluid.framework.Block (framework.py:591). Sub-blocks (while/cond
    bodies) reference their parent for outer-scope variable lookup.
    """

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # -- variables ---------------------------------------------------------
    def create_var(self, name: Optional[str] = None, **kw) -> Variable:
        if name is None:
            name = self.program.unique_name("tmp")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kw)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name: Optional[str] = None, **kw) -> Parameter:
        if name is None:
            name = self.program.unique_name("param")
        p = Parameter(self, name, **kw)
        self.vars[name] = p
        self.program._bump()
        return p

    def var(self, name: str) -> Variable:
        """Look up ``name`` here or in any ancestor block."""
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        raise KeyError(f"Variable {name!r} not found in block {self.idx} or ancestors")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    # -- ops ---------------------------------------------------------------
    def append_op(self, op_type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, op_type, inputs or {}, outputs or {}, attrs)
        seg = self.program._recompute_seg
        if seg is not None:
            op.attrs.setdefault("__recompute_seg__", seg)
        self.ops.append(op)
        self.program._bump()
        return op

    def prepend_op(self, op_type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        return self.insert_op(0, op_type, inputs, outputs, attrs)

    def insert_op(self, index: int, op_type: str, inputs=None, outputs=None,
                  attrs=None) -> Operator:
        op = Operator(self, op_type, inputs or {}, outputs or {}, attrs)
        self.ops.insert(index, op)
        self.program._bump()
        return op

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- pattern-match / rewrite helpers (transpiler plane) ----------------
    def var_producers(self) -> Dict[str, List[Tuple[int, "Operator"]]]:
        """name -> [(op index, op)] of every op writing that name, in
        program order. Aliased state (batch_norm's MeanOut writing onto
        Mean) shows up as multiple producers — matchers must handle it."""
        prod: Dict[str, List[Tuple[int, Operator]]] = {}
        for i, op in enumerate(self.ops):
            for name in op.output_names():
                prod.setdefault(name, []).append((i, op))
        return prod

    def var_consumers(self) -> Dict[str, List[Tuple[int, "Operator"]]]:
        """name -> [(op index, op)] of every op reading that name."""
        cons: Dict[str, List[Tuple[int, Operator]]] = {}
        for i, op in enumerate(self.ops):
            seen = set()
            for name in op.input_names():
                if name in seen:
                    continue
                seen.add(name)
                cons.setdefault(name, []).append((i, op))
        return cons

    def sole_producer(self, name: str,
                      producers=None) -> Optional["Operator"]:
        """The op producing ``name`` iff exactly one op writes it."""
        ps = (producers if producers is not None
              else self.var_producers()).get(name, [])
        return ps[0][1] if len(ps) == 1 else None

    def replace_ops(self, old_ops: Sequence["Operator"], op_type: str,
                    inputs=None, outputs=None, attrs=None) -> "Operator":
        """Replace a matched op chain with ONE new op, inserted at the
        position of the last replaced op so every input is still produced
        upstream and every consumer still reads downstream. The core
        rewrite primitive for fusion passes."""
        idxs = []
        for op in old_ops:
            for i, o in enumerate(self.ops):
                if o is op:
                    idxs.append(i)
                    break
            else:
                raise ValueError(f"op {op.type!r} not in block {self.idx}")
        at = max(idxs)
        new = Operator(self, op_type, inputs or {}, outputs or {}, attrs)
        self.ops[at] = new
        drop = set(idxs) - {at}
        self.ops = [o for i, o in enumerate(self.ops) if i not in drop]
        self.program._bump()
        return new

    def remove_ops(self, old_ops: Sequence["Operator"]) -> None:
        olds = {id(op) for op in old_ops}
        self.ops = [o for o in self.ops if id(o) not in olds]
        self.program._bump()

    def drop_unused_vars(self, keep: Sequence[str] = ()) -> List[str]:
        """Drop vars referenced by no op (transpile cleanup). ``keep``
        names (feeds/fetches) survive regardless. Returns dropped names."""
        used = set(keep)
        for op in self.ops:
            used.update(op.input_names())
            used.update(op.output_names())
        dropped = [n for n in self.vars if n not in used]
        for n in dropped:
            del self.vars[n]
        if dropped:
            self.program._bump()
        return dropped


class Program:
    """A list of blocks; block 0 is the global block (framework.py:747)."""

    _uid_counter = 0

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self._version = 0  # bumped on every mutation; part of the compile key
        self.random_seed: Optional[int] = None
        self._recompute_seg: Optional[int] = None  # active recompute_guard id

    # -- identity for executor caching ------------------------------------
    def _bump(self):
        self._version += 1

    @property
    def version(self) -> int:
        return self._version

    def unique_name(self, prefix: str) -> str:
        Program._uid_counter += 1
        return f"{prefix}_{Program._uid_counter}"

    # -- blocks ------------------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._bump()
        return b

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # -- whole-program transforms ------------------------------------------
    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self) -> List[Parameter]:
        return [v for b in self.blocks for v in b.vars.values() if isinstance(v, Parameter)]

    def clone(self, for_test: bool = False) -> "Program":
        """Deep-ish copy (vars and ops re-created; attrs shallow-copied).

        ``for_test=True`` flips every op's ``is_test`` attr to True (the
        reference's Program.clone(for_test=True) / inference_optimize):
        dropout becomes deterministic scaling and batch_norm reads its
        running stats instead of batch stats.
        """
        p = Program()
        p.random_seed = self.random_seed
        if getattr(self, "sharding_plan", None) is not None:
            # the ShardProgram plan rides along with its annotations
            p.sharding_plan = self.sharding_plan
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            for name, v in b.vars.items():
                cls = Parameter if isinstance(v, Parameter) else Variable
                nv = cls.__new__(cls)
                nv.__dict__.update(v.__dict__)
                nv.block = nb
                nb.vars[name] = nv
            for op in b.ops:
                attrs = dict(op.attrs)
                if for_test and "is_test" in attrs:
                    attrs["is_test"] = True
                nb.ops.append(Operator(nb, op.type, op.inputs, op.outputs,
                                       attrs))
            p.blocks.append(nb)
        p.current_block_idx = self.current_block_idx
        return p

    def __str__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"-- block {b.idx} (parent {b.parent_idx}) --")
            for v in b.vars.values():
                flag = "P" if v.persistable else " "
                lines.append(f"  var[{flag}] {v.name}: {v.shape} {v.dtype.name}")
            for op in b.ops:
                lines.append(f"  {op}")
        return "\n".join(lines)


# --- default program management (fluid framework.py program guards) --------
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(p: Program) -> Program:
    global _main_program
    old, _main_program = _main_program, p
    return old


def switch_startup_program(p: Program) -> Program:
    global _startup_program
    old, _startup_program = _startup_program, p
    return old


_recompute_seg_counter = 0


@contextlib.contextmanager
def recompute_guard(main_program: Optional[Program] = None):
    """Mark the ops built inside this scope as one rematerialization segment.

    The TPU-native activation-checkpointing plane (the capability the
    reference later grew as RecomputeOptimizer): ops tagged with the same
    segment id are differentiated as ONE composite ``grad_seg`` op whose vjp
    runs under ``jax.checkpoint`` with a save-only-named-residuals policy —
    matmul/conv outputs (and tiny stats) are kept, every elementwise
    intermediate (BN apply, activations, residual adds) is recomputed in the
    backward where XLA fuses it into the consuming kernels. This cuts the
    HBM activation traffic between forward and backward roughly in half for
    conv-BN-act stacks, which is what makes ResNet-class models exceed their
    naive HBM roofline (PERF.md). Nested guards are not supported; segments
    must not contain rng/special/custom-grad ops (backward falls back to
    per-op gradients for those automatically).
    """
    p = main_program or default_main_program()
    global _recompute_seg_counter
    _recompute_seg_counter += 1
    old = p._recompute_seg
    p._recompute_seg = _recompute_seg_counter
    try:
        yield
    finally:
        p._recompute_seg = old


def maybe_recompute(enabled: bool, main_program: Optional[Program] = None):
    """``recompute_guard`` when enabled, else a no-op context — the one
    helper model builders share so the guard always lands on the program
    the ops are actually appended to."""
    if enabled:
        return recompute_guard(main_program)
    return contextlib.nullcontext()


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Route layer construction into the given programs (fluid parity API)."""
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)

"""SelectedRows: row-sparse gradient values for large embeddings.

TPU-native redesign of the reference's SelectedRows
(/root/reference/paddle/framework/selected_rows.h) and the row-sparse
parameter machinery (/root/reference/paddle/math/SparseRowMatrix.h). The
reference's lookup_table emits its gradient as SelectedRows
(/root/reference/paddle/operators/lookup_table_op.cc:59) so the optimizer /
pserver applies a row-granular update instead of a dense [V, D] one.

Here SelectedRows is a registered pytree that flows through the executor's
single-XLA-computation trace like any array: ``rows`` ([n] int32 row ids,
possibly with duplicates and with the sentinel ``height`` marking padding)
plus ``values`` ([n, D]). All shapes are static — n is the number of looked-
up ids in the batch — so nothing here fights the compiler. Optimizer ops
consume it with gather + scatter (mode='drop' ignores sentinel rows), which
XLA lowers to dynamic-slice/dynamic-update-slice traffic proportional to
n*D, never to a [V, D] buffer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    """A row-sparse value: ``dense[rows[i]] += values[i]`` semantics.

    ``height`` (static) is the dense leading-dim size; a row id equal to
    ``height`` is padding and must be ignored by consumers (scatter
    mode='drop' does this for free).
    """

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        return cls(children[0], children[1], height)

    # -- array-ish surface -------------------------------------------------
    @property
    def dtype(self):
        return self.values.dtype

    @property
    def dense_shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def shape(self):
        """Dense-view shape: generic elementwise kernels (the gradient-
        accumulation ``acc += grad`` add) treat a SelectedRows like the
        dense tensor it represents; the arithmetic then densifies
        through ``__radd__``."""
        return self.dense_shape

    @property
    def ndim(self):
        return len(self.dense_shape)

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype), self.height)

    def scale(self, s):
        return SelectedRows(self.rows, self.values * s, self.height)

    def to_dense(self):
        """Materialize the dense [height, D] tensor (scatter-add).

        Only for small vocabularies / test comparison / explicit user
        densification — the optimizer paths never call this.
        """
        base = jnp.zeros(self.dense_shape, self.values.dtype)
        return base.at[self.rows].add(self.values, mode="drop")

    def merged(self) -> "SelectedRows":
        """Deduplicate rows: sort ids and segment-sum duplicate rows'
        values (the reference's merge_dups before sparse optimizer updates).
        Output keeps the static length n; slots past the unique count carry
        the ``height`` sentinel and zero values.
        """
        n = self.rows.shape[0]
        if n <= 1:
            return self
        order = jnp.argsort(self.rows)
        rows = jnp.take(self.rows, order)
        vals = jnp.take(self.values, order, axis=0)
        is_new = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             (rows[1:] != rows[:-1]).astype(jnp.int32)])
        seg = jnp.cumsum(is_new)
        merged_vals = jax.ops.segment_sum(vals, seg, num_segments=n)
        merged_rows = jnp.full((n,), self.height, dtype=rows.dtype)
        merged_rows = merged_rows.at[seg].set(rows)
        return SelectedRows(merged_rows, merged_vals, self.height)

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError(
                    f"SelectedRows height mismatch: {self.height} vs "
                    f"{other.height}")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values], axis=0),
                self.height)
        # dense + sparse: densify (fan-out through a dense consumer)
        return self.to_dense() + other

    __radd__ = __add__

    def __mul__(self, s):
        return self.scale(s)

    __rmul__ = __mul__

    def __repr__(self):
        return (f"SelectedRows(n={self.rows.shape[0]}, "
                f"dense_shape={self.dense_shape}, dtype={self.dtype})")


def is_selected_rows(x) -> bool:
    return isinstance(x, SelectedRows)


def densify(x):
    """Dense view of either a SelectedRows or a dense array."""
    return x.to_dense() if isinstance(x, SelectedRows) else x

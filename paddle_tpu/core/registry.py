"""Op registry: op type name -> pure JAX kernel + metadata.

The TPU-native replacement for the reference's OpRegistry / kernel-registry
pair (/root/reference/paddle/framework/op_registry.h:148,
/root/reference/paddle/framework/operator.cc:463-556). There is no per-device
kernel selection: every op is a pure JAX function; XLA picks the TPU lowering
and fuses across op boundaries because the executor compiles whole blocks.

Shape inference (the reference's InferShape, shape_inference.h) is derived
from the kernel itself via ``jax.eval_shape`` — one source of truth.

Gradients: ops normally do NOT register hand-written grad kernels. The
backward pass (core/backward.py) emits generic ``grad`` ops whose kernel
computes ``jax.vjp`` of the registered forward. Recomputed forward
subexpressions are CSE'd by XLA inside the single fused computation, so this
costs nothing relative to hand-written grad ops. Ops may still register a
custom ``grad_fn`` when vjp-of-forward is wrong or wasteful (e.g. ops with
integer inputs that need SelectedRows-style sparse grads).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

Arrays = Dict[str, List[jax.Array]]  # slot -> list of arrays


@dataclasses.dataclass
class OpDef:
    type: str
    fn: Callable  # fn(attrs, ins: Arrays, [rng]) -> Arrays
    # True, False, or a predicate over the op's attrs (for ops that only
    # sometimes draw randomness, e.g. sampling vs greedy decode). When not
    # strictly False the kernel fn must accept an ``rng`` kwarg (None when
    # the predicate says this instance draws nothing).
    needs_rng: object = False
    # Custom vjp: grad_fn(attrs, ins, outs, out_grads) -> dict varslot->grads
    grad_fn: Optional[Callable] = None
    # True when grad_fn is a pure HBM/FLOP optimization and vjp-of-forward
    # is STILL mathematically valid (batch_norm/layer_norm). Such ops stay
    # eligible for recompute segments, whose composite jax.vjp ignores
    # grad_fn; ops whose grad_fn exists for correctness (rng, sparse
    # grads) must keep this False so segments never swallow them.
    grad_fn_is_optimization: bool = False
    # Ops whose semantics are stateful/structural and are handled specially by
    # the executor trace (feed/fetch/control-flow) rather than called as fns.
    special: bool = False
    # Input slots that may legally be absent/empty (e.g. optional Bias).
    optional_inputs: tuple = ()
    # If set, only these input slots get gradients even if others are float.
    stop_gradient_inputs: tuple = ()


_REGISTRY: Dict[str, OpDef] = {}


def register_op(
    type: str,
    fn: Callable = None,
    *,
    needs_rng: bool = False,
    grad_fn: Callable = None,
    grad_fn_is_optimization: bool = False,
    special: bool = False,
    optional_inputs=(),
    stop_gradient_inputs=(),
):
    """Register an op kernel. Usable as decorator or direct call."""

    def _do(f):
        if type in _REGISTRY:
            raise ValueError(f"op {type!r} already registered")
        _REGISTRY[type] = OpDef(
            type=type,
            fn=f,
            needs_rng=needs_rng,
            grad_fn=grad_fn,
            grad_fn_is_optimization=grad_fn_is_optimization,
            special=special,
            optional_inputs=tuple(optional_inputs),
            stop_gradient_inputs=tuple(stop_gradient_inputs),
        )
        return f

    if fn is None:
        return _do
    return _do(fn)


def get_op(type: str) -> OpDef:
    if type not in _REGISTRY:
        raise KeyError(f"op {type!r} is not registered (known: {sorted(_REGISTRY)})")
    return _REGISTRY[type]


def op_uses_rng(opdef: OpDef, attrs) -> bool:
    """Does THIS op instance consume randomness? Attr-dependent ops
    declare needs_rng as a predicate; plain ops as a bool."""
    nr = opdef.needs_rng
    return bool(nr(attrs or {})) if callable(nr) else bool(nr)


def has_op(type: str) -> bool:
    return type in _REGISTRY


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


def infer_outputs(op_type: str, attrs, in_shapes: Arrays) -> Dict[str, List[jax.ShapeDtypeStruct]]:
    """Abstractly evaluate an op to get output shapes/dtypes.

    ``in_shapes`` maps slot -> list of ShapeDtypeStruct. Replaces the
    reference's per-op InferShape implementations.
    """
    opdef = get_op(op_type)
    if op_uses_rng(opdef, attrs):
        def f(ins, rng):
            return opdef.fn(attrs, ins, rng=rng)

        return jax.eval_shape(f, in_shapes,
                              jax.ShapeDtypeStruct((2,), jnp.uint32))
    if callable(opdef.needs_rng):
        return jax.eval_shape(lambda ins: opdef.fn(attrs, ins, rng=None),
                              in_shapes)
    return jax.eval_shape(lambda ins: opdef.fn(attrs, ins), in_shapes)

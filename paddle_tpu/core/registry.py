"""Op registry: op type name -> pure JAX kernel + metadata.

The TPU-native replacement for the reference's OpRegistry / kernel-registry
pair (/root/reference/paddle/framework/op_registry.h:148,
/root/reference/paddle/framework/operator.cc:463-556). There is no per-device
kernel selection: every op is a pure JAX function; XLA picks the TPU lowering
and fuses across op boundaries because the executor compiles whole blocks.

Shape inference (the reference's InferShape, shape_inference.h) is derived
from the kernel itself via ``jax.eval_shape`` — one source of truth.

Gradients: ops normally do NOT register hand-written grad kernels. The
backward pass (core/backward.py) emits generic ``grad`` ops whose kernel
computes ``jax.vjp`` of the registered forward. Recomputed forward
subexpressions are CSE'd by XLA inside the single fused computation, so this
costs nothing relative to hand-written grad ops. Ops may still register a
custom ``grad_fn`` when vjp-of-forward is wrong or wasteful (e.g. ops with
integer inputs that need SelectedRows-style sparse grads).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

Arrays = Dict[str, List[jax.Array]]  # slot -> list of arrays


@dataclasses.dataclass
class OpDef:
    type: str
    fn: Callable  # fn(attrs, ins: Arrays, [rng]) -> Arrays
    # True, False, or a predicate over the op's attrs (for ops that only
    # sometimes draw randomness, e.g. sampling vs greedy decode). When not
    # strictly False the kernel fn must accept an ``rng`` kwarg (None when
    # the predicate says this instance draws nothing).
    needs_rng: object = False
    # Custom vjp: grad_fn(attrs, ins, outs, out_grads) -> dict varslot->grads
    grad_fn: Optional[Callable] = None
    # True when grad_fn is a pure HBM/FLOP optimization and vjp-of-forward
    # is STILL mathematically valid (batch_norm/layer_norm). Such ops stay
    # eligible for recompute segments, whose composite jax.vjp ignores
    # grad_fn; ops whose grad_fn exists for correctness (rng, sparse
    # grads) must keep this False so segments never swallow them.
    grad_fn_is_optimization: bool = False
    # Ops whose semantics are stateful/structural and are handled specially by
    # the executor trace (feed/fetch/control-flow) rather than called as fns.
    special: bool = False
    # Input slots that may legally be absent/empty (e.g. optional Bias).
    optional_inputs: tuple = ()
    # If set, only these input slots get gradients even if others are float.
    stop_gradient_inputs: tuple = ()
    # Analytical cost handler fn(attrs, ins, outs) -> analysis.costmodel
    # OpCost, attached post-registration by paddle_tpu.analysis.costmodel
    # (register_cost) — the FLOP/HBM-byte twin of infer_outputs. Ops whose
    # cost is structurally meaningless (feed/fetch/unbounded decode loops)
    # set cost_exempt instead; the registry conformance audit requires one
    # of the two for every op.
    cost_fn: Optional[Callable] = None
    cost_exempt: bool = False


_REGISTRY: Dict[str, OpDef] = {}


def register_op(
    type: str,
    fn: Callable = None,
    *,
    needs_rng: bool = False,
    grad_fn: Callable = None,
    grad_fn_is_optimization: bool = False,
    special: bool = False,
    optional_inputs=(),
    stop_gradient_inputs=(),
):
    """Register an op kernel. Usable as decorator or direct call."""

    def _do(f):
        if type in _REGISTRY:
            raise ValueError(f"op {type!r} already registered")
        _REGISTRY[type] = OpDef(
            type=type,
            fn=f,
            needs_rng=needs_rng,
            grad_fn=grad_fn,
            grad_fn_is_optimization=grad_fn_is_optimization,
            special=special,
            optional_inputs=tuple(optional_inputs),
            stop_gradient_inputs=tuple(stop_gradient_inputs),
        )
        return f

    if fn is None:
        return _do
    return _do(fn)


def get_op(type: str) -> OpDef:
    opdef = _REGISTRY.get(type)
    if opdef is None:
        import difflib

        known = sorted(_REGISTRY)
        close = difflib.get_close_matches(type, known, n=3, cutoff=0.6)
        hint = ("; did you mean " + " / ".join(repr(c) for c in close) + "?"
                if close else "")
        sample = ", ".join(known[:8])
        raise KeyError(
            f"op {type!r} is not registered{hint} "
            f"({len(known)} ops registered, e.g. {sample}, ...; "
            f"see registry.registered_ops() for the full list)")
    return opdef


def op_uses_rng(opdef: OpDef, attrs) -> bool:
    """Does THIS op instance consume randomness? Attr-dependent ops
    declare needs_rng as a predicate; plain ops as a bool."""
    nr = opdef.needs_rng
    return bool(nr(attrs or {})) if callable(nr) else bool(nr)


def has_op(type: str) -> bool:
    return type in _REGISTRY


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# infer_outputs memoization
#
# Whole-program analysis (paddle_tpu.analysis) and repeated layer_helper
# build-time calls evaluate identical (op_type, attrs, input-signature)
# triples over and over — a ResNet block stamps the same conv/BN/relu
# signatures dozens of times, and the pass-sandwich verifier re-checks a
# mostly-unchanged program after every pass. jax.eval_shape is pure in
# those inputs (plus the process-global AMP policy, which changes kernel
# compute dtypes), so the result is cached. Hit/miss counters land in the
# profiler StatSet as registry/infer_cache/{hit,miss}.
# ---------------------------------------------------------------------------
_INFER_CACHE: Dict[tuple, object] = {}
_INFER_CACHE_MAX = 8192
_INFER_HITS = 0
_INFER_MISSES = 0


class _Unfreezable(Exception):
    """Attr value with no stable hashable form; skip memoization."""


def _freeze(x):
    """Stable hashable digest of an attr value. Keys starting with '_'
    (``_callsite``, ``__fused_from__`` provenance, recompute-segment
    tags) are metadata no kernel reads — excluding them is what lets two
    ops built at different source lines share a cache entry."""
    if isinstance(x, dict):
        return tuple(sorted(
            (k, _freeze(v)) for k, v in x.items()
            if not (isinstance(k, str) and k.startswith("_"))))
    if isinstance(x, (list, tuple)):
        return tuple(_freeze(v) for v in x)
    if isinstance(x, (set, frozenset)):
        return ("<set>",) + tuple(sorted(repr(_freeze(v)) for v in x))
    if isinstance(x, np.ndarray):
        return ("<ndarray>", x.shape, str(x.dtype), hash(x.tobytes()))
    if isinstance(x, (str, int, float, bool, bytes, type(None))):
        return x
    raise _Unfreezable(repr(type(x)))


def _signature_key(op_type: str, attrs, in_shapes) -> Optional[tuple]:
    """Cache key, or None when any part has no stable digest."""
    try:
        frozen_attrs = _freeze(attrs or {})
    except _Unfreezable:
        return None
    leaves, treedef = jax.tree_util.tree_flatten(in_shapes)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return None
        sig.append((tuple(shape), str(dtype)))
    from ..ops import common as ops_common

    return (op_type, frozen_attrs, tuple(sig), treedef,
            ops_common.amp_enabled())


def _copy_inferred(result):
    """Callers consume the result as {slot: [ShapeDtypeStruct]}; hand each
    one its own containers so a mutating caller can't poison the cache."""
    if isinstance(result, dict):
        return {k: (list(v) if isinstance(v, (list, tuple)) else v)
                for k, v in result.items()}
    return result


def infer_cache_stats() -> Dict[str, int]:
    """{'hits', 'misses', 'entries'} of the infer_outputs memo table."""
    return {"hits": _INFER_HITS, "misses": _INFER_MISSES,
            "entries": len(_INFER_CACHE)}


def clear_infer_cache() -> None:
    global _INFER_HITS, _INFER_MISSES
    _INFER_CACHE.clear()
    _INFER_HITS = 0
    _INFER_MISSES = 0


def _count_infer(kind: str) -> None:
    from .. import profiler

    profiler.global_stat.add_count(f"registry/infer_cache/{kind}", 1)


def infer_outputs(op_type: str, attrs, in_shapes: Arrays) -> Dict[str, List[jax.ShapeDtypeStruct]]:
    """Abstractly evaluate an op to get output shapes/dtypes.

    ``in_shapes`` maps slot -> list of ShapeDtypeStruct (concrete arrays
    are accepted too — only shape/dtype are read). Replaces the
    reference's per-op InferShape implementations. Results are memoized
    on (op_type, attrs digest, input signature, AMP policy); see
    ``infer_cache_stats``.
    """
    global _INFER_HITS, _INFER_MISSES
    key = _signature_key(op_type, attrs, in_shapes)
    if key is not None:
        cached = _INFER_CACHE.get(key)
        if cached is not None:
            _INFER_HITS += 1
            _count_infer("hit")
            return _copy_inferred(cached)
    result = _infer_outputs_uncached(op_type, attrs, in_shapes)
    if key is not None:
        _INFER_MISSES += 1
        _count_infer("miss")
        if len(_INFER_CACHE) >= _INFER_CACHE_MAX:
            _INFER_CACHE.clear()  # whole-table reset beats LRU bookkeeping
        _INFER_CACHE[key] = _copy_inferred(result)
    return result


def _infer_outputs_uncached(op_type: str, attrs, in_shapes: Arrays):
    opdef = get_op(op_type)
    if op_uses_rng(opdef, attrs):
        def f(ins, rng):
            return opdef.fn(attrs, ins, rng=rng)

        return jax.eval_shape(f, in_shapes,
                              jax.ShapeDtypeStruct((2,), jnp.uint32))
    if callable(opdef.needs_rng):
        return jax.eval_shape(lambda ins: opdef.fn(attrs, ins, rng=None),
                              in_shapes)
    return jax.eval_shape(lambda ins: opdef.fn(attrs, ins), in_shapes)

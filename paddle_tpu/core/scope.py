"""Scope: run-time name -> device-array store.

Analogue of the reference's hierarchical Scope (paddle/framework/scope.h:38),
holding jax.Arrays (device-resident, possibly sharded) instead of C++
Variables. The executor reads persistable state from the scope before a step
and writes updated state back after — the functional-XLA equivalent of the
reference's in-place variable mutation.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional

import jax
import numpy as np


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, jax.Array] = {}
        self.parent = parent
        self.kids = []
        # Bumped only when the KEY SET changes (a new name, or a delete) —
        # steady-state training rewrites existing names every step and must
        # not invalidate the executor's memoized cache-key key-set.
        self._keys_version = 0
        self._keyset_cache: Optional[tuple] = None
        if parent is not None:
            parent.kids.append(self)

    def new_scope(self) -> "Scope":
        return Scope(self)

    # -- access ------------------------------------------------------------
    def set(self, name: str, value) -> None:
        if name not in self._vars:
            self._keys_version += 1
        self._vars[name] = value

    def get(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        raise KeyError(f"variable {name!r} not found in scope")

    def has(self, name: str) -> bool:
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def delete(self, name: str) -> None:
        if name in self._vars:
            self._keys_version += 1
        self._vars.pop(name, None)

    def keys(self) -> Iterator[str]:
        return iter(self._vars.keys())

    def keys_version(self) -> tuple:
        """Composed key-set version up the parent chain: equal tuples
        guarantee the set of visible names is unchanged."""
        out = []
        s: Optional[Scope] = self
        while s is not None:
            out.append(s._keys_version)
            s = s.parent
        return tuple(out)

    def key_set(self) -> frozenset:
        """All names visible from this scope (self + ancestors), memoized
        per :meth:`keys_version` — the executor hashes this every run
        (core/executor.py _cache_key) so it must not rebuild an
        O(#params) set per step."""
        ver = self.keys_version()
        cached = self._keyset_cache
        if cached is not None and cached[0] == ver:
            return cached[1]
        names = set()
        s: Optional[Scope] = self
        while s is not None:
            names.update(s._vars)
            s = s.parent
        out = frozenset(names)
        self._keyset_cache = (ver, out)
        return out

    def find_var_scope(self, name: str) -> Optional["Scope"]:
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s
            s = s.parent
        return None

    # -- numpy convenience ---------------------------------------------------
    def get_numpy(self, name: str) -> np.ndarray:
        return np.asarray(self.get(name))

    def __contains__(self, name: str) -> bool:
        return self.has(name)

    def __repr__(self):
        return f"Scope({sorted(self._vars)})"


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


# --- default-scope helpers (fluid default_scope_funcs.py parity) ----------
# A thread-current scope stack over the global scope: code inside
# scoped_function/enter_local_scope sees (and pollutes) only a child scope
# that is dropped on exit — the reference uses this to keep temporary state
# out of the long-lived training scope.
_scope_stack = [_global_scope]


def get_cur_scope() -> Scope:
    return _scope_stack[-1]


def enter_local_scope() -> Scope:
    s = get_cur_scope().new_scope()
    _scope_stack.append(s)
    return s


def leave_local_scope() -> None:
    if len(_scope_stack) == 1:
        raise RuntimeError("cannot leave the global scope")
    s = _scope_stack.pop()
    if s.parent is not None and s in s.parent.kids:
        s.parent.kids.remove(s)


def scoped_function(fn, *args, **kwargs):
    """Run ``fn`` inside a fresh local scope, always restoring on exit."""
    enter_local_scope()
    try:
        return fn(*args, **kwargs)
    finally:
        leave_local_scope()


def find_var(name: str):
    return get_cur_scope().get(name)


def var(name: str, value=None):
    """Ensure ``name`` exists in the current scope (optionally set it)."""
    cur = get_cur_scope()
    if value is not None or not cur.has(name):
        cur.set(name, value)
    return cur.get(name)

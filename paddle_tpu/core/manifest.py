"""Signature manifests — the cold-start plane's compile record.

PERF.md measures first-compile at seconds per signature, and a bucketed
serving engine (or a resumed trainer) needs a dozen signatures before the
first token/step — so a new replica pays tens of seconds of dead time
unless it knows, ahead of traffic, exactly what to compile. The Executor
records every compiled ``(program digest, feed signature, fetch set)``
into a :class:`SignatureManifest`; engines and ``SGD.train`` persist it
next to the saved model / checkpoint as ``warmup_manifest.json``; a boot
replays it with :func:`replay` — AOT ``.lower().compile()`` of every
signature, concurrently (compilation is host-side work and releases the
GIL), WITHOUT executing anything. Combined with ``--compilation_cache_dir``
the replayed compiles are disk restores, and the first request/step after
replay is a pure in-process cache hit: zero fresh compiles.

The schema is versioned; an unknown version is rejected with an error
naming the file, so a manifest written by a future build degrades loudly
into execute-based warmup instead of silently half-warming a replica.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

MANIFEST_VERSION = 1
MANIFEST_NAME = "warmup_manifest.json"
MANIFEST_SCHEMA = "paddle_tpu/warmup_manifest"

__all__ = ["ManifestError", "SignatureManifest", "program_digest",
           "load", "try_load", "replay", "MANIFEST_NAME",
           "MANIFEST_VERSION"]


class ManifestError(ValueError):
    """A manifest file that cannot be trusted: wrong schema/version or a
    malformed signature record. The message names the file."""


def program_digest(program) -> str:
    """Stable cross-process digest of a program's structure (the
    ``program_to_dict`` JSON) — how a manifest signature finds the right
    program on the next boot. Private op attrs (``_callsite`` etc.) are
    stripped first: they record WHERE the program was built (a warmup CLI
    vs a server boot construct identical programs from different call
    sites) and must not split the digest. Memoized per program version,
    so recording a compile is O(1) in the steady state."""
    cached = getattr(program, "_sig_digest", None)
    if cached is not None and cached[0] == program.version:
        return cached[1]
    from ..io import program_to_dict

    d = program_to_dict(program)
    for block in d.get("blocks", []):
        for op in block.get("ops", []):
            attrs = op.get("attrs")
            if attrs and any(k.startswith("_") for k in attrs):
                op["attrs"] = {k: v for k, v in attrs.items()
                               if not k.startswith("_")}
    blob = json.dumps(d, sort_keys=True, separators=(",", ":"), default=str)
    digest = hashlib.sha1(blob.encode()).hexdigest()[:16]
    try:
        program._sig_digest = (program.version, digest)
    except AttributeError:  # exotic program-like objects: skip the memo
        pass
    return digest


def _norm_feeds(feeds) -> tuple:
    """Feeds as a canonical sorted tuple of (name, shape, dtype)."""
    out = []
    for name, shape, dtype in feeds:
        out.append((str(name), tuple(int(d) for d in shape), str(dtype)))
    return tuple(sorted(out))


class SignatureManifest:
    """A deduplicated, thread-safe set of compiled signatures."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sigs: Dict[tuple, dict] = {}

    def record(self, digest: str, feeds, fetches: Sequence[str]) -> bool:
        """Record one compiled signature; returns True when new.
        ``feeds`` is an iterable of (name, shape, dtype)."""
        feeds_t = _norm_feeds(feeds)
        key = (digest, feeds_t, tuple(str(f) for f in fetches))
        with self._lock:
            if key in self._sigs:
                return False
            self._sigs[key] = {
                "program": str(digest),
                "feeds": [[n, list(s), dt] for n, s, dt in feeds_t],
                "fetches": [str(f) for f in fetches],
            }
            return True

    def signatures(self) -> List[dict]:
        with self._lock:
            return list(self._sigs.values())

    def merge(self, other: "SignatureManifest") -> int:
        """Absorb another manifest's signatures; returns how many were
        new."""
        added = 0
        for sig in other.signatures():
            if self.record(sig["program"],
                           [tuple(f) for f in sig["feeds"]],
                           sig["fetches"]):
                added += 1
        return added

    def __len__(self) -> int:
        with self._lock:
            return len(self._sigs)

    # -- (de)serialisation -------------------------------------------------
    def to_dict(self) -> dict:
        return {"schema": MANIFEST_SCHEMA, "version": MANIFEST_VERSION,
                "signatures": self.signatures()}

    @classmethod
    def from_dict(cls, d: dict, where: str = "<manifest>") -> "SignatureManifest":
        version = d.get("version")
        if version != MANIFEST_VERSION:
            raise ManifestError(
                f"{where}: unsupported warmup-manifest version {version!r} "
                f"(this build reads version {MANIFEST_VERSION}); regenerate "
                f"it with tools/warmup.py or delete the file to fall back "
                f"to execute-based warmup")
        m = cls()
        for i, sig in enumerate(d.get("signatures", [])):
            try:
                m.record(sig["program"],
                         [tuple(f) for f in sig["feeds"]], sig["fetches"])
            except (KeyError, TypeError, ValueError) as exc:
                raise ManifestError(
                    f"{where}: malformed signature #{i}: {exc}") from exc
        return m

    def save(self, dirname: str, name: str = MANIFEST_NAME,
             merge: bool = True) -> str:
        """Atomically write this manifest into ``dirname`` (next to the
        saved model / checkpoints). With ``merge`` (default) an existing
        readable manifest's signatures are folded in first, so incremental
        warmups (a second bucket set, a later trainer run) accumulate."""
        os.makedirs(dirname, exist_ok=True)
        path = os.path.join(dirname, name)
        out = SignatureManifest()
        out.merge(self)
        if merge and os.path.exists(path):
            try:
                out.merge(load(dirname, name))
            except (ManifestError, OSError, json.JSONDecodeError):
                pass  # unreadable/foreign file: overwrite with ours
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(out.to_dict(), f, indent=1)
        os.replace(tmp, path)
        return path


def load(dirname: str, name: str = MANIFEST_NAME) -> SignatureManifest:
    """Read ``dirname/warmup_manifest.json``; raises FileNotFoundError
    when absent and :class:`ManifestError` (naming the path) when the
    version/schema is not one this build reads."""
    path = os.path.join(dirname, name)
    with open(path) as f:
        try:
            d = json.load(f)
        except json.JSONDecodeError as exc:
            raise ManifestError(f"{path}: not valid JSON: {exc}") from exc
    return SignatureManifest.from_dict(d, where=path)


def try_load(dirname: str,
             name: str = MANIFEST_NAME) -> Optional[SignatureManifest]:
    """:func:`load`, but an absent file returns None (the no-manifest
    boot path). Version/schema problems still raise — they must be loud."""
    try:
        return load(dirname, name)
    except FileNotFoundError:
        return None


def replay(executor, programs, scope=None, manifest=None,
           dirname: Optional[str] = None, max_workers: Optional[int] = None,
           device_ctx=None) -> dict:
    """AOT-compile every manifest signature that matches one of
    ``programs`` — ``Executor.warm_signature`` per record, fanned out over
    a thread pool (XLA compilation releases the GIL, so this is real
    concurrency). Nothing executes; state in ``scope`` is only read for
    shapes. Returns ``{"compiled", "already", "skipped", "seconds"}`` —
    ``skipped`` counts signatures whose program digest matched none of
    ``programs`` (an artifact from a different build: degrade, don't
    die)."""
    if manifest is None:
        if dirname is None:
            raise ValueError("replay needs a manifest or a dirname")
        manifest = load(dirname)
    by_digest = {}
    for p in programs:
        by_digest.setdefault(program_digest(p), p)
    jobs, skipped = [], 0
    for sig in manifest.signatures():
        prog = by_digest.get(sig["program"])
        if prog is None:
            skipped += 1
            continue
        jobs.append((prog, sig))
    if max_workers is None:
        try:
            from ..flags import FLAGS

            max_workers = max(int(FLAGS.warmup_concurrency), 1)
        except Exception:
            max_workers = 4

    def one(job):
        import contextlib

        prog, sig = job
        feeds = {n: (tuple(s), dt) for n, s, dt in
                 (tuple(f) for f in sig["feeds"])}
        ctx = device_ctx() if device_ctx is not None \
            else contextlib.nullcontext()
        with ctx:
            return executor.warm_signature(prog, feeds, sig["fetches"],
                                           scope=scope)

    t0 = time.perf_counter()
    if len(jobs) > 1 and max_workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
                max_workers=min(max_workers, len(jobs)),
                thread_name_prefix="paddle-tpu-warm") as pool:
            results = list(pool.map(one, jobs))
    else:
        results = [one(j) for j in jobs]
    compiled = sum(1 for r in results if r)
    return {"compiled": compiled, "already": len(jobs) - compiled,
            "skipped": skipped,
            "seconds": round(time.perf_counter() - t0, 6)}

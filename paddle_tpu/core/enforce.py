"""Structured error plane: PADDLE_ENFORCE + the op-level crash stack.

The reference throws EnforceNotMet with a captured C++ stack on any
violated precondition (/root/reference/paddle/platform/enforce.h:195-228)
and prints the layer/op call path on a crash via CustomStackTrace
(/root/reference/paddle/utils/CustomStackTrace.h). The TPU-native
equivalents:

- ``enforce*`` helpers raise ``EnforceError`` with a formatted message —
  used by kernels and framework code for argument/shape checks;
- every Operator records the USER call site that appended it (the graph is
  built in Python, so the interesting stack is the model-definition line,
  not the C++ frames); the executor wraps per-op lowering so a kernel
  failure reports the op, its input shapes, and where the user created it.
"""
from __future__ import annotations

from typing import Any, Optional


class EnforceError(RuntimeError):
    """EnforceNotMet analogue."""


def enforce(cond: Any, msg: str = "enforce failed", *args: Any) -> None:
    if not cond:
        raise EnforceError(msg % args if args else msg)


def _cmp(name, op, a, b, msg):
    if not op(a, b):
        detail = f"enforce_{name} failed: {a!r} {name} {b!r}"
        raise EnforceError(f"{detail}: {msg}" if msg else detail)


def enforce_eq(a, b, msg=""):
    _cmp("eq", lambda x, y: x == y, a, b, msg)


def enforce_ne(a, b, msg=""):
    _cmp("ne", lambda x, y: x != y, a, b, msg)


def enforce_lt(a, b, msg=""):
    _cmp("lt", lambda x, y: x < y, a, b, msg)


def enforce_le(a, b, msg=""):
    _cmp("le", lambda x, y: x <= y, a, b, msg)


def enforce_gt(a, b, msg=""):
    _cmp("gt", lambda x, y: x > y, a, b, msg)


def enforce_ge(a, b, msg=""):
    _cmp("ge", lambda x, y: x >= y, a, b, msg)


def enforce_not_none(v, msg=""):
    if v is None:
        raise EnforceError(f"enforce_not_none failed: {msg}" if msg
                           else "enforce_not_none failed")


def user_callsite() -> Optional[str]:
    """file:line of the innermost frame NOT inside paddle_tpu — the model
    definition line that appended the current op. Walks raw frames (no
    FrameSummary/linecache work: this runs for every op appended)."""
    import sys

    frame = sys._getframe(1)
    while frame is not None:
        fn = frame.f_code.co_filename.replace("\\", "/")
        if "/paddle_tpu/" not in fn:
            return f"{frame.f_code.co_filename}:{frame.f_lineno}"
        frame = frame.f_back
    return None


def format_input_sigs(ins) -> dict:
    """{slot: ['dtype[shape]', ...]} for arrays or ShapeDtypeStructs."""
    return {
        slot: [f"{getattr(a, 'dtype', type(a).__name__)}"
               f"{list(getattr(a, 'shape', ()))}" for a in arrs]
        for slot, arrs in ins.items()
    }


def op_error(op, index: int, ins, exc: BaseException) -> EnforceError:
    """Wrap a kernel failure with the op-level context CustomStackTrace
    would have printed: op type/position, input shapes+dtypes, and the
    user's model-definition call site."""
    shapes = format_input_sigs(ins)
    where = op.attrs.get("_callsite") or "<unknown call site>"
    msg = (f"op {op.type!r} (#{index} of the block) failed during "
           f"lowering\n  inputs: {shapes}\n  defined at: {where}\n"
           f"  cause: {type(exc).__name__}: {exc}")
    err = EnforceError(msg)
    err.__cause__ = exc
    return err

"""Symbolic backward-pass construction over the Program IR.

The TPU-native analogue of the reference's AppendBackward
(/root/reference/paddle/framework/backward.cc:523) and the python
append_backward_ops (/root/reference/python/paddle/v2/fluid/backward.py):
walks the block in reverse from the loss, emits one gradient op per forward
op, and sum-accumulates fan-out gradients, naming grad variables
``<var>@GRAD`` exactly like the reference.

Where the reference needs a hand-written GradOpDescMaker + grad kernel per op
(grad_op_desc_maker.h), we emit a generic ``grad`` op whose kernel computes
``jax.vjp`` of the registered forward function. The recomputed forward
subexpressions are CSE'd by XLA inside the single fused block computation, so
this is free at run time and guarantees analytically-consistent gradients for
every op. Ops with randomness or custom sparse grads register an explicit
``grad_fn`` and get a ``grad_custom`` op instead.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp

from .program import (GRAD_SUFFIX, Block, Operator, Program, Variable,
                      grad_var_name)
from .registry import get_op, register_op, op_uses_rng
from .types import is_floating

# Ops after which there is nothing to differentiate.
NON_DIFFERENTIABLE = {
    "fill_constant", "gaussian_random", "uniform_random", "feed", "fetch",
    "accuracy", "top_k", "assign_value", "fill_constant_batch_size_like",
    "sgd", "momentum", "adam", "adamax", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "ftrl", "proximal_gd", "proximal_adagrad",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "argmax", "one_hot", "truncated_gaussian_random",
    "gaussian_random_batch_size_like",
    # decode-side: generation is not trained through
    "beam_search_decoder",
}
# NOTE: "while" IS differentiable when built with max_iters (fixed-trip
# scan lowering); unbounded whiles on a loss path raise jax's
# while_loop-not-differentiable error at compile time.


# --------------------------------------------------------------------------
# Generic grad kernels
# --------------------------------------------------------------------------
def _rebuild_ins(attrs, ins):
    """Reconstruct the forward op's input dict from the grad op's I: slots."""
    return {slot: ins["I:" + slot] for slot in attrs["in_slots"] if "I:" + slot in ins}


@register_op("grad")
def generic_grad(attrs, ins):
    """vjp-of-forward gradient kernel.

    attrs:
      fwd_type, fwd_attrs — the forward op
      in_slots  — {slot: n_inputs} of the forward op
      out_slots — [slot, ...] deterministic output slot order
      og        — {slot: [bool per output]} which outputs have incoming grads
      diff      — {slot: [bool per input]} which inputs need gradients
    """
    opdef = get_op(attrs["fwd_type"])
    fwd_attrs = attrs["fwd_attrs"]
    primal = _rebuild_ins(attrs, ins)
    diff_mask: Dict[str, List[bool]] = attrs["diff"]

    # Split inputs into differentiated leaves and fixed leaves.
    diff_ins = {
        slot: [a for a, d in zip(primal[slot], diff_mask[slot]) if d]
        for slot in diff_mask
        if any(diff_mask[slot])
    }

    def merge(d_ins):
        full = {}
        for slot, arrs in primal.items():
            mask = diff_mask.get(slot)
            if not mask or not any(mask):
                full[slot] = list(arrs)
                continue
            it = iter(d_ins[slot])
            full[slot] = [next(it) if d else a for a, d in zip(arrs, mask)]
        return full

    # Discover float output leaf positions by abstract evaluation.
    probe = jax.eval_shape(lambda p: opdef.fn(fwd_attrs, p), primal)
    float_pos = [
        (slot, i)
        for slot in attrs["out_slots"]
        for i in range(len(probe.get(slot, [])))
        if is_floating(probe[slot][i].dtype)
    ]

    def f(d_ins):
        o = opdef.fn(fwd_attrs, merge(d_ins))
        return [o[s][i] for (s, i) in float_pos]

    outs, vjp = jax.vjp(f, diff_ins)

    # Build cotangents aligned with float_pos; missing grads are zeros.
    og_mask = attrs["og"]
    og_arrays: Dict[str, List] = {}
    for slot, mask in og_mask.items():
        arrs = iter(ins.get("OG:" + slot, []))
        og_arrays[slot] = [next(arrs) if m else None for m in mask]
    cts = []
    for (slot, i), leaf in zip(float_pos, outs):
        g = og_arrays.get(slot, [None] * (i + 1))[i] if slot in og_arrays else None
        cts.append(g.astype(leaf.dtype) if g is not None else jnp.zeros_like(leaf))
    (gins,) = vjp(cts)

    result = {}
    for slot, arrs in gins.items():
        result["IG:" + slot] = list(arrs)
    return result


# Outputs of these op types are saved across forward->backward inside a
# recompute segment (program.recompute_guard); everything else — BN applies,
# activations, residual adds — is rematerialized in the backward, where XLA
# fuses the recompute into the consuming kernels instead of round-tripping
# the intermediate through HBM. MXU ops are saved because recomputing them
# costs real FLOPs; tiny (ndim<=1) tensors are saved because storing them is
# free and recomputing them needs a full reduction over a big operand.
SEGMENT_SAVE_OPS = {
    "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "depthwise_conv2d", "mul", "matmul", "pool2d", "pool3d",
    "max_pool2d_with_index", "max_pool3d_with_index", "sequence_conv",
    "lstm", "gru",
}

_SEG_RESIDUAL = "seg_saved"
_SEG_VJP_PREFIX = "@SEGVJP@"


@register_op("seg_fwd", special=True)
def segment_forward(attrs, ins, *, executor=None, env=None, op=None,
                    program=None, scope=None):
    """Forward of a whole recompute segment as ONE composite call.

    Emitted by append_backward in place of the segment's individual forward
    ops (program.recompute_guard). Runs ``jax.vjp`` of the composite under
    ``jax.checkpoint`` with a save-only-named-residuals policy: matmul/conv
    outputs and tiny (ndim<=1) stats are the only values that survive to the
    backward; every other intermediate (BN applies, activations, residual
    adds) dies as soon as the forward consumes it and is rematerialized —
    fused into the consuming kernels — inside the paired ``grad_seg`` op.
    The vjp closure is stashed in the trace environment under a key only the
    paired grad op knows, so the forward is computed exactly once.

    attrs:
      seg_ops   — [{type, attrs, ins, outs}] the original forward ops
      ext_in    — external input names, aligned with the I slot
      diff      — bool per ext_in: which inputs receive gradients
      all_outs  — every segment output name, aligned with the O slot
      vjp_key   — env key for the vjp closure
    """
    from jax.ad_checkpoint import checkpoint_name

    ext = attrs["ext_in"]
    diff = attrs["diff"]
    vals = ins["I"]
    fixed = {n: v for n, v, d in zip(ext, vals, diff) if not d}
    dvals = {n: v for n, v, d in zip(ext, vals, diff) if d}

    def f(dins):
        local = dict(fixed)
        local.update(dins)
        for sop in attrs["seg_ops"]:
            opdef = get_op(sop["type"])
            op_ins = {slot: [local[n] for n in names]
                      for slot, names in sop["ins"].items() if names}
            outs = opdef.fn(sop["attrs"], op_ins)
            save_all = sop["type"] in SEGMENT_SAVE_OPS
            for slot, names in sop["outs"].items():
                for name, v in zip(names, outs.get(slot, [])):
                    if save_all or getattr(v, "ndim", 2) <= 1:
                        v = checkpoint_name(v, _SEG_RESIDUAL)
                    local[name] = v
        return [local[n] for n in attrs["all_outs"]]

    f_ck = jax.checkpoint(
        f, policy=jax.checkpoint_policies.save_only_these_names(_SEG_RESIDUAL))
    outs, vjp_fn = jax.vjp(f_ck, dvals)
    env[_SEG_VJP_PREFIX + attrs["vjp_key"]] = (vjp_fn, outs)
    return {"O": outs}


@register_op("grad_seg", special=True)
def segment_grad(attrs, ins, *, executor=None, env=None, op=None,
                 program=None, scope=None):
    """Backward of a recompute segment: applies the vjp closure stashed by
    the paired ``seg_fwd`` op.

    attrs:
      vjp_key   — env key of the closure
      ext_in / diff — as in seg_fwd (IG slot order = diff'ed ext_in order)
      og_outs   — names (subset of seg_fwd's all_outs) aligned with OG
      all_outs  — seg_fwd's output order, to place cotangents
    """
    vjp_fn, outs = env[_SEG_VJP_PREFIX + attrs["vjp_key"]]
    og_map = dict(zip(attrs["og_outs"], ins["OG"]))
    cts = []
    for name, o in zip(attrs["all_outs"], outs):
        g = og_map.get(name)
        cts.append(g.astype(o.dtype) if g is not None else jnp.zeros_like(o))
    (gins,) = vjp_fn(cts)
    dnames = [n for n, d in zip(attrs["ext_in"], attrs["diff"]) if d]
    return {"IG": [gins[n] for n in dnames]}


@register_op("grad_custom")
def custom_grad(attrs, ins):
    """Dispatch to an op's registered grad_fn (ops with rng/sparse grads)."""
    opdef = get_op(attrs["fwd_type"])
    fwd_attrs = attrs["fwd_attrs"]
    primal = _rebuild_ins(attrs, ins)
    outs = {slot: ins["O:" + slot] for slot in attrs["out_slots"] if "O:" + slot in ins}
    og_mask = attrs["og"]
    ogs = {}
    for slot, mask in og_mask.items():
        arrs = iter(ins.get("OG:" + slot, []))
        vals = [next(arrs) if m else None for m in mask]
        if any(m for m in mask):
            ogs[slot] = vals
    grads = opdef.grad_fn(fwd_attrs, primal, outs, ogs)
    result = {}
    diff_mask = attrs["diff"]
    for slot, mask in diff_mask.items():
        if not any(mask):
            continue
        vals = grads.get(slot, [None] * len(mask))
        picked = []
        for idx, (v, d) in enumerate(zip(vals, mask)):
            if not d:
                continue
            if v is None:  # grad_fn declined: zero gradient
                v = jnp.zeros_like(primal[slot][idx])
            picked.append(v)
        result["IG:" + slot] = picked
    return result


# --------------------------------------------------------------------------
# append_backward
# --------------------------------------------------------------------------
def _is_float_var(block: Block, name: str) -> bool:
    if not block.has_var(name):
        return True  # unknown vars: assume float tensors
    return is_floating(block.var(name).dtype)


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence[str]] = None,
    no_grad_set: Optional[Set[str]] = None,
) -> List[Tuple[Variable, Variable]]:
    """Append gradient ops for ``loss`` to its program's global block.

    Returns [(param, grad_var)] pairs, matching fluid's contract used by
    Optimizer.minimize (reference optimizer.py / backward.py).
    """
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())

    # 1. Find ops on the path to the loss (forward ops only — grad ops are
    # appended below and must not be revisited).
    n_fwd = len(block.ops)
    relevant: Set[str] = {loss.name}
    op_needed = [False] * n_fwd
    for i in range(n_fwd - 1, -1, -1):
        op = block.ops[i]
        if any(n in relevant for n in op.output_names()):
            if op.type in NON_DIFFERENTIABLE:
                continue
            op_needed[i] = True
            for name in op.input_names():
                if _is_float_var(block, name) and name not in no_grad:
                    var = block.var(name) if block.has_var(name) else None
                    if var is not None and var.stop_gradient and not var.is_parameter:
                        continue
                    relevant.add(name)

    # 2. Count grad contributions per var (outputs consumed by needed ops).
    contributions: Dict[str, List[str]] = {}

    # 3. Seed: d loss / d loss = 1.
    loss_grad_name = grad_var_name(loss.name)
    # declared shape must match the fill_constant below exactly — a ()
    # loss declares a () seed, not (1,) (the whole-program checker pins
    # declared-vs-inferred agreement)
    block.create_var(name=loss_grad_name,
                     shape=loss.shape if loss.shape is not None else (),
                     dtype=loss.dtype, stop_gradient=True)
    block.append_op(
        "fill_constant",
        outputs={"Out": [loss_grad_name]},
        attrs={"shape": list(loss.shape or ()), "value": 1.0,
               "dtype": str(loss.dtype)},
    )
    contributions[loss.name] = [loss_grad_name]
    finalized: Dict[str, Optional[str]] = {}

    def finalize_grad(name: str) -> Optional[str]:
        """Emit accumulation op if needed; returns grad var name or None."""
        if name in finalized:
            return finalized[name]
        contribs = contributions.get(name, [])
        gname = grad_var_name(name)
        if not contribs:
            result = None
        elif len(contribs) == 1:
            result = contribs[0]
        else:
            block.create_var(name=gname, stop_gradient=True)
            block.append_op("sum", inputs={"X": contribs}, outputs={"Out": [gname]})
            result = gname
        finalized[name] = result
        return result

    def add_contribution(name: str, gname: str):
        contributions.setdefault(name, []).append(gname)

    # The program is not SSA: in-place patterns (assign-into, the while
    # op's carried write-back) re-write existing names. Two consequences
    # for the reverse walk (the reference sidesteps both by renaming in
    # AppendBackward, /root/reference/paddle/framework/backward.cc:523):
    #
    # (a) gradient accounting is per-VERSION: once the writing op's output
    #     grads are taken, the name reverts to its previous definition, so
    #     its contribution/finalize state must be cleared (kill_versions);
    # (b) grad ops execute after ALL forward ops, so any primal value a
    #     grad op reads must be snapshotted before the overwrite if some
    #     op at/after the forward op's position re-writes that name
    #     (last_write + @PRE snapshots below).
    last_write: Dict[str, int] = {}
    for pos in range(n_fwd):
        for names in block.ops[pos].outputs.values():
            for name in names:
                last_write[name] = pos

    canonical_first: Dict[str, str] = {}

    def kill_versions(op):
        for names in op.outputs.values():
            for name in names:
                # Keep the latest version's grad for the canonical
                # ``<var>@GRAD`` alias (step 5): in the reverse walk the
                # first kill of a name belongs to its last write.
                g = finalized.get(name)
                if g is not None and name not in canonical_first:
                    canonical_first[name] = g
                contributions.pop(name, None)
                finalized.pop(name, None)

    def _seg_eligible(op) -> bool:
        """May this op be folded into a composite recompute-segment grad?"""
        if op.type in NON_DIFFERENTIABLE:
            return False
        opdef = get_op(op.type)
        return not (opdef.special or opdef.needs_rng
                    or (opdef.grad_fn is not None
                        and not opdef.grad_fn_is_optimization))

    def _diffable_input(name: str) -> bool:
        ok = (name in relevant and _is_float_var(block, name)
              and name not in no_grad)
        if ok and block.has_var(name):
            v = block.var(name)
            if v.stop_gradient and not v.is_parameter:
                ok = False
        return ok

    def _emit_segment_grad(j: int, i: int) -> None:
        """Differentiate block.ops[j..i] (one recompute segment): replace the
        forward run with one composite ``seg_fwd`` op and append the paired
        ``grad_seg``. No primal snapshots are needed — the vjp closure
        captures the segment inputs at their forward position, before any
        later in-place overwrite."""
        run = block.ops[j:i + 1]
        seg_ops_desc = []
        written: Set[str] = set()
        ext_in: List[str] = []
        ext_set: Set[str] = set()
        all_outs: List[str] = []
        for op2 in run:
            for names in op2.inputs.values():
                for name in names:
                    if name not in written and name not in ext_set:
                        ext_set.add(name)
                        ext_in.append(name)
            for name in op2.output_names():
                written.add(name)
                all_outs.append(name)
            seg_ops_desc.append({
                "type": op2.type,
                "attrs": dict(op2.attrs),
                "ins": {s: list(v) for s, v in op2.inputs.items()},
                "outs": {s: list(v) for s, v in op2.outputs.items()},
            })
        # Keep only the final version of names written more than once: that
        # is the version visible outside the segment.
        seen: Set[str] = set()
        dedup: List[str] = []
        for name in reversed(all_outs):
            if name not in seen:
                seen.add(name)
                dedup.append(name)
        all_outs = list(reversed(dedup))
        # OG for segment outputs (grads contributed by already-processed
        # later ops).
        og_outs, og_vars = [], []
        for name in all_outs:
            g = finalize_grad(name)
            if g is not None:
                og_outs.append(name)
                og_vars.append(g)
        for op2 in reversed(run):
            kill_versions(op2)
        diff = [_diffable_input(n) for n in ext_in]
        vjp_key = program.unique_name("seg")
        seg_attrs = {"seg_ops": seg_ops_desc, "ext_in": list(ext_in),
                     "diff": list(diff), "all_outs": all_outs,
                     "vjp_key": vjp_key}
        fwd_op = Operator(block, "seg_fwd",
                          inputs={"I": list(ext_in)},
                          outputs={"O": list(all_outs)},
                          attrs=seg_attrs)
        block.ops[j:i + 1] = [fwd_op]
        program._bump()
        if not og_outs or not any(diff):
            return
        ig_vars = []
        for name, d in zip(ext_in, diff):
            if not d:
                continue
            gvar = program.unique_name(grad_var_name(name) + "@R")
            block.create_var(name=gvar, stop_gradient=True)
            add_contribution(name, gvar)
            ig_vars.append(gvar)
        block.append_op(
            "grad_seg",
            inputs={"OG": og_vars},
            outputs={"IG": ig_vars},
            attrs={"vjp_key": vjp_key, "ext_in": list(ext_in),
                   "diff": list(diff), "og_outs": og_outs,
                   "all_outs": all_outs},
        )

    # 4. Walk forward ops in reverse, emitting grad ops. Contiguous runs of
    # ops tagged by program.recompute_guard collapse into one grad_seg op.
    i = n_fwd - 1
    while i >= 0:
        op = block.ops[i]
        if not op_needed[i]:
            kill_versions(op)
            i -= 1
            continue
        if op.type == "seg_fwd":
            raise NotImplementedError(
                "append_backward over a program that already contains a "
                "compiled recompute segment (seg_fwd): differentiate each "
                "loss from its own program build (clone before the first "
                "minimize), or disable recompute_guard for multi-loss "
                "programs")
        seg = op.attrs.get("__recompute_seg__")
        if seg is not None and _seg_eligible(op):
            j = i
            while j > 0 and (
                    block.ops[j - 1].attrs.get("__recompute_seg__") == seg
                    and op_needed[j - 1]
                    and _seg_eligible(block.ops[j - 1])):
                j -= 1
            _emit_segment_grad(j, i)
            i = j - 1
            continue
        opdef = get_op(op.type)

        out_slots = sorted(op.outputs)
        og_mask = {}
        og_inputs = {}
        any_og = False
        for slot in out_slots:
            mask = []
            arrs = []
            for name in op.outputs[slot]:
                g = finalize_grad(name)
                mask.append(g is not None)
                if g is not None:
                    arrs.append(g)
                    any_og = True
            og_mask[slot] = mask
            if arrs:
                og_inputs["OG:" + slot] = arrs
        kill_versions(op)
        if not any_og:
            i -= 1
            continue

        diff_mask = {}
        ig_outputs = {}
        for slot, names in op.inputs.items():
            mask = []
            outs_for_slot = []
            for name in names:
                ok = _diffable_input(name)
                mask.append(ok)
                if ok:
                    g = program.unique_name(grad_var_name(name) + "@R")
                    # Single-contribution grads keep the canonical name.
                    outs_for_slot.append((name, g))
            diff_mask[slot] = mask
            if outs_for_slot:
                ig_outputs[slot] = outs_for_slot
        if not ig_outputs:
            i -= 1
            continue

        use_custom = opdef.grad_fn is not None
        if op_uses_rng(opdef, op.attrs) and not use_custom:
            raise NotImplementedError(
                f"op {op.type!r} uses randomness and has no custom grad_fn"
            )

        # (b) above: snapshot primal INPUTS whose name is re-written by
        # this or any later op (the grad op would otherwise read the
        # post-overwrite value), and — for custom grads that take O: slots
        # — primal OUTPUTS overwritten strictly later. Snapshots are
        # assigns inserted at the op's position (inputs) / right after it
        # (outputs); XLA elides the copies.
        in_names = {n for names in op.inputs.values() for n in names}
        snap = {}
        for name in sorted(in_names):
            if last_write.get(name, -1) >= i:
                sname = program.unique_name(name + "@PRE")
                block.create_var(name=sname, stop_gradient=True)
                block.insert_op(i, "assign", inputs={"X": [name]},
                                outputs={"Out": [sname]})
                snap[name] = sname
        osnap = {}
        if use_custom:
            out_names = {n for names in op.outputs.values() for n in names}
            for name in sorted(out_names):
                if last_write.get(name, -1) > i:
                    sname = program.unique_name(name + "@POST")
                    block.create_var(name=sname, stop_gradient=True)
                    block.insert_op(i + 1 + len(snap), "assign",
                                    inputs={"X": [name]},
                                    outputs={"Out": [sname]})
                    osnap[name] = sname

        grad_inputs = {("I:" + slot): [snap.get(n, n) for n in names]
                       for slot, names in op.inputs.items() if names}
        if use_custom:
            for slot, names in op.outputs.items():
                if names:
                    grad_inputs["O:" + slot] = [osnap.get(n, n)
                                                for n in names]
        grad_inputs.update(og_inputs)

        grad_outputs = {}
        for slot, pairs in ig_outputs.items():
            slot_outs = []
            for name, gvar in pairs:
                block.create_var(name=gvar, stop_gradient=True)
                slot_outs.append(gvar)
                add_contribution(name, gvar)
            grad_outputs["IG:" + slot] = slot_outs

        block.append_op(
            "grad_custom" if use_custom else "grad",
            inputs=grad_inputs,
            outputs=grad_outputs,
            attrs={
                "fwd_type": op.type,
                "fwd_attrs": dict(op.attrs),
                "in_slots": {slot: len(names) for slot, names in op.inputs.items()},
                "out_slots": out_slots,
                "og": og_mask,
                "diff": diff_mask,
            },
        )
        i -= 1

    # 5. Finalize remaining contributions (producer-less vars: feeds/params)
    # and give every finalized grad its canonical ``<var>@GRAD`` alias so
    # users and transforms can fetch it by name. Unfetched grads are DCE'd by
    # XLA, so unused aliases cost nothing.
    for name in list(contributions):
        g = finalize_grad(name)
        canonical_first.setdefault(name, g)
    # Multi-version names resolve to the LATEST version's grad (recorded at
    # its first kill in the reverse walk) — the value the loss consumed.
    for name, g in canonical_first.items():
        canonical = grad_var_name(name)
        if g is not None and g != canonical and not block.has_var(canonical):
            src = block.var(name) if block.has_var(name) else None
            block.create_var(name=canonical,
                             shape=src.shape if src is not None else None,
                             dtype=src.dtype if src is not None else "float32",
                             stop_gradient=True)
            block.append_op("assign", inputs={"X": [g]},
                            outputs={"Out": [canonical]})

    # 6. Collect (param, grad) pairs.
    params = (
        [block.var(n) for n in parameter_list]
        if parameter_list
        else block.all_parameters()
    )
    result = []
    for p in params:
        g = finalize_grad(p.name)
        if g is None:
            continue
        canonical = grad_var_name(p.name)
        if not block.has_var(canonical):  # single direct contribution
            block.create_var(name=canonical, shape=p.shape, dtype=p.dtype,
                             stop_gradient=True)
            block.append_op("assign", inputs={"X": [g]},
                            outputs={"Out": [canonical]})
        result.append((p, block.var(canonical)))
    return result

from .executor import CPUPlace, Executor, RunHandle, TPUPlace
from .program import (Block, Operator, Parameter, Program, Variable,
                      default_main_program, default_startup_program,
                      program_guard, recompute_guard)
from .registry import get_op, has_op, register_op, registered_ops
from .scope import Scope, global_scope

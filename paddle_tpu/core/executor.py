"""Executor: lowers a whole program block to ONE jitted XLA computation.

This is the central idiomatic departure from the reference. The reference's
Executor is a per-op interpreter — it walks the block and dispatches a device
kernel per op (/root/reference/paddle/framework/executor.cc:73-129, hot loop
at :112-125), paying a host->device boundary per op. Here the entire block is
traced into a single pure JAX function and compiled once per (program,
shapes) signature; XLA fuses across op boundaries, keeps intermediates in
registers/VMEM, and overlaps collectives with compute. Feed variables become
function inputs; persistable state (parameters, optimizer accumulators) is
threaded functionally and donated so XLA can update buffers in place —
replacing the reference's in-place Scope mutation.

Run semantics match fluid's ``Executor.run`` feed/fetch contract
(/root/reference/python/paddle/v2/fluid/executor.py:112-168): only
persistable variables survive a run in the scope; intermediates must be
fetched.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import program as prog_mod
from .enforce import EnforceError, op_error
from .program import Program, RNG_VAR
from .registry import get_op, op_uses_rng
from .selected_rows import SelectedRows, densify
from .scope import Scope, global_scope
from .. import trace

logger = logging.getLogger("paddle_tpu")

# Sharding-invariant RNG: with the legacy (non-partitionable) threefry,
# XLA partitions a random op whose output lands sharded (GSPMD
# out_shardings — e.g. a vocab-sharded embedding table's uniform init)
# and produces DIFFERENT bits than the single-device run of the same
# program+seed. The partitionable implementation is invariant to
# sharding, which is the whole reproducibility contract of the one
# sharding plane: dp/tp runs must match their single-device reference.
# (No-op on jax versions where partitionable is already the default.)
try:
    jax.config.update("jax_threefry_partitionable", True)
except AttributeError:  # flag retired: partitionable is the only mode
    pass


class TPUPlace:
    """Device handle, analogue of platform::Place (place.h:53)."""

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def device(self):
        return jax.devices()[self.device_id]

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


class CPUPlace(TPUPlace):
    def device(self):
        cpus = [d for d in jax.devices() if d.platform == "cpu"]
        if cpus:
            return cpus[self.device_id]
        return jax.devices()[self.device_id]

    def __repr__(self):
        return f"CPUPlace({self.device_id})"


def _nonfinite_counts(value) -> Optional[Tuple[int, int]]:
    """(n_nan, n_inf) for float arrays, None for non-float / all-finite."""
    if isinstance(value, SelectedRows):
        value = value.values
    arr = np.asarray(value)
    if not np.issubdtype(arr.dtype, np.floating):
        return None
    n_nan = int(np.isnan(arr).sum())
    n_inf = int(np.isinf(arr).sum())
    return (n_nan, n_inf) if n_nan or n_inf else None


def _raise_nonfinite(name: str, n_nan: int, n_inf: int) -> None:
    raise FloatingPointError(
        f"variable {name!r} contains NaN/Inf "
        f"({n_nan} NaN, {n_inf} Inf); re-run with trace_level=2 "
        f"(or --trace_level=2) to locate the producing op")


def _check_nan_inf(name: str, value) -> None:
    bad = _nonfinite_counts(value)
    if bad is not None:
        _raise_nonfinite(name, bad[0], bad[1])


# On-device (n_nan, n_inf) reduction for the deferred check_nan_inf scan:
# written-back state is donated to the NEXT run_async dispatch, so the
# RunHandle must not hold the raw state arrays — it holds these two
# scalars per state instead (cheap, not donated, safe to read any time).
_nonfinite_count_kernel = jax.jit(
    lambda a: jnp.stack([jnp.isnan(a).sum(), jnp.isinf(a).sum()]))


def _device_nonfinite_counts(value):
    """Dispatch the non-finite count for a device array without any host
    sync; returns None for non-float values (nothing to check)."""
    if isinstance(value, SelectedRows):
        value = value.values
    if not np.issubdtype(np.dtype(value.dtype), np.floating):
        return None
    return _nonfinite_count_kernel(value)


def _value_stats(value) -> dict:
    """JSON-safe per-output stats for the interpret-mode op spans."""
    if isinstance(value, SelectedRows):
        value = value.values
    arr = np.asarray(value)
    out = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
    if arr.size and np.issubdtype(arr.dtype, np.floating):
        finite = arr[np.isfinite(arr)]
        out["nonfinite"] = int(arr.size - finite.size)
        if finite.size:
            out["mean"] = float(finite.mean())
            out["absmax"] = float(np.abs(finite).max())
    return out


_cache_enabled = False


def _pc_enabled() -> bool:
    """Is a persistent (on-disk) compilation cache active? Covers both
    the --compilation_cache_dir wiring below and a jax config set by the
    embedding application."""
    if _cache_enabled:
        return True
    try:
        return bool(jax.config.jax_compilation_cache_dir)
    except AttributeError:
        return False


def reset_compilation_cache() -> None:
    """Unwire the persistent compilation cache (tests / re-pointing the
    dir mid-process): the next Executor constructed re-reads
    --compilation_cache_dir and re-initialises the cache there."""
    global _cache_enabled
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
    except Exception:  # cache never initialised / private API moved
        pass
    _cache_enabled = False


# ---------------------------------------------------------------------------
# Compile-source classification: fresh XLA compile vs persistent-cache
# (disk) restore vs in-process hit. jax announces disk restores through its
# monitoring plane; the events fire synchronously on the compiling thread,
# so a thread-local window around each .lower().compile() attributes them
# correctly even when manifest replay compiles on a thread pool.
# ---------------------------------------------------------------------------
_pc_local = threading.local()
_pc_listener_on = False
_PC_HIT_EVENT = "/jax/compilation_cache/cache_hits"


def _on_jax_compile_event(event, **_kw) -> None:
    window = getattr(_pc_local, "window", None)
    if window is not None and event == _PC_HIT_EVENT:
        window["persistent_hits"] += 1


def _ensure_cache_listener() -> None:
    global _pc_listener_on
    if _pc_listener_on:
        return
    _pc_listener_on = True
    try:
        from jax._src import monitoring

        monitoring.register_event_listener(_on_jax_compile_event)
    except Exception:  # monitoring API moved: every compile reads 'fresh'
        pass


@contextlib.contextmanager
def _compile_window():
    prev = getattr(_pc_local, "window", None)
    window = {"persistent_hits": 0}
    _pc_local.window = window
    try:
        yield window
    finally:
        _pc_local.window = prev


# ---------------------------------------------------------------------------
# Donation verdict for cache-restored executables. Known defect (was
# tests/conftest.py's suite-wide workaround): on some jaxlibs, CPU
# executables DESERIALIZED from the persistent cache mishandle
# donated/aliased buffers — a training step that donates state reads freed
# memory and NaNs the model. The first execution of a restored donating
# executable is therefore verified against its no-donation twin
# (Executor._first_restored_donating_call); the verdict is memoized
# in-process and persisted into the cache dir so a fleet pays the check
# once per backend, not once per boot.
# ---------------------------------------------------------------------------
_donation_verdicts: Dict[str, str] = {}
_verdict_lock = threading.Lock()
DONATION_VERDICT_NAME = "donation_verify.json"

# Platforms whose RESTORED executables are known to corrupt donated
# buffers. On CPU this is witnessed as use-after-free: NaN'd training
# state, and (allocation-pattern-dependent) glibc heap aborts — so the
# probe itself is unsafe and restored donating executables are routed to
# their no-donation twin WITHOUT ever executing the donated form. Other
# platforms verify once on first execution and persist the verdict.
_RESTORED_DONATION_DENYLIST = ("cpu",)
_denylist_logged = False


def _verdict_key(platform: str) -> str:
    return f"{platform}/jax-{jax.__version__}"


def _verdict_path() -> Optional[str]:
    import os

    from ..flags import FLAGS

    d = FLAGS.compilation_cache_dir
    if not d:
        try:
            d = jax.config.jax_compilation_cache_dir
        except AttributeError:
            d = None
    if not d:
        return None
    return os.path.join(d, DONATION_VERDICT_NAME)


def _read_donation_verdict(platform: str) -> Optional[str]:
    """'ok' | 'broken' | None (never verified on this backend)."""
    import json
    import os

    key = _verdict_key(platform)
    with _verdict_lock:
        if key in _donation_verdicts:
            return _donation_verdicts[key]
        path = _verdict_path()
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    verdict = json.load(f).get(key)
            except (OSError, ValueError):
                verdict = None
            if verdict in ("ok", "broken"):
                _donation_verdicts[key] = verdict
                return verdict
        return None


def _write_donation_verdict(platform: str, verdict: str) -> None:
    import json
    import os

    key = _verdict_key(platform)
    with _verdict_lock:
        _donation_verdicts[key] = verdict
        path = _verdict_path()
        if path is None:
            return
        data = {}
        try:
            if os.path.exists(path):
                with open(path) as f:
                    data = json.load(f)
        except (OSError, ValueError):
            data = {}
        data[key] = verdict
        tmp = path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)
        except OSError:
            pass  # read-only cache volume: the in-process memo still holds


def _values_close(test, ref) -> bool:
    """Donation write-back comparison: the two executables run the same
    HLO, so honest outputs agree to float noise — corruption shows up as
    garbage/NaN, not as a rounding delta."""
    xs = jax.tree_util.tree_leaves(test)
    ys = jax.tree_util.tree_leaves(ref)
    if len(xs) != len(ys):
        return False
    for x, y in zip(xs, ys):
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        if x.dtype.kind in "iub":
            if not np.array_equal(x, y):
                return False
        else:
            xf = x.astype(np.float32) if x.dtype.kind not in "fc" else x
            yf = y.astype(np.float32) if y.dtype.kind not in "fc" else y
            if not np.allclose(xf, yf, rtol=1e-4, atol=1e-6,
                               equal_nan=True):
                return False
    return True


def _maybe_enable_compilation_cache() -> None:
    """Wire --compilation_cache_dir into jax's persistent compilation
    cache (once per process): repeat runs of the same program skip the
    first-compile latency entirely — the whole-block-compile design's
    answer to the reference's kernel warmup costs."""
    global _cache_enabled
    if _cache_enabled:
        return
    from ..flags import FLAGS

    d = FLAGS.compilation_cache_dir
    if not d:
        return
    import os

    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # cache every compile, however small/fast
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax initialises the persistent cache once, on the first compile:
    # if anything compiled before this flag was read (or a different
    # cache dir was active), the dir change would silently not take —
    # drop the initialised cache so the next compile re-inits at ``d``
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
    except Exception:  # cache never initialised / private API moved
        pass
    _cache_enabled = True


class _Compiled:
    """A compiled (program-block, signature) record.

    ``fn`` is the jitted callable; ``aot`` is its eagerly-compiled XLA
    executable (``.lower().compile()``), built under a classification
    window so ``source`` says whether it was a fresh compile or a
    persistent-cache (disk) restore. Donating entries restored from disk
    stay quarantined (``donation_checked=False``) until their first
    execution verifies donated write-back against the no-donation twin
    (``safe_aot``); a failed verdict flips ``use_safe`` permanently.
    ``jit_fallback`` routes everything through plain jit dispatch when
    the AOT plane rejects an entry (exotic pytrees, aval drift)."""

    __slots__ = ("fn", "raw_fn", "make_jit", "feed_names", "ro_state_names",
                 "rw_state_names", "out_state_names", "uses_rng",
                 "feed_shardings", "ro_shardings", "rw_shardings",
                 "aot", "safe_aot", "safe_fn", "source", "donation_checked",
                 "use_safe", "jit_fallback")

    def __init__(self, fn, raw_fn, feed_names, ro_state_names, rw_state_names,
                 out_state_names, uses_rng, feed_shardings=None,
                 ro_shardings=None, rw_shardings=None, make_jit=None):
        self.fn = fn
        self.raw_fn = raw_fn
        self.make_jit = make_jit
        self.feed_names = feed_names
        self.ro_state_names = ro_state_names
        self.rw_state_names = rw_state_names
        self.out_state_names = out_state_names
        self.uses_rng = uses_rng
        self.feed_shardings = feed_shardings
        self.ro_shardings = ro_shardings
        self.rw_shardings = rw_shardings
        self.aot = None
        self.safe_aot = None
        self.safe_fn = None
        self.source = None
        self.donation_checked = False
        self.use_safe = False
        self.jit_fallback = False


class RunHandle:
    """Deferred result of :meth:`Executor.run_async`.

    Holds the fetched values as device arrays (jax's async dispatch means
    the computation may still be in flight) plus per-state non-finite
    COUNT scalars for deferred ``check_nan_inf`` — never the written-back
    state arrays themselves, which are donated to the next dispatch and
    deleted on platforms that honor donation. Nothing touches the host
    until :meth:`result` / :meth:`numpy`; the scope write-back already
    happened at dispatch time with device arrays, so consecutive
    dispatches chain on-device without a host round-trip.
    """

    __slots__ = ("fetch_names", "_fetches", "_state_checks", "_check",
                 "_dense", "__weakref__")  # weakref: serving drain registry

    def __init__(self, fetches, fetch_names, state_checks=(),
                 check_nan_inf=False):
        self._fetches = list(fetches)
        self.fetch_names = list(fetch_names)
        self._state_checks = list(state_checks)
        self._check = check_nan_inf
        self._dense = None

    def done(self) -> bool:
        """Non-blocking readiness poll (True for host-resident values)."""
        return all(v.is_ready() for v in self._fetches
                   if isinstance(v, jax.Array))

    def block(self) -> "RunHandle":
        """Wait for device completion without transferring to host."""
        for v in self._fetches:
            if isinstance(v, jax.Array):
                v.block_until_ready()
        return self

    def result(self, return_numpy: bool = True):
        """Resolve the run: blocks on the device values, applies the
        deferred ``check_nan_inf`` scan (fetches AND written-back state,
        the latter via the count scalars computed at dispatch), and
        returns the fetch list — numpy by default, device arrays with
        ``return_numpy=False``."""
        if self._dense is None:
            if self._check:
                for name, counts in self._state_checks:
                    c = np.asarray(counts)
                    if c[0] or c[1]:
                        _raise_nonfinite(name, int(c[0]), int(c[1]))
                for name, val in zip(self.fetch_names, self._fetches):
                    _check_nan_inf(name, val)
            self._dense = [densify(v) for v in self._fetches]
            self._state_checks = []
        if return_numpy:
            return [Executor._fetch_numpy(v) for v in self._dense]
        return list(self._dense)

    def numpy(self):
        return self.result(return_numpy=True)

    def __repr__(self):
        state = "done" if self.done() else "in-flight"
        return f"RunHandle({self.fetch_names}, {state})"


class Executor:
    """Compiles and runs Programs.

    ``check_nan_inf`` mirrors the reference's --check_nan_inf executor flag
    (executor.cc:25,116-124): after each run, fetched values and updated
    state are scanned for non-finite values on the host.
    """

    def __init__(self, place: Optional[TPUPlace] = None,
                 check_nan_inf: Optional[bool] = None, mesh=None, plan=None):
        """``mesh``/``plan`` enable SPMD execution: the whole block is jitted
        with jax.sharding annotations from the parallel.ShardingPlan and XLA
        GSPMD inserts the collectives — the in-graph replacement for the
        reference's pserver / NCCL / MultiGradientMachine paths (SURVEY.md
        §5.8). With a mesh and no plan, a pure data-parallel plan is used.
        """
        from ..flags import FLAGS

        _maybe_enable_compilation_cache()
        _ensure_cache_listener()
        self.place = place or TPUPlace(0)
        self.check_nan_inf = (FLAGS.check_nan_inf if check_nan_inf is None
                              else check_nan_inf)
        if mesh is None and plan is not None:
            mesh = plan.mesh  # Executor(plan=...) — the plan carries it
        self.mesh = mesh
        if mesh is not None and plan is None:
            from ..parallel import data_parallel_plan
            plan = data_parallel_plan(
                mesh, data_axis=mesh.axis_names[0])
        self.plan = plan
        self._cache: Dict[Tuple, _Compiled] = {}
        # Compile-cache observability (the serving warm-path contract:
        # after warmup a steady-state server shows hits only). Counts
        # in-process (program, signature) cache lookups; misses further
        # classify into persistent_hits (executable restored from
        # --compilation_cache_dir) vs fresh_compiles (paid XLA compile) —
        # the cold-start A/B dimension bench_cold_start pins.
        self.cache_hits = 0
        self.cache_misses = 0
        self.persistent_hits = 0
        self.fresh_compiles = 0
        self.donation_fallbacks = 0
        # cumulative seconds inside ``.lower().compile()``, split by
        # source — the goodput plane's fresh_compile bucket deltas
        # fresh_compile_seconds around each run to re-attribute compile
        # wall out of device_compute
        self.compile_seconds = 0.0
        self.fresh_compile_seconds = 0.0
        from .manifest import SignatureManifest

        # every compiled signature is recorded here; engines/trainer
        # persist it next to the artifact for AOT replay on the next boot
        self.manifest = SignatureManifest()

    def cache_stats(self) -> Dict[str, int]:
        """{'hits', 'misses', 'entries', 'persistent_hits',
        'fresh_compiles', 'donation_fallbacks'} of the (program, shapes)
        -> compiled-executable cache. ``misses`` split into disk restores
        (persistent_hits) and real compiles (fresh_compiles); a
        manifest+cache-warm boot shows fresh_compiles == 0."""
        return {"hits": self.cache_hits, "misses": self.cache_misses,
                "entries": len(self._cache),
                "persistent_hits": self.persistent_hits,
                "fresh_compiles": self.fresh_compiles,
                "donation_fallbacks": self.donation_fallbacks}

    # ------------------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        trace_level: Optional[int] = None,
    ):
        """``trace_level`` overrides the global trace level for this run:
        at >= 2 the block is NOT compiled — it executes op-by-op through
        the un-jitted kernel dispatch (``_run_interpreted``), recording a
        span per op with host time and output stats and naming the exact
        op/output var on NaN/Inf. None inherits ``trace.active_level()``
        (seeded from --trace_level)."""
        program = program or prog_mod.default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        scope = scope or global_scope()

        fetch_names = [f.name if hasattr(f, "name") else str(f) for f in fetch_list]
        block = program.global_block

        feed_vals = self._normalize_feeds(block, feed)

        level = trace.active_level() if trace_level is None else trace_level
        if level >= 2 and self._mesh_plan_for(program)[0] is None:
            return self._run_interpreted(program, feed_vals, fetch_names,
                                         scope, return_numpy)

        key = self._cache_key(program, feed_vals, fetch_names, scope)
        compiled = self._cache.get(key)
        cache_hit = compiled is not None
        if compiled is None:
            self.cache_misses += 1
            with trace.span("executor/compile", cache="miss",
                            key=f"{hash(key) & 0xffffffff:08x}",
                            ops=len(block.ops), feeds=len(feed_vals),
                            fetches=len(fetch_names)) as csp:
                compiled = self._compile(program, feed_vals, fetch_names,
                                         scope)
                self._finish_compile(compiled, feed_vals, scope, program,
                                     csp)
            self._cache[key] = compiled
            self._record_signature(program, feed_vals, fetch_names)
        else:
            self.cache_hits += 1
        with trace.span("executor/run",
                        cache="hit" if cache_hit else "miss",
                        key=f"{hash(key) & 0xffffffff:08x}",
                        ops=len(block.ops)):
            return self._run_compiled(compiled, feed_vals, fetch_names,
                                      scope, program, return_numpy)

    # ------------------------------------------------------------------
    def run_async(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        trace_level: Optional[int] = None,
    ) -> RunHandle:
        """Dispatch a run WITHOUT any host synchronisation and return a
        :class:`RunHandle` of device arrays.

        jax's async dispatch does the overlap: the call returns as soon as
        the computation is enqueued; updated persistable state lands back
        in the scope as (possibly still in-flight) device arrays, so the
        next ``run_async`` chains on-device. ``check_nan_inf`` scans are
        deferred to ``handle.result()`` — the only point that touches the
        host. At trace level >= 2 the per-op interpret path runs eagerly
        and the handle comes back already resolved.
        """
        program = program or prog_mod.default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        scope = scope or global_scope()
        fetch_names = [f.name if hasattr(f, "name") else str(f)
                       for f in fetch_list]
        block = program.global_block
        feed_vals = self._normalize_feeds(block, feed)

        level = trace.active_level() if trace_level is None else trace_level
        if level >= 2 and self._mesh_plan_for(program)[0] is None:
            outs = self._run_interpreted(program, feed_vals, fetch_names,
                                         scope, return_numpy=False)
            return RunHandle(outs, fetch_names,
                             check_nan_inf=self.check_nan_inf)

        key = self._cache_key(program, feed_vals, fetch_names, scope)
        compiled = self._cache.get(key)
        cache_hit = compiled is not None
        if compiled is None:
            self.cache_misses += 1
            with trace.span("executor/compile", cache="miss",
                            key=f"{hash(key) & 0xffffffff:08x}",
                            ops=len(block.ops), feeds=len(feed_vals),
                            fetches=len(fetch_names)) as csp:
                compiled = self._compile(program, feed_vals, fetch_names,
                                         scope)
                self._finish_compile(compiled, feed_vals, scope, program,
                                     csp)
            self._cache[key] = compiled
            self._record_signature(program, feed_vals, fetch_names)
        else:
            self.cache_hits += 1
        with trace.span("executor/dispatch",
                        cache="hit" if cache_hit else "miss",
                        key=f"{hash(key) & 0xffffffff:08x}",
                        ops=len(block.ops)):
            fetches, new_states, new_rng = self._call_compiled(
                compiled, feed_vals, scope, program)
            # Write-back of donated state WITHOUT materializing on host:
            # the scope holds the in-flight device arrays directly.
            if new_rng is not None:
                scope.set(RNG_VAR, new_rng)
            checks = []
            for name, val in zip(compiled.out_state_names, new_states):
                scope.set(name, val)
                if self.check_nan_inf:
                    # count non-finites on device NOW, while the array is
                    # still ours: a later dispatch donates it, so the
                    # handle may only keep these scalars
                    counts = _device_nonfinite_counts(val)
                    if counts is not None:
                        checks.append((name, counts))
        return RunHandle(fetches, fetch_names, state_checks=checks,
                         check_nan_inf=self.check_nan_inf)

    def _call_compiled(self, compiled: "_Compiled", feed_vals,
                       scope: Scope, program: Program):
        """Invoke the compiled executable (pure dispatch, no scope
        writes). Returns ``(fetches, new_states, new_rng_or_None)``."""
        feed_args = [feed_vals[n] for n in compiled.feed_names]
        ro_args = [scope.get(n) for n in compiled.ro_state_names]
        rw_args = [scope.get(n) for n in compiled.rw_state_names]
        if compiled.feed_shardings is not None:
            # device_put is a no-op when the array already has the target
            # sharding; otherwise it reshards (e.g. state initialised by a
            # single-device startup run). On a multi-process mesh (DCN
            # plane, parallel/multihost.py) host data destined for
            # non-addressable devices goes through make_array_from_callback
            # — every process provides the full array and keeps only its
            # local shards, the analogue of each reference trainer feeding
            # its slice of the global batch.
            feed_args = [self._put(a, s)
                         for a, s in zip(feed_args, compiled.feed_shardings)]
            ro_args = [self._put(a, s)
                       for a, s in zip(ro_args, compiled.ro_shardings)]
            rw_args = [self._put(a, s)
                       for a, s in zip(rw_args, compiled.rw_shardings)]
        rng = self._rng_state(program, scope) if compiled.uses_rng else None
        if compiled.aot is None and not compiled.jit_fallback:
            # entry compiled lazily (as_function path): classify before
            # the first execution so a restored donating executable never
            # touches real state unverified
            self._finish_compile(compiled, feed_vals, scope, program)
        if not compiled.donation_checked:
            return self._first_restored_donating_call(
                compiled, feed_args, ro_args, rw_args, rng)
        out = self._invoke(compiled, feed_args, ro_args, rw_args, rng)
        return self._unpack(compiled, out)

    @staticmethod
    def _unpack(compiled: "_Compiled", out):
        if compiled.uses_rng:
            fetches, new_states, new_rng = out
            return fetches, new_states, new_rng
        fetches, new_states = out
        return fetches, new_states, None

    def _invoke(self, compiled: "_Compiled", feed_args, ro_args, rw_args,
                rng):
        """Call through the AOT executable (the steady-state fast path);
        an argument-layout rejection falls back to jit dispatch
        permanently for this entry."""
        tail = (rng,) if rng is not None else ()
        if compiled.use_safe:
            fn = compiled.safe_aot
            if fn is None:
                if compiled.safe_fn is None:
                    compiled.safe_fn = compiled.make_jit(False)
                fn = compiled.safe_fn
        else:
            fn = compiled.aot if compiled.aot is not None else compiled.fn
        try:
            return fn(feed_args, ro_args, rw_args, *tail)
        except (TypeError, ValueError) as exc:
            if fn is compiled.fn or fn is compiled.safe_fn:
                raise
            # AOT executables pin exact avals; drift (weak types, exotic
            # pytrees) reroutes through jit, which retraces as needed
            logger.warning(
                "AOT executable rejected the call (%s); falling back to "
                "jit dispatch for this signature", exc)
            compiled.aot = compiled.safe_aot = None
            compiled.jit_fallback = True
            if compiled.use_safe:
                compiled.safe_fn = compiled.make_jit(False)
                return compiled.safe_fn(feed_args, ro_args, rw_args, *tail)
            return compiled.fn(feed_args, ro_args, rw_args, *tail)

    # -- cold-start plane: AOT compile, classification, donation guard ---
    def _platform(self) -> str:
        try:
            return self.place.device().platform
        except Exception:  # noqa: BLE001 - backend probing must not fail
            return jax.default_backend()

    @staticmethod
    def _aval_like(x):
        """Shape/dtype skeleton for AOT lowering (no data touched)."""
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if isinstance(x, (jax.Array, np.ndarray)):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x  # SelectedRows etc.: lower from the concrete value

    def _aval_args(self, compiled: "_Compiled", feed_vals, scope: Scope,
                   program: Program):
        feed_args = [self._aval_like(feed_vals[n])
                     for n in compiled.feed_names]
        ro_args = [self._aval_like(scope.get(n))
                   for n in compiled.ro_state_names]
        rw_args = [self._aval_like(scope.get(n))
                   for n in compiled.rw_state_names]
        args = (feed_args, ro_args, rw_args)
        if compiled.uses_rng:
            args = args + (self._aval_like(
                self._rng_state(program, scope)),)
        return args

    def _aot_compile(self, jitted, args) -> Tuple[Any, bool]:
        """``.lower().compile()`` under a classification window; returns
        (executable, restored_from_disk) and bumps the source counters."""
        from .. import profiler

        t0 = time.perf_counter()
        with _compile_window() as window:
            executable = jitted.lower(*args).compile()
        dt = time.perf_counter() - t0
        self.compile_seconds += dt
        restored = window["persistent_hits"] > 0
        if restored:
            self.persistent_hits += 1
            profiler.global_stat.add_count(
                "executor/compile_cache/persistent_hit", 1)
        else:
            self.fresh_compiles += 1
            self.fresh_compile_seconds += dt
            profiler.global_stat.add_count(
                "executor/compile_cache/fresh_compile", 1)
            profiler.global_stat.add("executor/fresh_compile", dt)
        return executable, restored

    def _finish_compile(self, compiled: "_Compiled", feed_vals,
                        scope: Scope, program: Program, span=None) -> None:
        """Compile the entry's executable NOW (ahead of execution) and
        classify its source. Donating entries restored from the
        persistent cache get their no-donation twin compiled alongside
        and stay quarantined until the first execution verifies donated
        write-back; fresh donating compiles pre-populate the twin's disk
        entry so later boots verify without a fresh compile."""
        from ..flags import FLAGS

        if compiled.aot is not None or compiled.jit_fallback:
            return
        args = self._aval_args(compiled, feed_vals, scope, program)
        try:
            compiled.aot, restored = self._aot_compile(compiled.fn, args)
        except Exception as exc:  # noqa: BLE001 - AOT is an optimisation
            logger.warning("AOT compile failed (%s); using jit dispatch "
                           "for this signature", exc)
            compiled.jit_fallback = True
            compiled.source = "fresh"
            compiled.donation_checked = True
            return
        compiled.source = "persistent" if restored else "fresh"
        if span is not None:
            span.set_attr("source", compiled.source)
        if not compiled.rw_state_names:
            compiled.donation_checked = True  # nothing donated
            return
        platform = self._platform()
        verdict = _read_donation_verdict(platform)
        if verdict is None and platform in _RESTORED_DONATION_DENYLIST:
            # witnessed heap corruption: never probe, go straight to the
            # twin (the conftest-documented NaN bug, now handled here)
            verdict = "broken"
        if not restored or not FLAGS.verify_restored_donation:
            # freshly-built executables handle donation correctly; with a
            # persistent cache active, also land the no-donation twin on
            # disk (unless this backend's restores are known-good) so a
            # future boot's verification/fallback is never a fresh compile
            compiled.donation_checked = True
            if restored or not _pc_enabled() or verdict == "ok":
                return
            try:
                self._aot_compile(compiled.make_jit(False), args)
            except Exception:  # noqa: BLE001 - best-effort prewarm
                pass
            return
        if verdict == "ok":
            compiled.donation_checked = True
            return
        try:
            compiled.safe_aot, _ = self._aot_compile(
                compiled.make_jit(False), args)
        except Exception as exc:  # noqa: BLE001
            logger.warning(
                "no-donation twin failed to compile (%s); restored "
                "executable runs unverified", exc)
            compiled.donation_checked = True
            return
        if verdict == "broken":
            global _denylist_logged

            compiled.use_safe = True
            compiled.donation_checked = True
            self.donation_fallbacks += 1
            from .. import profiler

            profiler.global_stat.add_count(
                "executor/compile_cache/donation_fallback", 1)
            if not _denylist_logged:
                _denylist_logged = True
                logger.warning(
                    "executables restored from the persistent compilation "
                    "cache mishandle donated buffers on %s; cache-restored "
                    "steps run their no-donation twin (bit-identical "
                    "results, one extra state copy per step)", platform)
        # verdict unknown: donation_checked stays False — the first
        # execution runs _first_restored_donating_call

    def _first_restored_donating_call(self, compiled: "_Compiled",
                                      feed_args, ro_args, rw_args, rng):
        """First execution of a disk-restored executable that donates
        state: run the no-donation twin on the REAL state (reference;
        nothing donated, nothing at risk) and the restored donated
        executable on disposable copies, compare the written-back state,
        and persist the verdict. A mismatch — the known CPU jaxlib defect
        where deserialized executables read freed donated buffers —
        permanently reroutes this entry through the twin; the reference
        results are returned either way, so even the probing step is
        correct."""
        from .. import profiler

        tail = (rng,) if rng is not None else ()
        ref = compiled.safe_aot(feed_args, ro_args, rw_args, *tail)
        copies = [self._device_copy(a) for a in rw_args]
        test = None
        try:
            test = compiled.aot(feed_args, ro_args, copies, *tail)
        except Exception as exc:  # noqa: BLE001 - crash == broken
            logger.warning("restored donating executable failed its "
                           "verification run: %s", exc)
        broken = test is None or not _values_close(test, ref)
        compiled.donation_checked = True
        platform = self._platform()
        if broken:
            compiled.use_safe = True
            self.donation_fallbacks += 1
            profiler.global_stat.add_count(
                "executor/compile_cache/donation_fallback", 1)
            logger.warning(
                "executables restored from the persistent compilation "
                "cache mishandle donated buffers on %s; donation disabled "
                "for cache-restored executables (no-donation twin in use)",
                platform)
        _write_donation_verdict(platform, "broken" if broken else "ok")
        trace.record("executor/donation_verify", time.perf_counter(),
                     time.perf_counter(), platform=platform,
                     verdict="broken" if broken else "ok")
        return self._unpack(compiled, ref)

    @staticmethod
    def _device_copy(a):
        """Fresh buffer for a donation probe (np inputs are transferred
        into a new device buffer by the call itself — only live device
        arrays need protecting)."""
        if isinstance(a, jax.Array):
            return jnp.array(a)
        return a

    def _record_signature(self, program: Program, feed_vals,
                          fetch_names) -> None:
        from . import manifest as manifest_mod

        feeds = [(n, tuple(int(d) for d in v.shape), str(np.dtype(v.dtype)))
                 for n, v in feed_vals.items()
                 if hasattr(v, "shape") and hasattr(v, "dtype")]
        self.manifest.record(manifest_mod.program_digest(program), feeds,
                             list(fetch_names))

    def warm_signature(self, program: Program, feeds: Dict[str, tuple],
                       fetch_names: Sequence[str],
                       scope: Optional[Scope] = None) -> bool:
        """AOT-compile one (program, feed-signature) into the in-process
        cache WITHOUT executing anything: ``.lower().compile()`` of the
        whole block from shape/dtype skeletons. ``feeds`` maps feed name
        -> (shape, dtype). Returns True when a new executable was
        compiled, False when the signature was already warm. This is the
        boot path behind manifest replay (core.manifest.replay /
        engine.warm_start / SGD.train resume): with a persistent cache
        the compile is a disk restore, and the first real request/step is
        a pure in-process hit."""
        import ml_dtypes  # noqa: F401 — registers bfloat16/fp8 dtype names

        program = program or prog_mod.default_main_program()
        scope = scope or global_scope()
        block = program.global_block
        feed_vals = {
            name: jax.ShapeDtypeStruct(tuple(int(d) for d in shape),
                                       np.dtype(dtype))
            for name, (shape, dtype) in feeds.items()}
        fetch_names = list(fetch_names)
        if any(op_uses_rng(get_op(op.type), op.attrs) for op in block.ops):
            # seed the scope RNG plane BEFORE keying, so the scope key set
            # matches live traffic (the GenerationEngine.warmup contract)
            self._rng_state(program, scope)
        key = self._cache_key(program, feed_vals, fetch_names, scope)
        compiled = self._cache.get(key)
        if compiled is not None:
            if compiled.aot is None and not compiled.jit_fallback:
                self._finish_compile(compiled, feed_vals, scope, program)
            return False
        self.cache_misses += 1
        with trace.span("executor/compile", cache="miss", mode="aot_warm",
                        key=f"{hash(key) & 0xffffffff:08x}",
                        ops=len(block.ops), feeds=len(feed_vals),
                        fetches=len(fetch_names)) as csp:
            compiled = self._compile(program, feed_vals, fetch_names, scope)
            self._finish_compile(compiled, feed_vals, scope, program, csp)
        self._cache[key] = compiled
        self._record_signature(program, feed_vals, fetch_names)
        return True

    def _run_compiled(self, compiled: "_Compiled", feed_vals, fetch_names,
                      scope: Scope, program: Program, return_numpy: bool):
        fetches, new_states, new_rng = self._call_compiled(
            compiled, feed_vals, scope, program)
        if new_rng is not None:
            scope.set(RNG_VAR, new_rng)
        for name, val in zip(compiled.out_state_names, new_states):
            if self.check_nan_inf:
                _check_nan_inf(name, val)
            scope.set(name, val)
        if self.check_nan_inf:
            for name, val in zip(fetch_names, fetches):
                _check_nan_inf(name, val)
        if return_numpy:
            return [self._fetch_numpy(densify(v)) for v in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _run_interpreted(self, program: Program, feed_vals, fetch_names,
                         scope: Scope, return_numpy: bool = True):
        """Per-op debug execution (trace_level=2): walk the block and
        dispatch each kernel eagerly through the registry — the
        reference's per-op interpreter loop (executor.cc:112-125),
        deliberately revived for observability. Each op records a span
        with host wall time and output stats, and a non-finite output
        raises immediately naming the exact op, its callsite, and the
        output variable — upgrading --check_nan_inf's "a variable is
        bad" to a located diagnosis. Orders of magnitude slower than the
        compiled path; never use it for serving traffic."""
        block = program.global_block
        ops = list(block.ops)
        env: Dict[str, Any] = dict(feed_vals)
        state_read: set = set()
        rng = None
        uses_rng = any(op_uses_rng(get_op(op.type), op.attrs) for op in ops)
        if uses_rng:
            rng = self._rng_state(program, scope)
        with trace.span("executor/interpret", ops=len(ops),
                        feeds=len(feed_vals), fetches=len(fetch_names)):
            for op_index, op in enumerate(ops):
                opdef = get_op(op.type)
                ins = {}
                for slot, names in op.inputs.items():
                    if not names:
                        continue
                    vals = []
                    for name in names:
                        if name in env:
                            vals.append(env[name])
                        elif scope.has(name):
                            state_read.add(name)
                            env[name] = scope.get(name)
                            vals.append(env[name])
                        else:
                            raise RuntimeError(
                                f"op {op.type!r} input {slot}={name!r} is "
                                f"neither a feed, produced by a prior op, "
                                f"nor present in the scope. Did you forget "
                                f"to run the startup program? "
                                f"(paddle_tpu.analysis.check_program / "
                                f"tools/proglint.py locate dangling "
                                f"inputs statically)")
                    ins[slot] = vals
                t0 = time.perf_counter()
                try:
                    if opdef.special:
                        outs = opdef.fn(op.attrs, ins, executor=self,
                                        env=env, op=op, program=program,
                                        scope=scope)
                    elif op_uses_rng(opdef, op.attrs):
                        rng, sub = jax.random.split(rng)
                        outs = opdef.fn(op.attrs, ins, rng=sub)
                    elif callable(opdef.needs_rng):
                        outs = opdef.fn(op.attrs, ins, rng=None)
                    else:
                        outs = opdef.fn(op.attrs, ins)
                except EnforceError:
                    raise
                except Exception as exc:
                    raise op_error(op, op_index, ins, exc) from exc
                produced = []
                if outs:
                    for slot, names in op.outputs.items():
                        if slot not in outs:
                            continue
                        for name, val in zip(names, outs[slot]):
                            env[name] = val
                            produced.append((slot, name, val))
                # host time includes device completion: the stats readback
                # below blocks on the outputs, so the span closes after
                # the op's device work — per-op device-inclusive timing.
                stats = {name: _value_stats(val)
                         for _, name, val in produced}
                t1 = time.perf_counter()
                trace.record(
                    f"op/{op.type}", t0, t1,
                    parent=trace.current_span(), op_index=op_index,
                    callsite=op.attrs.get("_callsite"), outputs=stats)
                for slot, name, val in produced:
                    bad = _nonfinite_counts(val)
                    if bad is None:
                        continue
                    # NaN is never legitimate; Inf can be (top-k/beam
                    # masking emits -inf by design), so Inf-only outputs
                    # raise only under the strict --check_nan_inf mode.
                    if bad[0] == 0 and not self.check_nan_inf:
                        continue
                    site = op.attrs.get("_callsite")
                    raise FloatingPointError(
                        f"op #{op_index} {op.type!r}"
                        + (f" (created at {site})" if site else "")
                        + f" produced NaN/Inf in output {slot}="
                        f"{name!r}: {bad[0]} NaN, {bad[1]} Inf "
                        f"(inputs: "
                        + ", ".join(f"{s}={list(n)}" for s, n in
                                    op.inputs.items() if n)
                        + ")")
            # write-back contract matches the compiled path: persistable
            # outputs and state read from the scope land back in the scope
            for op in ops:
                for name in op.output_names():
                    if name not in env:
                        continue
                    is_persist = (block.has_var(name)
                                  and block.var(name).persistable)
                    if is_persist or name in state_read:
                        scope.set(name, env[name])
            if uses_rng:
                scope.set(RNG_VAR, rng)
            fetches = []
            for name in fetch_names:
                if name in env:
                    fetches.append(env[name])
                elif scope.has(name):
                    fetches.append(scope.get(name))
                else:
                    raise RuntimeError(
                        f"fetch variable {name!r} is never produced")
        if return_numpy:
            return [self._fetch_numpy(densify(v)) for v in fetches]
        return list(fetches)

    @staticmethod
    def _fetch_numpy(v):
        """np.asarray that also handles multi-process global arrays whose
        local shards cover the full value (replicated or intra-process
        sharded axes — the fetch contract on the DCN plane)."""
        if not isinstance(v, jax.Array) or v.is_fully_addressable:
            return np.asarray(v)
        out = np.zeros(v.shape, v.dtype)
        seen = np.zeros(v.shape, bool)
        for sh in v.addressable_shards:
            out[sh.index] = np.asarray(sh.data)
            seen[sh.index] = True
        if not seen.all():
            raise ValueError(
                "fetched value is not fully recoverable on this process; "
                "fetch replicated values or gather explicitly")
        return out

    # ------------------------------------------------------------------
    def as_function(self, program: Program, feed: Dict[str, Any],
                    fetch_list: Sequence, scope: Optional[Scope] = None):
        """Export a program block as a pure jittable function.

        Returns ``(fn, example_args)`` where ``fn(feed_args, ro_state,
        rw_state[, rng])`` is the untraced closure over the block (suitable
        for jax.jit / embedding in larger JAX programs) and ``example_args``
        are concrete arrays drawn from ``feed`` and the scope.
        """
        scope = scope or global_scope()
        feed_vals = self._normalize_feeds(program.global_block, feed)
        fetch_names = [f.name if hasattr(f, "name") else str(f)
                       for f in fetch_list]
        key = self._cache_key(program, feed_vals, fetch_names, scope)
        compiled = self._cache.get(key)
        if compiled is None:
            self.cache_misses += 1
            compiled = self._compile(program, feed_vals, fetch_names, scope)
            self._cache[key] = compiled
            self._record_signature(program, feed_vals, fetch_names)
        else:
            self.cache_hits += 1
        args = (
            [feed_vals[n] for n in compiled.feed_names],
            [scope.get(n) for n in compiled.ro_state_names],
            [scope.get(n) for n in compiled.rw_state_names],
        )
        if compiled.uses_rng:
            args = args + (self._rng_state(program, scope),)
        return compiled.raw_fn, args

    # ------------------------------------------------------------------
    @staticmethod
    def _put(a, sharding):
        if isinstance(a, jax.Array):
            # device_put reshards device arrays, including global->global
            # on a multi-process mesh (no-op when already right).
            return jax.device_put(a, sharding)
        if sharding.is_fully_addressable:
            return jax.device_put(a, sharding)
        arr = np.asarray(a)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    # ------------------------------------------------------------------
    @staticmethod
    def _normalize_feeds(block, feed: Dict[str, Any]) -> Dict[str, Any]:
        """Normalise feeds to device-dtype arrays. Feeds that are already
        device-resident jax.Arrays of the right dtype pass through without a
        host round-trip (on-device input pipelines depend on this)."""
        feed_vals = {}
        for name, value in feed.items():
            dtype = block.var(name).dtype if block.has_var(name) else None
            if isinstance(value, jax.Array) and (
                    dtype is None or value.dtype == dtype):
                feed_vals[name] = value
            else:
                feed_vals[name] = np.asarray(value, dtype=dtype)
        return feed_vals

    def _cache_key(self, program: Program, feed_vals, fetch_names,
                   scope: Scope) -> Tuple:
        from ..ops import common as ops_common

        feed_sig = tuple(sorted((n, v.shape, str(v.dtype))
                                for n, v in feed_vals.items()))
        # The data-flow classification depends on which names exist in the
        # scope (state inputs), so the set of scope keys is part of the key —
        # as are the global dtype policies (AMP / MXU precision) and the
        # mesh/plan, all of which change the traced computation. The key
        # set is memoized inside the Scope per key-set version: a training
        # step rewrites existing names, which does not bump the version,
        # so the steady-state path hashes a cached frozenset instead of
        # rebuilding an O(#params) set every run.
        scope_keys = scope.key_set() if hasattr(scope, "key_set") \
            else frozenset(self._all_scope_keys(scope))
        return (id(program), program.version, feed_sig, tuple(fetch_names),
                id(scope), scope_keys, ops_common.amp_enabled(),
                ops_common.mxu_precision(),
                self._sharding_key(program))

    # ------------------------------------------------------------------
    def _mesh_plan_for(self, program: Program):
        """(mesh, plan) for one program: the executor's own mesh/plan
        wins; otherwise a ShardProgram-annotated program
        (``program.sharding_plan`` over a real device mesh) makes ANY
        executor lower it sharded — the one-sharding-plane contract."""
        if self.mesh is not None:
            return self.mesh, self.plan
        plan = getattr(program, "sharding_plan", None)
        if plan is not None and getattr(plan.mesh, "devices", None) \
                is not None:
            return plan.mesh, plan
        return None, None

    def _sharding_key(self, program: Program):
        """Content key of the (mesh, plan) pair: mesh axes + device ids
        + the plan's rule digest. Two equivalent plans built
        independently (a fresh ``megatron_plan(mesh)`` per boot/request)
        key identically, so serving steady state stays at zero
        recompiles — ``id(plan)`` would thrash the cache."""
        mesh, plan = self._mesh_plan_for(program)
        if mesh is None:
            return None
        return (tuple(mesh.axis_names),
                tuple(int(s) for s in mesh.devices.shape),
                tuple(int(d.id) for d in mesh.devices.flat),
                plan.digest() if plan is not None else None)

    @staticmethod
    def _all_scope_keys(scope: Scope):
        s = scope
        while s is not None:
            yield from s.keys()
            s = s.parent

    def _rng_state(self, program: Program, scope: Scope):
        if not scope.has(RNG_VAR):
            from ..flags import FLAGS

            seed = (program.random_seed if program.random_seed is not None
                    else FLAGS.seed)
            scope.set(RNG_VAR, jax.random.PRNGKey(seed))
        return scope.get(RNG_VAR)

    def _compile(self, program: Program, feed_vals, fetch_names, scope: Scope) -> _Compiled:
        block = program.global_block
        feed_names = sorted(feed_vals)

        # Classify data flow: which op inputs come from the scope (state) and
        # which persistables get (re)written and must flow back out.
        produced = set(feed_names)
        state_names: List[str] = []
        state_set = set()
        written_persist: List[str] = []
        written_set = set()
        uses_rng = False
        for op in block.ops:
            opdef = get_op(op.type)
            if op_uses_rng(opdef, op.attrs):
                uses_rng = True
            for slot, names in op.inputs.items():
                for name in names:
                    if name in produced or name in state_set:
                        continue
                    if scope.has(name):
                        state_set.add(name)
                        state_names.append(name)
                    else:
                        raise RuntimeError(
                            f"op {op.type!r} input {slot}={name!r} is neither a feed, "
                            f"produced by a prior op, nor present in the scope. "
                            f"Did you forget to run the startup program? "
                            f"(paddle_tpu.analysis.check_program / "
                            f"tools/proglint.py locate dangling inputs "
                            f"statically)"
                        )
            for name in op.output_names():
                produced.add(name)
                is_persistable = block.has_var(name) and block.var(name).persistable
                if (is_persistable or name in state_set) and name not in written_set:
                    written_set.add(name)
                    written_persist.append(name)
        for name in fetch_names:
            if name not in produced and not scope.has(name):
                raise RuntimeError(f"fetch variable {name!r} is never produced")
        # Fetches resident only in the scope become state inputs.
        for name in fetch_names:
            if name not in produced and name not in state_set:
                state_set.add(name)
                state_names.append(name)

        # Split state inputs: written-back ones are donated to XLA (in-place
        # buffer update); read-only ones must NOT be donated or the arrays
        # still referenced by the scope would be invalidated.
        rw_state = [n for n in state_names if n in written_set]
        ro_state = [n for n in state_names if n not in written_set]

        ops = list(block.ops)
        mesh, plan = self._mesh_plan_for(program)

        def run_traced(feed_args, ro_args, rw_args, rng=None):
            from ..parallel.context import mesh_context

            with mesh_context(mesh):
                return _run_body(feed_args, ro_args, rw_args, rng)

        def _run_body(feed_args, ro_args, rw_args, rng=None):
            env: Dict[str, jax.Array] = {}
            env.update(zip(feed_names, feed_args))
            env.update(zip(ro_state, ro_args))
            env.update(zip(rw_state, rw_args))
            for op_index, op in enumerate(ops):
                opdef = get_op(op.type)
                ins = {
                    slot: [env[n] for n in names]
                    for slot, names in op.inputs.items()
                    if names
                }
                try:
                    if opdef.special:
                        outs = opdef.fn(op.attrs, ins, executor=self, env=env,
                                        op=op, program=program, scope=scope)
                    elif op_uses_rng(opdef, op.attrs):
                        rng, sub = jax.random.split(rng)
                        outs = opdef.fn(op.attrs, ins, rng=sub)
                    elif callable(opdef.needs_rng):
                        outs = opdef.fn(op.attrs, ins, rng=None)
                    else:
                        outs = opdef.fn(op.attrs, ins)
                except EnforceError:
                    raise  # already carries op context (nested blocks)
                except Exception as exc:
                    # CustomStackTrace analogue: report the failing op, its
                    # input signature, and the user line that created it.
                    raise op_error(op, op_index, ins, exc) from exc
                if outs:
                    for slot, names in op.outputs.items():
                        if slot not in outs:
                            continue
                        vals = outs[slot]
                        for name, val in zip(names, vals):
                            env[name] = val
            fetches = [env[n] for n in fetch_names]
            new_states = [env[n] for n in written_persist]
            if rng is None:
                return fetches, new_states
            return fetches, new_states, rng

        feed_sh = ro_sh = rw_sh = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..parallel.plan import spec_axes

            mesh_axes = set(mesh.axis_names)

            def _shape_of(name):
                v = block.var(name) if block.has_var(name) else None
                if v is not None and v.shape is not None:
                    return tuple(v.shape)
                val = scope.get(name) if scope.has(name) \
                    else feed_vals.get(name)
                try:
                    return tuple(np.shape(val))
                except Exception:  # SelectedRows-class pytrees
                    return None

            def _annotated(name):
                # a ShardProgram annotation wins over the plan rules —
                # but only when every axis it names exists on THIS mesh
                # (stale annotations from another plan never leak in)
                v = block.var(name) if block.has_var(name) else None
                sp = getattr(v, "sharding", None) if v is not None else None
                if sp is not None and all(ax in mesh_axes
                                          for ax in spec_axes(sp)):
                    return NamedSharding(mesh, sp)
                return None

            def _feed_sharding(name):
                sp = _annotated(name)
                if sp is not None:
                    return sp
                shape = _shape_of(name)
                return plan.feed_sharding(
                    name, len(shape) if shape is not None else 0)

            def _state_sharding(name):
                sp = _annotated(name)
                if sp is not None:
                    return sp
                shape = _shape_of(name)
                ndim = len(shape) if shape is not None else 0
                if shape is not None and any(int(d) < 0 for d in shape):
                    shape = None  # symbolic batch: no divisibility check
                return plan.state_sharding(name, ndim, shape=shape)

            feed_sh = [_feed_sharding(n) for n in feed_names]
            ro_sh = [_state_sharding(n) for n in ro_state]
            rw_sh = [_state_sharding(n) for n in rw_state]
            replicated = NamedSharding(mesh, PartitionSpec())
            in_shardings = (feed_sh, ro_sh, rw_sh)
            # written-back state must LAND with the plan's shardings (not
            # whatever GSPMD propagates — e.g. a ZeRO-sharded accumulator
            # feeding a momentum update would otherwise leak its dp
            # sharding into the updated parameter); fetches stay
            # unconstrained (None = compiler's choice)
            ws_sh = [_state_sharding(n) for n in written_persist]
            out_shardings = ([None] * len(fetch_names), ws_sh)
            if uses_rng:
                in_shardings = in_shardings + (replicated,)
                out_shardings = out_shardings + (replicated,)

            def make_jit(donate: bool = True):
                return jax.jit(run_traced,
                               donate_argnums=(2,) if donate else (),
                               in_shardings=in_shardings,
                               out_shardings=out_shardings)
        else:
            def make_jit(donate: bool = True):
                return jax.jit(run_traced,
                               donate_argnums=(2,) if donate else ())
        jitted = make_jit(True)
        logger.debug(
            "compiled block: %d ops, %d feeds, %d state vars, %d outputs",
            len(ops), len(feed_names), len(state_names), len(fetch_names),
        )
        return _Compiled(jitted, run_traced, feed_names, ro_state, rw_state,
                         written_persist, uses_rng, feed_sh, ro_sh, rw_sh,
                         make_jit=make_jit)

    def close(self):
        self._cache.clear()

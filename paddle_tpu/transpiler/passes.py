"""The standard pass library.

Program-to-program rewrites in the lineage of the reference's inference
transpiler (`inference_optimize`/`prune.cc`, conv+BN folding, dropout
stripping), adapted to this repo's IR and whole-block-XLA execution:

- ``ExpandRecomputeSegments`` — flatten composite ``seg_fwd`` ops back to
  their plain forward ops (checkpointing is a training concern; flat op
  lists stay consumable by every backend, including the native C machine).
- ``CanonicalizeIsTest`` — flip every ``is_test`` attr to True (the
  reference's Program.clone(for_test=True) as a reusable pass).
- ``DropoutToScale`` — inference dropout is downscale-in-infer
  (ops/nn_ops.py multiplies by ``1-p`` at test time), so the rewrite
  emits a ``scale`` op rather than deleting: token-exact vs the
  untranspiled is_test program.
- ``DeadOpElimination`` — backward slice from the fetch targets (the
  reversed walk that used to live inlined in ``io.prune_program``).
- ``ConstantFolding`` — evaluate feed-independent subgraphs once at
  transpile time via the kernel registry; results land in the scope as
  new persistable vars.
- ``FoldBatchNorm`` — fold an inference batch_norm's affine + running
  stats into the preceding conv2d filter / mul weight and a bias add
  (the classic inference-transpiler win). Optionally lowers the fused
  ``conv1x1_bn_act`` op back to folded conv2d + add (+relu) for
  portable/int8 deployment.
- ``FusePatterns`` — rewrite ``conv2d→batch_norm[→elementwise_add]→relu``
  chains into the fused ``conv1x1_bn_act`` epilogue op and primitive
  ``matmul→[scale]→softmax→matmul`` attention subgraphs into the
  flash-attention-backed ``scaled_dot_product_attention`` op.

Every structural rewrite stamps provenance attrs (``__fused_from__`` /
``__folded_from__``) so transpiled programs explain themselves in dumps
and survive ``program_to_dict`` round-trips.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.program import Block, Operator, Program
from ..core.registry import get_op, has_op, op_uses_rng
from .framework import Pass, PassContext, register_pass

SEG_ATTR = "__recompute_seg__"


def _drop_unused_vars(program: Program, ctx: PassContext) -> None:
    """Drop global-block vars referenced by no op in ANY block (sub-block
    ops read outer vars through the ancestor chain, so usage must be
    collected program-wide). Feeds/fetches always survive."""
    used = set(ctx.feed_names) | set(ctx.fetch_names)
    for b in program.blocks:
        for op in b.ops:
            used.update(op.input_names())
            used.update(op.output_names())
    gb = program.global_block
    for n in [n for n in gb.vars if n not in used]:
        del gb.vars[n]


def _same_segment(*ops: Operator) -> bool:
    """Fusing across recompute-segment boundaries would regroup the
    backward's composite vjp — only rewrite chains wholly inside one
    segment (or wholly outside any)."""
    segs = {op.attrs.get(SEG_ATTR) for op in ops}
    return len(segs) == 1


# --------------------------------------------------------------------------
@register_pass
class ExpandRecomputeSegments(Pass):
    """Inline ``seg_fwd`` composites back into their plain forward ops.

    Must be followed by DCE before the program is executed again: the
    paired ``grad_seg`` ops (if any survive) reference the vjp closure
    only a live ``seg_fwd`` stashes.
    """

    name = "expand_recompute_segments"

    def apply(self, program: Program, ctx: PassContext) -> None:
        for block in program.blocks:
            if not any(op.type == "seg_fwd" for op in block.ops):
                continue
            flat: List[Operator] = []
            for op in block.ops:
                if op.type != "seg_fwd":
                    flat.append(op)
                    continue
                for sop in op.attrs["seg_ops"]:
                    flat.append(Operator(block, sop["type"], sop["ins"],
                                         sop["outs"], dict(sop["attrs"])))
            block.ops = flat
            program._bump()


# --------------------------------------------------------------------------
@register_pass
class CanonicalizeIsTest(Pass):
    """Flip every op-level ``is_test`` attr to True (inference
    canonicalization; Program.clone(for_test=True) semantics)."""

    name = "canonicalize_is_test"

    def apply(self, program: Program, ctx: PassContext) -> None:
        for block in program.blocks:
            for op in block.ops:
                if "is_test" in op.attrs and not op.attrs["is_test"]:
                    op.attrs = dict(op.attrs)
                    op.attrs["is_test"] = True
                    program._bump()


# --------------------------------------------------------------------------
@register_pass
class DropoutToScale(Pass):
    """Rewrite inference-mode dropout to an explicit ``scale`` op.

    This repo's dropout is downscale-in-infer: the test-mode kernel
    multiplies by ``(1 - p)`` (ops/nn_ops.py), so deleting the op — the
    folk transpiler move — would change the math. Emitting
    ``scale(scale=1-p)`` is token-exact with the untranspiled is_test
    program while freeing the executor from threading RNG state through
    a program that no longer draws randomness.
    """

    name = "dropout_to_scale"

    def apply(self, program: Program, ctx: PassContext) -> None:
        fetches = set(ctx.fetch_names)
        for block in program.blocks:
            consumers = block.var_consumers()
            for op in list(block.ops):
                if op.type != "dropout" or not op.attrs.get("is_test"):
                    continue
                mask = op.output("Mask")
                if mask and (mask in fetches or consumers.get(mask)):
                    continue  # someone reads the mask; keep the real op
                p = op.attrs.get("dropout_prob", 0.5)
                block.replace_ops(
                    [op], "scale",
                    {"X": [op.input("X")]}, {"Out": [op.output("Out")]},
                    {"scale": 1.0 - p, "bias": 0.0,
                     "bias_after_scale": True,
                     "__rewritten_from__": "dropout"})
        _drop_unused_vars(program, ctx)


# --------------------------------------------------------------------------
@register_pass
class DeadOpElimination(Pass):
    """Backward slice from the fetch targets: keep exactly the ops whose
    outputs are (transitively) needed, drop everything else — optimizer
    updates, loss branches, metrics. With ``ctx.preserve_state_writes``
    ops that write a scope-resident name (KV-cache updates and other
    unfetched state) count as roots too.
    """

    name = "dead_op_elimination"

    def apply(self, program: Program, ctx: PassContext) -> None:
        block = program.global_block
        needed = set(ctx.fetch_names)
        if ctx.preserve_state_writes and ctx.scope is not None:
            for op in block.ops:
                needed.update(n for n in op.output_names()
                              if ctx.scope.has(n))
        feeds = set(ctx.feed_names)
        keep: List[Operator] = []
        for op in reversed(block.ops):
            if any(o in needed for o in op.output_names()):
                keep.append(op)
                needed.update(n for n in op.input_names() if n not in feeds)
        keep.reverse()
        if len(keep) != len(block.ops):
            block.ops = keep
            program._bump()
        _drop_unused_vars(program, ctx)


# --------------------------------------------------------------------------
@register_pass
class ConstantFolding(Pass):
    """Evaluate feed-independent subgraphs once at transpile time.

    Constant sources are literal generators (ops with no inputs, e.g.
    ``fill_constant``) and — when ``fold_params`` and a scope is given —
    persistable parameters the program never writes (weights are frozen
    at transpile time; the inference-pipeline premise). Folded values
    are written into the scope as new persistable vars; the executor
    then reads them as read-only state, and ``save_inference_model``
    persists them like any other parameter.

    Foldable ops are evaluated through the SAME kernel registry the
    executor traces, so a folded subgraph computes exactly what the
    compiled program would have.
    """

    name = "constant_fold"

    def __init__(self, fold_params: bool = True,
                 max_elems: int = 1 << 22):
        self.fold_params = fold_params
        self.max_elems = max_elems

    def apply(self, program: Program, ctx: PassContext) -> None:
        scope = ctx.scope
        if scope is None:
            ctx.note("constant_fold: skipped (no scope to hold results)")
            return
        import jax.numpy as jnp

        block = program.global_block
        written = {n for b in program.blocks for op in b.ops
                   for n in op.output_names()}
        fetches = set(ctx.fetch_names)
        feeds = set(ctx.feed_names)

        const: Dict[str, object] = {}
        if self.fold_params:
            for name, v in block.vars.items():
                if (v.persistable and not v.is_data and name not in written
                        and name not in feeds and scope.has(name)):
                    const[name] = scope.get(name)

        folded: Dict[str, object] = {}
        new_ops: List[Operator] = []
        for op in block.ops:
            if not self._try_fold(op, block, const, folded, fetches, jnp):
                new_ops.append(op)
                # outputs of a live op are runtime values, never constants
                for n in op.output_names():
                    const.pop(n, None)
                    folded.pop(n, None)
        if len(new_ops) == len(block.ops):
            return
        block.ops = new_ops
        # materialize only the folded values something still reads —
        # sub-block ops (while/cond bodies) read outer names too
        live = set(fetches)
        for b in program.blocks:
            for op in (new_ops if b is block else b.ops):
                live.update(op.input_names())
        for name, val in folded.items():
            if name not in live:
                continue
            scope.set(name, val)
            if name in block.vars:
                v = block.vars[name]
                v.persistable = True
                v.stop_gradient = True
        program._bump()
        _drop_unused_vars(program, ctx)

    # ------------------------------------------------------------------
    def _try_fold(self, op: Operator, block: Block, const: dict,
                  folded: dict, fetches: set, jnp) -> bool:
        if not has_op(op.type):
            return False
        opdef = get_op(op.type)
        if opdef.special or op_uses_rng(opdef, op.attrs):
            return False
        in_names = op.input_names()
        if not all(n in const for n in in_names):
            return False
        out_names = op.output_names()
        for n in out_names:
            if n in fetches or n in in_names:
                return False  # fetch roots / in-place state aliases stay
            if n in const and n not in folded:
                return False  # would clobber a live scope entry
            v = block.vars.get(n)
            if v is not None and v.shape is not None and -1 in v.shape:
                return False  # batch-dependent by declaration
        ins = {slot: [jnp.asarray(const[n]) for n in names]
               for slot, names in op.inputs.items() if names}
        try:
            if callable(opdef.needs_rng):
                outs = opdef.fn(op.attrs, ins, rng=None)
            else:
                outs = opdef.fn(op.attrs, ins)
        except Exception:
            return False  # keep the op; folding is best-effort
        vals = {}
        for slot, names in op.outputs.items():
            for name, val in zip(names, outs.get(slot, [])):
                if getattr(val, "size", self.max_elems + 1) > self.max_elems:
                    return False
                vals[name] = val
        if set(vals) != set(out_names):
            return False  # kernel returned fewer slots than the op declares
        for name, val in vals.items():
            const[name] = val
            folded[name] = val
        return True


# --------------------------------------------------------------------------
def _bn_affine(scope, bn_op: Operator):
    """(k, b) with y = x*k + b from a BN op's parameters/running stats:
    k = gamma * rsqrt(var + eps), b = beta - mean*k (f32, matching the
    kernel's compute dtype)."""
    eps = np.float32(bn_op.attrs.get("epsilon", 1e-5))
    g = scope.get_numpy(bn_op.input("Scale")).astype(np.float32)
    beta = scope.get_numpy(bn_op.input("Bias")).astype(np.float32)
    mean = scope.get_numpy(bn_op.input("Mean")).astype(np.float32)
    var = scope.get_numpy(bn_op.input("Variance")).astype(np.float32)
    k = g / np.sqrt(var + eps)
    return k, beta - mean * k


def _weight_out_axis(op: Operator, w_shape) -> Optional[int]:
    """Output-channel axis of the producer's weight, or None if this
    producer/layout combination is not foldable."""
    if op.type in ("conv2d", "depthwise_conv2d"):
        fmt = op.attrs.get("data_format", "NCHW")
        if len(w_shape) != 4:
            return None
        return 3 if fmt == "NHWC" else 0  # HWIO vs OIHW
    if op.type == "mul":
        if len(w_shape) == 2 and op.attrs.get("y_num_col_dims", 1) == 1:
            return 1
        return None
    return None


@register_pass
class FoldBatchNorm(Pass):
    """Fold inference batch_norm into the preceding conv2d/mul weights.

    Matches ``{conv2d|depthwise_conv2d|mul} [→ elementwise_add(bias)] →
    batch_norm(is_test=True)`` where the intermediate activations have a
    single consumer and the weight lives in the scope. The weight is
    scaled per output channel (W' = W·k) under a NEW name — the caller's
    original tensors are never mutated — and the batch_norm collapses to
    one per-channel bias add (b = beta − mean·k, plus any pre-existing
    bias folded through).

    ``lower_fused=True`` additionally lowers inference ``conv1x1_bn_act``
    ops to folded conv2d + bias add (+residual add, +relu) — the
    portable/int8 deployment form, where a plain conv2d filter is
    eligible for weight-only quantization and the native C machine's
    simplest kernels apply.
    """

    name = "fold_batch_norm"

    def __init__(self, lower_fused: bool = False):
        self.lower_fused = lower_fused

    def apply(self, program: Program, ctx: PassContext) -> None:
        scope = ctx.scope
        if scope is None:
            ctx.note("fold_batch_norm: skipped (no scope with weights)")
            return
        block = program.global_block
        written = {n for op in block.ops for n in op.output_names()}
        changed = True
        while changed:
            changed = False
            producers = block.var_producers()
            consumers = block.var_consumers()
            for op in list(block.ops):
                if (op.type == "batch_norm" and op.attrs.get("is_test")
                        and self._fold_bn(block, op, producers, consumers,
                                          written, scope, ctx)):
                    changed = True
                    break
                if (self.lower_fused and op.type == "conv1x1_bn_act"
                        and op.attrs.get("is_test")
                        and self._lower_fused(block, op, consumers, scope,
                                              ctx)):
                    changed = True
                    break
        _drop_unused_vars(program, ctx)

    # ------------------------------------------------------------------
    @staticmethod
    def _aux_outputs_unused(op: Operator, consumers, skip_slots=("Y",)):
        for slot, names in op.outputs.items():
            if slot in skip_slots:
                continue
            for n in names:
                for _, c in consumers.get(n, []):
                    if c is not op:
                        return False
        return True

    def _new_param(self, block: Block, scope, base: str, value: np.ndarray):
        import jax.numpy as jnp

        name = base
        i = 0
        while name in block.vars or scope.has(name):
            i += 1
            name = f"{base}{i}"
        block.create_parameter(name=name, shape=value.shape,
                               dtype=str(value.dtype), trainable=False)
        # device-resident: a numpy weight would re-upload on EVERY run
        scope.set(name, jnp.asarray(value))
        return name

    def _fold_bn(self, block: Block, bn: Operator, producers, consumers,
                 written, scope, ctx: PassContext) -> bool:
        x = bn.input("X")
        if x in ctx.fetch_names or len(consumers.get(x, [])) != 1:
            return False
        stat_names = [bn.input(s) for s in ("Scale", "Bias", "Mean",
                                            "Variance")]
        if any(n is None or not scope.has(n) for n in stat_names):
            return False
        p = block.sole_producer(x, producers)
        if p is None:
            return False
        if not self._aux_outputs_unused(bn, consumers):
            return False
        add_op, prior_bias = None, None
        base = p
        if p.type == "elementwise_add":
            bias_name = p.input("Y")
            mid = p.input("X")
            if (bias_name is None or mid is None or not scope.has(bias_name)
                    or bias_name in written
                    or np.ndim(scope.get(bias_name)) != 1
                    or mid in ctx.fetch_names
                    or len(consumers.get(mid, [])) != 1):
                return False
            base = block.sole_producer(mid, producers)
            if base is None:
                return False
            add_op, prior_bias = p, scope.get_numpy(bias_name)
        w_slot = "Filter" if base.type != "mul" else "Y"
        w_name = base.input(w_slot) if base.inputs.get(w_slot) else None
        if w_name is None or not scope.has(w_name) or w_name in written:
            return False
        w = scope.get_numpy(w_name)
        axis = _weight_out_axis(base, w.shape)
        if axis is None:
            return False
        fmt = base.attrs.get("data_format", "NCHW")
        xv = block.vars.get(x)
        if xv is not None and xv.shape is not None and len(xv.shape) == 4:
            bn_fmt = bn.attrs.get("data_layout",
                                  bn.attrs.get("data_format", "NCHW"))
            if bn_fmt != fmt:
                return False
        if not _same_segment(*(o for o in (base, add_op, bn) if o)):
            return False

        k, b = _bn_affine(scope, bn)
        if k.shape[0] != w.shape[axis]:
            return False
        bshape = tuple(-1 if a == axis else 1 for a in range(w.ndim))
        new_w = (w.astype(np.float32) * k.reshape(bshape)).astype(w.dtype)
        if prior_bias is not None:
            b = b + prior_bias.astype(np.float32) * k
        out_dtype = (xv.dtype if xv is not None and xv.shape is not None
                     else None)
        bias_val = b.astype(str(out_dtype)) if out_dtype is not None else b

        bn_y = bn.output("Y")
        new_w_name = self._new_param(block, scope, w_name + "@bnfold", new_w)
        bias_name = self._new_param(block, scope, bn_y + "@bnfold_bias",
                                    bias_val)
        base.inputs[w_slot] = [new_w_name]
        base.attrs["__bn_folded__"] = True
        add_axis = 1 if (w.ndim == 4 and fmt == "NCHW") else -1
        if add_op is not None:
            add_op.inputs["Y"] = [bias_name]
            add_op.outputs["Out"] = [bn_y]
            add_op.attrs["axis"] = add_axis
            add_op.attrs["__folded_from__"] = "batch_norm"
            block.remove_ops([bn])
        else:
            block.replace_ops(
                [bn], "elementwise_add",
                {"X": [x], "Y": [bias_name]}, {"Out": [bn_y]},
                {"axis": add_axis, "__folded_from__": "batch_norm",
                 SEG_ATTR: bn.attrs.get(SEG_ATTR)}
                if bn.attrs.get(SEG_ATTR) is not None else
                {"axis": add_axis, "__folded_from__": "batch_norm"})
        return True

    # ------------------------------------------------------------------
    def _lower_fused(self, block: Block, op: Operator, consumers, scope,
                     ctx: PassContext) -> bool:
        w_name = op.input("Filter")
        if w_name is None or not scope.has(w_name):
            return False
        if any(op.input(s) is None or not scope.has(op.input(s))
               for s in ("Scale", "Bias", "Mean", "Variance")):
            return False
        if not self._aux_outputs_unused(op, consumers):
            return False
        w = scope.get_numpy(w_name)
        wm = w.reshape(w.shape[-2], w.shape[-1])  # [1,1,I,O] or [I,O]
        k, b = _bn_affine(scope, op)
        if k.shape[0] != wm.shape[1]:
            return False
        new_w = (wm.astype(np.float32) * k[None, :]).astype(w.dtype)
        new_w = new_w.reshape(1, 1, *new_w.shape)  # conv2d HWIO
        x = op.input("X")
        y = op.output("Y")
        xv = block.vars.get(x)
        bias_val = (b.astype(str(xv.dtype)) if xv is not None else b)
        new_w_name = self._new_param(block, scope, w_name + "@bnfold", new_w)
        bias_name = self._new_param(block, scope, y + "@bnfold_bias",
                                    bias_val)
        res = op.input("Residual") if op.inputs.get("Residual") else None
        act = op.attrs.get("act") or ""
        yv = block.vars.get(y)
        oshape = yv.shape if yv is not None else None
        odtype = str(yv.dtype) if yv is not None else "float32"

        def tmp(tag):
            v = block.create_var(
                name=block.program.unique_name(y + tag), shape=oshape,
                dtype=odtype, stop_gradient=True)
            return v.name

        conv_attrs = {"data_format": "NHWC", "strides": [1, 1],
                      "paddings": [0, 0], "dilations": [1, 1], "groups": 1,
                      "__folded_from__": "conv1x1_bn_act"}
        chain = [("conv2d", {"Input": [x], "Filter": [new_w_name]},
                  "Output", conv_attrs),
                 ("elementwise_add", {"Y": [bias_name]}, "Out",
                  {"axis": -1})]
        if res is not None:
            chain.append(("elementwise_add", {"Y": [res]}, "Out", {}))
        if act == "relu":
            chain.append(("relu", {}, "Out", {}))
        idx = next(i for i, o in enumerate(block.ops) if o is op)
        new_ops, cur = [], None
        for j, (typ, ins, out_slot, attrs) in enumerate(chain):
            ins = dict(ins)
            if cur is not None:
                key = "Input" if typ == "conv2d" else "X"
                ins[key] = [cur]
            cur = y if j == len(chain) - 1 else tmp(f"@unfused{j}")
            new_ops.append(Operator(block, typ, ins, {out_slot: [cur]},
                                    attrs))
        block.ops[idx:idx + 1] = new_ops
        block.program._bump()
        return True


# --------------------------------------------------------------------------
@register_pass
class FusePatterns(Pass):
    """Pattern rewriter onto the repo's fused kernels.

    1. ``conv2d(1x1, stride 1, pad 0, NHWC) → batch_norm [→
       elementwise_add(residual)] [→ relu]`` becomes one
       ``conv1x1_bn_act`` op (kernels/conv_epilogue.py) — valid in both
       training and inference (the fused op implements the full
       batch-stat + running-stat contract and registers a grad_fn).
       Gated on ``--fused_conv_epilogue`` unless ``epilogue`` is forced,
       mirroring the model-layer gate.
    2. ``matmul(Q, K, transpose_Y) → [scale] → softmax → matmul(·, V)``
       over [B, H, T, D] heads becomes one
       ``scaled_dot_product_attention`` op (the flash-attention path);
       non-causal patterns only — a causal mask add is left alone.
    """

    name = "fuse_patterns"

    def __init__(self, epilogue: Optional[bool] = None,
                 attention: bool = True):
        self.epilogue = epilogue
        self.attention = attention

    def apply(self, program: Program, ctx: PassContext) -> None:
        from ..flags import FLAGS

        epilogue = (FLAGS.fused_conv_epilogue if self.epilogue is None
                    else self.epilogue)
        block = program.global_block
        changed = True
        while changed:
            changed = False
            producers = block.var_producers()
            consumers = block.var_consumers()
            for op in list(block.ops):
                if (epilogue and op.type == "conv2d"
                        and self._fuse_epilogue(block, op, consumers, ctx)):
                    changed = True
                    break
                if (self.attention and op.type == "matmul"
                        and self._fuse_attention(block, op, consumers, ctx)):
                    changed = True
                    break
        _drop_unused_vars(program, ctx)

    # ------------------------------------------------------------------
    @staticmethod
    def _sole_consumer(consumers, name, fetches) -> Optional[Operator]:
        if name in fetches:
            return None
        cs = consumers.get(name, [])
        return cs[0][1] if len(cs) == 1 else None

    def _fuse_epilogue(self, block: Block, conv: Operator, consumers,
                      ctx: PassContext) -> bool:
        from ..ops.common import normalize_pair

        if conv.attrs.get("data_format", "NCHW") != "NHWC":
            return False
        if (normalize_pair(conv.attrs.get("strides", [1, 1])) != [1, 1]
                or normalize_pair(conv.attrs.get("paddings", [0, 0]))
                != [0, 0]
                or normalize_pair(conv.attrs.get("dilations", [1, 1]))
                != [1, 1]
                or conv.attrs.get("groups", 1) != 1):
            return False
        w_name = conv.input("Filter")
        wv = block.vars.get(w_name)
        if wv is None or wv.shape is None or len(wv.shape) != 4 \
                or wv.shape[0] != 1 or wv.shape[1] != 1:
            return False
        fetches = set(ctx.fetch_names)
        out = conv.output("Output")
        bn = self._sole_consumer(consumers, out, fetches)
        if bn is None or bn.type != "batch_norm" or bn.input("X") != out:
            return False
        if bn.attrs.get("data_layout",
                        bn.attrs.get("data_format", "NCHW")) != "NHWC":
            return False
        if not FoldBatchNorm._aux_outputs_unused(bn, consumers):
            return False
        if any(bn.output(s) is None or bn.input(s2) is None
               for s in ("MeanOut", "VarianceOut", "SavedMean",
                         "SavedVariance")
               for s2 in ("Scale", "Bias", "Mean", "Variance")):
            return False  # hand-built bn missing the full slot contract
        y = bn.output("Y")
        matched = [conv, bn]
        residual, final = None, y
        nxt = self._sole_consumer(consumers, y, fetches)
        if nxt is not None and nxt.type == "elementwise_add":
            other = [n for n in nxt.input_names() if n != y]
            yv, ov = block.vars.get(y), None
            if len(other) == 1:
                ov = block.vars.get(other[0])
            if (ov is not None and yv is not None and ov.shape is not None
                    and ov.shape == yv.shape):
                residual, final = other[0], nxt.output("Out")
                matched.append(nxt)
                nxt = self._sole_consumer(consumers, final, fetches)
        act = ""
        if nxt is not None and nxt.type == "relu" \
                and nxt.input("X") == final:
            act, final = "relu", nxt.output("Out")
            matched.append(nxt)
        if not _same_segment(*matched):
            return False

        is_test = bool(bn.attrs.get("is_test", False))
        fv = block.vars.get(final)
        conv_out_shape = ([1, 1] if is_test
                          else (fv.shape if fv is not None else None))
        conv_out = block.create_var(
            name=block.program.unique_name(out + "@convout"),
            shape=conv_out_shape,
            dtype=str(fv.dtype) if fv is not None else "float32",
            stop_gradient=True)
        ins = {"X": [conv.input("Input")], "Filter": [w_name],
               "Scale": [bn.input("Scale")], "Bias": [bn.input("Bias")],
               "Mean": [bn.input("Mean")],
               "Variance": [bn.input("Variance")]}
        if residual is not None:
            ins["Residual"] = [residual]
        outs = {"Y": [final],
                "MeanOut": [bn.output("MeanOut")],
                "VarianceOut": [bn.output("VarianceOut")],
                "SavedMean": [bn.output("SavedMean")],
                "SavedVariance": [bn.output("SavedVariance")],
                "ConvOut": [conv_out.name]}
        attrs = {"momentum": bn.attrs.get("momentum", 0.9),
                 "epsilon": bn.attrs.get("epsilon", 1e-5),
                 "is_test": is_test, "act": act,
                 "__fused_from__": [o.type for o in matched]}
        if conv.attrs.get(SEG_ATTR) is not None:
            attrs[SEG_ATTR] = conv.attrs[SEG_ATTR]
        block.replace_ops(matched, "conv1x1_bn_act", ins, outs, attrs)
        return True

    # ------------------------------------------------------------------
    def _fuse_attention(self, block: Block, m1: Operator, consumers,
                        ctx: PassContext) -> bool:
        if m1.attrs.get("transpose_X") or not m1.attrs.get("transpose_Y"):
            return False
        q, k = m1.input("X"), m1.input("Y")
        qv, kv = block.vars.get(q), block.vars.get(k)
        if (qv is None or kv is None or qv.shape is None or kv.shape is None
                or len(qv.shape) != 4 or len(kv.shape) != 4
                or qv.shape[3] != kv.shape[3]
                or kv.shape[1] <= 0 or qv.shape[1] % kv.shape[1]):
            return False
        fetches = set(ctx.fetch_names)
        sm = float(m1.attrs.get("alpha", 1.0))
        matched = [m1]
        cur = m1.output("Out")
        nxt = self._sole_consumer(consumers, cur, fetches)
        if nxt is not None and nxt.type == "scale":
            if nxt.attrs.get("bias", 0.0):
                return False
            sm *= float(nxt.attrs.get("scale", 1.0))
            matched.append(nxt)
            cur = nxt.output("Out")
            nxt = self._sole_consumer(consumers, cur, fetches)
        if nxt is None or nxt.type != "softmax" \
                or nxt.attrs.get("axis", -1) not in (-1, 3):
            return False
        matched.append(nxt)
        cur = nxt.output("Out")
        m2 = self._sole_consumer(consumers, cur, fetches)
        if (m2 is None or m2.type != "matmul" or m2.input("X") != cur
                or m2.attrs.get("transpose_X") or m2.attrs.get("transpose_Y")
                or float(m2.attrs.get("alpha", 1.0)) != 1.0):
            return False
        v = m2.input("Y")
        vv = block.vars.get(v)
        if vv is None or vv.shape is None or tuple(vv.shape) != \
                tuple(kv.shape):
            return False
        matched.append(m2)
        if not _same_segment(*matched):
            return False
        attrs = {"causal": False, "sm_scale": sm,
                 "__fused_from__": [o.type for o in matched]}
        if m1.attrs.get(SEG_ATTR) is not None:
            attrs[SEG_ATTR] = m1.attrs[SEG_ATTR]
        block.replace_ops(matched, "scaled_dot_product_attention",
                          {"Q": [q], "K": [k], "V": [v]},
                          {"Out": [m2.output("Out")]}, attrs)
        return True

"""Pass framework: Pass base class, registry, PassManager.

The program-to-program rewriting plane the reference grew as
``inference_optimize``/``prune.cc`` and the inference/memory transpilers,
rebuilt in the spirit of XLA's HLO pass pipeline: a fixed, named ordering
of small rewrites, each instrumented (wall time + op-count delta into the
profiler ``StatSet`` plane) and dumpable (before/after op listings) so a
miscompile bisects to one pass instead of one monolith.

Passes mutate the given Program IN PLACE and run under a ``PassContext``
carrying the feed/fetch contract plus (optionally) a Scope — passes that
rewrite weights (BN folding, constant folding) write NEW names into that
scope and never clobber existing entries, so callers can hand a child
scope (``Scope(parent=user_scope)``) and keep the user's state pristine.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

from .. import profiler
from ..core.program import Program
from ..core.scope import Scope


class PassContext:
    """Everything a pass may consult: the feed/fetch contract, the scope
    holding parameter values, and policy knobs.

    ``preserve_state_writes``: DCE additionally keeps ops that write a
    name resident in ``scope`` — the stateful-program mode (generation
    engines whose KV-cache updates are outputs nobody fetches). Off for
    the save-inference path, where dropping optimizer state writes is
    exactly the point.
    """

    def __init__(self, feed_names: Sequence[str],
                 fetch_names: Sequence[str],
                 scope: Optional[Scope] = None,
                 preserve_state_writes: bool = False):
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.scope = scope
        self.preserve_state_writes = preserve_state_writes
        self.notes: List[str] = []

    def note(self, msg: str) -> None:
        self.notes.append(msg)


@dataclasses.dataclass
class PassResult:
    """One pass application: wall time + op-count delta (+ the time the
    pass-sandwich verifier spent re-checking the program afterwards)."""

    name: str
    seconds: float
    ops_before: int
    ops_after: int
    verify_seconds: float = 0.0

    @property
    def op_delta(self) -> int:
        return self.ops_after - self.ops_before

    @property
    def changed(self) -> bool:
        return self.ops_after != self.ops_before


class PassVerificationError(RuntimeError):
    """A pass broke the program: the pass-sandwich verifier found the
    program invalid AFTER this pass ran (it was valid before). Carries
    ``pass_name`` and the underlying verifier/checker error as
    ``__cause__``."""

    def __init__(self, pass_name: str, cause: BaseException):
        self.pass_name = pass_name
        super().__init__(
            f"pass {pass_name!r} broke the program: "
            f"{type(cause).__name__}: {cause}")


class Pass:
    """Base class: subclass, set ``name``, implement ``apply``.

    ``apply(program, ctx)`` mutates ``program`` in place; the return value
    is ignored. Idempotence is expected: running a pass twice must be a
    no-op the second time (pipelines re-run on already-transpiled saved
    models).
    """

    name: str = ""

    def apply(self, program: Program, ctx: PassContext) -> None:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


# --------------------------------------------------------------------------
# Registry: name -> Pass factory (zero-arg callable)
# --------------------------------------------------------------------------
_PASS_REGISTRY: Dict[str, Callable[[], Pass]] = {}


def register_pass(factory: Callable[[], Pass] = None, *,
                  name: Optional[str] = None):
    """Register a Pass class (or zero-arg factory) under its ``name``.
    Usable as a decorator on Pass subclasses."""

    def _do(f):
        key = name or getattr(f, "name", "") or getattr(f, "__name__", "")
        if not key:
            raise ValueError("pass factory needs a name")
        if key in _PASS_REGISTRY:
            raise ValueError(f"pass {key!r} already registered")
        _PASS_REGISTRY[key] = f
        return f

    if factory is None:
        return _do
    return _do(factory)


def get_pass(name: str) -> Pass:
    if name not in _PASS_REGISTRY:
        raise KeyError(f"pass {name!r} is not registered "
                       f"(known: {sorted(_PASS_REGISTRY)})")
    return _PASS_REGISTRY[name]()


def registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


# --------------------------------------------------------------------------
# PassManager
# --------------------------------------------------------------------------
def _op_count(program: Program) -> int:
    return sum(len(b.ops) for b in program.blocks)


def ir_dump_hook(dirname: str) -> Callable[[str, str, str], None]:
    """A dump hook writing ``NN_<pass>.{before,after}.txt`` op listings
    into ``dirname`` — plug into ``PassManager(dump_hook=...)``."""
    seq = {"n": 0}

    def hook(pass_name: str, before: str, after: str) -> None:
        os.makedirs(dirname, exist_ok=True)
        stem = os.path.join(dirname, f"{seq['n']:02d}_{pass_name}")
        with open(stem + ".before.txt", "w") as f:
            f.write(before)
        with open(stem + ".after.txt", "w") as f:
            f.write(after)
        seq["n"] += 1

    return hook


class PassManager:
    """Runs an ordered pass list over a program, instrumenting each pass.

    - Per-pass wall time lands in ``stat_set`` (default: the profiler's
      process-global StatSet) as ``transpiler/pass/<name>``; the op-count
      delta as ``transpiler/delta/<name>`` via ``StatSet.add_count`` (the
      ms-formatted columns then read as raw op counts).
    - ``dump_hook(pass_name, before, after)`` receives full op listings
      around every pass that changed the program (and all passes when
      ``dump_all``); see ``ir_dump_hook`` for the write-to-dir variant.
    - ``verify_each`` turns on the pass sandwich: the program is
      verified (paddle_tpu.analysis structural rules + whole-program
      shape/dtype inference with ``verify_shapes``) BEFORE the first
      pass and after EVERY pass, so the exact pass that broke a program
      is named in :class:`PassVerificationError` instead of the
      breakage surfacing as a JAX trace error at the next compile.
      ``None`` (default) follows the ``--verify_program`` flag.
      Verification wall time lands in the pass stats
      (``transpiler/verify/<name>``) and in each ``PassResult``.
    """

    def __init__(self, passes: Sequence, stat_set=None,
                 dump_hook: Optional[Callable[[str, str, str], None]] = None,
                 dump_all: bool = False,
                 verify_each: Optional[bool] = None,
                 verify_shapes: bool = True):
        self.passes: List[Pass] = [
            get_pass(p) if isinstance(p, str) else p for p in passes
        ]
        self.stat_set = stat_set if stat_set is not None \
            else profiler.global_stat
        self.dump_hook = dump_hook
        self.dump_all = dump_all
        self.verify_each = verify_each
        self.verify_shapes = verify_shapes
        self.results: List[PassResult] = []

    # ------------------------------------------------------------------
    def _verify(self, program: Program, ctx: PassContext) -> float:
        """One sandwich slice: structural verify (+ shape inference when
        ``verify_shapes``). Returns the wall time spent; raises the
        analysis error on an invalid program."""
        from .. import analysis

        t0 = time.perf_counter()
        if self.verify_shapes:
            analysis.check_program(program, ctx.feed_names,
                                   ctx.fetch_names, scope=ctx.scope,
                                   annotate=False)
        else:
            analysis.verify_program(program, ctx.feed_names,
                                    ctx.fetch_names, scope=ctx.scope)
        return time.perf_counter() - t0

    def run(self, program: Program, feed_names: Sequence[str],
            fetch_names: Sequence[str], scope: Optional[Scope] = None,
            **ctx_kw) -> Program:
        """Apply every pass in order (in place) and return the program."""
        ctx = PassContext(feed_names, fetch_names, scope=scope, **ctx_kw)
        self.results = []
        verify = self.verify_each
        if verify is None:
            from ..flags import FLAGS

            verify = FLAGS.verify_program
        if verify:
            # pre-verify so a broken INPUT program is not pinned on the
            # first pass — this one propagates the analysis error as-is
            dt = self._verify(program, ctx)
            if self.stat_set is not None:
                self.stat_set.add("transpiler/verify/<input>", dt)
        for p in self.passes:
            before = str(program) if self.dump_hook else ""
            n0 = _op_count(program)
            t0 = time.perf_counter()
            p.apply(program, ctx)
            dt = time.perf_counter() - t0
            n1 = _op_count(program)
            vdt = 0.0
            if verify:
                try:
                    vdt = self._verify(program, ctx)
                except Exception as exc:
                    self.results.append(PassResult(p.name, dt, n0, n1))
                    raise PassVerificationError(p.name, exc) from exc
            self.results.append(PassResult(p.name, dt, n0, n1, vdt))
            if self.stat_set is not None:
                self.stat_set.add(f"transpiler/pass/{p.name}", dt)
                self.stat_set.add_count(f"transpiler/delta/{p.name}",
                                        n1 - n0)
                if verify:
                    self.stat_set.add(f"transpiler/verify/{p.name}", vdt)
            if self.dump_hook and (self.dump_all or n1 != n0):
                self.dump_hook(p.name, before, str(program))
        self.last_notes = list(ctx.notes)
        return program

    # ------------------------------------------------------------------
    def stats(self) -> List[dict]:
        """JSON-safe per-pass rows from the last ``run``."""
        return [
            {"pass": r.name, "ms": round(r.seconds * 1e3, 3),
             "ops_before": r.ops_before, "ops_after": r.ops_after,
             "op_delta": r.op_delta,
             "verify_ms": round(r.verify_seconds * 1e3, 3)}
            for r in self.results
        ]

    def metrics_dict(self, prefix: str = "transpile/") -> Dict[str, float]:
        """Flat gauge dict for serving MetricsRegistry publication."""
        out: Dict[str, float] = {}
        for r in self.results:
            out[f"{prefix}{r.name}_ms"] = round(r.seconds * 1e3, 3)
            out[f"{prefix}{r.name}_op_delta"] = r.op_delta
        if self.results:
            out[prefix + "total_ms"] = round(
                sum(r.seconds for r in self.results) * 1e3, 3)
            out[prefix + "ops_removed"] = (self.results[0].ops_before
                                           - self.results[-1].ops_after)
            verify_s = sum(r.verify_seconds for r in self.results)
            if verify_s:
                out[prefix + "verify_ms"] = round(verify_s * 1e3, 3)
        return out

    def format_stats(self) -> str:
        """Human table of the last run (demo/debug output)."""
        if not self.results:
            return "(no passes run)"
        verified = any(r.verify_seconds for r in self.results)
        head = f"{'pass':<28}{'ms':>10}{'ops before':>12}" \
               f"{'ops after':>11}{'delta':>8}"
        if verified:
            head += f"{'verify ms':>11}"
        lines = [head, "-" * len(head)]
        for r in self.results:
            row = (f"{r.name:<28}{r.seconds * 1e3:>10.3f}"
                   f"{r.ops_before:>12}{r.ops_after:>11}"
                   f"{r.op_delta:>+8}")
            if verified:
                row += f"{r.verify_seconds * 1e3:>11.3f}"
            lines.append(row)
        return "\n".join(lines)

"""Memory-aware op scheduling: the ``reduce_peak_memory`` pass.

A topological reorder of the global block that shrinks the live-byte
watermark the memory analyzer (analysis/memory.py) computes — the
program-level lever the reference era shipped as its "memory transpiler".
Model builders naturally emit breadth-first programs (every branch of a
fork built before any is consumed); a depth-first schedule runs each
branch to its consumer before materializing the next, so fewer big
tensors overlap.

Semantics are preserved exactly:

- every data dependency (read-after-write, write-after-read,
  write-after-write — the IR is not SSA) becomes a scheduling edge, so
  every op sees bit-identical inputs;
- RNG-drawing ops keep their relative order (the executor splits the
  PRNG key in op order — reordering them would change the stream);
- ``special`` ops (seg_fwd/grad_seg env stashes, control flow) and
  unknown ops are chained in program order.

The pass commits a new order only when it strictly lowers the estimated
peak; ties keep the original order (idempotent re-runs). Registered in
the pass registry; opt into pipelines with ``--reduce_peak_memory`` or
by constructing the pass directly.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from ..core.program import Program
from ..core.registry import get_op, has_op, op_uses_rng
from .framework import Pass, PassContext, register_pass


def _sizes(program: Program, ctx: PassContext, batch_size: int):
    """name -> bytes via whole-program inference; None when the program
    cannot be inferred (the pass then declines to touch it)."""
    from ..analysis import costmodel
    from ..analysis.checker import infer_program
    from ..analysis.memory import _concrete

    try:
        analysis = infer_program(program, ctx.feed_names, ctx.fetch_names,
                                 scope=ctx.scope, annotate=False)
    except Exception:
        return None
    return {name: costmodel._nbytes(_concrete(sds, batch_size))
            for name, sds in analysis.types.items()}


def _resident_names(program: Program, ctx: PassContext) -> Set[str]:
    block = program.global_block
    names = set(ctx.feed_names)
    if ctx.scope is not None:
        s = ctx.scope
        while s is not None:
            names.update(s.keys())
            s = s.parent
    for name, v in block.vars.items():
        if v.persistable or v.is_data:
            names.add(name)
    return names


def _peak_of(order: Sequence, sizes: Dict[str, float], resident: Set[str],
             fetches: Set[str]) -> float:
    """Transient live-byte watermark of an op order (resident excluded —
    it is order-invariant)."""
    last_use: Dict[str, int] = {}
    for i, op in enumerate(order):
        for n in op.input_names():
            last_use[n] = i
    live: Dict[str, float] = {}
    peak = 0.0
    for i, op in enumerate(order):
        for n in op.output_names():
            if n not in resident:
                live[n] = sizes.get(n, 0.0)
        peak = max(peak, sum(live.values()))
        for n in list(live):
            if last_use.get(n, -1) <= i and n not in fetches:
                del live[n]
    return peak


def _build_deps(ops: List) -> List[Set[int]]:
    """deps[i] = set of op indices that must run before op i."""
    deps: List[Set[int]] = [set() for _ in ops]
    last_writer: Dict[str, int] = {}
    readers_since_write: Dict[str, List[int]] = {}
    prev_chained: Optional[int] = None
    for i, op in enumerate(ops):
        chained = True
        if has_op(op.type):
            opdef = get_op(op.type)
            chained = opdef.special or op_uses_rng(opdef, op.attrs)
        if chained:
            if prev_chained is not None:
                deps[i].add(prev_chained)
            prev_chained = i
        for n in op.input_names():
            if n in last_writer:
                deps[i].add(last_writer[n])  # RAW
            readers_since_write.setdefault(n, []).append(i)
        for n in op.output_names():
            if n in last_writer:
                deps[i].add(last_writer[n])  # WAW
            for r in readers_since_write.get(n, ()):
                if r != i:
                    deps[i].add(r)  # WAR
            last_writer[n] = i
            readers_since_write[n] = []
        deps[i].discard(i)
    return deps


def _greedy_schedule(ops: List, deps: List[Set[int]],
                     sizes: Dict[str, float], resident: Set[str],
                     fetches: Set[str]) -> List[int]:
    """List-schedule minimizing the live-byte delta at every step: among
    ready ops pick the one whose (bytes allocated - bytes freed) is
    smallest, tie-broken by original position (deterministic, stable)."""
    n = len(ops)
    succs: List[List[int]] = [[] for _ in range(n)]
    indeg = [0] * n
    for i, ds in enumerate(deps):
        indeg[i] = len(ds)
        for d in ds:
            succs[d].append(i)
    remaining_readers: Dict[str, int] = {}
    for op in ops:
        seen = set()
        for m in op.input_names():
            if m in seen:
                continue
            seen.add(m)
            remaining_readers[m] = remaining_readers.get(m, 0) + 1
    live: Set[str] = set()

    def delta(i: int) -> float:
        op = ops[i]
        alloc = sum(sizes.get(m, 0.0) for m in set(op.output_names())
                    if m not in resident and m not in live)
        freed = 0.0
        seen = set()
        for m in op.input_names():
            if m in seen or m not in live:
                continue
            seen.add(m)
            if remaining_readers.get(m, 0) <= 1 and m not in fetches:
                freed += sizes.get(m, 0.0)
        for m in set(op.output_names()):
            # never-read outputs die immediately
            if (m not in resident and m not in fetches
                    and remaining_readers.get(m, 0) == 0):
                freed += sizes.get(m, 0.0)
        return alloc - freed

    ready = [i for i in range(n) if indeg[i] == 0]
    order: List[int] = []
    while ready:
        # evaluate delta for every ready op; ready sets stay small (the
        # dependency chains of real programs bound the frontier)
        best = min(ready, key=lambda i: (delta(i), i))
        ready.remove(best)
        order.append(best)
        op = ops[best]
        for m in set(op.output_names()):
            if m not in resident:
                live.add(m)
        seen = set()
        for m in op.input_names():
            if m in seen:
                continue
            seen.add(m)
            c = remaining_readers.get(m, 0) - 1
            remaining_readers[m] = c
            if c <= 0 and m in live and m not in fetches:
                live.discard(m)
        for m in set(op.output_names()):
            if (m in live and m not in fetches
                    and remaining_readers.get(m, 0) == 0):
                live.discard(m)
        for s in succs[best]:
            indeg[s] -= 1
            if indeg[s] == 0:
                ready.append(s)
    return order


@register_pass
class ReducePeakMemory(Pass):
    """Reorder the global block to shrink the peak live-byte watermark.

    ``batch_size`` concretises ``-1`` batch dims for sizing (relative
    sizes drive the schedule, so the nominal default is fine). Outputs
    are bit-exact: only the op ORDER changes, never any op's inputs, and
    RNG/special/state orderings are pinned by dependency edges.
    """

    name = "reduce_peak_memory"

    def __init__(self, batch_size: int = 8):
        self.batch_size = int(batch_size)

    def apply(self, program: Program, ctx: PassContext) -> None:
        block = program.global_block
        ops = list(block.ops)
        if len(ops) < 3:
            return
        sizes = _sizes(program, ctx, self.batch_size)
        if sizes is None:
            ctx.note("reduce_peak_memory: program not inferable; skipped")
            return
        resident = _resident_names(program, ctx)
        fetches = set(ctx.fetch_names)
        deps = _build_deps(ops)
        order = _greedy_schedule(ops, deps, sizes, resident, fetches)
        new_ops = [ops[i] for i in order]
        before = _peak_of(ops, sizes, resident, fetches)
        after = _peak_of(new_ops, sizes, resident, fetches)
        if after < before:
            block.ops = new_ops
            program._bump()
            ctx.note(
                f"reduce_peak_memory: transient peak "
                f"{before / 1e6:.2f} MB -> {after / 1e6:.2f} MB "
                f"({(1 - after / max(before, 1e-9)) * 100:.1f}% lower) "
                f"at batch={self.batch_size}")
        else:
            ctx.note("reduce_peak_memory: no better order found")

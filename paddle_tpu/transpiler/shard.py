"""ShardProgram: program-level GSPMD annotation — the one sharding plane.

The pass that unifies the parallel islands: it consumes a
:class:`paddle_tpu.parallel.ShardingPlan` and annotates every variable
in the program with the PartitionSpec the plan resolves for it —
parameters and optimizer state through ``spec_for_state`` (accumulators
inherit their parameter's spec by the name-substring rules), feed
variables through ``spec_for_feed`` (batch dim on the ``dp`` axis),
activations deliberately left unannotated for XLA GSPMD propagation.
The executor then lowers the whole block through ``jax.jit(...,
in_shardings/out_shardings, donate_argnums)`` using the annotations, so
dp x tp (x sp/ep through the mesh-aware op kernels' ``shard_map`` escape
hatches) compose on ONE mesh — the in-graph replacement for the
reference's five separate entry points (pserver block sharding,
MultiGradientMachine batch splitting, and friends).

Annotations are plain metadata: ``var.sharding`` is a PartitionSpec (or
absent), ``program.sharding_plan`` holds the plan. The pass changes no
ops, so the pass-sandwich verifier (``verify_each=True``) stays clean by
construction, and the analysis plane reads the same annotations to
report per-device peak HBM and collective bytes
(:func:`paddle_tpu.analysis.analyze_memory` with ``plan=``).

Idempotent: re-running (same or different plan) overwrites every
annotation from scratch.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.program import Program
from ..parallel.plan import spec_axes  # noqa: F401  (re-export)
from .framework import Pass, PassContext, register_pass


@register_pass
class ShardProgram(Pass):
    """Annotate every program var with its plan-resolved PartitionSpec.

    ``ShardProgram(plan)`` applies that plan; the registry's zero-arg
    form (``get_pass("shard_program")``) re-applies the plan already
    attached to the program (``program.sharding_plan``) and is a no-op
    on unsharded programs — so the pass can sit in any pipeline.
    """

    name = "shard_program"

    def __init__(self, plan=None):
        self.plan = plan

    def apply(self, program: Program, ctx: PassContext) -> None:
        plan = self.plan if self.plan is not None \
            else getattr(program, "sharding_plan", None)
        if plan is None:
            return
        program.sharding_plan = plan
        feeds = set(ctx.feed_names)
        scope = ctx.scope
        n_state = n_feed = n_sharded = 0
        for block in program.blocks:
            for v in block.vars.values():
                # stale annotations (a previous plan) never survive
                v.__dict__.pop("sharding", None)
                shape = v.shape
                if shape is None and scope is not None and scope.has(v.name):
                    shape = np.shape(scope.get(v.name))
                if shape is None:
                    continue
                ndim = len(shape)
                if v.is_data or v.name in feeds:
                    v.sharding = plan.spec_for_feed(v.name, ndim)
                    n_feed += 1
                elif v.persistable or (scope is not None
                                       and scope.has(v.name)):
                    # located error contract: a rule set that cannot fit
                    # this var raises ShardingPlanError here, at pass
                    # time, naming var + rules — not at jit lowering
                    v.sharding = plan.spec_for_state(v.name, ndim,
                                                     shape=shape)
                    n_state += 1
                else:
                    continue  # activation: GSPMD propagation decides
                if tuple(v.sharding):
                    n_sharded += 1
        axes = "x".join(f"{a}={s}" for a, s in plan.mesh_axes().items())
        ctx.note(f"shard_program: mesh [{axes}] plan {plan.digest()} — "
                 f"{n_state} state + {n_feed} feed vars annotated, "
                 f"{n_sharded} sharded, activations left to GSPMD")


def shard_program(program: Program, plan, feed_names=(), fetch_names=(),
                  scope=None) -> Program:
    """Functional convenience: apply :class:`ShardProgram` in place and
    return the program (the ``SGD.train(plan=...)`` / engine entry)."""
    ShardProgram(plan).apply(
        program, PassContext(list(feed_names), list(fetch_names),
                             scope=scope))
    return program

"""paddle_tpu.transpiler — program-level optimization pass framework.

The program-to-program rewriting plane (the reference era's
``inference_optimize``/``prune.cc`` and inference/memory transpilers,
rebuilt as an XLA-HLO-style pass pipeline): a ``Pass`` base class, a
registry, a ``PassManager`` with per-pass wall-time + op-count-delta
stats published into the profiler ``StatSet`` plane, IR dump hooks, and
a standard pass library (dead-op elimination, constant folding, is_test
canonicalization, dropout→scale, conv/fc+BN folding, fused-kernel
pattern rewriting).

Typical use::

    from paddle_tpu import transpiler

    pm = transpiler.inference_pipeline()
    program = pm.run(program, feed_names, fetch_names, scope=scope)
    print(pm.format_stats())          # per-pass ms + op deltas

``io.save_inference_model`` runs the inference pipeline by default; the
serving engines transpile before warmup and publish the pass stats into
their ``MetricsRegistry``.

Every pipeline forwards ``verify_each=True`` (or the ``--verify_program``
flag) to the PassManager pass sandwich: the paddle_tpu.analysis verifier
+ whole-program shape checker re-run after every pass, so the exact pass
that breaks a program is named (``PassVerificationError``) instead of
the breakage surfacing as a JAX trace error at the next compile.
"""
from __future__ import annotations

from typing import Optional

from .framework import (Pass, PassContext, PassManager, PassResult,
                        PassVerificationError, get_pass, ir_dump_hook,
                        register_pass, registered_passes)
from .passes import (CanonicalizeIsTest, ConstantFolding,
                     DeadOpElimination, DropoutToScale,
                     ExpandRecomputeSegments, FoldBatchNorm, FusePatterns)
from .schedule import ReducePeakMemory
from .shard import ShardProgram, shard_program

__all__ = [
    "Pass", "PassContext", "PassManager", "PassResult",
    "PassVerificationError", "register_pass",
    "get_pass", "registered_passes", "ir_dump_hook",
    "ExpandRecomputeSegments", "CanonicalizeIsTest", "DropoutToScale",
    "DeadOpElimination", "ConstantFolding", "FoldBatchNorm",
    "FusePatterns", "ReducePeakMemory", "ShardProgram", "shard_program",
    "inference_pipeline", "training_pipeline", "deployment_pipeline",
    "prune_pipeline",
]


def _maybe_reduce_peak(reduce_peak):
    """Pipeline knob: None follows --reduce_peak_memory, True forces the
    memory-aware scheduling pass on."""
    if reduce_peak is None:
        from ..flags import FLAGS

        reduce_peak = FLAGS.reduce_peak_memory
    return [ReducePeakMemory()] if reduce_peak else []


def prune_pipeline(for_test: bool = True, **pm_kw) -> PassManager:
    """The minimal slice pipeline backing ``io.prune_program``: flatten
    recompute segments, (optionally) canonicalize ``is_test``, then
    dead-op-eliminate down to the fetch targets."""
    passes = [ExpandRecomputeSegments()]
    if for_test:
        passes.append(CanonicalizeIsTest())
    passes.append(DeadOpElimination())
    return PassManager(passes, **pm_kw)


def inference_pipeline(*, constant_fold: bool = True,
                       fold_batch_norm: bool = True,
                       fuse: bool = True,
                       epilogue: Optional[bool] = None,
                       reduce_peak: Optional[bool] = None,
                       **pm_kw) -> PassManager:
    """The deploy-time pipeline (``save_inference_model`` default):
    flatten → is_test → dropout→scale → DCE → constant-fold →
    fused-kernel rewrites → BN folding → cleanup DCE.

    Fusion runs BEFORE BN folding so that, when the fused conv epilogue
    is enabled (``--fused_conv_epilogue`` or ``epilogue=True``), 1x1-NHWC
    conv→BN chains become the Pallas-backed fused op and folding only
    absorbs the chains fusion cannot take (3x3 convs, fc+BN). Weight
    folding writes NEW names into the given scope — hand a child scope
    to keep the caller's state pristine.
    """
    # DCE runs BEFORE the dropout rewrite: in a training program the
    # backward's grad ops consume dropout's Mask, and only after the
    # training slice is gone is the mask provably dead.
    passes = [ExpandRecomputeSegments(), CanonicalizeIsTest(),
              DeadOpElimination(), DropoutToScale()]
    if constant_fold:
        passes.append(ConstantFolding())
    if fuse:
        passes.append(FusePatterns(epilogue=epilogue))
    if fold_batch_norm:
        passes.append(FoldBatchNorm())
    passes.append(DeadOpElimination())
    # memory-aware scheduling LAST: it reorders whatever the rewrites
    # left, bit-exact (``reduce_peak=None`` follows --reduce_peak_memory)
    passes.extend(_maybe_reduce_peak(reduce_peak))
    return PassManager(passes, **pm_kw)


def training_pipeline(*, epilogue: Optional[bool] = None,
                      **pm_kw) -> PassManager:
    """The build-time pipeline for training programs (run BEFORE
    ``append_backward``/``minimize`` — the rewrites carry grad_fns, but
    already-emitted grad ops reference the pre-rewrite op list): literal
    constant folding (parameters stay live — they change every step) and
    fused-kernel pattern rewrites."""
    return PassManager([ConstantFolding(fold_params=False),
                        FusePatterns(epilogue=epilogue)], **pm_kw)


def deployment_pipeline(reduce_peak: Optional[bool] = None,
                        **pm_kw) -> PassManager:
    """The portable-artifact pipeline (int8 quantization, the native C
    machine): like ``inference_pipeline`` but with NO fused ops — fused
    ``conv1x1_bn_act`` is lowered back to folded conv2d + bias add so
    plain-conv weights become weight-only-quantization eligible."""
    return PassManager(
        [ExpandRecomputeSegments(), CanonicalizeIsTest(),
         DeadOpElimination(), DropoutToScale(), ConstantFolding(),
         FoldBatchNorm(lower_fused=True), DeadOpElimination()]
        + _maybe_reduce_peak(reduce_peak), **pm_kw)

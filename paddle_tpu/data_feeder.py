"""DataFeeder: reader minibatches -> executor feed dicts.

Parity with /root/reference/python/paddle/v2/data_feeder.py and the SWIG
DataProviderConverter (/root/reference/paddle/py_paddle/
dataprovider_converter.py): a reader yields rows (tuples ordered like
``feed_order``); the feeder stacks each column into a dense device-ready
array of the declared dtype/shape.

Variable-length (LoD) columns — rows whose entries are sequences of
differing length — are padded to the batch max and returned together with a
``<name>@len`` int32 length vector, the dense+mask TPU replacement for the
reference's sequenceStartPositions (SURVEY.md §5.7).
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .core.program import Variable


def _is_ragged(col) -> bool:
    try:
        first = np.asarray(col[0])
    except Exception:
        return True
    if first.ndim == 0:
        return False
    lengths = set()
    for item in col:
        arr = np.asarray(item)
        lengths.add(arr.shape[0] if arr.ndim else 0)
        if len(lengths) > 1:
            return True
    return False


class DataFeeder:
    """``pad_to_multiple`` rounds every ragged column's padded length up
    to the next multiple (serving-engine-style bucket padding): the
    executor compiles one XLA computation per feed-shape signature, so
    padding to the exact batch max means every distinct max length is a
    fresh compile — bucketed padding caps the signature set. Pair with
    ``reader.bucket_by_length(..., pad_to_multiple=m)`` so batches also
    GROUP by the same buckets (occupancy)."""

    def __init__(self, feed_list: Sequence[Variable], place=None,
                 pad_to_multiple: int = None):
        self.feed_vars = list(feed_list)
        self.place = place
        self.pad_to_multiple = (int(pad_to_multiple)
                                if pad_to_multiple else None)

    def feed(self, data: Sequence[Sequence]) -> Dict[str, np.ndarray]:
        """Convert a minibatch (list of rows) into {name: array} feeds."""
        out: Dict[str, np.ndarray] = {}
        for i, var in enumerate(self.feed_vars):
            col = [row[i] for row in data]
            dtype = var.dtype
            sval = getattr(var, "sparse_values", None)
            if sval is not None:
                # sparse_float_vector rows: [(index, value), ...] — split
                # into the padded id feed and its companion value feed
                # (reference dataprovider_converter.py SparseFloatScanner).
                ids_col = [[p[0] for p in row[i]] for row in data]
                val_col = [[p[1] for p in row[i]] for row in data]
                out.update(self._pad_sequences(var, ids_col))
                vals = self._pad_sequences(sval, val_col)
                out[sval.name] = vals[sval.name]
                continue
            if var.lod_level > 0 or _is_ragged(col):
                out.update(self._pad_sequences(var, col))
            else:
                arr = np.asarray(col, dtype=dtype)
                shape = tuple(d for d in (var.shape or ()) if d != -1)
                if shape and arr.shape[1:] != shape and arr.size == len(col) * int(np.prod(shape)):
                    arr = arr.reshape((len(col),) + shape)
                out[var.name] = arr
        return out

    def _pad_sequences(self, var, col) -> Dict[str, np.ndarray]:
        seqs = [np.asarray(item, dtype=var.dtype) for item in col]
        lengths = np.asarray([s.shape[0] for s in seqs], dtype=np.int32)
        max_len = int(lengths.max()) if len(lengths) else 0
        m = self.pad_to_multiple
        if m and m > 1:
            max_len = -(-max_len // m) * m
        tail = seqs[0].shape[1:] if seqs and seqs[0].ndim > 1 else ()
        padded = np.zeros((len(seqs), max_len) + tail, dtype=var.dtype)
        for i, s in enumerate(seqs):
            padded[i, : s.shape[0]] = s
        return {var.name: padded, f"{var.name}@len": lengths}

"""Program visualisation: Graphviz DOT emitter for program blocks.

Parity with the reference's fluid net_drawer
(/root/reference/python/paddle/v2/fluid/net_drawer.py), self-contained:
emits DOT text directly (no graphviz python dependency), so the output can
be rendered with any dot binary or online viewer.
"""
from __future__ import annotations

from typing import Optional

from .core.program import Program, default_main_program

_OP_STYLE = 'shape=box, style="rounded,filled", fillcolor="#E8F0FE"'
_VAR_STYLE = 'shape=ellipse, fillcolor="#FEF7E0", style=filled'
_PARAM_STYLE = 'shape=ellipse, fillcolor="#E6F4EA", style=filled'


def _q(name: str) -> str:
    return '"' + name.replace('"', r'\"') + '"'


def draw_graph(program: Optional[Program] = None, path: Optional[str] = None,
               block_idx: int = 0, max_vars: int = 400) -> str:
    """Render ``program``'s block as DOT text; optionally write to ``path``.

    Ops become box nodes, variables ellipses (parameters green); edges
    follow dataflow. Grad-section ops are grouped into a subgraph so the
    forward topology stays readable after append_backward.
    """
    program = program or default_main_program()
    block = program.blocks[block_idx]
    lines = ["digraph Program {", "  rankdir=TB;",
             '  fontname="Helvetica";']
    seen_vars = set()
    var_decls, op_decls, edges = [], [], []
    for i, op in enumerate(block.ops):
        op_id = f"op_{i}"
        label = op.type
        site = op.attrs.get("_callsite")
        if site:
            label += "\\n" + site.rsplit("/", 1)[-1]
        op_decls.append(f"  {op_id} [label={_q(label)}, {_OP_STYLE}];")
        for names in op.inputs.values():
            for n in names:
                if n not in seen_vars and len(seen_vars) < max_vars:
                    seen_vars.add(n)
                    v = block.vars.get(n)
                    style = (_PARAM_STYLE
                             if v is not None and v.is_parameter
                             else _VAR_STYLE)
                    var_decls.append(f"  {_q(n)} [{style}];")
                edges.append(f"  {_q(n)} -> {op_id};")
        for names in op.outputs.values():
            for n in names:
                if n not in seen_vars and len(seen_vars) < max_vars:
                    seen_vars.add(n)
                    var_decls.append(f"  {_q(n)} [{_VAR_STYLE}];")
                edges.append(f"  {op_id} -> {_q(n)};")
    lines += var_decls + op_decls + edges + ["}"]
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot

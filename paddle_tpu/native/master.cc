// Task-queue master engine: the C++ core of the fault-tolerant data-sharding
// control plane.
//
// Native rebuild of the reference's Go master service
// (/root/reference/go/master/service.go): todo/pending/done task queues
// (service.go:106), per-task deadlines with lazy timeout re-queueing
// (checkTimeoutFunc service.go:341), failure counting with
// discard-after-K-failures (processFailedTask service.go:313), pass
// (epoch) semantics, and state snapshot/recovery (snapshot service.go:207,
// recover :166) — with a plain file replacing the etcd store (the TPU-native
// deployment runs one master; replication is a file on durable storage).
//
// C ABI only (loaded via ctypes from paddle_tpu/master). Thread-safe: all
// entry points take the engine mutex, so one master can serve many trainer
// threads or a socket front-end.
//
// Build: g++ -O2 -shared -fPIC master.cc -o libptmaster.so   (see build.py)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Task {
  int id = -1;
  std::string desc;     // opaque payload (e.g. "file.rec:chunk-3")
  int failures = 0;
  int64_t deadline = 0; // epoch seconds; only meaningful while pending
  int epoch = 0;        // bumped per assignment; stale reports are rejected
                        // (the Go reference's Task.Epoch, service.go)
};

int64_t now_s() { return static_cast<int64_t>(time(nullptr)); }

struct Master {
  std::mutex mu;
  int timeout_s;
  int max_failures;
  int next_id = 0;
  int pass = 0;
  std::deque<Task> todo;
  std::unordered_map<int, Task> pending;
  std::vector<Task> done;
  std::vector<Task> discarded;

  Master(int t, int f) : timeout_s(t), max_failures(f) {}

  void set_dataset(const char **descs, int n) {
    std::lock_guard<std::mutex> g(mu);
    todo.clear();
    pending.clear();
    done.clear();
    discarded.clear();
    pass = 0;
    for (int i = 0; i < n; ++i) {
      Task t;
      t.id = next_id++;
      t.desc = descs[i];
      todo.push_back(std::move(t));
    }
  }

  // Re-queue pending tasks whose deadline passed (lazy: called from
  // get_task, mirroring the periodic checkTimeoutFunc).
  void check_timeouts_locked() {
    int64_t now = now_s();
    std::vector<int> expired;
    for (auto &kv : pending) {
      if (kv.second.deadline <= now) expired.push_back(kv.first);
    }
    for (int id : expired) {
      Task t = pending[id];
      pending.erase(id);
      fail_locked(std::move(t));
    }
  }

  void fail_locked(Task t) {
    t.failures += 1;
    if (t.failures >= max_failures) {
      discarded.push_back(std::move(t)); // drop poisonous tasks
    } else {
      todo.push_back(std::move(t));
    }
  }

  // Returns task id >= 0, copies desc into buf, writes the claim epoch to
  // *epoch_out; -1 if nothing runnable right now; -2 if the pass is
  // complete (todo and pending both empty); -3 if buf is too small for the
  // desc (task stays queued).
  int get_task(char *buf, int buflen, int *epoch_out) {
    std::lock_guard<std::mutex> g(mu);
    check_timeouts_locked();
    if (todo.empty()) {
      return pending.empty() ? -2 : -1;
    }
    if (static_cast<int>(todo.front().desc.size()) + 1 > buflen) return -3;
    Task t = todo.front();
    todo.pop_front();
    t.deadline = now_s() + timeout_s;
    t.epoch += 1;
    int id = t.id;
    if (epoch_out) *epoch_out = t.epoch;
    snprintf(buf, buflen, "%s", t.desc.c_str());
    pending[id] = std::move(t);
    return id;
  }

  int task_finished(int id, int epoch) {
    std::lock_guard<std::mutex> g(mu);
    auto it = pending.find(id);
    if (it == pending.end()) return -1; // unknown/late (already timed out)
    if (it->second.epoch != epoch) return -1; // stale claim's report
    done.push_back(it->second);
    pending.erase(it);
    return 0;
  }

  // Explicit pass recycling: done tasks go back to todo. Callers decide
  // when a new epoch starts (the reference client drives passes the same
  // way — one start_get_records per pass).
  int new_pass() {
    std::lock_guard<std::mutex> g(mu);
    if (!pending.empty()) return -1; // a pass must fully drain first
    start_new_pass_locked();
    return pass;
  }

  int task_failed(int id, int epoch) {
    std::lock_guard<std::mutex> g(mu);
    auto it = pending.find(id);
    if (it == pending.end()) return -1;
    if (it->second.epoch != epoch) return -1; // stale claim's report
    Task t = it->second;
    pending.erase(it);
    fail_locked(std::move(t));
    return 0;
  }

  // Lease-preemption requeue: put a pending claim back in todo WITHOUT a
  // failure strike (losing a lease is not the task's fault). front=1
  // pushes to the queue head so a rejoining trainer re-trains it before
  // streaming on — keeping the effective task order stable for
  // checkpoint-lineage-consistent resume.
  int requeue(int id, int epoch, int front) {
    std::lock_guard<std::mutex> g(mu);
    auto it = pending.find(id);
    if (it == pending.end()) return -1;
    if (it->second.epoch != epoch) return -1;
    Task t = it->second;
    pending.erase(it);
    t.deadline = 0;
    if (front) todo.push_front(std::move(t));
    else todo.push_back(std::move(t));
    return 0;
  }

  // Deadline renewal for a live claim (the lease plane's heartbeat
  // extends claims so a long task under a healthy lease never hits the
  // per-task timeout requeue).
  int touch(int id, int epoch) {
    std::lock_guard<std::mutex> g(mu);
    auto it = pending.find(id);
    if (it == pending.end()) return -1;
    if (it->second.epoch != epoch) return -1;
    it->second.deadline = now_s() + timeout_s;
    return 0;
  }

  // 0 todo / 1 pending / 2 done / 3 discarded / -1 unknown — the
  // queue-state probe checkpoint-lineage consistency checks run.
  int task_status(int id) {
    std::lock_guard<std::mutex> g(mu);
    for (const auto &t : todo)
      if (t.id == id) return 0;
    if (pending.count(id)) return 1;
    for (const auto &t : done)
      if (t.id == id) return 2;
    for (const auto &t : discarded)
      if (t.id == id) return 3;
    return -1;
  }

  void start_new_pass_locked() {
    // all tasks done -> recycle into todo for the next pass
    pass += 1;
    for (auto &t : done) {
      Task nt;
      nt.id = t.id;
      nt.desc = t.desc;
      todo.push_back(std::move(nt));
    }
    done.clear();
  }

  // ---- snapshot: single-line-per-task text format ------------------------
  int snapshot(const char *path) {
    std::lock_guard<std::mutex> g(mu);
    std::string tmp = std::string(path) + ".tmp";
    FILE *f = fopen(tmp.c_str(), "w");
    if (!f) return -1;
    fprintf(f, "ptmaster1 %d %d %d %d\n", next_id, pass, timeout_s,
            max_failures);
    auto dump = [&](const char tag, const Task &t) {
      fprintf(f, "%c %d %d %zu %s\n", tag, t.id, t.failures, t.desc.size(),
              t.desc.c_str());
    };
    size_t n = 0;
    for (const auto &t : todo) { dump('T', t); ++n; }
    for (const auto &kv : pending) { dump('T', kv.second); ++n; }
    for (const auto &t : done) { dump('D', t); ++n; }
    for (const auto &t : discarded) { dump('X', t); ++n; }
    fprintf(f, "end %zu\n", n); // truncation sentinel
    fclose(f);
    return rename(tmp.c_str(), path); // atomic replace
  }

  int recover(const char *path) {
    std::lock_guard<std::mutex> g(mu);
    FILE *f = fopen(path, "r");
    if (!f) return -1;
    char magic[32];
    // runtime knobs (timeout/max_failures) stay as the operator configured
    // this instance; only queue state is restored from the snapshot.
    int snap_timeout, snap_failures;
    if (fscanf(f, "%31s %d %d %d %d\n", magic, &next_id, &pass,
               &snap_timeout, &snap_failures) != 5 ||
        strcmp(magic, "ptmaster1") != 0) {
      fclose(f);
      return -2;
    }
    todo.clear();
    pending.clear();
    done.clear();
    discarded.clear();
    char tag;
    int id, failures;
    size_t len, n = 0;
    bool bad = false;
    // NOTE: no trailing whitespace directive — it would eat the desc's own
    // leading whitespace; consume exactly the single separator space, read
    // exactly len bytes, then the record's newline.
    for (;;) {
      long rec_start = ftell(f);
      char word[8];
      if (fscanf(f, " %7s", word) != 1) { bad = true; break; }
      if (strcmp(word, "end") == 0) {
        size_t expect;
        if (fscanf(f, " %zu", &expect) != 1 || expect != n) bad = true;
        break;
      }
      fseek(f, rec_start, SEEK_SET);
      if (fscanf(f, " %c %d %d %zu", &tag, &id, &failures, &len) != 4 ||
          fgetc(f) != ' ') { bad = true; break; }
      // A corrupt snapshot could carry an absurd length; allocating it would
      // throw bad_alloc across the C ABI. Treat oversize as corruption.
      const size_t kMaxDescLen = 1 << 20; // 1 MiB
      if (len > kMaxDescLen) { bad = true; break; }
      std::string desc(len, '\0');
      if (fread(&desc[0], 1, len, f) != len) { bad = true; break; }
      fgetc(f); // trailing '\n'
      Task t;
      t.id = id;
      t.desc = std::move(desc);
      t.failures = failures;
      if (tag == 'T') todo.push_back(std::move(t));
      else if (tag == 'D') done.push_back(std::move(t));
      else discarded.push_back(std::move(t));
      ++n;
    }
    fclose(f);
    if (bad) { // truncated/corrupt: refuse the partial state
      todo.clear();
      pending.clear();
      done.clear();
      discarded.clear();
      return -3;
    }
    return 0;
  }
};

} // namespace

extern "C" {

void *ptmaster_create(int timeout_s, int max_failures) {
  return new Master(timeout_s, max_failures);
}
void ptmaster_destroy(void *m) { delete static_cast<Master *>(m); }
void ptmaster_set_dataset(void *m, const char **descs, int n) {
  static_cast<Master *>(m)->set_dataset(descs, n);
}
int ptmaster_get_task(void *m, char *buf, int buflen, int *epoch_out) {
  return static_cast<Master *>(m)->get_task(buf, buflen, epoch_out);
}
int ptmaster_task_finished(void *m, int id, int epoch) {
  return static_cast<Master *>(m)->task_finished(id, epoch);
}
int ptmaster_task_failed(void *m, int id, int epoch) {
  return static_cast<Master *>(m)->task_failed(id, epoch);
}
int ptmaster_requeue(void *m, int id, int epoch, int front) {
  return static_cast<Master *>(m)->requeue(id, epoch, front);
}
int ptmaster_touch(void *m, int id, int epoch) {
  return static_cast<Master *>(m)->touch(id, epoch);
}
int ptmaster_task_status(void *m, int id) {
  return static_cast<Master *>(m)->task_status(id);
}
int ptmaster_snapshot(void *m, const char *path) {
  return static_cast<Master *>(m)->snapshot(path);
}
int ptmaster_recover(void *m, const char *path) {
  return static_cast<Master *>(m)->recover(path);
}
int ptmaster_new_pass(void *m) {
  return static_cast<Master *>(m)->new_pass();
}
int ptmaster_pass(void *m) {
  Master *mm = static_cast<Master *>(m);
  std::lock_guard<std::mutex> g(mm->mu);
  return mm->pass;
}
int ptmaster_counts(void *m, int *todo, int *pending, int *done,
                    int *discarded) {
  Master *mm = static_cast<Master *>(m);
  std::lock_guard<std::mutex> g(mm->mu);
  *todo = static_cast<int>(mm->todo.size());
  *pending = static_cast<int>(mm->pending.size());
  *done = static_cast<int>(mm->done.size());
  *discarded = static_cast<int>(mm->discarded.size());
  return 0;
}

} // extern "C"

// Pure-C inference ABI over saved inference models — the TPU-native
// analogue of the reference's paddle/capi
// (/root/reference/paddle/capi/capi.h, gradient_machine.h: create a
// machine from a merged model, forward only, no Python) for embedded /
// host-side deployment. Loads the __model__.json + params/*.npy layout
// written by paddle_tpu.io.save_inference_model and interprets the pruned
// program with small CPU kernels (this is the deployment path; the TPU
// path compiles the same program through XLA).
//
// Exposed C surface (see paddle_tpu/capi.py for the ctypes binding):
//   pdtpu_load / pdtpu_free / pdtpu_last_error
//   pdtpu_num_feeds / pdtpu_feed_name / pdtpu_num_fetches / pdtpu_fetch_name
//   pdtpu_set_input(name, data, shape, rank)
//   pdtpu_run()
//   pdtpu_output_rank / pdtpu_output_shape / pdtpu_output_numel /
//   pdtpu_output_data
#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------
// Minimal JSON (the subset python json.dump emits).
// ---------------------------------------------------------------------
struct JValue {
  enum Type { kNull, kBool, kNum, kStr, kArr, kObj } type = kNull;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<JValue> arr;
  std::map<std::string, JValue> obj;

  bool has(const std::string& k) const { return obj.count(k) > 0; }
  const JValue& at(const std::string& k) const { return obj.at(k); }
  double as_num(double dflt) const { return type == kNum ? num : dflt; }
  bool as_bool(bool dflt) const {
    if (type == kBool) return b;
    if (type == kNum) return num != 0;
    return dflt;
  }
};

struct JParser {
  const char* p;
  const char* end;
  std::string err;

  explicit JParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void skip() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool fail(const std::string& m) {
    if (err.empty()) err = m;
    return false;
  }
  bool parse(JValue* v) {
    skip();
    if (p >= end) return fail("unexpected end of json");
    switch (*p) {
      case '{': return parse_obj(v);
      case '[': return parse_arr(v);
      case '"': v->type = JValue::kStr; return parse_str(&v->str);
      case 't':
        if (end - p >= 4 && !strncmp(p, "true", 4)) {
          v->type = JValue::kBool; v->b = true; p += 4; return true;
        }
        return fail("bad literal");
      case 'f':
        if (end - p >= 5 && !strncmp(p, "false", 5)) {
          v->type = JValue::kBool; v->b = false; p += 5; return true;
        }
        return fail("bad literal");
      case 'n':
        if (end - p >= 4 && !strncmp(p, "null", 4)) {
          v->type = JValue::kNull; p += 4; return true;
        }
        return fail("bad literal");
      case 'N':  // json.dump(..., allow_nan=True) emits NaN/Infinity
        if (end - p >= 3 && !strncmp(p, "NaN", 3)) {
          v->type = JValue::kNum; v->num = NAN; p += 3; return true;
        }
        return fail("bad literal");
      case 'I':
        if (end - p >= 8 && !strncmp(p, "Infinity", 8)) {
          v->type = JValue::kNum; v->num = INFINITY; p += 8; return true;
        }
        return fail("bad literal");
      default: return parse_num(v);
    }
  }
  bool parse_num(JValue* v) {
    char* q = nullptr;
    if (end - p >= 9 && !strncmp(p, "-Infinity", 9)) {
      v->type = JValue::kNum; v->num = -INFINITY; p += 9; return true;
    }
    double d = strtod(p, &q);
    if (q == p) return fail("bad number");
    v->type = JValue::kNum;
    v->num = d;
    p = q;
    return true;
  }
  bool parse_str(std::string* s) {
    ++p;  // opening quote
    s->clear();
    while (p < end && *p != '"') {
      if (*p == '\\') {
        if (++p >= end) return fail("bad escape");
        switch (*p) {
          case 'n': s->push_back('\n'); break;
          case 't': s->push_back('\t'); break;
          case 'r': s->push_back('\r'); break;
          case 'b': s->push_back('\b'); break;
          case 'f': s->push_back('\f'); break;
          case '"': s->push_back('"'); break;
          case '\\': s->push_back('\\'); break;
          case '/': s->push_back('/'); break;
          case 'u': {
            if (end - p < 5) return fail("bad \\u escape");
            unsigned code = strtoul(std::string(p + 1, p + 5).c_str(),
                                    nullptr, 16);
            p += 4;
            // UTF-8 encode (no surrogate-pair handling: var names are ascii)
            if (code < 0x80) s->push_back(static_cast<char>(code));
            else if (code < 0x800) {
              s->push_back(static_cast<char>(0xC0 | (code >> 6)));
              s->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            } else {
              s->push_back(static_cast<char>(0xE0 | (code >> 12)));
              s->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
              s->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            }
            break;
          }
          default: return fail("bad escape");
        }
        ++p;
      } else {
        s->push_back(*p++);
      }
    }
    if (p >= end) return fail("unterminated string");
    ++p;  // closing quote
    return true;
  }
  bool parse_arr(JValue* v) {
    v->type = JValue::kArr;
    ++p;
    skip();
    if (p < end && *p == ']') { ++p; return true; }
    while (true) {
      v->arr.emplace_back();
      if (!parse(&v->arr.back())) return false;
      skip();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; return true; }
      return fail("bad array");
    }
  }
  bool parse_obj(JValue* v) {
    v->type = JValue::kObj;
    ++p;
    skip();
    if (p < end && *p == '}') { ++p; return true; }
    while (true) {
      skip();
      if (p >= end || *p != '"') return fail("bad object key");
      std::string key;
      if (!parse_str(&key)) return false;
      skip();
      if (p >= end || *p != ':') return fail("missing ':'");
      ++p;
      if (!parse(&v->obj[key])) return false;
      skip();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; return true; }
      return fail("bad object");
    }
  }
};

// ---------------------------------------------------------------------
// Tensor (float compute; inference path)
// ---------------------------------------------------------------------
struct Tensor {
  std::vector<int64_t> shape;
  std::vector<float> data;

  int64_t numel() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
};

// .npy loader (format spec: magic, version, header dict, raw data).
bool load_npy(const std::string& path, Tensor* t, std::string* err,
              const std::string& logical_dtype = "") {
  std::ifstream f(path, std::ios::binary);
  if (!f) { *err = "cannot open " + path; return false; }
  char magic[6];
  f.read(magic, 6);
  if (memcmp(magic, "\x93NUMPY", 6) != 0) {
    *err = "bad npy magic in " + path;
    return false;
  }
  unsigned char ver[2];
  f.read(reinterpret_cast<char*>(ver), 2);
  uint32_t hlen = 0;
  if (ver[0] == 1) {
    unsigned char b[2];
    f.read(reinterpret_cast<char*>(b), 2);
    hlen = b[0] | (b[1] << 8);
  } else {
    unsigned char b[4];
    f.read(reinterpret_cast<char*>(b), 4);
    hlen = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24);
  }
  std::string header(hlen, '\0');
  f.read(&header[0], hlen);
  auto find_val = [&](const std::string& key) -> std::string {
    auto k = header.find("'" + key + "'");
    if (k == std::string::npos) return "";
    auto c = header.find(':', k);
    auto e = header.find_first_of(",}", c);
    // shape tuples contain commas: extend to the closing paren
    auto par = header.find('(', c);
    if (par != std::string::npos && par < e) e = header.find(')', par) + 1;
    return header.substr(c + 1, e - c - 1);
  };
  std::string descr = find_val("descr");
  std::string shape_s = find_val("shape");
  std::string order = find_val("fortran_order");
  if (order.find("True") != std::string::npos) {
    *err = "fortran_order npy not supported: " + path;
    return false;
  }
  t->shape.clear();
  for (size_t i = 0; i < shape_s.size();) {
    if (isdigit(shape_s[i])) {
      size_t j = i;
      while (j < shape_s.size() && isdigit(shape_s[j])) ++j;
      t->shape.push_back(std::stoll(shape_s.substr(i, j - i)));
      i = j;
    } else {
      ++i;
    }
  }
  int64_t n = t->numel();
  t->data.resize(n);
  auto read_as = [&](auto sample, int width) {
    using T = decltype(sample);
    std::vector<T> buf(n);
    f.read(reinterpret_cast<char*>(buf.data()),
           static_cast<std::streamsize>(n) * width);
    for (int64_t i = 0; i < n; ++i)
      t->data[static_cast<size_t>(i)] = static_cast<float>(buf[static_cast<size_t>(i)]);
  };
  // AMP saved models carry bf16 params as uint16 bit views with the
  // logical dtype in the manifest (python io.py save_vars); widen the
  // bits to f32 (bf16 is the top half of an IEEE float).
  bool bf16_bits = logical_dtype == "bfloat16" &&
                   (descr.find("u2") != std::string::npos ||
                    descr.find("i2") != std::string::npos);
  if (bf16_bits) {
    std::vector<uint16_t> buf(n);
    f.read(reinterpret_cast<char*>(buf.data()),
           static_cast<std::streamsize>(n) * 2);
    for (int64_t i = 0; i < n; ++i) {
      uint32_t bits = static_cast<uint32_t>(buf[static_cast<size_t>(i)]) << 16;
      float v;
      memcpy(&v, &bits, 4);
      t->data[static_cast<size_t>(i)] = v;
    }
  } else if (!logical_dtype.empty() && logical_dtype != "float32" &&
             logical_dtype != "float64") {
    *err = "unsupported manifest dtype " + logical_dtype + " in " + path;
    return false;
  } else if (descr.find("<f4") != std::string::npos) read_as(float{}, 4);
  else if (descr.find("<f8") != std::string::npos) read_as(double{}, 8);
  else if (descr.find("<i8") != std::string::npos) read_as(int64_t{}, 8);
  else if (descr.find("<i4") != std::string::npos) read_as(int32_t{}, 4);
  else if (descr.find("|b1") != std::string::npos) read_as(int8_t{}, 1);
  else {
    *err = "unsupported npy dtype " + descr + " in " + path;
    return false;
  }
  if (!f) { *err = "short read in " + path; return false; }
  return true;
}

// ---------------------------------------------------------------------
// Program model
// ---------------------------------------------------------------------
struct OpDesc {
  std::string type;
  std::map<std::string, std::vector<std::string>> ins, outs;
  JValue attrs;  // kObj

  const JValue* attr(const std::string& name) const {
    auto it = attrs.obj.find(name);
    return it == attrs.obj.end() ? nullptr : &it->second;
  }
  double attr_num(const std::string& name, double dflt) const {
    auto* a = attr(name);
    return a ? a->as_num(dflt) : dflt;
  }
  bool attr_bool(const std::string& name, bool dflt) const {
    auto* a = attr(name);
    return a ? a->as_bool(dflt) : dflt;
  }
  std::string attr_str(const std::string& name,
                       const std::string& dflt) const {
    auto* a = attr(name);
    return a && a->type == JValue::kStr ? a->str : dflt;
  }
  // integer-list attrs (split sections, slice axes/starts/ends)
  std::vector<int64_t> attr_ints(const std::string& name) const {
    std::vector<int64_t> v;
    const JValue* a = attr(name);
    if (a && a->type == JValue::kArr)
      for (const auto& e : a->arr) v.push_back(static_cast<int64_t>(e.num));
    else if (a && a->type == JValue::kNum)
      v.push_back(static_cast<int64_t>(a->num));
    return v;
  }
  // int-or-[int, int] attrs (strides/paddings/ksize)
  void attr_pair(const std::string& name, int dflt, int* a_, int* b_) const {
    const JValue* a = attr(name);
    *a_ = *b_ = dflt;
    if (!a) return;
    if (a->type == JValue::kNum) { *a_ = *b_ = static_cast<int>(a->num); }
    else if (a->type == JValue::kArr && a->arr.size() >= 2) {
      *a_ = static_cast<int>(a->arr[0].num);
      *b_ = static_cast<int>(a->arr[1].num);
    } else if (a->type == JValue::kArr && a->arr.size() == 1) {
      *a_ = *b_ = static_cast<int>(a->arr[0].num);
    }
  }
};

struct QTensor {  // weight-only int8 (per-output-channel scales)
  std::vector<int8_t> data;   // [rows, cols] row-major
  std::vector<float> scales;  // [cols]
  int64_t rows = 0, cols = 0;
};

struct Machine {
  std::vector<OpDesc> ops;
  std::vector<std::string> feeds, fetches;
  std::map<std::string, Tensor> params;  // persistables from params/
  std::map<std::string, QTensor> qweights;  // __quant__.json int8 weights
  std::map<std::string, Tensor> env;     // per-run values
  std::string error;
};

thread_local std::string g_last_error;

// ---------------------------------------------------------------------
// Kernels
// ---------------------------------------------------------------------
using Kernel = bool (*)(Machine&, const OpDesc&);

Tensor* lookup(Machine& m, const std::string& name) {
  auto it = m.env.find(name);
  if (it != m.env.end()) return &it->second;
  auto p = m.params.find(name);
  if (p != m.params.end()) return &p->second;
  return nullptr;
}

bool need(Machine& m, const OpDesc& op, const std::string& slot, Tensor** t,
          int idx = 0) {
  auto it = op.ins.find(slot);
  if (it == op.ins.end() || static_cast<int>(it->second.size()) <= idx) {
    m.error = "op '" + op.type + "': missing input slot " + slot;
    return false;
  }
  *t = lookup(m, it->second[static_cast<size_t>(idx)]);
  if (!*t) {
    m.error = "op '" + op.type + "': input '" + it->second[static_cast<size_t>(idx)] +
              "' has no value (feed it or run startup/save params)";
    return false;
  }
  return true;
}

Tensor& set_out(Machine& m, const OpDesc& op, const std::string& slot) {
  return m.env[op.outs.at(slot).at(0)];
}

bool k_mul_quant(Machine& m, const OpDesc& op, const QTensor& q) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  int xd = static_cast<int>(op.attr_num("x_num_col_dims", 1));
  // The int8 path stores Y as a 2-D [rows, cols] QTensor; a model asking
  // to re-flatten Y (y_num_col_dims != 1) cannot be served from it.
  int yd = static_cast<int>(op.attr_num("y_num_col_dims", 1));
  if (yd != 1) {
    m.error = "mul(int8): y_num_col_dims=" + std::to_string(yd) +
              " unsupported for quantized weights (expected 1)";
    return false;
  }
  if (xd <= 0 || xd >= static_cast<int>(x->shape.size())) {
    m.error = "mul(int8): x_num_col_dims=" + std::to_string(xd) +
              " out of range for rank " + std::to_string(x->shape.size());
    return false;
  }
  int64_t M = 1, K = 1;
  for (int i = 0; i < xd; ++i) M *= x->shape[static_cast<size_t>(i)];
  for (size_t i = static_cast<size_t>(xd); i < x->shape.size(); ++i)
    K *= x->shape[i];
  if (K != q.rows) {
    m.error = "mul(int8): contraction mismatch " + std::to_string(K) +
              " vs " + std::to_string(q.rows);
    return false;
  }
  int64_t N = q.cols;
  Tensor& o = set_out(m, op, "Out");
  o.shape.assign(x->shape.begin(), x->shape.begin() + xd);
  o.shape.push_back(N);
  o.data.assign(static_cast<size_t>(M * N), 0.f);
  const float* A = x->data.data();
  const int8_t* B = q.data.data();
  float* C = o.data.data();
  // accumulate against raw int8, fold the per-column scale once at the end
  for (int64_t i = 0; i < M; ++i)
    for (int64_t k = 0; k < K; ++k) {
      float a = A[i * K + k];
      if (a == 0.f) continue;
      const int8_t* Bk = B + k * N;
      float* Ci = C + i * N;
      for (int64_t n = 0; n < N; ++n) Ci[n] += a * Bk[n];
    }
  for (int64_t i = 0; i < M; ++i)
    for (int64_t n = 0; n < N; ++n) C[i * N + n] *= q.scales[n];
  return true;
}

bool k_mul(Machine& m, const OpDesc& op) {
  Tensor *x, *y;
  auto yit = op.ins.find("Y");
  if (yit != op.ins.end() && !yit->second.empty()) {
    auto q = m.qweights.find(yit->second[0]);
    if (q != m.qweights.end()) return k_mul_quant(m, op, q->second);
  }
  if (!need(m, op, "X", &x) || !need(m, op, "Y", &y)) return false;
  int xd = static_cast<int>(op.attr_num("x_num_col_dims", 1));
  int yd = static_cast<int>(op.attr_num("y_num_col_dims", 1));
  if (xd <= 0 || xd >= static_cast<int>(x->shape.size()) ||
      yd <= 0 || yd >= static_cast<int>(y->shape.size())) {
    m.error = "mul: num_col_dims (" + std::to_string(xd) + ", " +
              std::to_string(yd) + ") out of range for ranks (" +
              std::to_string(x->shape.size()) + ", " +
              std::to_string(y->shape.size()) + ")";
    return false;
  }
  int64_t M = 1, K = 1, K2 = 1, N = 1;
  for (int i = 0; i < xd; ++i) M *= x->shape[static_cast<size_t>(i)];
  for (size_t i = static_cast<size_t>(xd); i < x->shape.size(); ++i) K *= x->shape[i];
  for (int i = 0; i < yd; ++i) K2 *= y->shape[static_cast<size_t>(i)];
  for (size_t i = static_cast<size_t>(yd); i < y->shape.size(); ++i) N *= y->shape[i];
  if (K != K2) {
    m.error = "mul: contraction mismatch " + std::to_string(K) + " vs " +
              std::to_string(K2);
    return false;
  }
  Tensor& o = set_out(m, op, "Out");
  o.shape.assign(x->shape.begin(), x->shape.begin() + xd);
  o.shape.insert(o.shape.end(), y->shape.begin() + yd, y->shape.end());
  o.data.assign(static_cast<size_t>(M * N), 0.f);
  const float* A = x->data.data();
  const float* B = y->data.data();
  float* C = o.data.data();
  for (int64_t i = 0; i < M; ++i)
    for (int64_t k = 0; k < K; ++k) {
      float a = A[i * K + k];
      if (a == 0.f) continue;
      const float* brow = B + k * N;
      float* crow = C + i * N;
      for (int64_t j = 0; j < N; ++j) crow[j] += a * brow[j];
    }
  return true;
}

// reference elementwise broadcast: y aligns to x at `axis`
// (ops/common.py broadcast_to_x).
template <typename F>
bool k_elementwise(Machine& m, const OpDesc& op, F f) {
  Tensor *x, *y;
  if (!need(m, op, "X", &x) || !need(m, op, "Y", &y)) return false;
  int axis = static_cast<int>(op.attr_num("axis", -1));
  int xr = static_cast<int>(x->shape.size());
  int yr = static_cast<int>(y->shape.size());
  if (axis < 0) axis = xr - yr;
  if (axis < 0 || axis + yr > xr) {
    m.error = "elementwise: y rank/axis does not fit x (axis=" +
              std::to_string(axis) + ", rank(y)=" + std::to_string(yr) +
              ", rank(x)=" + std::to_string(xr) + ")";
    return false;
  }
  Tensor& o = set_out(m, op, "Out");
  o.shape = x->shape;
  o.data.resize(x->data.size());
  // strides for y broadcast: pre (dims before axis) x ymid x post
  int64_t pre = 1, mid = 1, post = 1;
  for (int i = 0; i < axis; ++i) pre *= x->shape[static_cast<size_t>(i)];
  for (int i = 0; i < yr; ++i) mid *= x->shape[static_cast<size_t>(axis + i)];
  for (int i = axis + yr; i < xr; ++i) post *= x->shape[static_cast<size_t>(i)];
  if (mid != y->numel()) {
    m.error = "elementwise: y shape does not align with x at axis " +
              std::to_string(axis);
    return false;
  }
  for (int64_t a = 0; a < pre; ++a)
    for (int64_t b = 0; b < mid; ++b) {
      float yv = y->data[static_cast<size_t>(b)];
      const float* xs = x->data.data() + (a * mid + b) * post;
      float* os = o.data.data() + (a * mid + b) * post;
      for (int64_t c = 0; c < post; ++c) os[c] = f(xs[c], yv);
    }
  return true;
}

template <typename F>
bool k_unary(Machine& m, const OpDesc& op, F f) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  Tensor& o = set_out(m, op, "Out");
  o.shape = x->shape;
  o.data.resize(x->data.size());
  for (size_t i = 0; i < x->data.size(); ++i) o.data[i] = f(x->data[i]);
  return true;
}

bool k_softmax(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  Tensor& o = set_out(m, op, "Out");
  o.shape = x->shape;
  o.data.resize(x->data.size());
  int64_t cols = x->shape.empty() ? 1 : x->shape.back();
  int64_t rows = x->numel() / cols;
  for (int64_t r = 0; r < rows; ++r) {
    const float* xi = x->data.data() + r * cols;
    float* oi = o.data.data() + r * cols;
    float mx = xi[0];
    for (int64_t c = 1; c < cols; ++c) mx = std::max(mx, xi[c]);
    float sum = 0;
    for (int64_t c = 0; c < cols; ++c) {
      oi[c] = std::exp(xi[c] - mx);
      sum += oi[c];
    }
    for (int64_t c = 0; c < cols; ++c) oi[c] /= sum;
  }
  return true;
}

bool k_conv2d(Machine& m, const OpDesc& op) {
  Tensor *x, *w;
  if (!need(m, op, "Input", &x) || !need(m, op, "Filter", &w)) return false;
  std::string fmt = op.attr_str("data_format", "NCHW");
  int sh, sw, ph, pw, dh, dw;
  op.attr_pair("strides", 1, &sh, &sw);
  op.attr_pair("paddings", 0, &ph, &pw);
  op.attr_pair("dilations", 1, &dh, &dw);
  int groups = static_cast<int>(op.attr_num("groups", 1));
  int64_t N, H, W, Ci, kh, kw, Co;
  bool nhwc = (fmt == "NHWC");
  if (nhwc) {  // filter HWIO
    N = x->shape[0]; H = x->shape[1]; W = x->shape[2]; Ci = x->shape[3];
    kh = w->shape[0]; kw = w->shape[1]; Co = w->shape[3];
  } else {     // filter OIHW
    N = x->shape[0]; Ci = x->shape[1]; H = x->shape[2]; W = x->shape[3];
    Co = w->shape[0]; kh = w->shape[2]; kw = w->shape[3];
  }
  int64_t cig = Ci / groups, cog = Co / groups;
  int64_t OH = (H + 2 * ph - dh * (kh - 1) - 1) / sh + 1;
  int64_t OW = (W + 2 * pw - dw * (kw - 1) - 1) / sw + 1;
  Tensor& o = set_out(m, op, "Output");
  o.shape = nhwc ? std::vector<int64_t>{N, OH, OW, Co}
                 : std::vector<int64_t>{N, Co, OH, OW};
  o.data.assign(static_cast<size_t>(N * OH * OW * Co), 0.f);
  auto xat = [&](int64_t n, int64_t h, int64_t ww, int64_t c) -> float {
    if (h < 0 || h >= H || ww < 0 || ww >= W) return 0.f;
    return nhwc ? x->data[static_cast<size_t>(((n * H + h) * W + ww) * Ci + c)]
                : x->data[static_cast<size_t>(((n * Ci + c) * H + h) * W + ww)];
  };
  auto wat = [&](int64_t fh, int64_t fw, int64_t ci, int64_t co) -> float {
    return nhwc ? w->data[static_cast<size_t>(((fh * kw + fw) * cig + ci) * Co + co)]
                : w->data[static_cast<size_t>(((co * cig + ci) * kh + fh) * kw + fw)];
  };
  auto oat = [&](int64_t n, int64_t h, int64_t ww, int64_t c) -> float& {
    return nhwc ? o.data[static_cast<size_t>(((n * OH + h) * OW + ww) * Co + c)]
                : o.data[static_cast<size_t>(((n * Co + c) * OH + h) * OW + ww)];
  };
  for (int64_t n = 0; n < N; ++n)
    for (int64_t g = 0; g < groups; ++g)
      for (int64_t co = g * cog; co < (g + 1) * cog; ++co)
        for (int64_t oh = 0; oh < OH; ++oh)
          for (int64_t ow = 0; ow < OW; ++ow) {
            float acc = 0.f;
            for (int64_t fh = 0; fh < kh; ++fh)
              for (int64_t fw = 0; fw < kw; ++fw) {
                int64_t ih = oh * sh - ph + fh * dh;
                int64_t iw = ow * sw - pw + fw * dw;
                if (ih < 0 || ih >= H || iw < 0 || iw >= W) continue;
                for (int64_t ci = 0; ci < cig; ++ci)
                  acc += xat(n, ih, iw, g * cig + ci) * wat(fh, fw, ci, co);
              }
            oat(n, oh, ow, co) = acc;
          }
  return true;
}

bool k_pool2d(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  std::string fmt = op.attr_str("data_format", "NCHW");
  bool nhwc = (fmt == "NHWC");
  std::string ptype = op.attr_str("pooling_type", "max");
  int kh, kw, sh, sw, ph, pw;
  op.attr_pair("ksize", 2, &kh, &kw);
  op.attr_pair("strides", 1, &sh, &sw);
  op.attr_pair("paddings", 0, &ph, &pw);
  int64_t N, H, W, C;
  if (nhwc) { N = x->shape[0]; H = x->shape[1]; W = x->shape[2]; C = x->shape[3]; }
  else { N = x->shape[0]; C = x->shape[1]; H = x->shape[2]; W = x->shape[3]; }
  if (op.attr_bool("global_pooling", false)) {
    kh = static_cast<int>(H); kw = static_cast<int>(W);
    ph = pw = 0; sh = sw = 1;
  }
  int64_t OH = (H + 2 * ph - kh) / sh + 1;
  int64_t OW = (W + 2 * pw - kw) / sw + 1;
  Tensor& o = set_out(m, op, "Out");
  o.shape = nhwc ? std::vector<int64_t>{N, OH, OW, C}
                 : std::vector<int64_t>{N, C, OH, OW};
  o.data.resize(static_cast<size_t>(N * OH * OW * C));
  auto xat = [&](int64_t n, int64_t h, int64_t ww, int64_t c) -> float {
    return nhwc ? x->data[static_cast<size_t>(((n * H + h) * W + ww) * C + c)]
                : x->data[static_cast<size_t>(((n * C + c) * H + h) * W + ww)];
  };
  bool is_max = (ptype == "max");
  // avg divisor: exclusive (default) counts only in-bounds cells; the
  // non-exclusive mode divides border windows by the full kh*kw
  // (ops/nn_ops.py pool2d).
  bool exclusive = op.attr_bool("exclusive", true);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t c = 0; c < C; ++c)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float acc = is_max ? -INFINITY : 0.f;
          int cnt = 0;
          for (int fh = 0; fh < kh; ++fh)
            for (int fw = 0; fw < kw; ++fw) {
              int64_t ih = oh * sh - ph + fh;
              int64_t iw = ow * sw - pw + fw;
              if (ih < 0 || ih >= H || iw < 0 || iw >= W) continue;
              float v = xat(n, ih, iw, c);
              if (is_max) acc = std::max(acc, v);
              else acc += v;
              ++cnt;
            }
          if (!is_max) {
            int div = exclusive ? cnt : kh * kw;
            if (div > 0) acc /= static_cast<float>(div);
          }
          size_t oi = nhwc
              ? static_cast<size_t>(((n * OH + oh) * OW + ow) * C + c)
              : static_cast<size_t>(((n * C + c) * OH + oh) * OW + ow);
          o.data[oi] = acc;
        }
  return true;
}

bool k_batch_norm(Machine& m, const OpDesc& op) {
  Tensor *x, *scale, *bias, *mean, *var;
  if (!need(m, op, "X", &x) || !need(m, op, "Scale", &scale) ||
      !need(m, op, "Bias", &bias) || !need(m, op, "Mean", &mean) ||
      !need(m, op, "Variance", &var))
    return false;
  std::string fmt = op.attr_str("data_layout", op.attr_str("data_format",
                                                           "NCHW"));
  double eps = op.attr_num("epsilon", 1e-5);
  Tensor& o = set_out(m, op, "Y");
  o.shape = x->shape;
  o.data.resize(x->data.size());
  int64_t C = mean->numel();
  int64_t n = x->numel();
  bool channels_last = (fmt != "NCHW") || x->shape.size() == 2;
  int64_t inner = 1;
  if (!channels_last)
    for (size_t i = 2; i < x->shape.size(); ++i) inner *= x->shape[i];
  for (int64_t i = 0; i < n; ++i) {
    int64_t c = channels_last ? (i % C) : ((i / inner) % C);
    float inv = 1.0f / std::sqrt(var->data[static_cast<size_t>(c)] +
                                 static_cast<float>(eps));
    o.data[static_cast<size_t>(i)] =
        (x->data[static_cast<size_t>(i)] - mean->data[static_cast<size_t>(c)]) * inv *
            scale->data[static_cast<size_t>(c)] +
        bias->data[static_cast<size_t>(c)];
  }
  return true;
}

bool k_conv1x1_bn_act(Machine& m, const OpDesc& op) {
  // Fused NHWC 1x1-conv + BN + act (+ residual) — ops/fusion_ops.py.
  // Serving form: fold (scale, bias, mean, var) into the elementwise
  // affine k = scale*rsqrt(var+eps), b = bias - mean*k, then
  // y = act((x . W) * k + b [+ residual]).
  Tensor *x, *w, *scale, *bias, *mean, *var;
  if (!need(m, op, "X", &x) || !need(m, op, "Filter", &w) ||
      !need(m, op, "Scale", &scale) || !need(m, op, "Bias", &bias) ||
      !need(m, op, "Mean", &mean) || !need(m, op, "Variance", &var))
    return false;
  Tensor* res = nullptr;
  auto rit = op.ins.find("Residual");
  if (rit != op.ins.end() && !rit->second.empty()) {
    auto e = m.env.find(rit->second[0]);
    if (e == m.env.end()) {
      m.error = "conv1x1_bn_act: residual input missing";
      return false;
    }
    res = &e->second;
  }
  if (x->shape.size() != 4) {
    m.error = "conv1x1_bn_act: X must be NHWC 4-D";
    return false;
  }
  int64_t N = x->shape[0], H = x->shape[1], W = x->shape[2],
          I = x->shape[3];
  int64_t O = w->shape[w->shape.size() - 1];
  if (w->numel() != I * O) {
    m.error = "conv1x1_bn_act: filter is not [1,1,I,O]";
    return false;
  }
  if (scale->numel() < O || bias->numel() < O || mean->numel() < O ||
      var->numel() < O) {
    m.error = "conv1x1_bn_act: BN vectors smaller than O=" +
              std::to_string(O);
    return false;
  }
  if (res && res->numel() != N * H * W * O) {
    m.error = "conv1x1_bn_act: residual numel " +
              std::to_string(res->numel()) + " != N*H*W*O";
    return false;
  }
  bool relu = op.attr_str("act", "") == std::string("relu");
  double eps = op.attr_num("epsilon", 1e-5);
  std::vector<float> kf(static_cast<size_t>(O)), bf(static_cast<size_t>(O));
  for (int64_t c = 0; c < O; ++c) {
    float inv = 1.0f / std::sqrt(var->data[static_cast<size_t>(c)] +
                                 static_cast<float>(eps));
    kf[static_cast<size_t>(c)] = scale->data[static_cast<size_t>(c)] * inv;
    bf[static_cast<size_t>(c)] =
        bias->data[static_cast<size_t>(c)] -
        mean->data[static_cast<size_t>(c)] * kf[static_cast<size_t>(c)];
  }
  Tensor& o = set_out(m, op, "Y");
  o.shape = {N, H, W, O};
  o.data.assign(static_cast<size_t>(N * H * W * O), 0.f);
  int64_t R = N * H * W;
  for (int64_t r = 0; r < R; ++r) {
    const float* xr = x->data.data() + r * I;
    float* orow = o.data.data() + r * O;
    for (int64_t i = 0; i < I; ++i) {
      float a = xr[i];
      if (a == 0.f) continue;
      const float* wrow = w->data.data() + i * O;
      for (int64_t c = 0; c < O; ++c) orow[c] += a * wrow[c];
    }
    for (int64_t c = 0; c < O; ++c) {
      float y = orow[c] * kf[static_cast<size_t>(c)] +
                bf[static_cast<size_t>(c)];
      if (res) y += res->data[static_cast<size_t>(r * O + c)];
      orow[c] = relu ? std::max(y, 0.f) : y;
    }
  }
  return true;
}

bool k_reshape(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  const JValue* sh = op.attr("shape");
  if (!sh || sh->type != JValue::kArr) {
    m.error = "reshape: missing shape attr";
    return false;
  }
  std::vector<int64_t> ns;
  int64_t known = 1, minus1 = -1;
  for (size_t i = 0; i < sh->arr.size(); ++i) {
    int64_t d = static_cast<int64_t>(sh->arr[i].num);
    if (d == 0) {  // reference: 0 copies the input dim
      if (i >= x->shape.size()) {
        m.error = "reshape: 0 at position " + std::to_string(i) +
                  " exceeds input rank";
        return false;
      }
      d = x->shape[i];
    }
    if (d == -1) minus1 = static_cast<int64_t>(i);
    else known *= d;
    ns.push_back(d);
  }
  if (minus1 >= 0) ns[static_cast<size_t>(minus1)] = x->numel() / known;
  Tensor& o = set_out(m, op, "Out");
  o.shape = ns;
  o.data = x->data;
  return true;
}

bool k_concat(Machine& m, const OpDesc& op) {
  const auto& names = op.ins.at("X");
  std::vector<Tensor*> xs;
  for (const auto& nm : names) {
    Tensor* t = lookup(m, nm);
    if (!t) { m.error = "concat: missing input " + nm; return false; }
    xs.push_back(t);
  }
  int axis = static_cast<int>(op.attr_num("axis", 0));
  int rank = static_cast<int>(xs[0]->shape.size());
  if (axis < 0) axis += rank;
  Tensor& o = set_out(m, op, "Out");
  o.shape = xs[0]->shape;
  int64_t cat = 0;
  for (auto* t : xs) cat += t->shape[static_cast<size_t>(axis)];
  o.shape[static_cast<size_t>(axis)] = cat;
  int64_t outer = 1, inner = 1;
  for (int i = 0; i < axis; ++i) outer *= xs[0]->shape[static_cast<size_t>(i)];
  for (int i = axis + 1; i < rank; ++i) inner *= xs[0]->shape[static_cast<size_t>(i)];
  o.data.resize(static_cast<size_t>(outer * cat * inner));
  int64_t off = 0;
  for (auto* t : xs) {
    int64_t tc = t->shape[static_cast<size_t>(axis)];
    for (int64_t a = 0; a < outer; ++a)
      memcpy(o.data.data() + (a * cat + off) * inner,
             t->data.data() + a * tc * inner,
             static_cast<size_t>(tc * inner) * sizeof(float));
    off += tc;
  }
  return true;
}

bool k_scale(Machine& m, const OpDesc& op) {
  float s = static_cast<float>(op.attr_num("scale", 1.0));
  float b = static_cast<float>(op.attr_num("bias", 0.0));
  return k_unary(m, op, [s, b](float v) { return s * v + b; });
}

bool k_dropout(Machine& m, const OpDesc& op) {
  // inference path only (downscale-in-infer, ops/nn_ops.py dropout)
  float p = static_cast<float>(op.attr_num("dropout_prob", 0.5));
  if (!op.attr_bool("is_test", false)) {
    m.error = "dropout: capi machine runs inference programs only "
              "(is_test=false)";
    return false;
  }
  return k_unary(m, op, [p](float v) { return v * (1.0f - p); });
}

bool k_mean(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  Tensor& o = set_out(m, op, "Out");
  o.shape.clear();  // rank-0
  double acc = 0;
  for (float v : x->data) acc += v;
  o.data.assign(1, static_cast<float>(acc / std::max<int64_t>(x->numel(), 1)));
  return true;
}

bool k_transpose(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  const JValue* ax = op.attr("axis");
  if (!ax || ax->type != JValue::kArr) {
    m.error = "transpose: missing axis attr";
    return false;
  }
  int rank = static_cast<int>(x->shape.size());
  std::vector<int> perm;
  for (auto& v : ax->arr) perm.push_back(static_cast<int>(v.num));
  Tensor& o = set_out(m, op, "Out");
  o.shape.resize(static_cast<size_t>(rank));
  for (int i = 0; i < rank; ++i)
    o.shape[static_cast<size_t>(i)] = x->shape[static_cast<size_t>(perm[static_cast<size_t>(i)])];
  o.data.resize(x->data.size());
  std::vector<int64_t> xstr(static_cast<size_t>(rank), 1), ostr(static_cast<size_t>(rank), 1);
  for (int i = rank - 2; i >= 0; --i)
    xstr[static_cast<size_t>(i)] = xstr[static_cast<size_t>(i + 1)] * x->shape[static_cast<size_t>(i + 1)];
  for (int i = rank - 2; i >= 0; --i)
    ostr[static_cast<size_t>(i)] = ostr[static_cast<size_t>(i + 1)] * o.shape[static_cast<size_t>(i + 1)];
  int64_t n = x->numel();
  for (int64_t i = 0; i < n; ++i) {
    int64_t rem = i, xi = 0;
    for (int d = 0; d < rank; ++d) {
      int64_t coord = rem / ostr[static_cast<size_t>(d)];
      rem %= ostr[static_cast<size_t>(d)];
      xi += coord * xstr[static_cast<size_t>(perm[static_cast<size_t>(d)])];
    }
    o.data[static_cast<size_t>(i)] = x->data[static_cast<size_t>(xi)];
  }
  return true;
}

bool k_assign(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  Tensor& o = set_out(m, op, "Out");
  o = *x;
  return true;
}

// --- recurrent kernels -------------------------------------------------
// The reference's C API serves gserver RNN gradient machines for
// deployment (/root/reference/paddle/capi/gradient_machine.h); the
// equivalents here are the scan kernels that ops/rnn_ops.py runs on TPU,
// re-expressed as plain loops: lookup_table -> mul -> lstm/gru ->
// sequence_pool -> mul is the classic saved text-classifier graph.

Tensor* opt_in(Machine& m, const OpDesc& op, const std::string& slot) {
  auto it = op.ins.find(slot);
  if (it == op.ins.end() || it->second.empty()) return nullptr;
  return lookup(m, it->second[0]);
}

bool has_out(const OpDesc& op, const std::string& slot) {
  auto it = op.outs.find(slot);
  return it != op.outs.end() && !it->second.empty();
}

float apply_act(const std::string& kind, float v) {
  if (kind == "sigmoid") return 1.f / (1.f + std::exp(-v));
  if (kind == "tanh") return std::tanh(v);
  if (kind == "relu") return v > 0.f ? v : 0.f;
  return v;  // identity
}

bool k_lookup_table(Machine& m, const OpDesc& op) {
  Tensor *w, *ids;
  if (!need(m, op, "W", &w) || !need(m, op, "Ids", &ids)) return false;
  int64_t V = w->shape[0], D = w->shape[1];
  bool squeeze = ids->shape.size() > 1 && ids->shape.back() == 1;
  int64_t n = ids->numel();
  double pad = op.attr_num("padding_idx", -1);
  Tensor& o = set_out(m, op, "Out");
  o.shape = ids->shape;
  if (squeeze) o.shape.pop_back();
  o.shape.push_back(D);
  o.data.resize(static_cast<size_t>(n * D));
  for (int64_t i = 0; i < n; ++i) {
    int64_t id = static_cast<int64_t>(ids->data[static_cast<size_t>(i)]);
    if (id < 0 || id >= V) {
      m.error = "lookup_table: id " + std::to_string(id) +
                " out of range [0, " + std::to_string(V) + ")";
      return false;
    }
    float* row = o.data.data() + i * D;
    if (pad >= 0 && id == static_cast<int64_t>(pad)) {
      for (int64_t d = 0; d < D; ++d) row[d] = 0.f;
    } else {
      const float* src = w->data.data() + id * D;
      for (int64_t d = 0; d < D; ++d) row[d] = src[d];
    }
  }
  return true;
}

bool k_lstm(Machine& m, const OpDesc& op) {
  Tensor *x, *w;  // x: [b, T, 4h] pre-projected; w: [h, 4h]
  if (!need(m, op, "Input", &x) || !need(m, op, "Weight", &w)) return false;
  Tensor* bias = opt_in(m, op, "Bias");   // [1, 4h] or [1, 7h] w/ peepholes
  Tensor* len = opt_in(m, op, "Length");  // [b]
  Tensor* h0 = opt_in(m, op, "H0");
  Tensor* c0 = opt_in(m, op, "C0");
  int64_t B = x->shape[0], T = x->shape[1], H4 = x->shape[2], H = H4 / 4;
  bool peep = op.attr_bool("use_peepholes", false);
  bool rev = op.attr_bool("is_reverse", false);
  std::string ag = op.attr_str("gate_activation", "sigmoid");
  std::string ac = op.attr_str("candidate_activation", "tanh");
  std::string ah = op.attr_str("cell_activation", "tanh");

  Tensor& hid = set_out(m, op, "Hidden");
  hid.shape = {B, T, H};
  hid.data.assign(static_cast<size_t>(B * T * H), 0.f);
  std::vector<float> hbuf(static_cast<size_t>(B * H), 0.f);
  std::vector<float> cbuf(static_cast<size_t>(B * H), 0.f);
  if (h0) hbuf.assign(h0->data.begin(), h0->data.end());
  if (c0) cbuf.assign(c0->data.begin(), c0->data.end());
  std::vector<float> cell_seq;
  if (has_out(op, "Cell"))
    cell_seq.assign(static_cast<size_t>(B * T * H), 0.f);

  std::vector<float> gates(static_cast<size_t>(H4));
  for (int64_t step = 0; step < T; ++step) {
    int64_t t = rev ? T - 1 - step : step;
    for (int64_t n = 0; n < B; ++n) {
      bool active = !len ||
          t < static_cast<int64_t>(len->data[static_cast<size_t>(n)]);
      float* hrow = hbuf.data() + n * H;
      float* crow = cbuf.data() + n * H;
      if (!active) continue;  // frozen state, zero output row (mask calc)
      const float* xrow = x->data.data() + (n * T + t) * H4;
      // gates = x_t + h @ W (+ bias); gate order (c, i, f, o)
      for (int64_t j = 0; j < H4; ++j)
        gates[static_cast<size_t>(j)] =
            xrow[j] + (bias ? bias->data[static_cast<size_t>(j)] : 0.f);
      for (int64_t k = 0; k < H; ++k) {
        float hv = hrow[k];
        if (hv == 0.f) continue;
        const float* wrow = w->data.data() + k * H4;
        for (int64_t j = 0; j < H4; ++j)
          gates[static_cast<size_t>(j)] += hv * wrow[j];
      }
      const float* pw = (peep && bias) ? bias->data.data() + 4 * H : nullptr;
      for (int64_t k = 0; k < H; ++k) {
        float gc = gates[static_cast<size_t>(k)];
        float gi = gates[static_cast<size_t>(H + k)];
        float gf = gates[static_cast<size_t>(2 * H + k)];
        float go = gates[static_cast<size_t>(3 * H + k)];
        if (pw) {
          gi += pw[k] * crow[k];          // W_ic
          gf += pw[H + k] * crow[k];      // W_fc
        }
        float i = apply_act(ag, gi);
        float f = apply_act(ag, gf);
        float cn = f * crow[k] + i * apply_act(ac, gc);
        if (pw) go += pw[2 * H + k] * cn;  // W_oc on NEW cell
        float o = apply_act(ag, go);
        float hn = o * apply_act(ah, cn);
        crow[k] = cn;
        hrow[k] = hn;
        hid.data[static_cast<size_t>((n * T + t) * H + k)] = hn;
        if (!cell_seq.empty())
          cell_seq[static_cast<size_t>((n * T + t) * H + k)] = cn;
      }
    }
  }
  if (has_out(op, "Cell")) {
    Tensor& c = set_out(m, op, "Cell");
    c.shape = {B, T, H};
    c.data = std::move(cell_seq);
  }
  if (has_out(op, "LastH")) {
    Tensor& lh = set_out(m, op, "LastH");
    lh.shape = {B, H};
    lh.data = hbuf;
  }
  if (has_out(op, "LastC")) {
    Tensor& lc = set_out(m, op, "LastC");
    lc.shape = {B, H};
    lc.data = cbuf;
  }
  return true;
}

bool k_gru(Machine& m, const OpDesc& op) {
  Tensor *x, *w;  // x: [b, T, 3h] pre-projected; w: [h, 3h]
  if (!need(m, op, "Input", &x) || !need(m, op, "Weight", &w)) return false;
  Tensor* bias = opt_in(m, op, "Bias");   // [1, 3h], added to x upfront
  Tensor* len = opt_in(m, op, "Length");
  Tensor* h0 = opt_in(m, op, "H0");
  int64_t B = x->shape[0], T = x->shape[1], H3 = x->shape[2], H = H3 / 3;
  bool rev = op.attr_bool("is_reverse", false);
  std::string ag = op.attr_str("gate_activation", "sigmoid");
  std::string ac = op.attr_str("activation", "tanh");

  Tensor& hid = set_out(m, op, "Hidden");
  hid.shape = {B, T, H};
  hid.data.assign(static_cast<size_t>(B * T * H), 0.f);
  std::vector<float> hbuf(static_cast<size_t>(B * H), 0.f);
  if (h0) hbuf.assign(h0->data.begin(), h0->data.end());

  std::vector<float> g(static_cast<size_t>(2 * H)), cand(static_cast<size_t>(H));
  for (int64_t step = 0; step < T; ++step) {
    int64_t t = rev ? T - 1 - step : step;
    for (int64_t n = 0; n < B; ++n) {
      bool active = !len ||
          t < static_cast<int64_t>(len->data[static_cast<size_t>(n)]);
      if (!active) continue;
      float* hrow = hbuf.data() + n * H;
      const float* xrow = x->data.data() + (n * T + t) * H3;
      // u|r gates: act(x_g + h @ W[:, :2h])
      for (int64_t j = 0; j < 2 * H; ++j)
        g[static_cast<size_t>(j)] =
            xrow[j] + (bias ? bias->data[static_cast<size_t>(j)] : 0.f);
      for (int64_t k = 0; k < H; ++k) {
        float hv = hrow[k];
        if (hv == 0.f) continue;
        const float* wrow = w->data.data() + k * H3;
        for (int64_t j = 0; j < 2 * H; ++j)
          g[static_cast<size_t>(j)] += hv * wrow[j];
      }
      for (int64_t j = 0; j < 2 * H; ++j)
        g[static_cast<size_t>(j)] = apply_act(ag, g[static_cast<size_t>(j)]);
      // candidate: act(x_c + (r . h) @ W[:, 2h:])
      for (int64_t k = 0; k < H; ++k)
        cand[static_cast<size_t>(k)] = xrow[2 * H + k] +
            (bias ? bias->data[static_cast<size_t>(2 * H + k)] : 0.f);
      for (int64_t k = 0; k < H; ++k) {
        float rh = g[static_cast<size_t>(H + k)] * hrow[k];
        if (rh == 0.f) continue;
        const float* wrow = w->data.data() + k * H3 + 2 * H;
        for (int64_t j = 0; j < H; ++j)
          cand[static_cast<size_t>(j)] += rh * wrow[j];
      }
      for (int64_t k = 0; k < H; ++k) {
        float u = g[static_cast<size_t>(k)];
        float hn = (1.f - u) * hrow[k] + u * apply_act(ac, cand[static_cast<size_t>(k)]);
        hrow[k] = hn;
        hid.data[static_cast<size_t>((n * T + t) * H + k)] = hn;
      }
    }
  }
  if (has_out(op, "LastH")) {
    Tensor& lh = set_out(m, op, "LastH");
    lh.shape = {B, H};
    lh.data = hbuf;
  }
  return true;
}

bool k_sequence_pool(Machine& m, const OpDesc& op) {
  Tensor* x;  // [b, T, F...]
  if (!need(m, op, "X", &x)) return false;
  Tensor* len = opt_in(m, op, "Length");
  std::string ptype = op.attr_str("pool_type", "average");
  for (auto& ch : ptype) ch = static_cast<char>(tolower(ch));
  int64_t B = x->shape[0], T = x->shape[1];
  int64_t F = 1;
  for (size_t i = 2; i < x->shape.size(); ++i) F *= x->shape[i];
  Tensor& o = set_out(m, op, "Out");
  o.shape.assign(1, B);
  for (size_t i = 2; i < x->shape.size(); ++i) o.shape.push_back(x->shape[i]);
  o.data.assign(static_cast<size_t>(B * F), 0.f);
  for (int64_t n = 0; n < B; ++n) {
    int64_t L = len ? static_cast<int64_t>(len->data[static_cast<size_t>(n)]) : T;
    if (L > T) L = T;
    float* orow = o.data.data() + n * F;
    if (L <= 0) continue;  // empty sequences pool to 0
    const float* base = x->data.data() + n * T * F;
    if (ptype == "first") {
      for (int64_t f = 0; f < F; ++f) orow[f] = base[f];
    } else if (ptype == "last") {
      for (int64_t f = 0; f < F; ++f) orow[f] = base[(L - 1) * F + f];
    } else if (ptype == "max") {
      for (int64_t f = 0; f < F; ++f) orow[f] = base[f];
      for (int64_t t = 1; t < L; ++t)
        for (int64_t f = 0; f < F; ++f)
          orow[f] = std::max(orow[f], base[t * F + f]);
    } else {  // sum / average / sqrt
      for (int64_t t = 0; t < L; ++t)
        for (int64_t f = 0; f < F; ++f) orow[f] += base[t * F + f];
      if (ptype == "average")
        for (int64_t f = 0; f < F; ++f) orow[f] /= static_cast<float>(L);
      else if (ptype == "sqrt")
        for (int64_t f = 0; f < F; ++f)
          orow[f] /= std::sqrt(static_cast<float>(L));
    }
  }
  return true;
}

// ---------------------------------------------------------------------
// Transformer inference kernels (the per-layer encoder path: layer_norm /
// rms_norm, split/slice, rotary positions, scaled-dot-product attention
// with GQA broadcast). Mirrors ops/attention_ops.py + ops/nn_ops.py
// semantics in plain loops, f32.
// ---------------------------------------------------------------------
static int64_t prod_range(const std::vector<int64_t>& shape, size_t a,
                          size_t b) {
  int64_t p = 1;
  for (size_t i = a; i < b && i < shape.size(); ++i) p *= shape[i];
  return p;
}

bool k_layer_norm(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  Tensor* scale = opt_in(m, op, "Scale");
  Tensor* bias = opt_in(m, op, "Bias");
  float eps = static_cast<float>(op.attr_num("epsilon", 1e-5));
  int begin = static_cast<int>(op.attr_num("begin_norm_axis", 1));
  int64_t rows = prod_range(x->shape, 0, static_cast<size_t>(begin));
  int64_t cols = x->numel() / rows;
  Tensor& o = set_out(m, op, "Y");
  o.shape = x->shape;
  o.data.resize(x->data.size());
  for (int64_t r = 0; r < rows; ++r) {
    const float* xi = x->data.data() + r * cols;
    float* oi = o.data.data() + r * cols;
    double mean = 0;
    for (int64_t c = 0; c < cols; ++c) mean += xi[c];
    mean /= static_cast<double>(cols);
    double var = 0;
    for (int64_t c = 0; c < cols; ++c) {
      double d = xi[c] - mean;
      var += d * d;
    }
    var /= static_cast<double>(cols);
    float inv = 1.0f / std::sqrt(static_cast<float>(var) + eps);
    for (int64_t c = 0; c < cols; ++c) {
      float y = (xi[c] - static_cast<float>(mean)) * inv;
      if (scale) y *= scale->data[static_cast<size_t>(c)];
      if (bias) y += bias->data[static_cast<size_t>(c)];
      oi[c] = y;
    }
  }
  return true;
}

bool k_rms_norm(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  Tensor* scale = opt_in(m, op, "Scale");
  Tensor* bias = opt_in(m, op, "Bias");
  float eps = static_cast<float>(op.attr_num("epsilon", 1e-6));
  int begin = static_cast<int>(op.attr_num("begin_norm_axis", 1));
  int64_t rows = prod_range(x->shape, 0, static_cast<size_t>(begin));
  int64_t cols = x->numel() / rows;
  Tensor& o = set_out(m, op, "Y");
  o.shape = x->shape;
  o.data.resize(x->data.size());
  for (int64_t r = 0; r < rows; ++r) {
    const float* xi = x->data.data() + r * cols;
    float* oi = o.data.data() + r * cols;
    double ms = 0;
    for (int64_t c = 0; c < cols; ++c) ms += double(xi[c]) * xi[c];
    float inv = 1.0f /
        std::sqrt(static_cast<float>(ms / double(cols)) + eps);
    for (int64_t c = 0; c < cols; ++c) {
      float y = xi[c] * inv;
      if (scale) y *= scale->data[static_cast<size_t>(c)];
      if (bias) y += bias->data[static_cast<size_t>(c)];
      oi[c] = y;
    }
  }
  return true;
}

bool k_split(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  int axis = static_cast<int>(op.attr_num("axis", 0));
  if (axis < 0) axis += static_cast<int>(x->shape.size());
  std::vector<int64_t> sections = op.attr_ints("sections");
  auto oit = op.outs.find("Out");
  if (oit == op.outs.end()) { m.error = "split: no Out"; return false; }
  const auto& names = oit->second;
  if (axis < 0 || axis >= static_cast<int>(x->shape.size())) {
    m.error = "split: axis out of range for rank " +
              std::to_string(x->shape.size());
    return false;
  }
  int64_t ax = x->shape[static_cast<size_t>(axis)];
  if (sections.empty()) {
    int64_t num = static_cast<int64_t>(op.attr_num(
        "num", static_cast<double>(names.size())));
    if (num <= 0 || ax % num != 0) {
      m.error = "split: axis size " + std::to_string(ax) +
                " not divisible into " + std::to_string(num) + " parts";
      return false;
    }
    sections.assign(static_cast<size_t>(num), ax / num);
  }
  int64_t sec_sum = 0;
  for (int64_t s : sections) sec_sum += s;
  if (sections.size() != names.size() || sec_sum != ax) {
    m.error = "split: sections sum " + std::to_string(sec_sum) + " (" +
              std::to_string(sections.size()) + " outputs) does not cover "
              "axis size " + std::to_string(ax);
    return false;
  }
  int64_t pre = prod_range(x->shape, 0, static_cast<size_t>(axis));
  int64_t post = x->numel() / (pre * ax);
  int64_t off = 0;
  for (size_t s = 0; s < names.size(); ++s) {
    Tensor& o = m.env[names[s]];
    o.shape = x->shape;
    o.shape[static_cast<size_t>(axis)] = sections[s];
    o.data.resize(static_cast<size_t>(pre * sections[s] * post));
    for (int64_t p = 0; p < pre; ++p)
      std::copy(x->data.data() + (p * ax + off) * post,
                x->data.data() + (p * ax + off + sections[s]) * post,
                o.data.data() + p * sections[s] * post);
    off += sections[s];
  }
  return true;
}

bool k_slice(Machine& m, const OpDesc& op) {
  Tensor* x;
  if (!need(m, op, "X", &x)) return false;
  std::vector<int64_t> axes = op.attr_ints("axes");
  std::vector<int64_t> starts = op.attr_ints("starts");
  std::vector<int64_t> ends = op.attr_ints("ends");
  if (starts.size() != axes.size() || ends.size() != axes.size()) {
    m.error = "slice: axes/starts/ends length mismatch";
    return false;
  }
  std::vector<int64_t> lo(x->shape.size(), 0), hi = x->shape;
  for (size_t i = 0; i < axes.size(); ++i) {
    int64_t a = axes[i];
    if (a < 0) a += static_cast<int64_t>(x->shape.size());
    if (a < 0 || a >= static_cast<int64_t>(x->shape.size())) {
      m.error = "slice: axis " + std::to_string(axes[i]) +
                " out of range for rank " +
                std::to_string(x->shape.size());
      return false;
    }
    size_t ax = static_cast<size_t>(a);
    int64_t dim = x->shape[ax];
    int64_t st = starts[i] < 0 ? starts[i] + dim : starts[i];
    int64_t en = ends[i] < 0 ? ends[i] + dim : ends[i];
    lo[ax] = std::max<int64_t>(0, st);
    hi[ax] = std::max(lo[ax], std::min<int64_t>(dim, en));
  }
  Tensor& o = set_out(m, op, "Out");
  o.shape.resize(x->shape.size());
  for (size_t i = 0; i < x->shape.size(); ++i) o.shape[i] = hi[i] - lo[i];
  o.data.resize(static_cast<size_t>(o.numel()));
  // generic strided copy
  std::vector<int64_t> xstr(x->shape.size(), 1), ostr(o.shape.size(), 1);
  for (int i = static_cast<int>(x->shape.size()) - 2; i >= 0; --i) {
    xstr[i] = xstr[i + 1] * x->shape[i + 1];
    ostr[i] = ostr[i + 1] * o.shape[i + 1];
  }
  for (int64_t oi = 0; oi < o.numel(); ++oi) {
    int64_t rem = oi, xi = 0;
    for (size_t d = 0; d < o.shape.size(); ++d) {
      int64_t id = rem / ostr[d];
      rem %= ostr[d];
      xi += (id + lo[d]) * xstr[d];
    }
    o.data[static_cast<size_t>(oi)] = x->data[static_cast<size_t>(xi)];
  }
  return true;
}

bool k_gelu(Machine& m, const OpDesc& op) {
  // tanh approximation — jax.nn.gelu's DEFAULT (approximate=True), which
  // is what ops/activation_ops.py registers; exact-erf GELU differs by
  // up to ~5e-4 per activation and breaks executor parity
  return k_unary(m, op, [](float v) {
    float c = 0.7978845608028654f;  // sqrt(2/pi)
    float u = c * (v + 0.044715f * v * v * v);
    return 0.5f * v * (1.0f + std::tanh(u));
  });
}

bool k_rotary_embed(Machine& m, const OpDesc& op) {
  Tensor* x;  // [B, H, T, D]
  if (!need(m, op, "X", &x)) return false;
  if (x->shape.size() != 4) { m.error = "rotary_embed: rank != 4"; return false; }
  double base = op.attr_num("base", 10000.0);
  int64_t B = x->shape[0], H = x->shape[1], T = x->shape[2],
          D = x->shape[3];
  int64_t half = D / 2;
  Tensor& o = set_out(m, op, "Out");
  o.shape = x->shape;
  o.data.resize(x->data.size());
  // the angle depends only on (t, i): one [T, half] cos/sin table
  // instead of B*H repeated transcendentals
  std::vector<float> cst(static_cast<size_t>(T * half)),
      snt(static_cast<size_t>(T * half));
  for (int64_t t = 0; t < T; ++t)
    for (int64_t i = 0; i < half; ++i) {
      // ops/attention_ops.py: pair (x[2i], x[2i+1]) rotates by
      // pos * base^(-i/half)
      double ang = double(t) * std::pow(base, -double(i) / double(half));
      cst[static_cast<size_t>(t * half + i)] =
          static_cast<float>(std::cos(ang));
      snt[static_cast<size_t>(t * half + i)] =
          static_cast<float>(std::sin(ang));
    }
  for (int64_t b = 0; b < B; ++b)
    for (int64_t h = 0; h < H; ++h)
      for (int64_t t = 0; t < T; ++t) {
        const float* xi = x->data.data() + ((b * H + h) * T + t) * D;
        float* oi = o.data.data() + ((b * H + h) * T + t) * D;
        const float* ct = cst.data() + t * half;
        const float* st = snt.data() + t * half;
        for (int64_t i = 0; i < half; ++i) {
          float x1 = xi[2 * i], x2 = xi[2 * i + 1];
          oi[2 * i] = x1 * ct[i] - x2 * st[i];
          oi[2 * i + 1] = x1 * st[i] + x2 * ct[i];
        }
      }
  return true;
}

bool k_sdpa(Machine& m, const OpDesc& op) {
  Tensor *q, *k, *v;  // Q [B, H, T, D], K/V [B, Hkv, Tk, D]
  if (!need(m, op, "Q", &q) || !need(m, op, "K", &k) ||
      !need(m, op, "V", &v))
    return false;
  Tensor* len = opt_in(m, op, "Length");
  bool causal = op.attr_bool("causal", false);
  int64_t B = q->shape[0], H = q->shape[1], Tq = q->shape[2],
          D = q->shape[3];
  int64_t Hkv = k->shape[1], Tk = k->shape[2];
  if (H % Hkv) { m.error = "sdpa: H not a multiple of Hkv"; return false; }
  int64_t group = H / Hkv;
  float scale = static_cast<float>(
      op.attr_num("sm_scale", 1.0 / std::sqrt(double(D))));
  Tensor& o = set_out(m, op, "Out");
  o.shape = q->shape;
  o.data.resize(q->data.size());
  std::vector<float> row(static_cast<size_t>(Tk));
  for (int64_t b = 0; b < B; ++b) {
    int64_t limit = Tk;
    if (len)
      limit = std::min(
          Tk, static_cast<int64_t>(len->data[static_cast<size_t>(b)]));
    for (int64_t h = 0; h < H; ++h) {
      int64_t hk = h / group;
      const float* kb = k->data.data() + (b * Hkv + hk) * Tk * D;
      const float* vb = v->data.data() + (b * Hkv + hk) * Tk * D;
      for (int64_t tq = 0; tq < Tq; ++tq) {
        const float* qi = q->data.data() + ((b * H + h) * Tq + tq) * D;
        int64_t kmax = causal ? std::min(limit, tq + 1) : limit;
        float mx = -1e30f;
        for (int64_t tk = 0; tk < kmax; ++tk) {
          float s = 0;
          const float* ki = kb + tk * D;
          for (int64_t d = 0; d < D; ++d) s += qi[d] * ki[d];
          row[static_cast<size_t>(tk)] = s * scale;
          mx = std::max(mx, row[static_cast<size_t>(tk)]);
        }
        float sum = 0;
        for (int64_t tk = 0; tk < kmax; ++tk) {
          row[static_cast<size_t>(tk)] =
              std::exp(row[static_cast<size_t>(tk)] - mx);
          sum += row[static_cast<size_t>(tk)];
        }
        float* oi = o.data.data() + ((b * H + h) * Tq + tq) * D;
        for (int64_t d = 0; d < D; ++d) oi[d] = 0;
        if (sum > 0 && kmax > 0) {
          for (int64_t tk = 0; tk < kmax; ++tk) {
            float p = row[static_cast<size_t>(tk)] / sum;
            const float* vi = vb + tk * D;
            for (int64_t d = 0; d < D; ++d) oi[d] += p * vi[d];
          }
        }
      }
    }
  }
  return true;
}

bool run_op(Machine& m, const OpDesc& op) {
  const std::string& t = op.type;
  if (t == "mul") return k_mul(m, op);
  if (t == "elementwise_add")
    return k_elementwise(m, op, [](float a, float b) { return a + b; });
  if (t == "elementwise_sub")
    return k_elementwise(m, op, [](float a, float b) { return a - b; });
  if (t == "elementwise_mul")
    return k_elementwise(m, op, [](float a, float b) { return a * b; });
  if (t == "elementwise_div")
    return k_elementwise(m, op, [](float a, float b) { return a / b; });
  if (t == "relu") return k_unary(m, op, [](float v) { return v > 0 ? v : 0; });
  if (t == "sigmoid")
    return k_unary(m, op, [](float v) { return 1.f / (1.f + std::exp(-v)); });
  if (t == "tanh") return k_unary(m, op, [](float v) { return std::tanh(v); });
  if (t == "exp") return k_unary(m, op, [](float v) { return std::exp(v); });
  if (t == "sqrt") return k_unary(m, op, [](float v) { return std::sqrt(v); });
  if (t == "abs") return k_unary(m, op, [](float v) { return std::fabs(v); });
  if (t == "square") return k_unary(m, op, [](float v) { return v * v; });
  if (t == "softmax") return k_softmax(m, op);
  if (t == "conv2d") return k_conv2d(m, op);
  if (t == "pool2d") return k_pool2d(m, op);
  if (t == "batch_norm") return k_batch_norm(m, op);
  if (t == "conv1x1_bn_act") return k_conv1x1_bn_act(m, op);
  if (t == "reshape") return k_reshape(m, op);
  if (t == "concat") return k_concat(m, op);
  if (t == "scale") return k_scale(m, op);
  if (t == "dropout") return k_dropout(m, op);
  if (t == "mean") return k_mean(m, op);
  if (t == "transpose") return k_transpose(m, op);
  if (t == "assign") return k_assign(m, op);
  if (t == "lookup_table") return k_lookup_table(m, op);
  if (t == "lstm") return k_lstm(m, op);
  if (t == "gru") return k_gru(m, op);
  if (t == "sequence_pool") return k_sequence_pool(m, op);
  if (t == "layer_norm") return k_layer_norm(m, op);
  if (t == "rms_norm") return k_rms_norm(m, op);
  if (t == "split") return k_split(m, op);
  if (t == "slice") return k_slice(m, op);
  if (t == "gelu") return k_gelu(m, op);
  if (t == "rotary_embed") return k_rotary_embed(m, op);
  if (t == "scaled_dot_product_attention") return k_sdpa(m, op);
  m.error = "unsupported op in capi inference machine: '" + t +
            "' (supported: mul, elementwise_*, relu/sigmoid/tanh/exp/sqrt/"
            "abs/square/gelu, softmax, conv2d, pool2d, batch_norm, "
            "layer_norm, rms_norm, reshape, concat, split, slice, scale, "
            "dropout, mean, transpose, assign, lookup_table, lstm, gru, "
            "sequence_pool, rotary_embed, scaled_dot_product_attention)";
  return false;
}

// impl bodies (may throw on malformed models; the extern "C" wrappers
// below convert that into g_last_error + failure codes)
template <typename T>
bool read_raw(const std::string& path, size_t n, std::vector<T>* out,
              std::string* err) {
  std::ifstream f(path, std::ios::binary);
  if (!f) { *err = "cannot open " + path; return false; }
  out->resize(n);
  f.read(reinterpret_cast<char*>(out->data()),
         static_cast<std::streamsize>(n * sizeof(T)));
  if (!f) { *err = "short read in " + path; return false; }
  return true;
}

void* load_impl(const char* model_dir) {
  auto m = std::make_unique<Machine>();
  std::string dir(model_dir);
  std::ifstream f(dir + "/__model__.json");
  if (!f) {
    g_last_error = "cannot open " + dir + "/__model__.json";
    return nullptr;
  }
  std::stringstream ss;
  ss << f.rdbuf();
  std::string text = ss.str();
  JValue root;
  JParser parser(text);
  if (!parser.parse(&root)) {
    g_last_error = "json parse error: " + parser.err;
    return nullptr;
  }
  for (auto& v : root.at("feed_names").arr) m->feeds.push_back(v.str);
  for (auto& v : root.at("fetch_names").arr) m->fetches.push_back(v.str);
  const JValue& block0 = root.at("program").at("blocks").arr.at(0);
  for (auto& od : block0.at("ops").arr) {
    OpDesc op;
    op.type = od.at("type").str;
    for (auto& kv : od.at("inputs").obj)
      for (auto& n : kv.second.arr) op.ins[kv.first].push_back(n.str);
    for (auto& kv : od.at("outputs").obj)
      for (auto& n : kv.second.arr) op.outs[kv.first].push_back(n.str);
    op.attrs = od.at("attrs");
    m->ops.push_back(std::move(op));
  }
  // persistables ship as params/*.npy indexed by params/MANIFEST.json
  // (io.py save_vars)
  std::ifstream mf(dir + "/params/MANIFEST.json");
  if (!mf) {
    g_last_error = "cannot open " + dir + "/params/MANIFEST.json";
    return nullptr;
  }
  std::stringstream ms;
  ms << mf.rdbuf();
  const std::string mtext = ms.str();
  JValue manifest;
  JParser mp(mtext);
  if (!mp.parse(&manifest)) {
    g_last_error = "manifest parse error: " + mp.err;
    return nullptr;
  }
  for (auto& entry : manifest.arr) {
    Tensor t;
    std::string err;
    std::string logical =
        entry.has("dtype") ? entry.at("dtype").str : std::string();
    if (!load_npy(dir + "/params/" + entry.at("file").str, &t, &err,
                  logical)) {
      g_last_error = err;
      return nullptr;
    }
    m->params[entry.at("name").str] = std::move(t);
  }
  // optional weight-only int8 sidecars (io.quantize_inference_model)
  std::ifstream qf(dir + "/__quant__.json");
  if (qf) {
    std::stringstream qs;
    qs << qf.rdbuf();
    const std::string qtext = qs.str();
    JValue quant;
    JParser qp(qtext);
    if (!qp.parse(&quant)) {
      g_last_error = "__quant__.json parse error: " + qp.err;
      return nullptr;
    }
    for (auto& entry : quant.arr) {
      const std::string kind =
          entry.has("kind") ? entry.at("kind").str : std::string("mul");
      std::string err;
      if (kind == "mul") {
        QTensor q;
        q.rows = static_cast<int64_t>(entry.at("rows").num);
        q.cols = static_cast<int64_t>(entry.at("cols").num);
        if (!read_raw(dir + "/params/" + entry.at("qfile").str,
                      static_cast<size_t>(q.rows * q.cols), &q.data,
                      &err) ||
            !read_raw(dir + "/params/" + entry.at("sfile").str,
                      static_cast<size_t>(q.cols), &q.scales, &err)) {
          g_last_error = err;
          return nullptr;
        }
        m->qweights[entry.at("name").str] = std::move(q);
        continue;
      }
      // conv filters: int8 on disk only — dequantize once into the f32
      // param table (filters are small next to activations; the win is
      // the shipped artifact)
      std::vector<int64_t> shape;
      int64_t numel = 1;
      for (auto& d : entry.at("shape").arr) {
        shape.push_back(static_cast<int64_t>(d.num));
        numel *= static_cast<int64_t>(d.num);
      }
      int out_axis = static_cast<int>(entry.at("out_axis").num);
      if (shape.empty() || out_axis < 0 ||
          out_axis >= static_cast<int>(shape.size())) {
        g_last_error = "__quant__.json: bad out_axis for '" +
                       entry.at("name").str + "'";
        return nullptr;
      }
      int64_t oc = shape[static_cast<size_t>(out_axis)];
      std::vector<int8_t> qd;
      std::vector<float> sc;
      if (!read_raw(dir + "/params/" + entry.at("qfile").str,
                    static_cast<size_t>(numel), &qd, &err) ||
          !read_raw(dir + "/params/" + entry.at("sfile").str,
                    static_cast<size_t>(oc), &sc, &err)) {
        g_last_error = err;
        return nullptr;
      }
      Tensor t;
      t.shape = shape;
      t.data.resize(static_cast<size_t>(numel));
      int64_t inner = 1;
      for (size_t a = static_cast<size_t>(out_axis) + 1; a < shape.size();
           ++a)
        inner *= shape[a];
      for (int64_t i = 0; i < numel; ++i) {
        int64_t c = (i / inner) % oc;
        t.data[static_cast<size_t>(i)] =
            static_cast<float>(qd[static_cast<size_t>(i)]) *
            sc[static_cast<size_t>(c)];
      }
      m->params[entry.at("name").str] = std::move(t);
    }
  }
  return m.release();
}

int run_impl(Machine* m) {
  // keep the feed values; drop stale intermediates from the previous run
  std::map<std::string, Tensor> kept;
  for (const auto& f : m->feeds) {
    auto it = m->env.find(f);
    if (it == m->env.end()) {
      g_last_error = "pdtpu_run: input '" + f + "' not set";
      return 1;
    }
    kept[f] = std::move(it->second);
  }
  m->env = std::move(kept);
  for (size_t i = 0; i < m->ops.size(); ++i) {
    if (!run_op(*m, m->ops[i])) {
      g_last_error = "op #" + std::to_string(i) + ": " + m->error;
      return 2;
    }
  }
  return 0;
}

// No C++ exception may cross the C ABI (it would std::terminate the
// embedding application): every exported body runs under this barrier,
// converting throws (map::at on malformed models, bad_alloc on corrupt
// npy headers) into g_last_error + the function's failure value.
template <typename R, typename F>
R guarded(R fail_value, F body) {
  try {
    return body();
  } catch (const std::exception& e) {
    g_last_error = std::string("internal error: ") + e.what();
    return fail_value;
  } catch (...) {
    g_last_error = "internal error (unknown exception)";
    return fail_value;
  }
}

}  // namespace

// ---------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------
extern "C" {

const char* pdtpu_last_error() { return g_last_error.c_str(); }

void* pdtpu_load(const char* model_dir) {
  return guarded<void*>(nullptr,
                        [&]() -> void* { return load_impl(model_dir); });
}

void pdtpu_free(void* h) { delete static_cast<Machine*>(h); }

int pdtpu_num_feeds(void* h) {
  return static_cast<int>(static_cast<Machine*>(h)->feeds.size());
}
const char* pdtpu_feed_name(void* h, int i) {
  return guarded<const char*>("", [&] {
    return static_cast<Machine*>(h)->feeds.at(static_cast<size_t>(i)).c_str();
  });
}
int pdtpu_num_fetches(void* h) {
  return static_cast<int>(static_cast<Machine*>(h)->fetches.size());
}
const char* pdtpu_fetch_name(void* h, int i) {
  return guarded<const char*>("", [&] {
    return static_cast<Machine*>(h)->fetches.at(static_cast<size_t>(i)).c_str();
  });
}

int pdtpu_set_input(void* h, const char* name, const float* data,
                    const int64_t* shape, int rank) {
  return guarded<int>(3, [&] {
    Machine* m = static_cast<Machine*>(h);
    Tensor t;
    t.shape.assign(shape, shape + rank);
    t.data.assign(data, data + t.numel());
    m->env[name] = std::move(t);
    return 0;
  });
}

int pdtpu_run(void* h) {
  return guarded<int>(3, [&] { return run_impl(static_cast<Machine*>(h)); });
}

int pdtpu_output_rank(void* h, const char* name) {
  return guarded<int>(-1, [&]() -> int {
    Machine* m = static_cast<Machine*>(h);
    Tensor* t = lookup(*m, name);
    if (!t) { g_last_error = std::string("no output ") + name; return -1; }
    return static_cast<int>(t->shape.size());
  });
}

int pdtpu_output_shape(void* h, const char* name, int64_t* out) {
  return guarded<int>(3, [&]() -> int {
    Machine* m = static_cast<Machine*>(h);
    Tensor* t = lookup(*m, name);
    if (!t) { g_last_error = std::string("no output ") + name; return 1; }
    for (size_t i = 0; i < t->shape.size(); ++i) out[i] = t->shape[i];
    return 0;
  });
}

int64_t pdtpu_output_numel(void* h, const char* name) {
  return guarded<int64_t>(-1, [&]() -> int64_t {
    Machine* m = static_cast<Machine*>(h);
    Tensor* t = lookup(*m, name);
    if (!t) { g_last_error = std::string("no output ") + name; return -1; }
    return t->numel();
  });
}

int pdtpu_output_data(void* h, const char* name, float* buf, int64_t cap) {
  return guarded<int>(3, [&]() -> int {
    Machine* m = static_cast<Machine*>(h);
    Tensor* t = lookup(*m, name);
    if (!t) { g_last_error = std::string("no output ") + name; return 1; }
    if (cap < t->numel()) { g_last_error = "buffer too small"; return 2; }
    memcpy(buf, t->data.data(),
           static_cast<size_t>(t->numel()) * sizeof(float));
    return 0;
  });
}

}  // extern "C"

// RecordIO-style record file + threaded prefetching reader.
//
// Native rebuild of two reference components:
// - the RecordIO chunk files the Go master shards into tasks
//   (/root/reference/go/master/service.go:106 partition; the cloud data
//   plane's on-disk format)
// - the DoubleBuffer async prefetch of the legacy DataProvider
//   (/root/reference/paddle/gserver/dataproviders/DataProvider.h:249-271):
//   a background thread keeps a bounded queue of decoded records ahead of
//   the consumer.
//
// File format (little-endian):
//   per record: u32 MAGIC | u32 len | u32 checksum(payload) | payload bytes
// Records are self-delimiting; a (offset, count) byte-range identifies a
// chunk, which is what master task descriptors carry ("path:offset:count").
//
// The prefetcher is pure C++ IO on a detached thread — it runs while Python
// holds or releases the GIL (ctypes releases it during calls), overlapping
// disk reads with host-side decode and device compute.
//
// C ABI only; built by native/build.py, wrapped by paddle_tpu/recordio.py.

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50545243; // "PTRC"

uint32_t checksum(const uint8_t *data, size_t n) {
  // FNV-1a: cheap, good enough to catch torn writes (the reference uses
  // CRC32 via the recordio library; the property needed is corruption
  // detection, not cryptographic strength).
  uint32_t h = 2166136261u;
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= 16777619u;
  }
  return h;
}

struct Writer {
  FILE *f;
  int64_t count = 0;
};

struct Reader {
  FILE *f;
};

struct Prefetcher {
  FILE *f = nullptr;
  int64_t remaining; // records left to read (-1 = until EOF)
  size_t cap;
  std::deque<std::vector<uint8_t>> queue;
  std::mutex mu;
  std::condition_variable cv_pop, cv_push;
  bool eof = false;
  bool error = false;
  bool stop = false;
  std::thread worker;

  void run() {
    for (;;) {
      if (remaining == 0) break;
      uint32_t head[3];
      if (fread(head, 4, 3, f) != 3) break; // EOF
      if (head[0] != kMagic) { error = true; break; }
      std::vector<uint8_t> payload(head[1]);
      if (fread(payload.data(), 1, payload.size(), f) != payload.size()) {
        error = true;
        break;
      }
      if (checksum(payload.data(), payload.size()) != head[2]) {
        error = true;
        break;
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_push.wait(lk, [&] { return queue.size() < cap || stop; });
      if (stop) return;
      queue.push_back(std::move(payload));
      cv_pop.notify_one();
      if (remaining > 0) --remaining;
    }
    std::lock_guard<std::mutex> g(mu);
    eof = true;
    cv_pop.notify_all();
  }
};

} // namespace

extern "C" {

// ---- writer ---------------------------------------------------------------
void *ptrec_writer_open(const char *path, int append) {
  FILE *f = fopen(path, append ? "ab" : "wb");
  if (!f) return nullptr;
  // "ab" leaves the stdio position at 0 until the first write on glibc;
  // seek explicitly so ptrec_write's ftell reports true offsets.
  if (append) fseek(f, 0, SEEK_END);
  Writer *w = new Writer{f};
  return w;
}

// Returns the record's byte offset, or -1 on error.
int64_t ptrec_write(void *wp, const uint8_t *data, uint32_t len) {
  Writer *w = static_cast<Writer *>(wp);
  int64_t off = ftell(w->f);
  uint32_t head[3] = {kMagic, len, checksum(data, len)};
  if (fwrite(head, 4, 3, w->f) != 3) return -1;
  if (len && fwrite(data, 1, len, w->f) != len) return -1;
  w->count++;
  return off;
}

int64_t ptrec_writer_close(void *wp) {
  Writer *w = static_cast<Writer *>(wp);
  int64_t n = w->count;
  fclose(w->f);
  delete w;
  return n;
}

// ---- sequential reader ----------------------------------------------------
void *ptrec_reader_open(const char *path, int64_t offset) {
  FILE *f = fopen(path, "rb");
  if (!f) return nullptr;
  if (offset > 0 && fseek(f, static_cast<long>(offset), SEEK_SET) != 0) {
    fclose(f);
    return nullptr;
  }
  return new Reader{f};
}

// Reads the next record into buf (cap bytes). Returns payload length,
// -1 at EOF, -2 on corruption, -3 if buf too small (the stream rewinds to
// the record start so the caller can retry with a bigger buffer).
int64_t ptrec_read(void *rp, uint8_t *buf, uint32_t cap) {
  Reader *r = static_cast<Reader *>(rp);
  uint32_t head[3];
  if (fread(head, 4, 3, r->f) != 3) return -1;
  if (head[0] != kMagic) return -2;
  if (head[1] > cap) {
    // rewind past the header so the caller can retry with a bigger buffer
    fseek(r->f, -12, SEEK_CUR);
    return -3;
  }
  if (fread(buf, 1, head[1], r->f) != head[1]) return -2;
  if (checksum(buf, head[1]) != head[2]) return -2;
  return head[1];
}

void ptrec_reader_close(void *rp) {
  Reader *r = static_cast<Reader *>(rp);
  fclose(r->f);
  delete r;
}

// ---- prefetcher (DoubleBuffer) -------------------------------------------
void *ptrec_prefetch_open(const char *path, int64_t offset, int64_t count,
                          int queue_cap) {
  FILE *f = fopen(path, "rb");
  if (!f) return nullptr;
  if (offset > 0) fseek(f, static_cast<long>(offset), SEEK_SET);
  Prefetcher *p = new Prefetcher;
  p->f = f;
  p->remaining = count;
  p->cap = queue_cap > 0 ? queue_cap : 64;
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Pops the next record (blocking). Returns length, -1 on end-of-stream,
// -2 on file corruption, -3 if buf too small (record stays queued).
int64_t ptrec_prefetch_next(void *pp, uint8_t *buf, uint32_t cap) {
  Prefetcher *p = static_cast<Prefetcher *>(pp);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_pop.wait(lk, [&] { return !p->queue.empty() || p->eof; });
  if (p->queue.empty()) return p->error ? -2 : -1;
  if (p->queue.front().size() > cap) return -3;
  std::vector<uint8_t> rec = std::move(p->queue.front());
  p->queue.pop_front();
  p->cv_push.notify_one();
  lk.unlock();
  memcpy(buf, rec.data(), rec.size());
  return static_cast<int64_t>(rec.size());
}

void ptrec_prefetch_close(void *pp) {
  Prefetcher *p = static_cast<Prefetcher *>(pp);
  {
    std::lock_guard<std::mutex> g(p->mu);
    p->stop = true;
    p->cv_push.notify_all();
  }
  p->worker.join();
  fclose(p->f);
  delete p;
}

} // extern "C"

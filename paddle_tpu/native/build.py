"""Compile-on-demand for the native C++ components.

The reference ships its native plane through CMake
(/root/reference/CMakeLists.txt); here the runtime C++ pieces are small
single-TU libraries compiled at first import with g++ and cached by source
hash, so the package needs no install step. A missing compiler degrades to
the pure-Python fallbacks where the caller provides one.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

_CACHE: dict = {}

NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
BUILD_DIR = os.environ.get(
    "PADDLE_TPU_NATIVE_BUILD",
    os.path.join(tempfile.gettempdir(), "paddle_tpu_native"))


def load_library(source_name: str):
    """Compile ``<source_name>.cc`` (if needed) and dlopen it. Returns the
    ctypes.CDLL, or None when no toolchain is available. A compile ERROR
    (toolchain present, bad source) raises — and keeps raising with the
    same diagnostics on every retry, never degrading to the None path."""
    if source_name in _CACHE:
        cached = _CACHE[source_name]
        if isinstance(cached, RuntimeError):
            raise cached
        return cached
    src = os.path.join(NATIVE_DIR, source_name + ".cc")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    os.makedirs(BUILD_DIR, exist_ok=True)
    so_path = os.path.join(BUILD_DIR, f"{source_name}-{digest}.so")
    if not os.path.exists(so_path):
        tmp = so_path + f".tmp{os.getpid()}"
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src,
                 "-o", tmp],
                check=True, capture_output=True)
            os.replace(tmp, so_path)
        except FileNotFoundError:
            _CACHE[source_name] = None  # genuinely no toolchain
            return None
        except subprocess.CalledProcessError as e:
            err = RuntimeError(
                f"native build of {source_name}.cc failed:\n"
                + e.stderr.decode())
            _CACHE[source_name] = err
            raise err
    lib = ctypes.CDLL(so_path)
    _CACHE[source_name] = lib
    return lib

"""Native (C++) runtime components, compiled on demand (see build.py)."""
from .build import load_library  # noqa: F401

"""Deterministic fault injection — the chaos plane of the resilience stack.

The reference proves its fault-tolerance claims with process-level chaos
(the Go master/pserver tests kill and restart components mid-run); a
single-process TPU port needs the same experiments to be *deterministic*
so a crash-recovery parity test can assert bitwise equality. A
:class:`FaultPlan` is an explicit schedule of faults — each entry fires
exactly once, at an exact step — consumed by the subsystems' injection
points:

- ``crash``           trainer, before step k: raises :class:`SimulatedCrash`
                      (hard kill — no final checkpoint).
- ``preempt``         trainer, after step k: sets the graceful-shutdown
                      flag, as if SIGTERM had arrived (drain + final
                      checkpoint + ``EndPass(interrupted=True)``).
- ``executor_error``  trainer, before step k: raises a retryable
                      :class:`TransientFault` (consumed by the step retry
                      policy — the step still runs exactly once).
- ``torn_checkpoint`` CheckpointManager, at the save of step k: the
                      written payload is truncated after the fact, so the
                      md5 no longer matches (a torn write).
- ``master_drop``     MasterClient, at RPC #k: the client socket is torn
                      down right before the call (a dropped connection the
                      retry policy must survive).
- ``replica_crash``   serving fleet, replica #k: the replica goes hard-down
                      (every attempt raises ConnectionError until
                      ``revive()``) — the router's breaker must open and
                      traffic must flow around it.
- ``slow_replica``    serving fleet, replica #k: every attempt on the
                      replica is delayed by ``delay_s`` (default 0.05) —
                      the tail-latency case hedging must absorb.

The elastic crash/rejoin chaos matrix (StreamingTrainer + master lease
plane) adds trainer-granular kinds:

- ``trainer_crash``   StreamingTrainer, right after claiming its k-th task:
                      raises :class:`SimulatedCrash` with the claim left
                      dangling — the lease plane must fence the dead
                      trainer and requeue the claim (front) for the next
                      registrant.
- ``trainer_preempt_rejoin`` StreamingTrainer, at its k-th task boundary:
                      graceful stop before claiming (the preemption notice
                      case); the harness restarts the trainer, whose
                      re-registration fences the old incarnation.
- ``zombie_ack``      StreamingTrainer, at the ack flush of its k-th saved
                      generation: the trainer's lease is expired server-side
                      first (a partition outliving the lease), so the acks
                      it then sends are rejected by token and counted
                      (``master/zombie_acks_rejected``).
- ``master_partition`` MasterClient, at RPC #k: the lease is expired
                      server-side and the connection torn — the
                      reconnecting client's next tokened call raises
                      ``FencedTokenError`` (the rejoin signal).

The work-preserving serving-recovery matrix adds mid-stream kinds (the
``replica_crash``/``slow_replica`` kinds fire BEFORE an attempt begins;
these fire with generations in flight):

- ``replica_kill``    serving engine, once ``after_tokens`` (default 1)
                      tokens have been emitted engine-wide: the engine
                      hard-dies mid-stream — every in-flight generation
                      fails with ConnectionError, pages are released,
                      and subsequent admissions raise EngineClosedError
                      until ``revive()``. The fleet's lineage plane must
                      resume every survivor on a healthy replica.
- ``decode_leg_crash`` disagg remote decode leg, at KV handoff #k: the
                      leg dies AFTER ``serialize_handoff`` released the
                      prefill pages (the no-rollback window) — the
                      DisaggEngine must fail over by re-prefilling the
                      handoff context on another leg.

Manual chaos runs go through ``--fault_plan`` (flags.py), e.g.
``--fault_plan=preempt@5,torn_checkpoint@3`` — the trainer parses it when
no plan is installed programmatically.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Tuple

FAULT_KINDS = ("crash", "preempt", "executor_error", "torn_checkpoint",
               "master_drop", "replica_crash", "slow_replica",
               "trainer_crash", "trainer_preempt_rejoin", "zombie_ack",
               "master_partition", "replica_kill", "decode_leg_crash")


class SimulatedCrash(RuntimeError):
    """Fault-plan hard kill: the process dies with no graceful shutdown."""


class TransientFault(RuntimeError):
    """Fault-plan transient error: retry policies treat it as retryable."""


class _Entry:
    __slots__ = ("kind", "step", "params", "fired")

    def __init__(self, kind: str, step: Optional[int], params: dict):
        self.kind = kind
        self.step = step
        self.params = params
        self.fired = False


class FaultPlan:
    """An ordered, one-shot schedule of injected faults.

    ``plan.at(step=5, kind="preempt")`` arms a fault; injection points
    call ``plan.fire(kind, step)`` which consumes (and reports) the first
    matching unfired entry. ``step=None`` entries match the first
    opportunity of their kind. Thread-safe: the master client fires from
    reader threads.
    """

    def __init__(self):
        self._entries: List[_Entry] = []
        self._lock = threading.Lock()
        self.fired_log: List[Tuple[str, Optional[int]]] = []

    def at(self, step: Optional[int] = None, kind: str = "crash",
           **params) -> "FaultPlan":
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; one of "
                             f"{FAULT_KINDS}")
        self._entries.append(_Entry(kind, None if step is None else int(step),
                                    params))
        return self

    def fire(self, kind: str, step: Optional[int] = None) -> Optional[dict]:
        """Consume the first unfired entry matching (kind, step); returns
        its params dict (possibly empty) or None when nothing matches."""
        with self._lock:
            for e in self._entries:
                if e.fired or e.kind != kind:
                    continue
                if e.step is not None and step is not None \
                        and e.step != step:
                    continue
                e.fired = True
                self.fired_log.append((kind, step))
                from .. import profiler

                profiler.global_stat.add_count(f"fault/{kind}", 1)
                return dict(e.params)
        return None

    def peek(self, kind: str,
             step: Optional[int] = None) -> Optional[dict]:
        """Params of the first unfired entry matching (kind, step)
        WITHOUT consuming it — for injection points that must check a
        threshold carried in the params (e.g. ``replica_kill``'s
        ``after_tokens``) before committing to fire."""
        with self._lock:
            for e in self._entries:
                if e.fired or e.kind != kind:
                    continue
                if e.step is not None and step is not None \
                        and e.step != step:
                    continue
                return dict(e.params)
        return None

    def pending(self) -> List[Tuple[str, Optional[int]]]:
        with self._lock:
            return [(e.kind, e.step) for e in self._entries if not e.fired]

    @contextlib.contextmanager
    def active(self):
        """Install this plan as the process-global active plan."""
        install_plan(self)
        try:
            yield self
        finally:
            clear_plan()

    @staticmethod
    def parse(spec: str) -> "FaultPlan":
        """``"kind@step,kind@step,kind"`` -> plan (the --fault_plan
        syntax). A bare ``kind`` fires at the first opportunity."""
        plan = FaultPlan()
        for tok in spec.split(","):
            tok = tok.strip()
            if not tok:
                continue
            kind, _, step = tok.partition("@")
            plan.at(step=int(step) if step else None, kind=kind.strip())
        return plan

    def __repr__(self):
        return (f"FaultPlan(pending={self.pending()}, "
                f"fired={self.fired_log})")


_active_plan: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _active_plan


def install_plan(plan: Optional[FaultPlan]) -> None:
    global _active_plan
    _active_plan = plan


def clear_plan() -> None:
    install_plan(None)

"""Checkpoint manager + per-run trainer resilience state.

The reference keeps a job alive through two persistence loops: the Go
master snapshots its task queue to etcd on every state transition, and
the pserver checkpoints parameter blocks on a timer so a restarted job
*resumes* (doc/design/cluster_train/checkpointing.md). This module is the
trainer-side half for the TPU port:

- :class:`CheckpointConfig` — declarative policy handed to
  ``SGD.train(checkpoint=...)``: where, how often, how many to keep,
  whether saves run in the background, resume semantics.
- :class:`CheckpointManager` — executes the policy. A save has two
  phases: the *snapshot* (device->host copy of every scope value) runs on
  the trainer thread at a drained safe point — PR 4's handle-drain
  guarantees no donated buffer is captured mid-dispatch — and the
  *write* (npz + md5 + atomic rename + retention pruning, via
  ``paddle_tpu.checkpoint``) runs on a background thread when
  ``background=True``, keeping the multi-MB serialization off the step
  critical path. ``ckpt/save`` spans cover the stall portion,
  ``ckpt/write`` the background write, and ``ckpt/*`` StatSet counters
  feed ``tools/trace_summary.py --resilience``.
- :class:`TrainResilience` — one ``SGD.train()`` call's run state:
  resume position (pass/iteration/samples), checkpoint cadence, the
  graceful-shutdown flag, and fault-plan stepping.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Iterator, Optional

import numpy as np

from .faults import FaultPlan, SimulatedCrash, TransientFault, active_plan
from .retry import Retry
from .signals import ShutdownFlag, graceful_shutdown


class CheckpointConfig:
    """Checkpoint policy for ``SGD.train(checkpoint=...)``.

    dirname:            checkpoint directory (created on first save).
    every_n_steps:      periodic cadence in completed steps; 0 disables
                        periodic saves (final/interrupt saves still run).
    keep:               retention — newest ``keep`` checkpoints survive.
    background:         serialize + write on a background thread; only
                        the host snapshot stalls the step loop.
    resume:             auto-restore the latest intact checkpoint (and
                        training position) at ``train()`` start.
    strict:             propagate a corrupt-latest error instead of
                        walking back to an older intact checkpoint.
    save_on_interrupt:  write a final checkpoint on SIGTERM/SIGINT (or a
                        fault-plan preemption) before exiting the loop.
    save_final:         write a checkpoint when training completes.
    skip_batches_on_resume: on resume, skip the already-consumed batches
                        of the interrupted pass. None = auto: skip unless
                        the reader advertises ``master_backed`` (a
                        MasterClient.task_reader, whose master already
                        tracks consumed tasks).
    install_signal_handlers: wrap the training loop in
                        :func:`graceful_shutdown`.
    keep_last_n:        retention alias for ``keep`` (bounded checkpoint
                        GC for endless-pass online training; overrides
                        ``keep`` when given). The newest intact
                        generation and a Publisher-pinned one (see
                        ``checkpoint.pin_generation``) always survive.
    extra_fn:           callable ``() -> dict`` merged into each save's
                        ``extra`` record at save time — how the elastic
                        StreamingTrainer stamps its checkpoint-lineage
                        manifest (writer token, master pass, covered
                        tasks) onto every generation.
    pre_save_fn:        callable ``() -> bool`` consulted right before a
                        save; returning False VETOES it (counted as
                        ``ckpt/saves_vetoed``) — the fencing hook that
                        stops a zombie trainer from publishing a
                        generation after its lease expired.
    on_saved:           callable ``(step, extra) -> None`` invoked after
                        a save's write completes (on the background
                        thread when ``background=True``) — the elastic
                        trainer flushes its deferred task acks here, so
                        the ack horizon never runs ahead of durable
                        state.
    accept_fn:          callable ``meta -> bool`` filtering resume
                        candidates by their meta/lineage (forwarded to
                        ``load_checkpoint(accept=...)``).
    """

    def __init__(self, dirname: str, every_n_steps: int = 100,
                 keep: int = 3, background: bool = True,
                 resume: bool = True, strict: bool = False,
                 save_on_interrupt: bool = True, save_final: bool = True,
                 skip_batches_on_resume: Optional[bool] = None,
                 install_signal_handlers: bool = True,
                 keep_last_n: Optional[int] = None,
                 extra_fn=None, pre_save_fn=None, on_saved=None,
                 accept_fn=None):
        if every_n_steps < 0:
            raise ValueError("every_n_steps must be >= 0")
        self.dirname = dirname
        self.every_n_steps = int(every_n_steps)
        self.keep = int(keep if keep_last_n is None else keep_last_n)
        self.background = bool(background)
        self.resume = bool(resume)
        self.strict = bool(strict)
        self.save_on_interrupt = bool(save_on_interrupt)
        self.save_final = bool(save_final)
        self.skip_batches_on_resume = skip_batches_on_resume
        self.install_signal_handlers = bool(install_signal_handlers)
        self.extra_fn = extra_fn
        self.pre_save_fn = pre_save_fn
        self.on_saved = on_saved
        self.accept_fn = accept_fn

    def __repr__(self):
        return (f"CheckpointConfig({self.dirname!r}, "
                f"every_n_steps={self.every_n_steps}, keep={self.keep}, "
                f"background={self.background}, resume={self.resume})")


def _host_copy(value):
    """Host copy of one scope value. Values sharded across processes stay
    as device arrays (checkpoint.py saves their local shards); everything
    else materializes to numpy so the background writer never touches a
    buffer a later dispatch might donate."""
    import sys

    if "jax" in sys.modules:
        import jax

        if isinstance(value, jax.Array) and not value.is_fully_addressable:
            return value
    return np.asarray(value)


class _HostSnapshot:
    """Scope-shaped view over host copies — what the background writer
    serializes (checkpoint.save_checkpoint only needs keys()/get())."""

    def __init__(self, scope):
        self._vars = {name: _host_copy(scope.get(name))
                      for name in scope.keys()}

    def keys(self):
        return iter(self._vars.keys())

    def get(self, name):
        return self._vars[name]

    def nbytes(self) -> int:
        return int(sum(getattr(v, "nbytes", 0) for v in self._vars.values()))


class CheckpointManager:
    """Drives periodic / on-signal checkpointing for one scope.

    Not thread-safe by itself: ``save``/``wait``/``close`` are called
    from the training thread at drained safe points; only the npz write
    runs elsewhere. A background write error is re-raised on the next
    ``save``/``wait`` — a checkpoint that silently fails to persist is a
    resume-time data loss.
    """

    def __init__(self, config: CheckpointConfig, scope=None, plan=None):
        from ..core.scope import global_scope

        self.config = config
        self.scope = scope if scope is not None else global_scope()
        # reshard-on-restore: with a plan, resume() re-places every
        # restored value through the plan's PartitionSpecs (a checkpoint
        # saved under another mesh shape lands directly sharded)
        self.plan = plan
        self.last_saved_step: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    # -- cadence -----------------------------------------------------------
    def due(self, step: int) -> bool:
        n = self.config.every_n_steps
        return (n > 0 and step > 0 and step % n == 0
                and step != self.last_saved_step)

    # -- restore -----------------------------------------------------------
    def resume(self) -> Optional[dict]:
        """Restore the latest intact checkpoint into the scope; returns
        its meta (with ``extra`` position) or None when the directory has
        no checkpoint yet. Corruption of the latest walks back to an
        older intact one unless ``strict``."""
        from .. import checkpoint as ckpt_mod
        from .. import profiler, trace

        meta_path = os.path.join(self.config.dirname, ckpt_mod.META_NAME)
        if not os.path.exists(meta_path):
            return None
        with trace.span("ckpt/restore", dirname=self.config.dirname) as sp:
            meta = ckpt_mod.load_checkpoint(self.config.dirname,
                                            scope=self.scope,
                                            strict=self.config.strict,
                                            plan=self.plan,
                                            accept=self.config.accept_fn)
            if sp is not None:
                sp.set_attrs(step=meta.get("step"),
                             fallback=bool(meta.get("fallback")))
        profiler.global_stat.add_count("ckpt/restores", 1)
        if meta.get("fallback"):
            profiler.global_stat.add_count("ckpt/restore_fallbacks", 1)
        self.last_saved_step = int(meta.get("step", 0))
        return meta

    # -- save --------------------------------------------------------------
    def save(self, step: int, pass_id: int = 0, iteration: int = -1,
             samples_seen: int = 0, reason: str = "periodic",
             wait: bool = False) -> None:
        """Checkpoint the scope as of ``step`` completed steps. MUST be
        called at a drained safe point (no in-flight async handles): the
        snapshot reads every scope value. With ``background`` the write
        happens off-thread; ``wait=True`` forces a synchronous save
        (interrupt/final checkpoints must hit disk before exit)."""
        from .. import profiler, trace

        if self.config.pre_save_fn is not None \
                and not self.config.pre_save_fn():
            # fencing veto: a zombie (lease-expired) trainer must not
            # publish a generation — the master already requeued its
            # tasks to a live trainer
            profiler.global_stat.add_count("ckpt/saves_vetoed", 1)
            t = time.perf_counter()
            trace.record("ckpt/save_vetoed", t, t, step=step,
                         reason=reason)
            return
        extra = {"pass_id": int(pass_id), "iteration": int(iteration),
                 "samples_seen": int(samples_seen), "reason": reason}
        if self.config.extra_fn is not None:
            extra.update(self.config.extra_fn() or {})
        background = self.config.background and not wait
        with profiler.timer("ckpt/stall"), \
                trace.span("ckpt/save", step=step, reason=reason,
                           mode="background" if background else "sync"):
            # joining a still-running previous write IS step-loop stall
            self.wait()  # also surfaces background errors
            snap = _HostSnapshot(self.scope)
            if background:
                self._thread = threading.Thread(
                    target=self._write_guarded, args=(snap, step, extra),
                    name="paddle-tpu-ckpt", daemon=True)
                self._thread.start()
            else:
                self._write(snap, step, extra)
        profiler.global_stat.add_count("ckpt/saves", 1)
        self.last_saved_step = int(step)

    def _write(self, snap: _HostSnapshot, step: int, extra: dict) -> None:
        from .. import checkpoint as ckpt_mod
        from .. import trace

        t0 = time.perf_counter()
        payload = ckpt_mod.save_checkpoint(
            self.config.dirname, scope=snap, step=step,
            max_keep=self.config.keep, extra=extra)
        plan = active_plan()
        if plan is not None \
                and plan.fire("torn_checkpoint", step) is not None:
            _tear(payload)
        trace.record("ckpt/write", t0, time.perf_counter(), step=step,
                     bytes=snap.nbytes())
        if self.config.on_saved is not None:
            # generation-durable hook (elastic ack flush): runs on the
            # writer thread — the trainer thread for sync saves, the
            # background thread otherwise
            self.config.on_saved(step, extra)

    def _write_guarded(self, snap, step, extra) -> None:
        try:
            self._write(snap, step, extra)
        except BaseException as exc:  # noqa: BLE001 - re-raised on wait()
            self._error = exc

    def wait(self) -> None:
        """Join an in-flight background write; re-raises its error."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            exc, self._error = self._error, None
            raise exc

    def join_quietly(self) -> None:
        """Join without raising — the exception-path cleanup, where a
        background-write failure must not mask the original error."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None

    def close(self) -> None:
        self.wait()


def _tear(payload: str) -> None:
    """Truncate a just-written checkpoint payload (torn-write fault)."""
    size = os.path.getsize(payload)
    with open(payload, "r+b") as f:
        f.truncate(max(size // 2, 1))


# ---------------------------------------------------------------------------
# Trainer run state
# ---------------------------------------------------------------------------
class TrainResilience:
    """One ``SGD.train()`` call's resilience state machine.

    The trainer calls, in order:

    - ``resume()`` once after param init (restores scope + position);
    - ``before_step()`` as each step enters the loop (fires ``crash`` /
      ``executor_error`` faults; the latter through the step retry);
    - ``after_step(...)`` as each step's results RESOLVE. In the sync
      loop it checkpoints inline and returns True on graceful interrupt;
      the async loop passes ``defer=True`` and, when ``pause_requested``,
      drains its window then calls ``commit()`` — the snapshot must not
      race in-flight donated state (PR 4 contract);
    - ``finalize()`` after the pass loop (final checkpoint + join).
    """

    def __init__(self, config: Optional[CheckpointConfig], scope=None,
                 plan=None):
        from ..flags import FLAGS

        self.config = config
        self.manager = (CheckpointManager(config, scope=scope, plan=plan)
                        if config is not None else None)
        plan = active_plan()
        if plan is None and FLAGS.fault_plan:
            plan = FaultPlan.parse(FLAGS.fault_plan)
        self.plan = plan
        self.flag = ShutdownFlag()
        self.step_retry = Retry(max_attempts=3, backoff=0.01,
                                name="trainer/step")
        self.dispatched = 0      # steps entered (dispatch order)
        self.completed = 0       # steps whose results resolved
        self.samples_seen = 0
        self.start_pass = 0
        self.skip_iterations = 0
        self.pause_requested = False
        self.interrupted = False
        self.resumed_meta: Optional[dict] = None
        self._last_pos = (0, -1)  # (pass_id, batch_id) last completed
        self._due_save = False    # latched: cadence hit, save not yet done

    # -- resume ------------------------------------------------------------
    def resume(self) -> Optional[dict]:
        if self.manager is None or not self.config.resume:
            return None
        meta = self.manager.resume()
        if meta is None:
            return None
        extra = meta.get("extra") or {}
        self.dispatched = self.completed = int(meta.get("step", 0))
        self.samples_seen = int(extra.get("samples_seen", 0))
        self.start_pass = int(extra.get("pass_id", 0))
        self.skip_iterations = int(extra.get("iteration", -1)) + 1
        self._last_pos = (self.start_pass, self.skip_iterations - 1)
        self.resumed_meta = meta
        return meta

    def skip_for_pass(self, pass_id: int, reader) -> int:
        """Batches of ``pass_id`` already consumed before the interrupt.
        Master-backed readers skip nothing: the master re-serves only
        unfinished tasks, so replaying its stream IS the resume."""
        if pass_id != self.start_pass or self.skip_iterations <= 0:
            return 0
        skip = self.config.skip_batches_on_resume if self.config else None
        if skip is None:
            skip = not getattr(reader, "master_backed", False)
        return self.skip_iterations if skip else 0

    def signal_context(self) -> Iterator[ShutdownFlag]:
        if self.config is not None and self.config.install_signal_handlers:
            return graceful_shutdown(flag=self.flag)
        return contextlib.nullcontext(self.flag)

    # -- step hooks --------------------------------------------------------
    def before_step(self) -> None:
        step = self.dispatched + 1
        if self.plan is not None:
            if self.plan.fire("crash", step) is not None:
                raise SimulatedCrash(
                    f"fault plan: hard crash before step {step}")

            def _maybe_transient():
                if self.plan.fire("executor_error", step) is not None:
                    raise TransientFault(
                        f"fault plan: transient executor error at step "
                        f"{step}")

            self.step_retry.call(_maybe_transient)
        self.dispatched += 1

    def after_step(self, pass_id: int, batch_id: int,
                   batch_size: Optional[int], defer: bool = False) -> bool:
        self.completed += 1
        if batch_size:
            self.samples_seen += int(batch_size)
        self._last_pos = (pass_id, batch_id)
        if self.plan is not None \
                and self.plan.fire("preempt", self.completed) is not None:
            self.flag.set(reason="fault-plan preemption")
        if self.manager is not None and self.manager.due(self.completed):
            # latched: the async loop drains PAST the cadence boundary
            # before it can save, so the due-ness must survive the drain
            self._due_save = True
        stop = self.flag.is_set()
        if not (self._due_save or stop):
            return False
        if defer:
            self.pause_requested = True
            return stop
        return self.commit(pass_id)

    def commit(self, pass_id: int) -> bool:  # noqa: ARG002 - symmetry
        """At a drained safe point: checkpoint if due / on interrupt;
        returns True when the loop must stop."""
        self.pause_requested = False
        stop = self.flag.is_set()
        if self.manager is not None:
            if stop and self.config.save_on_interrupt:
                self._save(reason="interrupt", wait=True)
                self._due_save = False
            elif self._due_save:
                self._save(reason="periodic")
                self._due_save = False
        if stop:
            self.interrupted = True
        return stop

    def _save(self, reason: str, wait: bool = False) -> None:
        p, b = self._last_pos
        self.manager.save(self.completed, pass_id=p, iteration=b,
                          samples_seen=self.samples_seen, reason=reason,
                          wait=wait)

    def finalize(self) -> None:
        if self.manager is None:
            return
        if (self.config.save_final and not self.interrupted
                and self.completed > 0
                and self.completed != self.manager.last_saved_step):
            self._save(reason="final", wait=True)
        self.manager.close()

    def abort(self) -> None:
        """Exception-path cleanup: join (never start) writes so no
        background thread keeps mutating the checkpoint dir after the
        crash propagates."""
        if self.manager is not None:
            self.manager.join_quietly()

"""Graceful-shutdown signal plumbing for preemptible workers.

TPU VMs (like the reference's spot-instance trainers) get SIGTERM with a
short grace window before preemption. :func:`graceful_shutdown` installs
handlers that only set a :class:`ShutdownFlag`; the training loop checks
the flag at step boundaries and performs the orderly exit itself — drain
in-flight async handles, write a final checkpoint, emit
``EndPass(interrupted=True)`` — because none of that is async-signal-safe.

The same flag is the target of the fault plan's ``preempt`` kind, so a
simulated preemption exercises exactly the code path a real SIGTERM does.
"""
from __future__ import annotations

import contextlib
import signal
import threading
from typing import Iterator, Optional, Tuple


class ShutdownFlag:
    """Thread-safe latch: set by a signal handler (or a fault plan),
    polled by the training loop at step boundaries."""

    def __init__(self):
        self._evt = threading.Event()
        self.reason: Optional[str] = None

    def set(self, reason: str = "signal") -> None:
        if not self._evt.is_set():
            self.reason = reason
        self._evt.set()

    def is_set(self) -> bool:
        return self._evt.is_set()

    def clear(self) -> None:
        self._evt.clear()
        self.reason = None

    def __repr__(self):
        return f"ShutdownFlag(set={self.is_set()}, reason={self.reason!r})"


@contextlib.contextmanager
def graceful_shutdown(
        signums: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
        flag: Optional[ShutdownFlag] = None) -> Iterator[ShutdownFlag]:
    """Install set-flag-only handlers for ``signums`` for the duration of
    the block; previous handlers are restored on exit. Off the main
    thread (where CPython forbids ``signal.signal``) the flag is still
    yielded — fault-plan preemptions keep working, OS signals don't.
    """
    flag = flag or ShutdownFlag()
    prev = {}

    def _handler(signum, frame):  # noqa: ARG001 - signal API
        flag.set(reason=signal.Signals(signum).name)

    for s in signums:
        try:
            prev[s] = signal.signal(s, _handler)
        except (ValueError, OSError):  # non-main thread / unsupported
            pass
    try:
        yield flag
    finally:
        for s, h in prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass

"""Generic bounded-retry policy with exponential backoff.

The reference's Go master client retries every RPC in a backoff loop
(/root/reference/go/master/client.go — ``for { err := backoff... }``) and
the pserver client reconnects through etcd re-discovery; :class:`Retry`
is that loop as a reusable policy object, applied to
:class:`paddle_tpu.master.MasterClient` (auto-reconnect + idempotent-op
retry) and available to serving dispatch and the trainer's transient-step
path.

Every failed attempt is visible: a ``retry/attempt`` trace span (with the
error and attempt index) and ``retry/attempts`` / ``retry/recovered`` /
``retry/exhausted`` StatSet counters, so ``tools/trace_summary.py
--resilience`` shows retry pressure at a glance.
"""
from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type

from .faults import TransientFault

#: Errors worth retrying by default: transport failures and injected
#: transients. Deliberately NOT OSError at large — a FileNotFoundError is
#: not a flaky network.
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, TransientFault)


class Retry:
    """``Retry(...).call(fn)`` runs ``fn`` until it succeeds, a
    non-retryable error escapes, attempts are exhausted, or the deadline
    passes (whichever first; the last error is re-raised).

    Also usable as a decorator: ``@Retry(max_attempts=3)``.
    """

    def __init__(self, max_attempts: int = 5, backoff: float = 0.05,
                 multiplier: float = 2.0, max_backoff: float = 2.0,
                 jitter: float = 0.0, deadline: Optional[float] = None,
                 retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE,
                 retry_on: Optional[Tuple[Type[BaseException], ...]] = None,
                 give_up_on: Tuple[Type[BaseException], ...] = (),
                 name: str = "retry", sleep: Callable[[float], None] = time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.backoff = float(backoff)
        self.multiplier = float(multiplier)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.deadline = deadline
        # ``retry_on`` is the explicit filter spelling (and wins over the
        # legacy ``retryable`` default); ``give_up_on`` carves exceptions
        # OUT of the retryable set — a ConnectionRefusedError subclass a
        # caller knows is permanent must escape on the first attempt.
        self.retryable = tuple(retry_on if retry_on is not None
                               else retryable)
        self.give_up_on = tuple(give_up_on)
        self.name = name
        self._sleep = sleep

    def remaining(self, t_start: float) -> Optional[float]:
        """Seconds left of the absolute deadline measured from
        ``t_start`` (None = unbounded)."""
        if self.deadline is None:
            return None
        return self.deadline - (time.monotonic() - t_start)

    def call(self, fn: Callable, *args,
             on_retry: Optional[Callable] = None, **kwargs):
        from .. import profiler, trace

        t_start = time.monotonic()
        delay = self.backoff
        for attempt in range(1, self.max_attempts + 1):
            t0 = time.perf_counter()
            try:
                out = fn(*args, **kwargs)
            except self.retryable as exc:
                if self.give_up_on and isinstance(exc, self.give_up_on):
                    raise
                t1 = time.perf_counter()
                trace.record("retry/attempt", t0, t1, policy=self.name,
                             attempt=attempt, error=repr(exc)[:200])
                profiler.global_stat.add_count("retry/attempts", 1)
                remaining = self.remaining(t_start)
                if attempt >= self.max_attempts or (
                        remaining is not None and remaining <= 0):
                    profiler.global_stat.add_count("retry/exhausted", 1)
                    raise
                if on_retry is not None:
                    on_retry(exc, attempt)
                sleep_s = min(delay, self.max_backoff)
                if self.jitter:
                    sleep_s += random.uniform(0.0, self.jitter * sleep_s)
                if remaining is not None and sleep_s >= remaining:
                    # the backoff would overshoot the caller's remaining
                    # budget — exhaust NOW instead of sleeping past the
                    # deadline and retrying into certain failure
                    profiler.global_stat.add_count("retry/exhausted", 1)
                    raise
                self._sleep(sleep_s)
                delay *= self.multiplier
                continue
            if attempt > 1:
                profiler.global_stat.add_count("retry/recovered", 1)
            return out

    def wrap(self, fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return wrapped

    __call__ = wrap

    def __repr__(self):
        return (f"Retry({self.name!r}, max_attempts={self.max_attempts}, "
                f"backoff={self.backoff}, deadline={self.deadline})")

"""paddle_tpu.resilience — preemption-safe training.

The layer between "a demo that trains" and "a job that survives the
cloud": periodic + on-signal checkpointing with auto-resume
(:class:`CheckpointConfig` / :class:`CheckpointManager`, driven by
``SGD.train(checkpoint=...)``), graceful SIGTERM/SIGINT shutdown
(:func:`graceful_shutdown`), a generic bounded-retry policy
(:class:`Retry`, applied to the reconnecting ``MasterClient``), and a
deterministic fault-injection plan (:class:`FaultPlan`) powering the
crash-matrix tests and ``--fault_plan`` chaos runs.

Quick start::

    from paddle_tpu.resilience import CheckpointConfig
    trainer.train(reader, num_passes=10,
                  checkpoint=CheckpointConfig("/ckpt/run1",
                                              every_n_steps=200))

Interrupt it (SIGTERM, preemption, crash) and run the same script again:
it resumes from the latest intact checkpoint — parameters, optimizer
slots, RNG stream, and data position — to the bit-identical end state.
"""
from .faults import (FAULT_KINDS, FaultPlan, SimulatedCrash, TransientFault,
                     active_plan, clear_plan, install_plan)
from .manager import (CheckpointConfig, CheckpointManager, TrainResilience)
from .retry import DEFAULT_RETRYABLE, Retry
from .signals import ShutdownFlag, graceful_shutdown

__all__ = [
    "FAULT_KINDS", "FaultPlan", "SimulatedCrash", "TransientFault",
    "active_plan", "clear_plan", "install_plan",
    "CheckpointConfig", "CheckpointManager", "TrainResilience",
    "DEFAULT_RETRYABLE", "Retry",
    "ShutdownFlag", "graceful_shutdown",
]

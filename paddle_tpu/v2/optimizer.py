"""v2 optimizer objects (reference python/paddle/v2/optimizer.py): thin
names over the fluid-style optimizers-as-ops."""
from .. import optimizer as _opt


def Momentum(learning_rate=0.01, momentum=0.9, **kw):
    return _opt.MomentumOptimizer(learning_rate=learning_rate,
                                  momentum=momentum)


def Adam(learning_rate=1e-3, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
    return _opt.AdamOptimizer(learning_rate=learning_rate, beta1=beta1,
                              beta2=beta2, epsilon=epsilon)


def AdaGrad(learning_rate=1e-2, **kw):
    return _opt.AdagradOptimizer(learning_rate=learning_rate)


def RMSProp(learning_rate=1e-3, **kw):
    return _opt.RMSPropOptimizer(learning_rate=learning_rate)


def SGD(learning_rate=1e-2, **kw):
    return _opt.SGDOptimizer(learning_rate=learning_rate)

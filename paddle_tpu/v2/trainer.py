"""v2 trainer (reference python/paddle/v2/trainer.py:24 SGD): the
cost/parameters/update_equation constructor and the event-driven
train(reader, num_passes, event_handler, feeding) loop — served by the
XLA executor instead of the SWIG gradient machine."""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from .. import event as evt
from ..data_feeder import DataFeeder


class SGD:
    def __init__(self, cost, parameters, update_equation,
                 extra_layers=None, is_local=True, accumulate_steps=1):
        """``accumulate_steps`` > 1: every k reader batches apply as ONE
        optimizer step on the mean gradient (in-graph gradient
        accumulation — optimizer.Optimizer.minimize)."""
        self.cost = cost
        self.parameters = parameters
        self.extra_layers = list(extra_layers or [])
        update_equation.minimize(
            cost, startup_program=parameters.startup_program,
            accumulate_steps=accumulate_steps)

    def _feeder(self, feeding: Optional[Dict[str, int]]):
        return DataFeeder(self.parameters.data_vars(feeding))

    def train(self, reader: Callable, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              feeding: Optional[Dict[str, int]] = None):
        event_handler = event_handler or (lambda e: None)
        self.parameters.init()
        feeder = self._feeder(feeding)
        exe, scope = self.parameters.executor, self.parameters.scope
        fetch = [self.cost] + self.extra_layers
        for pass_id in range(num_passes):
            event_handler(evt.BeginPass(pass_id))
            costs = []
            for batch_id, batch in enumerate(reader()):
                event_handler(evt.BeginIteration(pass_id, batch_id))
                out = exe.run(self.parameters.main_program,
                              feed=feeder.feed(batch), fetch_list=fetch,
                              scope=scope)
                cost = float(np.asarray(out[0]))
                costs.append(cost)
                event_handler(evt.EndIteration(pass_id, batch_id, cost, {}))
            event_handler(evt.EndPass(
                pass_id, metrics={"cost": float(np.mean(costs))
                                  if costs else 0.0}))

    def test(self, reader: Callable,
             feeding: Optional[Dict[str, int]] = None) -> "evt.TestResult":
        self.parameters.init()
        feeder = self._feeder(feeding)
        exe, scope = self.parameters.executor, self.parameters.scope
        prog = self.parameters.test_program_for(self.cost)
        costs = []
        for batch in reader():
            out = exe.run(prog, feed=feeder.feed(batch),
                          fetch_list=[self.cost], scope=scope)
            costs.append(float(np.asarray(out[0])))
        return evt.TestResult(float(np.mean(costs)) if costs else 0.0, {})

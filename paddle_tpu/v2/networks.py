"""v2 network composites (reference trainer_config_helpers/networks.py via
v2): the load-bearing recipes built from the layer namespace."""
from __future__ import annotations

from .. import layers as L
from . import activation as _act
from . import layer as l2


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=None, act=None, data_format="NHWC",
                         **kw):
    conv = l2.img_conv(input, filter_size=filter_size,
                       num_filters=num_filters, act=act,
                       padding=(filter_size - 1) // 2,
                       data_format=data_format)
    return l2.img_pool(conv, pool_size=pool_size,
                       stride=pool_stride or pool_size,
                       data_format=data_format)


def simple_lstm(input, size, reverse=False, **kw):
    """fc(4*size) + lstmemory — the v1 simple_lstm recipe."""
    proj = L.fc(input, size=4 * size, num_flatten_dims=2, bias_attr=False)
    return l2.lstmemory(proj, size=size, reverse=reverse)


def bidirectional_lstm(input, size, return_concat=True, **kw):
    fwd = simple_lstm(input, size)
    bwd = simple_lstm(input, size, reverse=True)
    if return_concat:
        return L.concat([fwd, bwd], axis=-1)
    return fwd, bwd


def simple_gru(input, size, reverse=False, **kw):
    proj = L.fc(input, size=3 * size, num_flatten_dims=2, bias_attr=False)
    return l2.grumemory(proj, size=size, reverse=reverse)


def sequence_conv_pool(input, context_len, hidden_size, pool_type=None,
                       **kw):
    conv = L.sequence_conv(input, num_filters=hidden_size,
                           filter_size=context_len, act="relu")
    return l2.pooling(conv, pooling_type=pool_type or "max")

"""v2 network composites (reference trainer_config_helpers/networks.py via
v2): the load-bearing recipes built from the layer namespace."""
from __future__ import annotations

from .. import layers as L
from . import activation as _act
from . import layer as l2


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=1, act=None, pool_type=None,
                         groups=1, conv_stride=1, conv_padding=0,
                         bias_attr=None, param_attr=None, pool_padding=0,
                         data_format="NHWC", **kw):
    """conv -> pool with the REFERENCE defaults (reference networks.py:144:
    conv_padding=0, conv_stride=1, pool_stride=1, pool_padding=0) so
    unmodified configs reproduce the reference's output geometry."""
    conv = l2.img_conv(input, filter_size=filter_size,
                       num_filters=num_filters, act=act,
                       stride=conv_stride, padding=conv_padding,
                       groups=groups, param_attr=param_attr,
                       bias_attr=bias_attr, data_format=data_format)
    return l2.img_pool(conv, pool_size=pool_size, stride=pool_stride,
                       padding=pool_padding, pool_type=pool_type,
                       data_format=data_format)


def simple_lstm(input, size, reverse=False, **kw):
    """fc(4*size) + lstmemory — the v1 simple_lstm recipe."""
    proj = L.fc(input, size=4 * size, num_flatten_dims=2, bias_attr=False)
    return l2.lstmemory(proj, size=size, reverse=reverse)


def bidirectional_lstm(input, size, return_concat=True, **kw):
    fwd = simple_lstm(input, size)
    bwd = simple_lstm(input, size, reverse=True)
    if return_concat:
        out = L.concat([fwd, bwd], axis=-1)
        sl = getattr(input, "seq_len", None)
        if sl is not None:
            out.seq_len = sl  # concat keeps [b, T, .] — the mask survives
        return out
    return fwd, bwd


def simple_gru(input, size, reverse=False, **kw):
    proj = L.fc(input, size=3 * size, num_flatten_dims=2, bias_attr=False)
    return l2.grumemory(proj, size=size, reverse=reverse)


def sequence_conv_pool(input, context_len, hidden_size, pool_type=None,
                       **kw):
    conv = L.sequence_conv(input, num_filters=hidden_size,
                           filter_size=context_len, act="relu")
    return l2.pooling(conv, pooling_type=pool_type or "max")


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     pool_stride=None, act=None, conv_padding=None,
                     drop_rate=0.0, data_format="NHWC", pool_type=None,
                     **kw):
    """conv -> batch_norm(+act) -> [dropout] -> pool (reference
    trainer_config_helpers/networks.py:231 img_conv_bn_pool)."""
    tmp = l2.img_conv(input, filter_size=filter_size,
                      num_filters=num_filters, act=None,
                      padding=(filter_size - 1) // 2
                      if conv_padding is None else conv_padding,
                      data_format=data_format)
    tmp = l2.batch_norm(tmp, act=act, data_format=data_format)
    if drop_rate:
        tmp = l2.dropout(tmp, drop_rate)
    return l2.img_pool(tmp, pool_size=pool_size,
                       stride=pool_stride or pool_size,
                       pool_type=pool_type, data_format=data_format)


def img_conv_group(input, conv_num_filter, num_channels=None, pool_size=2,
                   pool_stride=2, conv_padding=1, conv_filter_size=3,
                   conv_act=None, conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_type=None,
                   data_format="NHWC", **kw):
    """VGG-style group: N convs (+BN (+dropout)) then one pool (reference
    trainer_config_helpers/networks.py img_conv_group). Honors the v1
    conv_padding contract."""
    n = len(conv_num_filter)

    def per(x):
        return list(x) if isinstance(x, (list, tuple)) else [x] * n

    pads, sizes = per(conv_padding), per(conv_filter_size)
    with_bn, drops = per(conv_with_batchnorm), per(conv_batchnorm_drop_rate)
    tmp = input
    for i in range(n):
        tmp = l2.img_conv(tmp, sizes[i], conv_num_filter[i], stride=1,
                          padding=pads[i],
                          act=None if with_bn[i] else conv_act,
                          data_format=data_format)
        if with_bn[i]:
            tmp = l2.batch_norm(tmp, act=conv_act, data_format=data_format)
            if drops[i] > 0:
                tmp = l2.dropout(tmp, drops[i])
    return l2.img_pool(tmp, pool_size, stride=pool_stride,
                       pool_type=pool_type, data_format=data_format)


def small_vgg(input_image, num_channels=None, num_classes=10, **kw):
    """The 2-2-3-3 batchnormed VGG (reference networks.py:517)."""
    tmp = input_image
    for filt, times, drops in ((64, 2, [0.3, 0]), (128, 2, [0.4, 0]),
                               (256, 3, [0.4, 0.4, 0]),
                               (512, 3, [0.4, 0.4, 0])):
        tmp = img_conv_group(tmp, [filt] * times, pool_size=2,
                             pool_stride=2, conv_padding=1,
                             conv_filter_size=3, conv_act="relu",
                             conv_with_batchnorm=True,
                             conv_batchnorm_drop_rate=drops)
    tmp = l2.img_pool(tmp, 2, stride=2)
    tmp = l2.dropout(tmp, 0.5)
    tmp = L.fc(tmp, size=512)
    tmp = l2.dropout(tmp, 0.5)
    tmp = l2.batch_norm(tmp, act="relu")
    return L.fc(tmp, size=num_classes, act="softmax")


def vgg_16_network(input_image, num_channels=None, num_classes=1000, **kw):
    """VGG-16 (reference networks.py:547)."""
    tmp = input_image
    for filters in ([64, 64], [128, 128], [256, 256, 256],
                    [512, 512, 512], [512, 512, 512]):
        tmp = img_conv_group(tmp, filters, pool_size=2, pool_stride=2,
                             conv_padding=1, conv_filter_size=3,
                             conv_act="relu")
    tmp = L.fc(tmp, size=4096, act="relu")
    tmp = l2.dropout(tmp, 0.5)
    tmp = L.fc(tmp, size=4096, act="relu")
    tmp = l2.dropout(tmp, 0.5)
    return L.fc(tmp, size=num_classes, act="softmax")


def text_conv_pool(input, context_len=5, hidden_size=128, **kw):
    """Context conv + max pool over time (reference networks.py
    text_conv_pool)."""
    return sequence_conv_pool(input, context_len, hidden_size,
                              pool_type="max")


def bidirectional_gru(input, size, return_concat=True, **kw):
    """Forward + backward simple_gru (reference networks.py:1226)."""
    fwd = simple_gru(input, size)
    bwd = simple_gru(input, size, reverse=True)
    if return_concat:
        out = L.concat([fwd, bwd], axis=-1)
        sl = getattr(input, "seq_len", None)
        if sl is not None:
            out.seq_len = sl
        return out
    return fwd, bwd


def simple_gru2(input, size, reverse=False, **kw):
    """simple_gru with the alternative parameter grouping (reference
    networks.py:1163) — numerically the same recurrence here."""
    return simple_gru(input, size, reverse=reverse)


def _masked_softmax_over_time(scores, seq_len):
    """softmax over the last (source-time) axis, padding masked out.
    scores [b, Td, Te]; seq_len int32 [b] or None."""
    if seq_len is None:
        return L.softmax(scores)
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("attn_mask")
    Te = int(scores.shape[-1])
    if Te > 0:
        mask = helper.simple_op(  # [b, Te] 1/0
            "sequence_mask", {"X": [seq_len]},
            {"maxlen": Te, "out_dtype": "float32"}, out_slot="Y")
    else:
        # Dynamic source-time dim: resolve maxlen from the scores' own
        # runtime shape at executor compile time.
        mask = helper.simple_op(
            "sequence_mask", {"X": [seq_len], "MaxLenRef": [scores]},
            {"maxlen": -1, "out_dtype": "float32"}, out_slot="Y")
    penalty = L.scale(mask, 1e9, bias=-1e9)  # 0 where valid, -1e9 at pads
    penalty = L.reshape(penalty, shape=[0, 1, Te if Te > 0 else -1])
    return L.softmax(L.elementwise_add(scores, penalty))


def dot_product_attention(encoded_sequence, attending_sequence=None,
                          attended_sequence=None, softmax_param_attr=None,
                          **kw):
    """Luong dot-product attention (reference networks.py:1498), batched
    over every decoder step at once — the TPU-first replacement for the
    per-step recurrent_group form: context[i] = sum_j softmax(s_i.h_j) h_j.

    ``encoded_sequence`` [b, Te, H] attends; the query states are
    ``attending_sequence`` [b, Td, H] (teacher-forced decoder states)."""
    q = attending_sequence
    v = attended_sequence if attended_sequence is not None \
        else encoded_sequence
    scores = L.matmul(q, encoded_sequence, transpose_y=True)
    attn = _masked_softmax_over_time(
        scores, getattr(encoded_sequence, "seq_len", None))
    ctx = L.matmul(attn, v)
    sl = getattr(q, "seq_len", None)
    if sl is not None:
        ctx.seq_len = sl
    return ctx


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, **kw):
    """Bahdanau additive attention (reference networks.py:1400), batched:
    e_ij = v . f(W s_i + U h_j) with f=tanh; U h_j is the pre-computed
    ``encoded_proj`` [b, Te, A]. ``decoder_state`` may be [b, D] (one
    step) or [b, Td, D] (all steps, teacher-forced)."""
    from ..layers.layer_helper import LayerHelper

    A = int(encoded_proj.shape[-1])
    single_step = len(decoder_state.shape) == 2
    dec = decoder_state
    if single_step:
        dec = L.reshape(dec, shape=[0, 1, int(dec.shape[-1])])
    dec_proj = L.fc(dec, size=A, num_flatten_dims=2, bias_attr=False,
                    param_attr=transform_param_attr)  # [b, Td, A]
    Te = int(encoded_proj.shape[1])
    Td = int(dec_proj.shape[1])
    dp = L.reshape(dec_proj, shape=[0, Td, 1, A])
    ep = L.reshape(encoded_proj, shape=[0, 1, Te, A])
    act = _act.resolve(weight_act) or "tanh"
    helper = LayerHelper("simple_attention")
    pre = helper.append_activation(L.elementwise_add(dp, ep), act)
    vvec = helper.create_parameter(softmax_param_attr, shape=[A],
                                   dtype="float32")
    scores = L.reduce_sum(L.elementwise_mul(pre, vvec), dim=-1)  # [b,Td,Te]
    attn = _masked_softmax_over_time(
        scores, getattr(encoded_sequence, "seq_len", None))
    ctx = L.matmul(attn, encoded_sequence)
    if single_step:
        ctx = L.reshape(ctx, shape=[0, int(encoded_sequence.shape[-1])])
    else:
        sl = getattr(decoder_state, "seq_len", None)
        if sl is not None:
            ctx.seq_len = sl
    return ctx


def gru_encoder_decoder(src, trg_in, src_dict_dim, trg_dict_dim,
                        word_vector_dim=512, encoder_size=512,
                        decoder_size=512, with_attention=True,
                        bidirectional=False, **kw):
    """Teacher-forced GRU encoder-decoder (the seqToseq recipe the
    reference builds from recurrent_group in demo configs; here batched:
    encoder GRU -> decoder GRU seeded with the final encoder state ->
    [dot attention ->] per-step vocabulary logits [b, Td, trg_dict_dim].

    ``src``/``trg_in`` are integer id sequences (data vars, lod_level=1).
    Pair the result with softmax_with_cross_entropy over trg_next for the
    training cost (demos/nmt_seq2seq.py shows the full loop)."""
    s_emb = l2.embedding(src, word_vector_dim, vocab_size=src_dict_dim)
    s_emb.seq_len = src.seq_len
    if bidirectional:
        enc = bidirectional_gru(s_emb, encoder_size)
        enc_dim = 2 * encoder_size
    else:
        enc = simple_gru(s_emb, encoder_size)
        enc_dim = encoder_size
    # simple_gru's fc projection drops seq_len; without it
    # sequence_last_step would read the last PADDED timestep and the
    # attention softmax would attend to padding.
    enc.seq_len = src.seq_len
    enc_last = L.sequence_last_step(enc)
    t_emb = l2.embedding(trg_in, word_vector_dim, vocab_size=trg_dict_dim)
    t_emb.seq_len = trg_in.seq_len
    t_proj = L.fc(t_emb, size=3 * decoder_size, num_flatten_dims=2,
                  bias_attr=False)
    h0 = enc_last if enc_dim == decoder_size \
        else L.fc(enc_last, size=decoder_size, act="tanh")
    dec = L.dynamic_gru(t_proj, size=decoder_size, h0=h0)
    dec.seq_len = trg_in.seq_len
    if with_attention:
        if enc_dim == decoder_size:
            ctx = dot_product_attention(enc, attending_sequence=dec)
        else:
            keys = L.fc(enc, size=decoder_size, num_flatten_dims=2,
                        bias_attr=False)
            keys.seq_len = src.seq_len  # mask must survive the projection
            ctx = dot_product_attention(keys, attending_sequence=dec,
                                        attended_sequence=enc)
        both = L.concat([dec, ctx], axis=2)
    else:
        both = dec
    both.seq_len = trg_in.seq_len
    logits = L.fc(both, size=trg_dict_dim, num_flatten_dims=2)
    logits.seq_len = trg_in.seq_len
    return logits

"""v2 data-type declarations (reference v2/data_type.py →
trainer/PyDataProvider2.py InputType): each describes one feed slot; the
layer.data builder turns them into typed data variables.

Sparse types are served as PADDED ID-LIST feeds, not dense multi-hot rows:
a ``sparse_binary_vector(dim)`` row is a list of active indices, fed as an
int64 id array + length mask, and consumed through the embedding-sum path —
so the gradient is a SelectedRows sparse update over the touched rows
(core/selected_rows.py), the TPU-native equivalent of the reference's
scipy-CSR → Arguments feed (/root/reference/paddle/py_paddle/
dataprovider_converter.py SparseBinaryScanner/SparseFloatScanner). At CTR
dims (1e5+) this is what keeps the feed and the update O(nnz), not O(dim).
"""


class InputType:
    def __init__(self, dim, seq_type, dtype, sparse=None):
        self.dim = dim
        self.seq_type = seq_type  # 0 = no sequence, 1 = sequence
        self.dtype = dtype
        self.sparse = sparse  # None | "binary" | "float"


def dense_vector(dim):
    return InputType(dim, 0, "float32")


def dense_array(dim):
    return InputType(dim, 0, "float32")


def integer_value(value_range):
    return InputType(value_range, 0, "int64")


def dense_vector_sequence(dim):
    return InputType(dim, 1, "float32")


def integer_value_sequence(value_range):
    return InputType(value_range, 1, "int64")


def sparse_binary_vector(dim):
    """Rows are lists of active indices (multi-hot positions)."""
    return InputType(dim, 0, "int64", sparse="binary")


def sparse_float_vector(dim):
    """Rows are lists of (index, value) pairs."""
    return InputType(dim, 0, "int64", sparse="float")

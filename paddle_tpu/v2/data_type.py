"""v2 data-type declarations (reference v2/data_type.py →
trainer/PyDataProvider2.py InputType): each describes one feed slot; the
layer.data builder turns them into typed data variables."""


class InputType:
    def __init__(self, dim, seq_type, dtype):
        self.dim = dim
        self.seq_type = seq_type  # 0 = no sequence, 1 = sequence
        self.dtype = dtype


def dense_vector(dim):
    return InputType(dim, 0, "float32")


def dense_array(dim):
    return InputType(dim, 0, "float32")


def integer_value(value_range):
    return InputType(value_range, 0, "int64")


def dense_vector_sequence(dim):
    return InputType(dim, 1, "float32")


def integer_value_sequence(value_range):
    return InputType(value_range, 1, "int64")


def sparse_binary_vector(dim):
    # served densely (multi-hot rows); the SelectedRows path handles true
    # sparsity at the embedding level
    return InputType(dim, 0, "float32")

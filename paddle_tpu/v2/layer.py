"""v2 layer namespace (reference python/paddle/v2/layer.py re-exporting
trainer_config_helpers/layers.py): keyword-style builders (input=, size=,
act=activation.Relu()) over the fluid-style layers package. Each function
documents the v1 DSL name it serves."""
from __future__ import annotations

from .. import layers as L
from . import activation as _act
from . import pooling as _pool
from .data_type import InputType


def data(name, type: InputType, **kw):
    """data_layer. ``type`` is a data_type.* declaration; sequence types
    become padded+length feeds (lod_level=1). For integer types the dim is
    the VALUE RANGE (vocab/class count) — the tensor itself is one id per
    (sequence) position, exactly the reference's InputType contract. The
    declaration rides on the returned handle (``.input_type``) so downstream
    builders (embedding, fc-over-sparse) can read the vocab/width from the
    graph, as the reference's config_parser propagates LayerConfig input
    sizes (/root/reference/python/paddle/trainer/config_parser.py)."""
    if type.sparse:
        # padded active-id list + length mask (+ values for sparse_float)
        var = L.data(name, shape=[1], dtype="int64", lod_level=1)
        if type.sparse == "float":
            val = L.data(f"{name}@val", shape=[-1], dtype="float32",
                         append_batch_size=False)
            val.is_companion = True
            var.sparse_values = val
        var.input_type = type
        return var
    width = 1 if type.dtype == "int64" else type.dim
    var = L.data(name, shape=[width], dtype=type.dtype,
                 lod_level=1 if type.seq_type else 0)
    var.input_type = type
    return var


def _sparse_fc_branch(inp, size, param_attr):
    """One fc branch over a sparse id-list input: sum of weight rows for the
    active ids (optionally value-weighted) == multi-hot row @ W, but fed
    O(nnz) and backed by SelectedRows sparse gradients."""
    t = inp.input_type
    emb = L.embedding(inp, size=[t.dim, size], param_attr=param_attr)
    emb.seq_len = inp.seq_len
    values = getattr(inp, "sparse_values", None)
    if values is not None:
        vals3 = L.reshape(values, shape=[0, -1, 1])
        emb = L.elementwise_mul(emb, vals3)
        emb.seq_len = inp.seq_len
    return L.sequence_pool(emb, "sum")


def _is_sparse(v):
    t = getattr(v, "input_type", None)
    return t is not None and t.sparse


def _fc_flatten_dims(inputs):
    """The v1 fc_layer contract, PER INPUT: a [b, T, d] sequence input is
    transformed per timestep (reference fc_layer applied inside the time
    loop); a static image tensor is flattened whole. Per-timestep
    whenever an input carries sequence-ness or a dynamic inner dim that
    would poison the flattened fan-in with the -1 sentinel."""
    nfds = []
    for v in inputs:
        shape = v.shape or ()
        if len(shape) > 2 and (getattr(v, "seq_len", None) is not None
                               or getattr(getattr(v, "input_type", None),
                                          "seq_type", 0)
                               or -1 in shape[1:-1]):
            nfds.append(len(shape) - 1)
        else:
            nfds.append(1)
    return nfds


def fc(input, size, act=None, param_attr=None, bias_attr=None, **kw):
    """fc_layer. ``input`` may be a list (each gets its own weight); sparse
    id-list inputs route through the embedding-sum path. The bias (one per
    fc, reference fc_layer contract) is carried by the dense sub-fc when
    one exists, else created here so sparse-only fcs keep their bias."""
    inputs = input if isinstance(input, (list, tuple)) else [input]
    sparse = [v for v in inputs if _is_sparse(v)]
    dense = [v for v in inputs if not _is_sparse(v)]
    if not sparse:
        r = L.fc(input, size=size, act=_act.resolve(act),
                 param_attr=param_attr, bias_attr=bias_attr,
                 num_flatten_dims=_fc_flatten_dims(inputs))
        sl = next((getattr(v, "seq_len", None) for v in inputs
                   if getattr(v, "seq_len", None) is not None), None)
        if sl is not None and len(r.shape) >= 2:
            r.seq_len = sl
        return r
    from ..layers.layer_helper import LayerHelper

    branches = [_sparse_fc_branch(v, size, param_attr) for v in sparse]
    if dense:
        # the dense sub-fc owns the (single) bias
        branches.append(L.fc(dense, size=size, act=None,
                             param_attr=param_attr, bias_attr=bias_attr))
        return L.addto(branches, act=_act.resolve(act))
    summed = L.addto(branches, act=None)
    helper = LayerHelper("fc")
    if bias_attr is not False:
        summed = helper.append_bias_op(summed, bias_attr, size, dim_start=1)
    return helper.append_activation(summed, _act.resolve(act))


def embedding(input, size, param_attr=None, **kw):
    """embedding_layer: size is the embedding dim; the vocab comes from the
    upstream data layer's InputType.dim (v1 DSL contract), overridable with
    an explicit ``vocab_size`` kwarg."""
    vocab = kw.get("vocab_size")
    if vocab is None:
        t = getattr(input, "input_type", None)
        if t is not None:
            vocab = t.dim
    if vocab is None:
        raise ValueError(
            "embedding(): the input does not carry an InputType to read the "
            "vocab from (it is not a data layer); pass vocab_size=...")
    return L.embedding(input, size=[vocab, size], param_attr=param_attr)


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, groups=1, act=None, param_attr=None, bias_attr=None,
             data_format="NHWC", **kw):
    """img_conv_layer."""
    return L.conv2d(input, num_filters=num_filters, filter_size=filter_size,
                    stride=stride, padding=padding, groups=groups,
                    act=_act.resolve(act), param_attr=param_attr,
                    bias_attr=bias_attr, data_format=data_format)


def img_pool(input, pool_size, stride=1, padding=0, pool_type=None,
             ceil_mode=True, data_format="NHWC", **kw):
    """img_pool_layer. ``ceil_mode`` defaults True — the v1 DSL's output
    size rule (reference trainer_config_helpers/layers.py img_pool_layer
    ceil_mode=True)."""
    return L.pool2d(input, pool_size=pool_size, pool_stride=stride,
                    pool_padding=padding,
                    pool_type=_pool.resolve(pool_type),
                    ceil_mode=ceil_mode, data_format=data_format)


def batch_norm(input, act=None, **kw):
    """batch_norm_layer."""
    return L.batch_norm(input, act=_act.resolve(act),
                        data_layout=kw.get("data_format", "NHWC"),
                        is_test=kw.get("is_test", False))


def dropout_keep_len(var, rate):
    """Dropout that preserves the sequence-length annotation (dropout is
    shape-preserving, so the mask survives)."""
    sl = getattr(var, "seq_len", None)
    var = dropout(var, rate)
    if sl is not None:
        var.seq_len = sl
    return var


def dropout(input, dropout_rate=0.5, **kw):
    """dropout_layer."""
    return L.dropout(input, dropout_prob=dropout_rate)


def concat(input, **kw):
    """concat_layer (feature axis)."""
    return L.concat(list(input), axis=-1)


def addto(input, act=None, bias_attr=None, **kw):
    """addto_layer."""
    return L.addto(list(input), act=_act.resolve(act))


def lstmemory(input, size=None, reverse=False, act=None, **kw):
    """lstmemory: input must be the 4x-projected sequence, as in the v1
    DSL (pair with fc(size=4*hidden, act=Linear()) or use
    networks.simple_lstm). ``size`` is the HIDDEN width (projected/4)."""
    proj = int(input.shape[-1])
    if size is not None and proj != 4 * size:
        raise ValueError(
            f"lstmemory(size={size}) expects a {4 * size}-wide projected "
            f"input, got {proj} (v1 DSL contract)")
    h, _ = L.dynamic_lstm(input, proj, is_reverse=reverse)
    return h


def grumemory(input, size=None, reverse=False, **kw):
    """grumemory: input is the 3x-projected sequence."""
    if size is None:
        size = int(input.shape[-1]) // 3
    return L.dynamic_gru(input, size, is_reverse=reverse)


def pooling(input, pooling_type=None, **kw):
    """pooling_layer over the sequence axis."""
    return L.sequence_pool(input, _pool.resolve(pooling_type))


def first_seq(input, **kw):
    return L.sequence_first_step(input)


def last_seq(input, **kw):
    return L.sequence_last_step(input)


def expand(input, expand_as, **kw):
    """expand_layer."""
    return L.sequence_expand(input, expand_as)


def max_id(input, **kw):
    """maxid_layer."""
    return L.argmax(input, axis=-1)


def crf(input, label, size=None, param_attr=None, **kw):
    """crf_layer: per-sequence negative log-likelihood [b, 1]; the
    transition parameter rides on ``.transition`` for crf_decoding."""
    return L.linear_chain_crf(input, label, param_attr=param_attr)


def crf_decoding(input, size=None, param_attr=None, label=None,
                 transition=None, **kw):
    """crf_decoding_layer. Pass ``transition=cost.transition`` from the
    crf() cost so Viterbi uses the TRAINED transitions."""
    return L.crf_decoding(input, param_attr=param_attr, label=label,
                          transition=transition)


def ctc(input, label, blank=0, **kw):
    """ctc_layer / warp_ctc_layer."""
    return L.warpctc(input, label, blank=blank)


# ---- cost layers (CostLayer.cpp family) --------------------------------
def classification_cost(input, label, **kw):
    """classification_cost: softmax cross-entropy over class scores."""
    return L.mean(L.softmax_with_cross_entropy(input, label))


def cross_entropy_cost(input, label, **kw):
    return L.mean(L.cross_entropy(input, label))


def square_error_cost(input, label, **kw):
    """regression_cost."""
    return L.mean(L.square_error_cost(input, label))


def rank_cost(left, right, label, **kw):
    """rank_cost (RankingCost): pairwise logistic loss."""
    diff = L.elementwise_sub(left, right)
    return L.mean(L.log(L.elementwise_add(
        L.exp(L.elementwise_mul(L.scale(label, -2.0, bias=1.0), diff)),
        L.fill_constant(shape=[1], value=1.0, dtype="float32"))))


def huber_regression_cost(input, label, delta=1.0, **kw):
    """huber_regression_cost (HuberRegressionLoss, CostLayer.cpp)."""
    from ..layers.layer_helper import LayerHelper

    h = LayerHelper("huber_cost")
    outs, _ = h.append_op("huber_loss", {"X": [input], "Y": [label]},
                          ["Out", "Residual"], {"delta": float(delta)})
    return L.mean(outs["Out"][0])


# ---------------------------------------------------------------------------
# mixed_layer + projections (reference trainer_config_helpers/layers.py
# mixed_layer, *_projection: a mixed layer sums the projected inputs, then
# bias + activation — MixedLayer.cpp. Projections are deferred builders;
# mixed_layer(input=[...]) is the immediate form, `with mixed_layer(...)
# as m: m += proj` the incremental one.)
# ---------------------------------------------------------------------------

class BaseProjection:
    def __init__(self, input, param_attr=None):
        self.input = input
        self.param_attr = param_attr

    def build(self, size):
        raise NotImplementedError


class full_matrix_projection(BaseProjection):
    """input @ W, no bias (FullMatrixProjection.cpp)."""

    def __init__(self, input, size=0, param_attr=None):
        super().__init__(input, param_attr)

    def build(self, size):
        # via the v2 fc: per-timestep on sequence inputs (the reference
        # projection operates inside the time loop)
        return fc(self.input, size, act=None,
                  param_attr=self.param_attr, bias_attr=False)


class trans_full_matrix_projection(BaseProjection):
    """input @ W^T — the weight is stored [size, in] and shared with a
    forward projection by name (TransposedFullMatrixProjection.cpp)."""

    def __init__(self, input, size=0, param_attr=None):
        super().__init__(input, param_attr)

    def build(self, size):
        from ..layers.layer_helper import LayerHelper

        helper = LayerHelper("trans_fc")
        d = int(self.input.shape[-1])
        w = helper.create_parameter(self.param_attr, shape=[size, d],
                                    dtype=self.input.dtype)
        return L.matmul(self.input, L.transpose(w, axis=[1, 0]))


class table_projection(BaseProjection):
    """Embedding-table lookup of integer input (TableProjection.cpp)."""

    def __init__(self, input, size=0, param_attr=None):
        super().__init__(input, param_attr)

    def build(self, size):
        return embedding(self.input, size, param_attr=self.param_attr)


class identity_projection(BaseProjection):
    """Pass-through, or a feature slice when offset is given
    (IdentityProjection.cpp / IdentityOffsetProjection.cpp)."""

    def __init__(self, input, offset=None, size=None):
        super().__init__(input)
        self.offset = offset
        self.size = size

    def build(self, size):
        if self.offset is None:
            return self.input
        end = self.offset + (self.size or size)
        from ..layers.layer_helper import LayerHelper

        helper = LayerHelper("identity_offset")
        rank = len(self.input.shape)
        return helper.simple_op(
            "slice", {"X": [self.input]},
            {"axes": [rank - 1], "starts": [int(self.offset)],
             "ends": [int(end)]})


class scaling_projection(BaseProjection):
    """w * input with a single learned scalar (ScalingProjection.cpp)."""

    def build(self, size):
        from ..layers.layer_helper import LayerHelper

        helper = LayerHelper("scaling_projection")
        w = helper.create_parameter(self.param_attr, shape=[1],
                                    dtype=self.input.dtype)
        return L.elementwise_mul(self.input, w)


class dotmul_projection(BaseProjection):
    """input .* w with a learned per-feature vector (DotMulProjection)."""

    def build(self, size):
        from ..layers.layer_helper import LayerHelper

        helper = LayerHelper("dotmul_projection")
        d = int(self.input.shape[-1])
        w = helper.create_parameter(self.param_attr, shape=[d],
                                    dtype=self.input.dtype)
        return L.elementwise_mul(self.input, w)


class context_projection(BaseProjection):
    """Neighbour-window concat over the time axis (ContextProjection.cpp);
    trainable out-of-range padding is not supported (rows are zeros)."""

    def __init__(self, input, context_len, context_start=None, **kw):
        super().__init__(input)
        self.context_len = int(context_len)
        self.context_start = (-(self.context_len // 2)
                              if context_start is None else
                              int(context_start))

    def build(self, size):
        from ..layers.layer_helper import LayerHelper
        from ..layers.sequence import _len_input

        helper = LayerHelper("context_project")
        out = helper.simple_op(
            "context_project",
            {"X": [self.input], **_len_input(self.input)},
            {"context_length": self.context_len,
             "context_start": self.context_start})
        sl = getattr(self.input, "seq_len", None)
        if sl is not None:
            out.seq_len = sl
        return out


class MixedLayerType:
    """What mixed_layer() returns: collects projections via ``+=`` inside
    a ``with`` block; at exit it BECOMES the built output variable (the
    instance adopts the Variable's class/state), so the reference idiom
    of using the mixed object as a layer input works unchanged."""

    def __init__(self, size, act=None, bias_attr=False, drop_rate=0.0):
        self._size = size
        self._act = act
        self._bias_attr = bias_attr
        self._drop_rate = drop_rate
        self._projections = []

    def __iadd__(self, proj):
        if not isinstance(proj, BaseProjection):
            raise TypeError(f"mixed_layer += expects a projection, got "
                            f"{type(proj).__name__}")
        self._projections.append(proj)
        return self

    def __enter__(self):
        return self

    def _finalize(self):
        var = _build_mixed(self._projections, self._size, self._act,
                           self._bias_attr)
        if self._drop_rate:
            var = dropout_keep_len(var, self._drop_rate)
        # adopt the Variable's identity: everything downstream reads
        # name/shape/block from the shared state
        self.__class__ = var.__class__
        self.__dict__ = var.__dict__
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._finalize()
        return False


def _build_mixed(projections, size, act, bias_attr):
    if not projections:
        raise ValueError("mixed_layer has no projections")
    from ..layers.layer_helper import LayerHelper

    built = [p.build(size) for p in projections]
    widths = {int(v.shape[-1]) for v in built}
    if len(widths) > 1:
        raise ValueError(
            f"mixed_layer projections disagree on width: {sorted(widths)}")
    summed = built[0] if len(built) == 1 else L.sums(built)
    helper = LayerHelper("mixed")
    out_size = widths.pop()
    if bias_attr is not False:
        summed = helper.append_bias_op(summed, bias_attr, out_size,
                                       dim_start=len(summed.shape) - 1)
    result = helper.append_activation(summed, _act.resolve(act))
    sl = next((getattr(v, "seq_len", None) for v in built
               if getattr(v, "seq_len", None) is not None), None)
    if sl is not None:
        result.seq_len = sl
    return result


def mixed_layer(size=0, input=None, act=None, bias_attr=False,
                drop_rate=0.0, **kw):
    """mixed_layer: immediate form returns the Variable; without input,
    a context manager collecting ``+=`` projections. NO bias unless
    bias_attr is given — the reference decorates mixed_layer with
    wrap_bias_attr_default(has_bias=False) (layers.py:865)."""
    if input is not None:
        projs = input if isinstance(input, (list, tuple)) else [input]
        var = _build_mixed(list(projs), size, act, bias_attr)
        if drop_rate:
            var = dropout_keep_len(var, drop_rate)
        return var
    return MixedLayerType(size, act=act, bias_attr=bias_attr,
                          drop_rate=drop_rate)


mixed = mixed_layer


# ---------------------------------------------------------------------------
# v1 layer-name tail: thin keyword adapters over the fluid layer fns
# (reference trainer_config_helpers/layers.py names; math in
# layers/legacy.py and the op registry)
# ---------------------------------------------------------------------------

def cos_sim(a, b, scale=1.0, **kw):
    """cos_sim layer (CosSimLayer.cpp); ``scale`` multiplies the cosine."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("cos_sim")
    outs, _ = helper.append_op("cos_sim", {"X": [a], "Y": [b]},
                               ["Out", "XNorm", "YNorm"], {})
    sim = outs["Out"][0]
    return L.scale(sim, float(scale)) if scale != 1.0 else sim


def trans(input, **kw):
    """trans_layer: transpose the two feature dims (TransLayer.cpp)."""
    return L.transpose(input, axis=[0, 2, 1])


def interpolation(input, weight, **kw):
    """interpolation_layer: w*x + (1-w)*y (InterpolationLayer.cpp)."""
    x, y = input
    return L.interpolation(x, y, weight)


def power(input, weight, **kw):
    return L.power(input, weight)


def scaling(input, weight, **kw):
    return L.scaling(input, weight)


def slope_intercept(input, slope=1.0, intercept=0.0, **kw):
    return L.slope_intercept(input, slope=slope, intercept=intercept)


def sum_to_one_norm(input, **kw):
    return L.sum_to_one_norm(input)


def row_l2_norm(input, **kw):
    return L.row_l2_norm(input)


def scale_shift(input, param_attr=None, bias_attr=None, **kw):
    return L.scale_shift(input, param_attr=param_attr, bias_attr=bias_attr)


def linear_comb(weights, vectors, size=None, **kw):
    return L.linear_comb(weights, vectors)


def dot_prod(a, b, **kw):
    return L.dot_prod(a, b)


def out_prod(a, b, **kw):
    return L.out_prod(a, b)


def l2_distance(a, b, **kw):
    return L.l2_distance(a, b)


def repeat(input, num_repeats, as_row_vector=True, **kw):
    return L.repeat(input, num_repeats, as_row_vector=as_row_vector)


def resize(input, size, **kw):
    return L.resize(input, size)


def rotate(input, height, width=None, **kw):
    return L.rotate(input, height, width or height)


def multiplex(input, index, **kw):
    return L.multiplex(list(input), index)


def kmax_seq_score(input, beam_size=1, **kw):
    return L.kmax_seq_score(input, beam_size=beam_size)


def seq_reshape(input, reshape_size, **kw):
    return L.sequence_reshape(input, reshape_size)


def seq_concat(a, b, **kw):
    return L.sequence_concat([a, b])


def sampling_id(input, **kw):
    return L.sampling_id(input)


def factorization_machine(input, factor_size, param_attr=None, **kw):
    return L.factorization_machine(input, factor_size,
                                   param_attr=param_attr)


def gated_unit(input, size, act=None, **kw):
    return L.gated_unit(input, size, act=_act.resolve(act) or "tanh")


def maxout(input, groups, num_channels=None, **kw):
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("maxout")
    return helper.simple_op("maxout", {"X": [input]}, {"groups": groups})


def prelu(input, param_attr=None, **kw):
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("prelu")
    alpha = helper.create_parameter(
        param_attr, shape=[1], dtype=input.dtype,
        default_initializer=None) if param_attr is not None else None
    if alpha is None:
        from ..initializer import ConstantInitializer
        from ..param_attr import ParamAttr as _PA

        alpha = helper.create_parameter(
            _PA(initializer=ConstantInitializer(0.25)), shape=[1],
            dtype=input.dtype)
    return helper.simple_op("prelu", {"X": [input], "Alpha": [alpha]}, {})


def pad(input, paddings, pad_value=0.0, **kw):
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("pad")
    return helper.simple_op("pad", {"X": [input]},
                            {"paddings": list(paddings),
                             "pad_value": float(pad_value)})


def block_expand(input, block_x=1, block_y=1, stride_x=1, stride_y=1,
                 padding_x=0, padding_y=0, num_channels=None, **kw):
    """block_expand_layer (BlockExpandLayer.cpp -> im2sequence_op)."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("block_expand")
    return helper.simple_op(
        "im2sequence", {"X": [input]},
        {"kernels": [block_y, block_x], "strides": [stride_y, stride_x],
         "paddings": [padding_y, padding_x, padding_y, padding_x]})


def conv_shift(a, b, **kw):
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("conv_shift")
    return helper.simple_op("conv_shift", {"X": [a], "Y": [b]}, {})


def sum_cost(input, **kw):
    """sum_cost (SumCostLayer.cpp): plain sum of the input."""
    return L.reduce_sum(input)


def huber_classification_cost(input, label, delta=1.0, **kw):
    """HuberTwoClassification (CostLayer.cpp): labels {0,1} -> y in
    {-1,+1}; loss = max(0, 1-z)^2 where z = y*f for z >= -1, else -4z."""
    y = L.scale(L.cast(label, "float32"), 2.0, bias=-1.0)
    z = L.elementwise_mul(y, input)
    sq = L.square(L.relu(L.scale(z, -1.0, bias=1.0)))
    lin = L.scale(z, -4.0)
    ge = L.cast(L.greater_equal(
        z, L.fill_constant(shape=[1], value=-1.0, dtype="float32")),
        "float32")
    cost = L.elementwise_add(
        L.elementwise_mul(ge, sq),
        L.elementwise_mul(L.scale(ge, -1.0, bias=1.0), lin))
    return L.mean(cost)


def multi_binary_label_cross_entropy(input, label, **kw):
    """multi_binary_label_cross_entropy_layer: per-class sigmoid CE."""
    return L.mean(L.sigmoid_cross_entropy_with_logits(input, label))


def smooth_l1_cost(input, label, **kw):
    """smooth_l1_cost (SmoothL1CostLayer.cpp)."""
    d = L.elementwise_sub(input, label)
    a = L.abs(d)
    lt = L.cast(L.less_than(
        a, L.fill_constant(shape=[1], value=1.0, dtype="float32")),
        "float32")
    quad = L.scale(L.square(d), 0.5)
    lin = L.scale(a, 1.0, bias=-0.5)
    return L.mean(L.elementwise_add(
        L.elementwise_mul(lt, quad),
        L.elementwise_mul(L.scale(lt, -1.0, bias=1.0), lin)))


def nce(input, label, num_classes, num_neg_samples=10, param_attr=None,
        bias_attr=None, **kw):
    """nce_layer (NCELayer.cpp): noise-contrastive estimation cost."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("nce")
    d = int(input.shape[-1])
    w = helper.create_parameter(param_attr, shape=[num_classes, d],
                                dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[num_classes],
                                dtype=input.dtype, is_bias=True)
    return helper.simple_op(
        "nce", {"Input": [input], "Label": [label], "Weight": [w],
                "Bias": [b]},
        {"num_total_classes": int(num_classes),
         "num_neg_samples": int(num_neg_samples)}, out_slot="Cost")


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             **kw):
    return L.hsigmoid(input, label, num_classes, param_attr=param_attr,
                      bias_attr=bias_attr)


def eos(input, eos_id, **kw):
    """eos_layer: 1 where the id equals eos_id (EosIdCheckLayer.cpp)."""
    return L.cast(L.equal(
        input, L.fill_constant(shape=[1], value=int(eos_id),
                               dtype=input.dtype)), "float32")


# ---------------------------------------------------------------------------
# final layer-name tail (VERDICT r4 Missing #3): 3-D conv/pool wrappers,
# cmrnorm, sub_seq, switch_order, scale_sub_region, selective_fc,
# lambda_cost, cross_entropy_with_selfnorm, conv projections/operators
# ---------------------------------------------------------------------------

def img_cmrnorm(input, size=5, scale=0.0128, power=0.75,
                data_format="NHWC", **kw):
    """img_cmrnorm_layer — cross-map response normalization, a thin
    wrapper over the lrn op (reference CMRProjectionNormLayer; attrs map
    scale -> alpha*n, power -> beta per the v1 config_parser rule)."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("img_cmrnorm")
    return helper.simple_op(
        "lrn", {"X": [input]},
        {"n": int(size), "alpha": float(scale) / int(size), "k": 1.0,
         "beta": float(power), "data_format": data_format})


def img_conv3d(input, filter_size, num_filters, num_channels=None,
               stride=1, padding=0, groups=1, act=None, param_attr=None,
               bias_attr=None, **kw):
    """img_conv3d_layer over the conv3d op (NCDHW, reference
    trainer_config_helpers img_conv3d_layer)."""
    from ..initializer import NormalInitializer
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("img_conv3d")
    ksz = ([filter_size] * 3 if isinstance(filter_size, int)
           else list(filter_size))
    cin = int(input.shape[1]) if num_channels is None else num_channels
    fan_in = (cin // groups) * ksz[0] * ksz[1] * ksz[2]
    w = helper.create_parameter(
        param_attr, shape=[num_filters, cin // groups] + ksz,
        dtype=input.dtype,
        default_initializer=NormalInitializer(0.0, (2.0 / fan_in) ** 0.5))
    o = helper.simple_op(
        "conv3d", {"Input": [input], "Filter": [w]},
        {"strides": stride, "paddings": padding, "groups": groups},
        out_slot="Output")
    o = helper.append_bias_op(o, bias_attr, num_filters, dim_start=1)
    return helper.append_activation(o, _act.resolve(act))


def img_pool3d(input, pool_size, stride=1, padding=0, pool_type=None,
               **kw):
    """img_pool3d_layer over the pool3d op (NCDHW)."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("img_pool3d")
    return helper.simple_op(
        "pool3d", {"X": [input]},
        {"pooling_type": _pool.resolve(pool_type) or "max",
         "ksize": pool_size, "strides": stride, "paddings": padding})


def sub_seq(input, offsets, sizes, **kw):
    """sub_seq_layer (SubSequenceLayer.cpp): per-row [offset, offset+size)
    time slice; the result carries the new lengths."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("sub_seq")
    outs, _ = helper.append_op(
        "sub_seq", {"X": [input], "Offsets": [offsets], "Sizes": [sizes]},
        ["Out", "OutLength"], {})
    o = outs["Out"][0]
    o.seq_len = outs["OutLength"][0]
    return o


def switch_order(input, reshape_axis=None, act=None, **kw):
    """switch_order_layer: NCHW -> NHWC (+ optional 2-D reshape split at
    ``reshape_axis``)."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("switch_order")
    o = helper.simple_op("switch_order", {"X": [input]},
                         {"reshape_axis": int(reshape_axis or 0)})
    return helper.append_activation(o, _act.resolve(act))


def scale_sub_region(input, indices, value=1.0, **kw):
    """scale_sub_region_layer: scale the per-sample sub-region named by
    ``indices`` [b, 6] (1-based inclusive) by ``value``."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("scale_sub_region")
    return helper.simple_op(
        "scale_sub_region", {"X": [input], "Indices": [indices]},
        {"value": float(value)})


def selective_fc(input, select, size, act=None, param_attr=None,
                 bias_attr=None, pass_generation=False, **kw):
    """selective_fc_layer (SelectiveFullyConnectedLayer.cpp): a full fc
    whose output is masked to the selected columns (``select`` is a
    0/1 [b, size] selection plane; zeros elsewhere). The reference's
    sparse-compute fast path is a serving optimization — on TPU the
    dense matmul + mask IS the fast path (MXU-shaped, no gather)."""
    out_full = fc(input, size, act=act, param_attr=param_attr,
                  bias_attr=bias_attr)
    return L.elementwise_mul(out_full, select)


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, **kw):
    """lambda_cost (LambdaRank): ``input`` is the MODEL SCORE sequence
    (the network output, LambdaCost's first input in the reference
    CostLayer.cpp), ``score`` the ground-truth relevance sequence —
    the reference's counter-intuitive but load-bearing argument order,
    which v1 configs depend on."""
    from ..layers.layer_helper import LayerHelper
    from ..layers.sequence import _len_input

    helper = LayerHelper("lambda_cost")
    return helper.simple_op(
        "lambda_cost",
        {"Score": [input], "Label": [score], **_len_input(input)},
        {"NDCG_num": int(NDCG_num),
         "max_sort_size": int(max_sort_size)})


def cross_entropy_with_selfnorm(input, label, softmax_selfnorm_alpha=0.1,
                                **kw):
    """cross_entropy_with_selfnorm (CostLayer.cpp:113): CE over softmax
    probs + log(Z) + alpha*log(Z)^2 self-normalization penalty."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("ce_selfnorm")
    return helper.simple_op(
        "cross_entropy_with_selfnorm", {"X": [input], "Label": [label]},
        {"softmax_selfnorm_alpha": float(softmax_selfnorm_alpha)})


class conv_projection(BaseProjection):
    """conv_projection (ConvProjection.cpp): a conv2d as a mixed_layer
    projection; NHWC input, same-geometry knobs as img_conv."""

    def __init__(self, input, filter_size, num_filters, stride=1,
                 padding=0, groups=1, param_attr=None, **kw):
        super().__init__(input, param_attr)
        self.filter_size = filter_size
        self.num_filters = num_filters
        self.stride = stride
        self.padding = padding
        self.groups = groups

    def build(self, size):
        return img_conv(self.input, self.filter_size, self.num_filters,
                        stride=self.stride, padding=self.padding,
                        groups=self.groups, act=None,
                        param_attr=self.param_attr, bias_attr=False)


def conv_operator(*a, **kw):
    """Reference conv_operator convolves with a LAYER's output as the
    filter (dynamic filters, ConvOperator.cpp) — unsupported; use
    conv_projection for learned-filter convolution projections."""
    raise NotImplementedError(
        "conv_operator (dynamic data-dependent conv filters) is not "
        "supported; use conv_projection")


# ---------------------------------------------------------------------------
# the step-level recurrent DSL, re-exported (reference v2/layer.py carries
# recurrent_group/memory/StaticInput from trainer_config_helpers into the
# v2 namespace). The machinery lives in v1/helpers.py and needs no parse
# context — it builds directly on StaticRNN.
# ---------------------------------------------------------------------------

_DSL_REEXPORTS = ("recurrent_group", "memory", "StaticInput",
                  "GeneratedInput", "gru_step_layer", "lstm_step_layer")


def __getattr__(name):
    if name in _DSL_REEXPORTS:
        from ..v1 import helpers as _h

        return getattr(_h, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_DSL_REEXPORTS))

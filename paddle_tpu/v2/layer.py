"""v2 layer namespace (reference python/paddle/v2/layer.py re-exporting
trainer_config_helpers/layers.py): keyword-style builders (input=, size=,
act=activation.Relu()) over the fluid-style layers package. Each function
documents the v1 DSL name it serves."""
from __future__ import annotations

from .. import layers as L
from . import activation as _act
from . import pooling as _pool
from .data_type import InputType


def data(name, type: InputType, **kw):
    """data_layer. ``type`` is a data_type.* declaration; sequence types
    become padded+length feeds (lod_level=1). For integer types the dim is
    the VALUE RANGE (vocab/class count) — the tensor itself is one id per
    (sequence) position, exactly the reference's InputType contract. The
    declaration rides on the returned handle (``.input_type``) so downstream
    builders (embedding, fc-over-sparse) can read the vocab/width from the
    graph, as the reference's config_parser propagates LayerConfig input
    sizes (/root/reference/python/paddle/trainer/config_parser.py)."""
    if type.sparse:
        # padded active-id list + length mask (+ values for sparse_float)
        var = L.data(name, shape=[1], dtype="int64", lod_level=1)
        if type.sparse == "float":
            val = L.data(f"{name}@val", shape=[-1], dtype="float32",
                         append_batch_size=False)
            val.is_companion = True
            var.sparse_values = val
        var.input_type = type
        return var
    width = 1 if type.dtype == "int64" else type.dim
    var = L.data(name, shape=[width], dtype=type.dtype,
                 lod_level=1 if type.seq_type else 0)
    var.input_type = type
    return var


def _sparse_fc_branch(inp, size, param_attr):
    """One fc branch over a sparse id-list input: sum of weight rows for the
    active ids (optionally value-weighted) == multi-hot row @ W, but fed
    O(nnz) and backed by SelectedRows sparse gradients."""
    t = inp.input_type
    emb = L.embedding(inp, size=[t.dim, size], param_attr=param_attr)
    emb.seq_len = inp.seq_len
    values = getattr(inp, "sparse_values", None)
    if values is not None:
        vals3 = L.reshape(values, shape=[0, -1, 1])
        emb = L.elementwise_mul(emb, vals3)
        emb.seq_len = inp.seq_len
    return L.sequence_pool(emb, "sum")


def _is_sparse(v):
    t = getattr(v, "input_type", None)
    return t is not None and t.sparse


def fc(input, size, act=None, param_attr=None, bias_attr=None, **kw):
    """fc_layer. ``input`` may be a list (each gets its own weight); sparse
    id-list inputs route through the embedding-sum path. The bias (one per
    fc, reference fc_layer contract) is carried by the dense sub-fc when
    one exists, else created here so sparse-only fcs keep their bias."""
    inputs = input if isinstance(input, (list, tuple)) else [input]
    sparse = [v for v in inputs if _is_sparse(v)]
    dense = [v for v in inputs if not _is_sparse(v)]
    if not sparse:
        return L.fc(input, size=size, act=_act.resolve(act),
                    param_attr=param_attr, bias_attr=bias_attr)
    from ..layers.layer_helper import LayerHelper

    branches = [_sparse_fc_branch(v, size, param_attr) for v in sparse]
    if dense:
        # the dense sub-fc owns the (single) bias
        branches.append(L.fc(dense, size=size, act=None,
                             param_attr=param_attr, bias_attr=bias_attr))
        return L.addto(branches, act=_act.resolve(act))
    summed = L.addto(branches, act=None)
    helper = LayerHelper("fc")
    if bias_attr is not False:
        summed = helper.append_bias_op(summed, bias_attr, size, dim_start=1)
    return helper.append_activation(summed, _act.resolve(act))


def embedding(input, size, param_attr=None, **kw):
    """embedding_layer: size is the embedding dim; the vocab comes from the
    upstream data layer's InputType.dim (v1 DSL contract), overridable with
    an explicit ``vocab_size`` kwarg."""
    vocab = kw.get("vocab_size")
    if vocab is None:
        t = getattr(input, "input_type", None)
        if t is not None:
            vocab = t.dim
    if vocab is None:
        raise ValueError(
            "embedding(): the input does not carry an InputType to read the "
            "vocab from (it is not a data layer); pass vocab_size=...")
    return L.embedding(input, size=[vocab, size], param_attr=param_attr)


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, groups=1, act=None, param_attr=None, bias_attr=None,
             data_format="NHWC", **kw):
    """img_conv_layer."""
    return L.conv2d(input, num_filters=num_filters, filter_size=filter_size,
                    stride=stride, padding=padding, groups=groups,
                    act=_act.resolve(act), param_attr=param_attr,
                    bias_attr=bias_attr, data_format=data_format)


def img_pool(input, pool_size, stride=1, padding=0, pool_type=None,
             ceil_mode=True, data_format="NHWC", **kw):
    """img_pool_layer. ``ceil_mode`` defaults True — the v1 DSL's output
    size rule (reference trainer_config_helpers/layers.py img_pool_layer
    ceil_mode=True)."""
    return L.pool2d(input, pool_size=pool_size, pool_stride=stride,
                    pool_padding=padding,
                    pool_type=_pool.resolve(pool_type),
                    ceil_mode=ceil_mode, data_format=data_format)


def batch_norm(input, act=None, **kw):
    """batch_norm_layer."""
    return L.batch_norm(input, act=_act.resolve(act),
                        data_layout=kw.get("data_format", "NHWC"),
                        is_test=kw.get("is_test", False))


def dropout(input, dropout_rate=0.5, **kw):
    """dropout_layer."""
    return L.dropout(input, dropout_prob=dropout_rate)


def concat(input, **kw):
    """concat_layer (feature axis)."""
    return L.concat(list(input), axis=-1)


def addto(input, act=None, bias_attr=None, **kw):
    """addto_layer."""
    return L.addto(list(input), act=_act.resolve(act))


def lstmemory(input, size=None, reverse=False, act=None, **kw):
    """lstmemory: input must be the 4x-projected sequence, as in the v1
    DSL (pair with fc(size=4*hidden, act=Linear()) or use
    networks.simple_lstm). ``size`` is the HIDDEN width (projected/4)."""
    proj = int(input.shape[-1])
    if size is not None and proj != 4 * size:
        raise ValueError(
            f"lstmemory(size={size}) expects a {4 * size}-wide projected "
            f"input, got {proj} (v1 DSL contract)")
    h, _ = L.dynamic_lstm(input, proj, is_reverse=reverse)
    return h


def grumemory(input, size=None, reverse=False, **kw):
    """grumemory: input is the 3x-projected sequence."""
    if size is None:
        size = int(input.shape[-1]) // 3
    return L.dynamic_gru(input, size, is_reverse=reverse)


def pooling(input, pooling_type=None, **kw):
    """pooling_layer over the sequence axis."""
    return L.sequence_pool(input, _pool.resolve(pooling_type))


def first_seq(input, **kw):
    return L.sequence_first_step(input)


def last_seq(input, **kw):
    return L.sequence_last_step(input)


def expand(input, expand_as, **kw):
    """expand_layer."""
    return L.sequence_expand(input, expand_as)


def max_id(input, **kw):
    """maxid_layer."""
    return L.argmax(input, axis=-1)


def crf(input, label, size=None, param_attr=None, **kw):
    """crf_layer: per-sequence negative log-likelihood [b, 1]; the
    transition parameter rides on ``.transition`` for crf_decoding."""
    return L.linear_chain_crf(input, label, param_attr=param_attr)


def crf_decoding(input, size=None, param_attr=None, label=None,
                 transition=None, **kw):
    """crf_decoding_layer. Pass ``transition=cost.transition`` from the
    crf() cost so Viterbi uses the TRAINED transitions."""
    return L.crf_decoding(input, param_attr=param_attr, label=label,
                          transition=transition)


def ctc(input, label, blank=0, **kw):
    """ctc_layer / warp_ctc_layer."""
    return L.warpctc(input, label, blank=blank)


# ---- cost layers (CostLayer.cpp family) --------------------------------
def classification_cost(input, label, **kw):
    """classification_cost: softmax cross-entropy over class scores."""
    return L.mean(L.softmax_with_cross_entropy(input, label))


def cross_entropy_cost(input, label, **kw):
    return L.mean(L.cross_entropy(input, label))


def square_error_cost(input, label, **kw):
    """regression_cost."""
    return L.mean(L.square_error_cost(input, label))


def rank_cost(left, right, label, **kw):
    """rank_cost (RankingCost): pairwise logistic loss."""
    diff = L.elementwise_sub(left, right)
    return L.mean(L.log(L.elementwise_add(
        L.exp(L.elementwise_mul(L.scale(label, -2.0, bias=1.0), diff)),
        L.fill_constant(shape=[1], value=1.0, dtype="float32"))))


def huber_regression_cost(input, label, delta=1.0, **kw):
    """huber_regression_cost (HuberRegressionLoss, CostLayer.cpp)."""
    from ..layers.layer_helper import LayerHelper

    h = LayerHelper("huber_cost")
    outs, _ = h.append_op("huber_loss", {"X": [input], "Y": [label]},
                          ["Out", "Residual"], {"delta": float(delta)})
    return L.mean(outs["Out"][0])

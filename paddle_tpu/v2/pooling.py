"""v2 sequence-pooling objects (reference v2/pooling.py →
trainer_config_helpers/poolings.py)."""


class BasePooling:
    name: str = "average"


class Max(BasePooling):
    name = "max"


class Avg(BasePooling):
    name = "average"


class Sum(BasePooling):
    name = "sum"


class SquareRootN(BasePooling):
    name = "sqrt"


def resolve(p):
    if p is None:
        return "average"
    if isinstance(p, str):
        return p
    if isinstance(p, type):
        p = p()
    return p.name

"""v2 attribute objects (reference v2/attr.py): Param/Extra attrs map onto
the fluid-style ParamAttr."""
from ..param_attr import ParamAttr as _ParamAttr


def Param(name=None, initial_std=None, initial_mean=None, l2_rate=None,
          learning_rate=1.0, is_static=False, **kw):
    from ..initializer import NormalInitializer
    from ..regularizer import L2Decay

    init = None
    if initial_std is not None or initial_mean is not None:
        init = NormalInitializer(initial_mean or 0.0, initial_std or 0.01)
    reg = L2Decay(l2_rate) if l2_rate else None
    return _ParamAttr(name=name, initializer=init, regularizer=reg,
                      learning_rate=learning_rate,
                      trainable=not is_static)


def Extra(drop_rate=None, **kw):
    """ExtraAttr subset: only drop_rate is load-bearing here."""
    return {"drop_rate": drop_rate}


ParamAttr = Param
ExtraAttr = Extra

"""v2 Parameters facade (reference python/paddle/v2/parameters.py:44
Parameters — numpy in/out access to model weights by name, created from a
topology). Here the topology is the cost Variable's program; create() runs
the startup program into a private scope and hands back name-keyed access,
plus the program/scope/executor plumbing the v2 trainer and infer() use."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.executor import Executor, TPUPlace
from ..core.program import Program, default_startup_program
from ..core.scope import Scope


class Parameters:
    def __init__(self, main_program: Program, startup_program: Program):
        self.main_program = main_program
        self.startup_program = startup_program
        self.scope = Scope()
        self.executor = Executor(TPUPlace())
        self._init_done = False
        # inference clone BEFORE optimizer ops are appended; for_test
        # flips is_test so dropout/batch_norm run in inference mode
        self._test_program = main_program.clone(for_test=True)

    # -- lifecycle ----------------------------------------------------
    def init(self):
        if not self._init_done:
            self.executor.run(self.startup_program, scope=self.scope)
            self._init_done = True
        return self

    # -- v2 surface ---------------------------------------------------
    def names(self) -> List[str]:
        return [p.name for p in self.main_program.global_block
                .all_parameters()]

    def keys(self):
        return self.names()

    def __contains__(self, name: str) -> bool:
        return name in self.names()

    def get(self, name: str) -> np.ndarray:
        self.init()
        return np.asarray(self.scope.get_numpy(name))

    __getitem__ = get

    def set(self, name: str, value: np.ndarray) -> None:
        self.init()
        self.scope.set(name, np.asarray(value))

    __setitem__ = set

    def to_tar(self, f) -> None:
        """Serialize all parameters (reference to_tar) — npz stream."""
        self.init()
        np.savez(f, **{n: self.get(n) for n in self.names()})

    @staticmethod
    def from_tar(f) -> Dict[str, np.ndarray]:
        data = np.load(f)
        return {k: data[k] for k in data.files}

    def load(self, mapping: Dict[str, np.ndarray]) -> None:
        for k, v in mapping.items():
            self.set(k, v)

    # -- plumbing for trainer/infer -----------------------------------
    def test_program_for(self, output_vars) -> Program:
        """Inference clone pruned to the output variable(s) (reference
        inference_optimize): drops the label branch so infer() only needs
        the actual input columns."""
        from ..io import prune_program

        if not isinstance(output_vars, (list, tuple)):
            output_vars = [output_vars]
        feeds = [v.name for v in self.data_vars()]
        return prune_program(self._test_program, feeds,
                             [v.name for v in output_vars])

    def data_vars(self, feeding: Optional[Dict[str, int]] = None,
                  program: Optional[Program] = None):
        block = (program or self.main_program).global_block
        data_vars = [v for v in block.vars.values()
                     if v.is_data and not getattr(v, "is_companion", False)]
        if feeding:
            order = sorted(feeding, key=feeding.get)
            by_name = {v.name: v for v in data_vars}
            return [by_name[n] for n in order if n in by_name]
        return data_vars


def create(cost) -> Parameters:
    """paddle.parameters.create(cost): capture the cost's program pair."""
    return Parameters(cost.block.program, default_startup_program())

"""The v2 user API, served by the TPU engine.

The reference's paddle.v2 surface (/root/reference/python/paddle/v2:
layer.py, activation.py, pooling.py, attr.py, parameters.py, trainer.py,
event.py, reader/, dataset/, minibatch.py) drove the legacy gserver engine
through SWIG; here the SAME user-facing shapes build fluid-style programs
and run through the XLA executor — the architecture stance SURVEY.md §7
prescribes ("the v2 user API can be served by a Fluid-style engine").

Usage mirrors the reference's book examples::

    import paddle_tpu.v2 as paddle
    paddle.init(trainer_count=1)
    images = paddle.layer.data("pixel",
                               paddle.data_type.dense_vector(784))
    label = paddle.layer.data("label", paddle.data_type.integer_value(10))
    h = paddle.layer.fc(input=images, size=128,
                        act=paddle.activation.Relu())
    cost = paddle.layer.classification_cost(
        input=paddle.layer.fc(input=h, size=10,
                              act=paddle.activation.Softmax()),
        label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.01))
    trainer.train(paddle.batch(reader, 64), num_passes=2,
                  event_handler=handler)
"""
from __future__ import annotations

from .. import dataset, event  # noqa: F401  (reference re-exports)
from .. import evaluator, image, master, plot  # noqa: F401
from ..core.program import (default_main_program,  # noqa: F401
                            default_startup_program)
from ..reader import decorator as reader  # noqa: F401
from ..reader.minibatch import batch  # noqa: F401
from . import activation, attr, data_type, layer, networks, optimizer, \
    parameters, pooling, trainer  # noqa: F401

__all__ = ["init", "infer", "batch", "reader", "dataset", "event", "layer",
           "activation", "pooling", "attr", "data_type", "optimizer",
           "parameters", "trainer", "networks", "image",
           "evaluator", "master", "plot",
           "default_main_program", "default_startup_program"]


def init(use_gpu: bool = False, trainer_count: int = 1, seed: int = None,
         **kwargs) -> None:
    """paddle.init analogue: device/trainer knobs become flags. use_gpu is
    accepted-and-ignored (the device is the TPU/XLA backend)."""
    from ..flags import FLAGS

    if seed is not None:
        FLAGS.seed = int(seed)
    del use_gpu, trainer_count, kwargs  # topology comes from the mesh


def infer(output_layer, parameters, input, feeding=None):
    """paddle.infer analogue: run the inference clone of output_layer's
    program over ``input`` rows; returns the stacked outputs. Accepts a
    single layer or (like the reference's ``outputs([...])`` configs) a
    list, returning one array per requested layer."""
    import numpy as np

    from ..data_feeder import DataFeeder

    multi = isinstance(output_layer, (list, tuple))
    outputs = list(output_layer) if multi else [output_layer]
    parameters.init()
    prog = parameters.test_program_for(outputs)
    consumed = {n for op in prog.global_block.ops
                for names in op.inputs.values() for n in names}
    feed_vars = [v for v in parameters.data_vars(feeding, program=prog)
                 if v.name in consumed]
    feeder = DataFeeder(feed_vars)
    out = parameters.executor.run(
        prog, feed=feeder.feed(input), fetch_list=outputs,
        scope=parameters.scope)
    arrays = [np.asarray(o) for o in out]
    return arrays if multi else arrays[0]

"""v2 activation objects (reference python/paddle/v2/activation.py →
trainer_config_helpers/activations.py). Each carries the fluid-style act
name the layer builders understand."""


class BaseActivation:
    name: str = ""

    def __repr__(self):
        return f"activation.{type(self).__name__}()"


def _make(cls_name, act_name):
    t = type(cls_name, (BaseActivation,), {"name": act_name})
    return t


Linear = _make("Linear", "")
Relu = _make("Relu", "relu")
BRelu = _make("BRelu", "brelu")
SoftRelu = _make("SoftRelu", "softplus")
Tanh = _make("Tanh", "tanh")
STanh = _make("STanh", "stanh")
Sigmoid = _make("Sigmoid", "sigmoid")
Softmax = _make("Softmax", "softmax")
Exp = _make("Exp", "exp")
Log = _make("Log", "log")
Abs = _make("Abs", "abs")
Square = _make("Square", "square")
SequenceSoftmax = _make("SequenceSoftmax", "softmax")
Sqrt = _make("Sqrt", "sqrt")
Reciprocal = _make("Reciprocal", "reciprocal")
SoftSign = _make("SoftSign", "softsign")


def resolve(act):
    """None | BaseActivation | str -> fluid act name (or None)."""
    if act is None:
        return None
    if isinstance(act, str):
        return act or None
    return act.name or None

"""Training events, parity with /root/reference/python/paddle/v2/event.py:13.

The v2 trainer drives an event_handler callback with these marker objects so
user scripts can log, test, checkpoint, or plot mid-training without touching
the train loop.
"""


class WithMetric:
    def __init__(self, metrics):
        # metrics: dict name -> float (evaluator results for the span)
        self.metrics = dict(metrics or {})


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, metrics=None, interrupted=False):
        super().__init__(metrics)
        self.pass_id = pass_id
        # True when the pass was cut short by a graceful shutdown
        # (SIGTERM/SIGINT or a fault-plan preemption): metrics cover only
        # the completed iterations and a final checkpoint was written
        self.interrupted = interrupted


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, metrics=None,
                 batch_size=None, host_wall_s=None, device_wall_s=None,
                 mfu=None):
        super().__init__(metrics)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        # rows in the just-trained minibatch (None when the reader yields
        # something len() can't see through) — trace.RunLog derives
        # examples/sec from it
        self.batch_size = batch_size
        # goodput split of this step's wall (seconds): host-side
        # dispatch/feed vs time blocked on device results; and the
        # step's achieved model-FLOPs-utilization when the trainer's
        # GoodputMeter priced the program. All optional — events from
        # older/custom loops carry None.
        self.host_wall_s = host_wall_s
        self.device_wall_s = device_wall_s
        self.mfu = mfu


class TestResult(WithMetric):
    def __init__(self, cost, metrics=None):
        super().__init__(metrics)
        self.cost = cost

"""MQ2007 learning-to-rank (reference v2/dataset/mq2007.py API).

``train_reader(format=...)``/``test_reader`` with formats "pointwise"
(feature, relevance), "pairwise" ((f_hi, f_lo) preference pairs) and
"listwise" (query group lists) — mq2007.py Query/QueryList. Synthetic
fallback: relevance is a noisy linear function of the 46-dim feature vector.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train_reader", "test_reader", "FEATURE_DIM"]

FEATURE_DIM = 46
N_QUERIES_TRAIN = 256
N_QUERIES_TEST = 32
DOCS_PER_QUERY = 8


def _true_weights():
    rng = common.synthetic_rng("mq2007-w")
    return rng.normal(0, 1, FEATURE_DIM)


def _queries(n_queries, seed_name):
    w = _true_weights()

    def gen():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n_queries):
            feats = rng.normal(0, 1, (DOCS_PER_QUERY, FEATURE_DIM)) \
                .astype(np.float32)
            scores = feats @ w + rng.normal(0, 0.5, DOCS_PER_QUERY)
            rel = np.digitize(scores, np.quantile(scores, [0.5, 0.8]))
            yield feats, rel.astype(np.int64)

    return gen


def _reader(n_queries, seed_name, format):
    queries = _queries(n_queries, seed_name)

    def pointwise():
        for feats, rel in queries():
            for f, r in zip(feats, rel):
                yield f, int(r)

    def pairwise():
        for feats, rel in queries():
            for i in range(len(rel)):
                for j in range(len(rel)):
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for feats, rel in queries():
            yield feats, rel

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train_reader(format="pointwise"):
    return _reader(N_QUERIES_TRAIN, "mq2007-train", format)


def test_reader(format="pointwise"):
    return _reader(N_QUERIES_TEST, "mq2007-test", format)

"""MQ2007 learning-to-rank (reference v2/dataset/mq2007.py API).

``train_reader(format=...)``/``test_reader`` with formats "pointwise"
(feature, relevance), "pairwise" ((f_hi, f_lo) preference pairs) and
"listwise" (query group lists) — mq2007.py Query/QueryList. When the
real LETOR files are present in the cache dir (``train.txt`` /
``test.txt``, lines "rel qid:n 1:v 2:v ... #docid = ..." —
mq2007.py:96) they are parsed and grouped by qid; otherwise a
synthetic fallback whose relevance is a noisy linear function of the
46-dim feature vector.
"""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train_reader", "test_reader", "FEATURE_DIM"]

FEATURE_DIM = 46
N_QUERIES_TRAIN = 256
N_QUERIES_TEST = 32
DOCS_PER_QUERY = 8


def _true_weights():
    rng = common.synthetic_rng("mq2007-w")
    return rng.normal(0, 1, FEATURE_DIM)


def _queries(n_queries, seed_name):
    w = _true_weights()

    def gen():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n_queries):
            feats = rng.normal(0, 1, (DOCS_PER_QUERY, FEATURE_DIM)) \
                .astype(np.float32)
            scores = feats @ w + rng.normal(0, 0.5, DOCS_PER_QUERY)
            rel = np.digitize(scores, np.quantile(scores, [0.5, 0.8]))
            yield feats, rel.astype(np.int64)

    return gen


def _reader(n_queries, seed_name, format):
    queries = _queries(n_queries, seed_name)

    def pointwise():
        for feats, rel in queries():
            for f, r in zip(feats, rel):
                yield f, int(r)

    def pairwise():
        for feats, rel in queries():
            for i in range(len(rel)):
                for j in range(len(rel)):
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for feats, rel in queries():
            yield feats, rel

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def _real_path(split):
    p = os.path.join(common.DATA_HOME, "MQ2007", f"{split}.txt")
    return p if os.path.exists(p) else None


def _parse_letor(path):
    """LETOR line format (reference mq2007.py Query.__init__ /
    _parse_one_line): "rel qid:n 1:v ... 46:v #docid = ..." grouped by
    qid in file order."""
    groups = {}
    order = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(parts[0])
            qid = parts[1].split(":")[1]
            feats = np.zeros(FEATURE_DIM, np.float32)
            for pair in parts[2:]:
                k, _, v = pair.partition(":")
                idx = int(k) - 1
                if 0 <= idx < FEATURE_DIM:
                    feats[idx] = float(v)
            if qid not in groups:
                groups[qid] = []
                order.append(qid)
            groups[qid].append((feats, rel))
    for qid in order:
        rows = groups[qid]
        yield (np.stack([f for f, _ in rows]),
               np.array([r for _, r in rows], np.int64))


def _real_queries(split):
    def gen():
        yield from _parse_letor(_real_path(split))

    return gen


def _real_format_reader(split, format):
    queries = _real_queries(split)

    def pointwise():
        for feats, rel in queries():
            for f, r in zip(feats, rel):
                yield f, int(r)

    def pairwise():
        for feats, rel in queries():
            for i in range(len(rel)):
                for j in range(len(rel)):
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for feats, rel in queries():
            yield feats, rel

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train_reader(format="pointwise"):
    if _real_path("train"):
        return _real_format_reader("train", format)
    return _reader(N_QUERIES_TRAIN, "mq2007-train", format)


def test_reader(format="pointwise"):
    if _real_path("test"):
        return _real_format_reader("test", format)
    return _reader(N_QUERIES_TEST, "mq2007-test", format)

"""Movie-review sentiment polarity (reference v2/dataset/sentiment.py API —
the NLTK movie_reviews corpus). ``get_word_dict()`` then ``train()``/
``test()`` yield ``(ids, 0|1)``. When the corpus is present on disk
(``movie_reviews/pos|neg/*.txt`` under the cache dir — the layout
nltk.download unpacks) it is parsed with the reference's rules
(frequency-sorted dict over the whole corpus, neg=0/pos=1,
neg/pos-interleaved file order, first 1600 rows train —
sentiment.py:53-128) WITHOUT needing nltk; otherwise the synthetic
fallback shares the IMDB topic construction with a distinct seed/vocab.
"""
from __future__ import annotations

import collections
import glob
import os
import re

import numpy as np

from . import common

__all__ = ["get_word_dict", "train", "test"]

VOCAB_SIZE = 1024
TRAIN_SIZE = 1024
TEST_SIZE = 128
NUM_TRAINING_INSTANCES = 1600  # the reference's train/test split point

_TOKEN = re.compile(r"[A-Za-z0-9']+|[^\sA-Za-z0-9]")


def _real_dir():
    for cand in (os.path.join(common.DATA_HOME, "movie_reviews"),
                 os.path.join(common.DATA_HOME, "corpora",
                              "movie_reviews")):
        if os.path.isdir(os.path.join(cand, "pos")) \
                and os.path.isdir(os.path.join(cand, "neg")):
            return cand
    return None


def _files(category):
    return sorted(glob.glob(os.path.join(_real_dir(), category, "*.txt")))


def _words(path):
    with open(path, errors="ignore") as f:
        return [w.lower() for w in _TOKEN.findall(f.read())]


_CACHE = {}  # parsed word dict + rows, keyed by the corpus dir


def _real_word_dict():
    d = _real_dir()
    if ("dict", d) in _CACHE:
        return _CACHE[("dict", d)]
    freq = collections.defaultdict(int)
    for cat in ("neg", "pos"):
        for path in _files(cat):
            for w in _words(path):
                freq[w] += 1
    ordered = sorted(freq.items(), key=lambda kv: -kv[1])
    wd = {w: i for i, (w, _) in enumerate(ordered)}
    _CACHE[("dict", d)] = wd
    return wd


def _real_rows():
    d = _real_dir()
    if ("rows", d) in _CACHE:
        return _CACHE[("rows", d)]
    wd = _real_word_dict()
    neg, pos = _files("neg"), _files("pos")
    rows = []
    # neg/pos interleaved, neg=0 / pos=1 (reference sort_files +
    # load_sentiment_data)
    for n, p in zip(neg, pos):
        rows.append(([wd[w] for w in _words(n)], 0))
        rows.append(([wd[w] for w in _words(p)], 1))
    _CACHE[("rows", d)] = rows
    return rows


def get_word_dict():
    if _real_dir():
        return _real_word_dict()
    return {f"s{i}": i for i in range(VOCAB_SIZE)}


def _reader(n, seed_name):
    def reader():
        rng = common.synthetic_rng(seed_name)
        pos = np.arange(0, VOCAB_SIZE // 4)
        neg = np.arange(VOCAB_SIZE // 4, VOCAB_SIZE // 2)
        neutral = np.arange(VOCAB_SIZE // 2, VOCAB_SIZE)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(10, 50))
            topic = pos if label else neg
            k = max(1, length // 3)
            ids = np.concatenate([rng.choice(topic, size=k),
                                  rng.choice(neutral, size=length - k)])
            rng.shuffle(ids)
            yield ids.astype(np.int64).tolist(), label

    return reader


def train():
    if _real_dir():
        def reader():
            yield from _real_rows()[:NUM_TRAINING_INSTANCES]

        return reader
    return _reader(TRAIN_SIZE, "sentiment-train")


def test():
    if _real_dir():
        def reader():
            yield from _real_rows()[NUM_TRAINING_INSTANCES:]

        return reader
    return _reader(TEST_SIZE, "sentiment-test")

"""Movie-review sentiment polarity (reference v2/dataset/sentiment.py API —
the NLTK movie_reviews corpus). ``get_word_dict()`` then ``train()``/
``test()`` yield ``(ids, 0|1)``. Synthetic fallback shares the IMDB topic
construction with a distinct seed/vocab."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["get_word_dict", "train", "test"]

VOCAB_SIZE = 1024
TRAIN_SIZE = 1024
TEST_SIZE = 128


def get_word_dict():
    return {f"s{i}": i for i in range(VOCAB_SIZE)}


def _reader(n, seed_name):
    def reader():
        rng = common.synthetic_rng(seed_name)
        pos = np.arange(0, VOCAB_SIZE // 4)
        neg = np.arange(VOCAB_SIZE // 4, VOCAB_SIZE // 2)
        neutral = np.arange(VOCAB_SIZE // 2, VOCAB_SIZE)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(10, 50))
            topic = pos if label else neg
            k = max(1, length // 3)
            ids = np.concatenate([rng.choice(topic, size=k),
                                  rng.choice(neutral, size=length - k)])
            rng.shuffle(ids)
            yield ids.astype(np.int64).tolist(), label

    return reader


def train():
    return _reader(TRAIN_SIZE, "sentiment-train")


def test():
    return _reader(TEST_SIZE, "sentiment-test")

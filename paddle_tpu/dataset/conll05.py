"""CoNLL-2005 semantic role labeling (reference v2/dataset/conll05.py API).

Samples are the reference's 9-feature SRL tuple ``(word_ids, ctx_n2,
ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark, label_ids)``
(conll05.py:176 reader_creator yield order) consumed by the
label_semantic_roles book test. When the real corpus is present in the
cache dir (``conll05st-tests.tar.gz`` + the wordDict/verbDict/targetDict
text files), the bracket-tag props format is parsed with the reference's
own state machine (conll05.py:52-123); otherwise a synthetic fallback
whose tags follow a deterministic word-and-distance-to-predicate rule in
IOB space so the CRF tagger has learnable structure.
"""
from __future__ import annotations

import gzip
import os
import tarfile

import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test", "load_dict"]

WORD_VOCAB = 512
PRED_VOCAB = 64
N_LABELS = 9  # 4 chunk types x B/I + O  (IOB encoding, tag 8 = O)
TEST_SIZE = 512

_DIR = "conll05st"
_TAR = "conll05st-tests.tar.gz"
_WORDS_NAME = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS_NAME = "conll05st-release/test.wsj/props/test.wsj.props.gz"
UNK_IDX = 0


def _real_paths():
    d = os.path.join(common.DATA_HOME, _DIR)
    paths = {k: os.path.join(d, name) for k, name in
             (("tar", _TAR), ("word", "wordDict.txt"),
              ("verb", "verbDict.txt"), ("label", "targetDict.txt"))}
    if all(os.path.exists(p) for p in paths.values()):
        return paths
    return None


def load_dict(filename):
    """Line-per-entry dict file -> {entry: line_no} (reference
    conll05.py:44 load_dict)."""
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def _corpus_reader(data_path, words_name, props_name):
    """(sentence words, verb, bracket-decoded IOB label seq) triples —
    the reference's props state machine (conll05.py:52-123)."""

    def reader():
        with tarfile.open(data_path) as tf:
            wf = tf.extractfile(words_name)
            pf = tf.extractfile(props_name)
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentences, labels, one_seg = [], [], []
                for word, label in zip(words_file, props_file):
                    word = word.decode("utf-8").strip()
                    label = label.decode("utf-8").strip().split()
                    if not label:  # end of sentence
                        for i in range(len(one_seg[0]) if one_seg else 0):
                            labels.append([x[i] for x in one_seg])
                        if len(labels) >= 1:
                            verb_list = [x for x in labels[0] if x != "-"]
                            for i, lbl in enumerate(labels[1:]):
                                cur_tag, in_bracket = "O", False
                                lbl_seq = []
                                for l in lbl:
                                    if l == "*" and not in_bracket:
                                        lbl_seq.append("O")
                                    elif l == "*" and in_bracket:
                                        lbl_seq.append("I-" + cur_tag)
                                    elif l == "*)":
                                        lbl_seq.append("I-" + cur_tag)
                                        in_bracket = False
                                    elif "(" in l and ")" in l:
                                        cur_tag = l[1:l.find("*")]
                                        lbl_seq.append("B-" + cur_tag)
                                        in_bracket = False
                                    elif "(" in l:
                                        cur_tag = l[1:l.find("*")]
                                        lbl_seq.append("B-" + cur_tag)
                                        in_bracket = True
                                    else:
                                        raise RuntimeError(
                                            f"Unexpected label: {l}")
                                yield sentences, verb_list[i], lbl_seq
                        sentences, labels, one_seg = [], [], []
                    else:
                        sentences.append(word)
                        one_seg.append(label)

    return reader


def _real_reader(paths):
    word_dict = load_dict(paths["word"])
    predicate_dict = load_dict(paths["verb"])
    label_dict = load_dict(paths["label"])
    corpus = _corpus_reader(paths["tar"], _WORDS_NAME, _PROPS_NAME)

    def reader():
        for sentence, predicate, labels in corpus():
            sen_len = len(sentence)
            verb_index = labels.index("B-V")
            mark = [0] * len(labels)
            if verb_index > 0:
                mark[verb_index - 1] = 1
                ctx_n1 = sentence[verb_index - 1]
            else:
                ctx_n1 = "bos"
            if verb_index > 1:
                mark[verb_index - 2] = 1
                ctx_n2 = sentence[verb_index - 2]
            else:
                ctx_n2 = "bos"
            mark[verb_index] = 1
            ctx_0 = sentence[verb_index]
            if verb_index < len(labels) - 1:
                mark[verb_index + 1] = 1
                ctx_p1 = sentence[verb_index + 1]
            else:
                ctx_p1 = "eos"
            if verb_index < len(labels) - 2:
                mark[verb_index + 2] = 1
                ctx_p2 = sentence[verb_index + 2]
            else:
                ctx_p2 = "eos"
            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            yield (word_idx,
                   [word_dict.get(ctx_n2, UNK_IDX)] * sen_len,
                   [word_dict.get(ctx_n1, UNK_IDX)] * sen_len,
                   [word_dict.get(ctx_0, UNK_IDX)] * sen_len,
                   [word_dict.get(ctx_p1, UNK_IDX)] * sen_len,
                   [word_dict.get(ctx_p2, UNK_IDX)] * sen_len,
                   [predicate_dict.get(predicate)] * sen_len,
                   mark,
                   [label_dict.get(w) for w in labels])

    return reader


def get_dict():
    paths = _real_paths()
    if paths:
        return (load_dict(paths["word"]), load_dict(paths["verb"]),
                load_dict(paths["label"]))
    word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(PRED_VOCAB)}
    label_dict = {}
    for c in range(4):
        label_dict[f"B-A{c}"] = 2 * c
        label_dict[f"I-A{c}"] = 2 * c + 1
    label_dict["O"] = 8
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Pretrained-style word embedding table [vocab, 32]: parsed from a
    whitespace-float ``emb`` file next to the real corpus when present,
    else deterministic synthetic SIZED TO THE ACTIVE DICT (so ids from
    get_dict() always index into it — with the real word dict loaded the
    table is [len(word_dict), 32], not the synthetic vocab)."""
    paths = _real_paths()
    emb_path = (os.path.join(common.DATA_HOME, _DIR, "emb")
                if paths else None)
    vocab = len(load_dict(paths["word"])) if paths else WORD_VOCAB
    if emb_path and os.path.exists(emb_path):
        try:
            table = np.loadtxt(emb_path, dtype=np.float32)
            if table.ndim == 2 and table.shape[0] >= vocab:
                return table[:vocab]
        except (ValueError, UnicodeDecodeError):
            pass  # the reference's emb is a binary Paddle parameter
            # file; fall through to a dict-sized synthetic table
    rng = common.synthetic_rng("conll05-emb")
    return rng.normal(0, 0.1, (vocab, 32)).astype(np.float32)


def _reader(n, seed_name):
    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            length = int(rng.randint(5, 18))
            words = rng.randint(0, WORD_VOCAB, size=length)
            pred_pos = int(rng.randint(0, length))
            pred = int(words[pred_pos] % PRED_VOCAB)
            # rule: arguments are 1-2 token spans adjacent to the predicate
            labels = np.full(length, 8, np.int64)  # O
            if pred_pos > 0:
                labels[pred_pos - 1] = 0  # B-A0
                if pred_pos > 1 and words[pred_pos - 2] % 2 == 0:
                    labels[pred_pos - 2] = 0
                    labels[pred_pos - 1] = 1  # I-A0
            if pred_pos + 1 < length:
                labels[pred_pos + 1] = 2  # B-A1
                if pred_pos + 2 < length and words[pred_pos + 2] % 2 == 1:
                    labels[pred_pos + 2] = 3  # I-A1
            ctx = []
            for off in (-2, -1, 0, 1, 2):
                p = min(max(pred_pos + off, 0), length - 1)
                ctx.append(int(words[p]))
            mark = (np.arange(length) == pred_pos).astype(np.int64)
            w = words.astype(np.int64).tolist()
            yield (w, [ctx[0]] * length, [ctx[1]] * length,
                   [ctx[2]] * length, [ctx[3]] * length, [ctx[4]] * length,
                   [pred] * length, mark.tolist(), labels.tolist())

    return reader


def test():
    paths = _real_paths()
    if paths:
        return _real_reader(paths)
    return _reader(TEST_SIZE, "conll05-test")

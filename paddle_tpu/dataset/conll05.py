"""CoNLL-2005 semantic role labeling (reference v2/dataset/conll05.py API).

Samples are ``(word_ids, pred_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
mark, label_ids)`` — the 8-feature SRL tuple of the label_semantic_roles
book test (conll05.py reader_creator). Synthetic fallback: tags follow a
deterministic word-and-distance-to-predicate rule in IOB space so the CRF
tagger has learnable structure.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test"]

WORD_VOCAB = 512
PRED_VOCAB = 64
N_LABELS = 9  # 4 chunk types x B/I + O  (IOB encoding, tag 8 = O)
TEST_SIZE = 512


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(PRED_VOCAB)}
    label_dict = {}
    for c in range(4):
        label_dict[f"B-A{c}"] = 2 * c
        label_dict[f"I-A{c}"] = 2 * c + 1
    label_dict["O"] = 8
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Deterministic pretrained-style word embedding table [vocab, 32]."""
    rng = common.synthetic_rng("conll05-emb")
    return rng.normal(0, 0.1, (WORD_VOCAB, 32)).astype(np.float32)


def _reader(n, seed_name):
    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            length = int(rng.randint(5, 18))
            words = rng.randint(0, WORD_VOCAB, size=length)
            pred_pos = int(rng.randint(0, length))
            pred = int(words[pred_pos] % PRED_VOCAB)
            # rule: arguments are 1-2 token spans adjacent to the predicate
            labels = np.full(length, 8, np.int64)  # O
            if pred_pos > 0:
                labels[pred_pos - 1] = 0  # B-A0
                if pred_pos > 1 and words[pred_pos - 2] % 2 == 0:
                    labels[pred_pos - 2] = 0
                    labels[pred_pos - 1] = 1  # I-A0
            if pred_pos + 1 < length:
                labels[pred_pos + 1] = 2  # B-A1
                if pred_pos + 2 < length and words[pred_pos + 2] % 2 == 1:
                    labels[pred_pos + 2] = 3  # I-A1
            ctx = []
            for off in (-2, -1, 0, 1, 2):
                p = min(max(pred_pos + off, 0), length - 1)
                ctx.append(int(words[p]))
            mark = (np.arange(length) == pred_pos).astype(np.int64)
            w = words.astype(np.int64).tolist()
            yield (w, [pred] * length, [ctx[0]] * length, [ctx[1]] * length,
                   [ctx[2]] * length, [ctx[3]] * length, [ctx[4]] * length,
                   mark.tolist(), labels.tolist())

    return reader


def test():
    return _reader(TEST_SIZE, "conll05-test")

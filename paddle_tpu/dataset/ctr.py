"""Synthetic CTR click-stream for the online-learning plane.

The Wide&Deep flagship's data side (BASELINE.json configs[5]): an
endless stream of (sparse slot ids, dense features, click label)
impressions with the statistics real CTR traffic has — Zipf-ish id
popularity (most lookups hit a small hot set while the vocabulary stays
huge, which is exactly what makes the row-sparse update path matter)
and a click probability driven by a few "magic" id buckets plus one
dense feature, so AUC is learnable and improves measurably within a
short run.

Deterministic by (shard, pass): ``task_descs(n)`` names the shards a
master task queue serves (``ctr:<shard>:<n_records>``), and
``task_reader(desc)`` regenerates a shard's records from its name alone
— a preempted trainer that gets the task re-served replays byte-
identical data, the contract the streaming resume tests pin.

Samples: (ids int64[SLOTS], dense float32[DENSE_DIM], label float32[1]).
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["SLOTS", "DENSE_DIM", "VOCAB_SIZE", "train", "task_descs",
           "task_reader", "make_batch"]

SLOTS = 8
DENSE_DIM = 4
VOCAB_SIZE = 100_000
HOT_IDS = 200          # the hot set most impressions hit
HOT_FRACTION = 0.9


def _impressions(rng: np.random.RandomState, n: int, vocab: int):
    """n impressions as (ids [n, SLOTS], dense [n, DENSE_DIM],
    label [n, 1]) — vectorized; callers slice rows out."""
    hot = rng.randint(0, min(HOT_IDS, vocab), size=(n, SLOTS))
    cold = rng.randint(0, vocab, size=(n, SLOTS))
    ids = np.where(rng.rand(n, SLOTS) < HOT_FRACTION, hot,
                   cold).astype(np.int64)
    dense = rng.rand(n, DENSE_DIM).astype(np.float32)
    # clickiness: a few magic id buckets + one dense feature
    signal = (ids % 7 == 3).sum(1) * 0.8 + dense[:, 0] * 2.0 - 2.2
    prob = 1.0 / (1.0 + np.exp(-signal))
    label = (rng.rand(n) < prob).astype(np.float32)[:, None]
    return ids, dense, label


def train(n: int = 4096, vocab: int = VOCAB_SIZE, seed: str = "ctr-train"):
    """Plain bounded reader: ``n`` (ids, dense, label) samples."""

    def reader():
        ids, dense, label = _impressions(common.synthetic_rng(seed), n,
                                         vocab)
        for i in range(n):
            yield ids[i], dense[i], label[i]

    return reader


def task_descs(n_shards: int, records_per_shard: int = 256,
               vocab: int = VOCAB_SIZE):
    """Shard names for a master task queue: ``ctr:<shard>:<n>:<vocab>``.
    Each desc fully determines its records (deterministic replay on
    task re-serve)."""
    return [f"ctr:{i}:{int(records_per_shard)}:{int(vocab)}"
            for i in range(n_shards)]


def task_reader(desc: str):
    """Records of one task desc (the ``make_reader`` a
    MasterClient.task_reader wants)."""
    tag, shard, n, vocab = desc.split(":")
    if tag != "ctr":
        raise ValueError(f"not a ctr task desc: {desc!r}")
    n, vocab = int(n), int(vocab)
    ids, dense, label = _impressions(
        common.synthetic_rng(f"ctr-shard-{shard}"), n, vocab)
    return ((ids[i], dense[i], label[i]) for i in range(n))


def make_batch(rows):
    """Stack a list of (ids, dense, label) rows into the feed arrays a
    wide_deep program wants: {'ids', 'dense', 'label'}."""
    return {"ids": np.stack([r[0] for r in rows]),
            "dense": np.stack([r[1] for r in rows]),
            "label": np.stack([r[2] for r in rows])}

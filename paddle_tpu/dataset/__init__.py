"""Datasets (reference python/paddle/v2/dataset package API)."""
from . import (cifar, common, conll05, ctr, flowers, imdb, imikolov, mnist,
               movielens, mq2007, sentiment, uci_housing, voc2012, wmt14)

__all__ = ["cifar", "common", "conll05", "ctr", "flowers", "imdb",
           "imikolov", "mnist", "movielens", "mq2007", "sentiment",
           "uci_housing", "voc2012", "wmt14"]

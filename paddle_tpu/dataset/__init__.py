"""Datasets (reference python/paddle/v2/dataset package API)."""
from . import common, mnist, uci_housing

__all__ = ["common", "mnist", "uci_housing"]

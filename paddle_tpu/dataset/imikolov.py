"""PTB language-model n-grams (reference python/paddle/v2/dataset/imikolov.py).

``build_dict()`` -> {word: idx}; ``train(word_idx, n)`` yields n-gram tuples
of ids (the word2vec book-test interface, imikolov.py reader_creator).
When the real ``simple-examples.tgz`` PTB corpus is present in the cache
dir it is parsed with the reference's rules (freq-cutoff dict over
train+valid with <s>/<e> counted per line and <unk> appended last,
n-gram windows over <s>-prefixed <e>-suffixed lines —
imikolov.py:35-103); otherwise a synthetic Markov-chain corpus with a
deterministic transition structure, so n-gram models (word2vec) have
real signal to fit.
"""
from __future__ import annotations

import collections
import os
import tarfile

import numpy as np

from . import common

__all__ = ["build_dict", "train", "test"]

_TRAIN_MEMBER = "./simple-examples/data/ptb.train.txt"
_TEST_MEMBER = "./simple-examples/data/ptb.valid.txt"


def _real_path():
    p = os.path.join(common.DATA_HOME, "imikolov", "simple-examples.tgz")
    return p if os.path.exists(p) else None


def _member(tf, name):
    try:
        return tf.extractfile(name)
    except KeyError:
        return tf.extractfile(name.lstrip("./"))


def _word_count(f, word_freq):
    for line in f:
        for w in line.decode("utf-8").strip().split():
            word_freq[w] += 1
        word_freq["<s>"] += 1
        word_freq["<e>"] += 1
    return word_freq


def _real_build_dict(min_word_freq):
    word_freq = collections.defaultdict(int)
    with tarfile.open(_real_path()) as tf:
        _word_count(_member(tf, _TRAIN_MEMBER), word_freq)
        _word_count(_member(tf, _TEST_MEMBER), word_freq)
    word_freq.pop("<unk>", None)  # re-added as the last index
    kept = sorted(((w, f) for w, f in word_freq.items()
                   if f > min_word_freq), key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _real_reader(member, word_idx, n):
    def reader():
        unk = word_idx["<unk>"]
        with tarfile.open(_real_path()) as tf:
            for line in _member(tf, member):
                words = (["<s>"] + line.decode("utf-8").strip().split()
                         + ["<e>"])
                if len(words) < n:
                    continue
                ids = [word_idx.get(w, unk) for w in words]
                for i in range(n, len(ids) + 1):
                    yield tuple(ids[i - n:i])

    return reader

VOCAB_SIZE = 256
TRAIN_SENTENCES = 2048
TEST_SENTENCES = 256


def build_dict(min_word_freq=50):
    if _real_path():
        return _real_build_dict(min_word_freq)
    d = {f"w{i}": i for i in range(VOCAB_SIZE - 2)}
    d["<s>"] = VOCAB_SIZE - 2
    d["<e>"] = VOCAB_SIZE - 1
    return d


def _transition(seed="imikolov-chain"):
    rng = common.synthetic_rng(seed)
    # each word strongly prefers 4 successors
    succ = rng.randint(0, VOCAB_SIZE - 2, size=(VOCAB_SIZE, 4))
    return succ


def _sentences(n, seed_name):
    succ = _transition()

    def gen():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            length = int(rng.randint(5, 20))
            w = int(rng.randint(0, VOCAB_SIZE - 2))
            sent = [w]
            for _ in range(length - 1):
                w = int(succ[w, rng.randint(0, 4)])
                sent.append(w)
            yield sent

    return gen


def _ngram_reader(n_sents, seed_name, word_idx, n):
    sents = _sentences(n_sents, seed_name)
    bos = len(word_idx) - 2
    eos = len(word_idx) - 1

    def reader():
        for sent in sents():
            # <s>*(n-1) + words + <e>, like the reference reader_creator
            padded = [bos] * (n - 1) + sent + [eos]
            for i in range(n - 1, len(padded)):
                yield tuple(padded[i - n + 1: i + 1])

    return reader


def train(word_idx, n):
    if _real_path():
        return _real_reader(_TRAIN_MEMBER, word_idx, n)
    return _ngram_reader(TRAIN_SENTENCES, "imikolov-train", word_idx, n)


def test(word_idx, n):
    if _real_path():
        return _real_reader(_TEST_MEMBER, word_idx, n)
    return _ngram_reader(TEST_SENTENCES, "imikolov-test", word_idx, n)

"""PTB language-model n-grams (reference python/paddle/v2/dataset/imikolov.py).

``build_dict()`` -> {word: idx}; ``train(word_idx, n)`` yields n-gram tuples
of ids (the word2vec book-test interface, imikolov.py reader_creator).
Synthetic fallback: a Markov-chain corpus with a deterministic transition
structure, so n-gram models (word2vec) have real signal to fit.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["build_dict", "train", "test"]

VOCAB_SIZE = 256
TRAIN_SENTENCES = 2048
TEST_SENTENCES = 256


def build_dict(min_word_freq=50):
    d = {f"w{i}": i for i in range(VOCAB_SIZE - 2)}
    d["<s>"] = VOCAB_SIZE - 2
    d["<e>"] = VOCAB_SIZE - 1
    return d


def _transition(seed="imikolov-chain"):
    rng = common.synthetic_rng(seed)
    # each word strongly prefers 4 successors
    succ = rng.randint(0, VOCAB_SIZE - 2, size=(VOCAB_SIZE, 4))
    return succ


def _sentences(n, seed_name):
    succ = _transition()

    def gen():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            length = int(rng.randint(5, 20))
            w = int(rng.randint(0, VOCAB_SIZE - 2))
            sent = [w]
            for _ in range(length - 1):
                w = int(succ[w, rng.randint(0, 4)])
                sent.append(w)
            yield sent

    return gen


def _ngram_reader(n_sents, seed_name, word_idx, n):
    sents = _sentences(n_sents, seed_name)
    bos = len(word_idx) - 2
    eos = len(word_idx) - 1

    def reader():
        for sent in sents():
            # <s>*(n-1) + words + <e>, like the reference reader_creator
            padded = [bos] * (n - 1) + sent + [eos]
            for i in range(n - 1, len(padded)):
                yield tuple(padded[i - n + 1: i + 1])

    return reader


def train(word_idx, n):
    return _ngram_reader(TRAIN_SENTENCES, "imikolov-train", word_idx, n)


def test(word_idx, n):
    return _ngram_reader(TEST_SENTENCES, "imikolov-test", word_idx, n)

"""Dataset infrastructure.

API parity with /root/reference/python/paddle/v2/dataset/common.py (download
cache, md5, cluster file splitting). This environment has no network egress,
so ``download`` resolves only against the local cache or an explicit
``DATA_HOME`` drop; every dataset module provides a deterministic synthetic
fallback with the real dataset's shapes, dtype and vocabulary so models,
readers and tests exercise identical code paths.
"""
from __future__ import annotations

import hashlib
import os
import pickle
from typing import Callable

import numpy as np

DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                              "~/.cache/paddle_tpu/dataset"))


def must_mkdirs(path: str) -> None:
    os.makedirs(path, exist_ok=True)


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: str = None) -> str:
    """Resolve a dataset file from the local cache (no network egress)."""
    dirname = os.path.join(DATA_HOME, module_name)
    must_mkdirs(dirname)
    filename = os.path.join(dirname, url.split("/")[-1])
    if os.path.exists(filename) and (md5sum is None or md5file(filename) == md5sum):
        return filename
    raise FileNotFoundError(
        f"dataset file {filename} not present and downloads are disabled; "
        f"place the file manually or use the synthetic reader")


def split(reader, line_count: int, suffix: str = "%05d.pickle",
          dumper: Callable = pickle.dump):
    """Split a reader's samples into multiple pickled files
    (reference common.py split)."""
    lines = []
    index = 0
    for d in reader():
        lines.append(d)
        if len(lines) == line_count:
            with open(suffix % index, "wb") as f:
                dumper(lines, f)
            lines = []
            index += 1
    if lines:
        with open(suffix % index, "wb") as f:
            dumper(lines, f)
        index += 1
    return index


def cluster_files_reader(files_pattern: str, trainer_count: int,
                         trainer_id: int, loader: Callable = pickle.load):
    """Read this trainer's shard of pickled sample files
    (reference common.py cluster_files_reader)."""
    import glob

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        for i, fn in enumerate(file_list):
            if i % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    yield from loader(f)

    return reader


def synthetic_rng(name: str) -> np.random.RandomState:
    """Deterministic per-dataset RNG so synthetic data is reproducible."""
    seed = int(hashlib.md5(name.encode()).hexdigest()[:8], 16) % (2**31)
    return np.random.RandomState(seed)

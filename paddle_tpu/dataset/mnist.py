"""MNIST dataset (reference python/paddle/v2/dataset/mnist.py API).

Samples are ``(image, label)`` with image a flat float32[784] in [-1, 1] and
label int in [0, 10), exactly like the reference. With no network egress the
default readers serve a deterministic synthetic MNIST: 10 fixed blob-pattern
prototypes + noise — linearly separable enough that LeNet converges, so the
book tests exercise the full training path.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]

TRAIN_SIZE = 8192
TEST_SIZE = 1024


def _prototypes():
    rng = common.synthetic_rng("mnist-protos")
    protos = []
    for d in range(10):
        img = np.zeros((28, 28), np.float32)
        # each digit: 3 gaussian blobs at digit-specific locations
        for _ in range(3):
            cy, cx = rng.randint(4, 24, size=2)
            yy, xx = np.mgrid[0:28, 0:28]
            img += np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / 18.0)
        protos.append(np.clip(img, 0, 1))
    return protos


def _synthetic_reader(n: int, seed_name: str):
    protos = _prototypes()

    def reader():
        rng = common.synthetic_rng(seed_name)
        for i in range(n):
            label = int(rng.randint(0, 10))
            img = protos[label] + rng.normal(0, 0.15, (28, 28)).astype(np.float32)
            img = np.clip(img, 0, 1) * 2.0 - 1.0  # [-1, 1] like the reference
            yield img.reshape(784).astype(np.float32), label

    return reader


def _idx_reader(img_path: str, lab_path: str):
    """Parse real MNIST IDX files if present in the data cache
    (format per the reference's reader_creator mnist.py)."""

    def reader():
        with gzip.open(img_path, "rb") as fi, gzip.open(lab_path, "rb") as fl:
            fi.read(16)
            fl.read(8)
            while True:
                buf = fi.read(784)
                if len(buf) < 784:
                    break
                lab = fl.read(1)
                img = np.frombuffer(buf, np.uint8).astype(np.float32)
                img = img / 127.5 - 1.0
                yield img, int(lab[0])

    return reader


def _reader(kind: str, n: int):
    d = os.path.join(common.DATA_HOME, "mnist")
    img = os.path.join(d, f"{kind}-images-idx3-ubyte.gz")
    lab = os.path.join(d, f"{kind}-labels-idx1-ubyte.gz")
    if os.path.exists(img) and os.path.exists(lab):
        return _idx_reader(img, lab)
    return _synthetic_reader(n, f"mnist-{kind}")


def train():
    return _reader("train", TRAIN_SIZE)


def test():
    return _reader("t10k", TEST_SIZE)

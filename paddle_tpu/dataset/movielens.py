"""MovieLens-1M recommender data (reference v2/dataset/movielens.py API).

Samples are ``(user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, score)`` — the recommender book-test feature tuple. Synthetic
fallback: a low-rank latent-factor model generates consistent ratings, so
matrix-factorisation models can actually fit.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "movie_categories", "get_movie_title_dict"]

N_USERS = 512
N_MOVIES = 256
N_JOBS = 21
N_CATEGORIES = 18
TITLE_VOCAB = 512
RANK = 6
TRAIN_SIZE = 8192
TEST_SIZE = 1024

age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return N_USERS


def max_movie_id():
    return N_MOVIES


def max_job_id():
    return N_JOBS - 1


def movie_categories():
    return {f"cat{i}": i for i in range(N_CATEGORIES)}


def get_movie_title_dict():
    return {f"t{i}": i for i in range(TITLE_VOCAB)}


def _factors():
    rng = common.synthetic_rng("movielens-factors")
    u = rng.normal(0, 1, (N_USERS + 1, RANK))
    m = rng.normal(0, 1, (N_MOVIES + 1, RANK))
    return u, m


def _movie_meta():
    rng = common.synthetic_rng("movielens-meta")
    cats = [rng.randint(0, N_CATEGORIES,
                        size=rng.randint(1, 4)).tolist()
            for _ in range(N_MOVIES + 1)]
    titles = [rng.randint(0, TITLE_VOCAB,
                          size=rng.randint(2, 6)).tolist()
              for _ in range(N_MOVIES + 1)]
    return cats, titles


def _reader(n, seed_name):
    u_f, m_f = _factors()
    cats, titles = _movie_meta()

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            uid = int(rng.randint(1, N_USERS + 1))
            mid = int(rng.randint(1, N_MOVIES + 1))
            raw = float(u_f[uid] @ m_f[mid]) / RANK ** 0.5
            score = float(np.clip(np.round(3.0 + 1.5 * raw), 1, 5))
            gender = uid % 2
            age = int(rng.randint(0, len(age_table)))
            job = uid % N_JOBS
            yield (uid, gender, age, job, mid, cats[mid], titles[mid], score)

    return reader


def train():
    return _reader(TRAIN_SIZE, "movielens-train")


def test():
    return _reader(TEST_SIZE, "movielens-test")

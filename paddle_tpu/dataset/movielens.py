"""MovieLens-1M recommender data (reference v2/dataset/movielens.py API).

Samples are ``(user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, score)`` — the recommender book-test feature tuple. When the
real ``ml-1m.zip`` is present in the cache dir its '::'-separated
movies/users/ratings .dat files are parsed with the reference's rules
(title-year stripping, age bucketing via age_table, deterministic
0.1 train/test ratings split — movielens.py:101-160); otherwise a
low-rank latent-factor synthetic model generates consistent ratings, so
matrix-factorisation models can actually fit.
"""
from __future__ import annotations

import os
import random
import re
import zipfile

import numpy as np

from . import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "movie_categories", "get_movie_title_dict",
           "ctr_train", "ctr_test", "ctr_vocab_size", "CTR_DENSE_DIM"]

N_USERS = 512
N_MOVIES = 256
N_JOBS = 21
N_CATEGORIES = 18
TITLE_VOCAB = 512
RANK = 6
TRAIN_SIZE = 8192
TEST_SIZE = 1024

age_table = [1, 18, 25, 35, 45, 50, 56]

_META = None  # (movies {mid: (cats, title_ids)}, users {uid: tuple},
#                title_dict, cat_dict) from the real zip, once parsed


def _real_path():
    p = os.path.join(common.DATA_HOME, "movielens", "ml-1m.zip")
    return p if os.path.exists(p) else None


def _meta():
    """Parse movies.dat/users.dat from the real zip (reference
    __initialize_meta_info__)."""
    global _META
    if _META is not None:
        return _META
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    movies, users = {}, {}
    title_words, cat_names = set(), set()
    raw_movies = []
    with zipfile.ZipFile(_real_path()) as pkg:
        with pkg.open("ml-1m/movies.dat") as f:
            for line in f:
                mid, title, cats = line.decode(
                    "latin1").strip().split("::")
                cats = cats.split("|")
                title = pattern.match(title).group(1)
                raw_movies.append((int(mid), cats, title))
                cat_names.update(cats)
                title_words.update(w.lower() for w in title.split())
        cat_dict = {c: i for i, c in enumerate(sorted(cat_names))}
        title_dict = {w: i for i, w in enumerate(sorted(title_words))}
        for mid, cats, title in raw_movies:
            movies[mid] = ([cat_dict[c] for c in cats],
                           [title_dict[w.lower()] for w in title.split()])
        with pkg.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job, _zip = line.decode(
                    "latin1").strip().split("::")
                users[int(uid)] = (int(uid), 0 if gender == "M" else 1,
                                   age_table.index(int(age)), int(job))
    _META = (movies, users, title_dict, cat_dict)
    return _META


def max_user_id():
    if _real_path():
        return max(_meta()[1])
    return N_USERS


def max_movie_id():
    if _real_path():
        return max(_meta()[0])
    return N_MOVIES


def max_job_id():
    if _real_path():
        return max(u[3] for u in _meta()[1].values())
    return N_JOBS - 1


def movie_categories():
    if _real_path():
        return dict(_meta()[3])
    return {f"cat{i}": i for i in range(N_CATEGORIES)}


def get_movie_title_dict():
    if _real_path():
        return dict(_meta()[2])
    return {f"t{i}": i for i in range(TITLE_VOCAB)}


def _real_reader(is_test, test_ratio=0.1, rand_seed=0):
    """Ratings stream from the real zip; the same deterministic
    rand.random() < test_ratio row split as the reference __reader__."""

    def reader():
        movies, users, _, _ = _meta()
        rand = random.Random(x=rand_seed)
        with zipfile.ZipFile(_real_path()) as pkg:
            with pkg.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rand.random() < test_ratio) != is_test:
                        continue
                    uid, mid, score, _ts = line.decode(
                        "latin1").strip().split("::")
                    uid, mid = int(uid), int(mid)
                    cats, titles = movies[mid]
                    u = users[uid]
                    yield (u[0], u[1], u[2], u[3], mid, cats, titles,
                           float(score))

    return reader


def _factors():
    rng = common.synthetic_rng("movielens-factors")
    u = rng.normal(0, 1, (N_USERS + 1, RANK))
    m = rng.normal(0, 1, (N_MOVIES + 1, RANK))
    return u, m


def _movie_meta():
    rng = common.synthetic_rng("movielens-meta")
    cats = [rng.randint(0, N_CATEGORIES,
                        size=rng.randint(1, 4)).tolist()
            for _ in range(N_MOVIES + 1)]
    titles = [rng.randint(0, TITLE_VOCAB,
                          size=rng.randint(2, 6)).tolist()
              for _ in range(N_MOVIES + 1)]
    return cats, titles


def _reader(n, seed_name):
    u_f, m_f = _factors()
    cats, titles = _movie_meta()

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            uid = int(rng.randint(1, N_USERS + 1))
            mid = int(rng.randint(1, N_MOVIES + 1))
            raw = float(u_f[uid] @ m_f[mid]) / RANK ** 0.5
            score = float(np.clip(np.round(3.0 + 1.5 * raw), 1, 5))
            gender = uid % 2
            age = int(rng.randint(0, len(age_table)))
            job = uid % N_JOBS
            yield (uid, gender, age, job, mid, cats[mid], titles[mid], score)

    return reader


def train():
    if _real_path():
        return _real_reader(is_test=False)
    return _reader(TRAIN_SIZE, "movielens-train")


def test():
    if _real_path():
        return _real_reader(is_test=True)
    return _reader(TEST_SIZE, "movielens-test")


# ---------------------------------------------------------------------------
# CTR impressions through the varlen plane (ROADMAP 4c): each rating
# becomes one impression whose sparse features are a single VARIABLE-
# LENGTH id list — the fixed slots (user, gender, age, job, movie) plus
# every category id and title word, each slot offset into its own
# disjoint band of one shared vocabulary. The ragged lists flow through
# reader.bucket_by_length + DataFeeder(pad_to_multiple=...) into an
# embedding + sequence_pool CTR tower; the label is click/no-click
# (score >= 4). Works identically off the real ml-1m.zip or the
# synthetic fallback — tests never touch the network.
# ---------------------------------------------------------------------------

CTR_DENSE_DIM = 4


def _ctr_bands():
    """(band base offsets, total vocab) for the shared id space."""
    n_users = max_user_id() + 1
    n_movies = max_movie_id() + 1
    n_jobs = max_job_id() + 1
    n_cats = len(movie_categories())
    n_title = len(get_movie_title_dict())
    bases = {}
    off = 0
    for name, size in (("user", n_users), ("gender", 2),
                       ("age", len(age_table)), ("job", n_jobs),
                       ("movie", n_movies), ("category", n_cats),
                       ("title", n_title)):
        bases[name] = off
        off += size
    return bases, off


def ctr_vocab_size() -> int:
    return _ctr_bands()[1]


def _ctr_reader(base_reader):
    bases, _ = _ctr_bands()

    def reader():
        for (uid, gender, age, job, mid, cats, titles,
             score) in base_reader():
            ids = [bases["user"] + uid, bases["gender"] + gender,
                   bases["age"] + age, bases["job"] + job,
                   bases["movie"] + mid]
            ids += [bases["category"] + c for c in cats]
            ids += [bases["title"] + t for t in titles]
            dense = np.asarray(
                [age / len(age_table), gender,
                 len(cats) / 6.0, len(titles) / 8.0], np.float32)
            label = np.asarray([1.0 if score >= 4.0 else 0.0],
                               np.float32)
            yield np.asarray(ids, np.int64), dense, label

    return reader


def ctr_train():
    """Varlen CTR impressions: ``(id_list int64[varlen],
    dense float32[CTR_DENSE_DIM], click float32[1])`` rows."""
    return _ctr_reader(train())


def ctr_test():
    return _ctr_reader(test())

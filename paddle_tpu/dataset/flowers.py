"""Oxford 102 Flowers (reference v2/dataset/flowers.py API).

``train()``/``test()``/``valid()`` yield ``(image, label)`` with image flat
float32[3*224*224] CHW — the reference's default_mapper output. When the
real corpus is present in the cache dir (``102flowers.tgz`` +
``imagelabels.mat`` + ``setid.mat``) it is parsed with the reference's
rules (1-based .mat labels; the tstid/trnid TRAIN/TEST swap the
reference documents at flowers.py:50-54; short-side-256 resize +
center crop 224 + mean subtraction) via PIL/scipy — deterministic (no
random aug). Otherwise a synthetic fallback: 102 colour-field
prototypes upsampled to 224.
"""
from __future__ import annotations

import os
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]

N_CLASSES = 102
TRAIN_SIZE = 512
TEST_SIZE = 64
SIZE = 224


def _upsample(small):
    return small.repeat(SIZE // 8, axis=1).repeat(SIZE // 8, axis=2)


def _protos():
    rng = common.synthetic_rng("flowers-protos")
    return [_upsample(rng.rand(3, 8, 8).astype(np.float32))
            for _ in range(N_CLASSES)]


def _reader(n, seed_name):
    protos = _protos()

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            label = int(rng.randint(0, N_CLASSES))
            img = protos[label] + rng.normal(0, 0.05,
                                             protos[label].shape)
            yield np.clip(img, 0, 1).astype(np.float32).reshape(-1), label

    return reader


_MEAN = np.array([103.94, 116.78, 123.68], np.float32)


def _real_dir():
    d = os.path.join(common.DATA_HOME, "flowers")
    need = ("102flowers.tgz", "imagelabels.mat", "setid.mat")
    if all(os.path.exists(os.path.join(d, n)) for n in need):
        return d
    return None


def _decode(raw):
    """The reference default_mapper, deterministically: short side 256,
    center crop 224, BGR CHW float32 minus the channel means (the
    reference loads via cv2, so its channel order and its
    [103.94, 116.78, 123.68] means are BGR — image.py
    simple_transform)."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(raw)).convert("RGB")
    w, h = img.size
    scale = 256.0 / min(w, h)
    img = img.resize((max(224, int(w * scale)),
                      max(224, int(h * scale))))
    w, h = img.size
    left, top = (w - SIZE) // 2, (h - SIZE) // 2
    img = img.crop((left, top, left + SIZE, top + SIZE))
    arr = np.asarray(img, np.float32)[:, :, ::-1]  # HWC RGB -> BGR
    arr = arr - _MEAN[None, None, :]
    return arr.transpose(2, 0, 1).reshape(-1)


def _real_reader(flag):
    def reader():
        import scipy.io as scio

        d = _real_dir()
        labels = scio.loadmat(
            os.path.join(d, "imagelabels.mat"))["labels"][0]
        indexes = scio.loadmat(os.path.join(d, "setid.mat"))[flag][0]
        wanted = {f"jpg/image_{i:05d}.jpg": int(labels[i - 1])
                  for i in indexes}
        with tarfile.open(os.path.join(d, "102flowers.tgz")) as tf:
            m = tf.next()
            while m is not None:
                if m.name in wanted:
                    raw = tf.extractfile(m).read()
                    yield (_decode(raw),
                           wanted[m.name] - 1)  # 1-based -> 0-based
                m = tf.next()

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    if _real_dir():
        # the reference's documented swap: tstid flags the TRAIN split
        return _real_reader("tstid")
    return _reader(TRAIN_SIZE, "flowers-train")


def test(mapper=None, buffered_size=1024, use_xmap=False):
    if _real_dir():
        return _real_reader("trnid")
    return _reader(TEST_SIZE, "flowers-test")


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    if _real_dir():
        return _real_reader("valid")
    return _reader(TEST_SIZE, "flowers-valid")

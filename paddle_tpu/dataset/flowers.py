"""Oxford 102 Flowers (reference v2/dataset/flowers.py API).

``train()``/``test()``/``valid()`` yield ``(image, label)`` with image flat
float32[3*224*224] CHW — the reference's default_mapper output. Synthetic
fallback: 102 colour-field prototypes at lower internal resolution upsampled
to 224, keeping per-sample cost reasonable.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]

N_CLASSES = 102
TRAIN_SIZE = 512
TEST_SIZE = 64
SIZE = 224


def _upsample(small):
    return small.repeat(SIZE // 8, axis=1).repeat(SIZE // 8, axis=2)


def _protos():
    rng = common.synthetic_rng("flowers-protos")
    return [_upsample(rng.rand(3, 8, 8).astype(np.float32))
            for _ in range(N_CLASSES)]


def _reader(n, seed_name):
    protos = _protos()

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            label = int(rng.randint(0, N_CLASSES))
            img = protos[label] + rng.normal(0, 0.05,
                                             protos[label].shape)
            yield np.clip(img, 0, 1).astype(np.float32).reshape(-1), label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TRAIN_SIZE, "flowers-train")


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TEST_SIZE, "flowers-test")


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TEST_SIZE, "flowers-valid")

"""CIFAR-10 / CIFAR-100 (reference python/paddle/v2/dataset/cifar.py API).

Samples are ``(image, label)`` with image flat float32[3072] (CHW, [0, 1])
— the reference's layout (cifar.py reader_creator). Real python-pickle
tarballs are parsed if present in the cache; otherwise a deterministic
synthetic set with per-class colour/texture prototypes.
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]

TRAIN_SIZE = 4096
TEST_SIZE = 512


def _protos(n_classes, seed):
    rng = common.synthetic_rng(seed)
    protos = []
    for _ in range(n_classes):
        base = rng.rand(3, 1, 1).astype(np.float32)
        freq = rng.randint(1, 5, size=2)
        yy, xx = np.mgrid[0:32, 0:32] / 32.0
        tex = 0.25 * np.sin(2 * np.pi * (freq[0] * yy + freq[1] * xx))
        protos.append(np.clip(base + tex[None], 0, 1).astype(np.float32))
    return protos


def _synthetic_reader(n, n_classes, seed_name):
    protos = _protos(n_classes, seed_name + "-protos")

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            label = int(rng.randint(0, n_classes))
            img = protos[label] + rng.normal(0, 0.1, (3, 32, 32))
            yield (np.clip(img, 0, 1).astype(np.float32).reshape(3072),
                   label)

    return reader


def _tar_reader(path, sub_name, label_key):
    def reader():
        with tarfile.open(path, mode="r") as f:
            names = [n for n in f.getnames() if sub_name in n]
            for name in names:
                batch = pickle.load(f.extractfile(name), encoding="latin1")
                for s, l in zip(batch["data"], batch[label_key]):
                    yield s.astype(np.float32) / 255.0, int(l)

    return reader


def _reader(flavor, sub_name, n_classes, n):
    fname = os.path.join(common.DATA_HOME, "cifar",
                         f"cifar-{flavor}-python.tar.gz")
    if os.path.exists(fname):
        key = "labels" if flavor == "10" else "fine_labels"
        return _tar_reader(fname, sub_name, key)
    return _synthetic_reader(n, n_classes, f"cifar{flavor}-{sub_name}")


def train10():
    return _reader("10", "data_batch", 10, TRAIN_SIZE)


def test10():
    return _reader("10", "test_batch", 10, TEST_SIZE)


def train100():
    return _reader("100", "train", 100, TRAIN_SIZE)


def test100():
    return _reader("100", "test", 100, TEST_SIZE)

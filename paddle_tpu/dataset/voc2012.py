"""PASCAL VOC2012 segmentation (reference v2/dataset/voc2012.py API).

``train()``/``test()``/``val()`` yield ``(image, label_mask)``: image
float32[3, H, W], mask int64[H, W] with 21 classes — the reference's
(image, label) segmentation pairs. When the real
``VOCtrainval_11-May-2012.tar`` is present in the cache dir it is
parsed with the reference's rules (ImageSets/Segmentation/{split}.txt
name lists, JPEGImages + palette-PNG SegmentationClass pairs —
voc2012.py:34-63; splits: train()='trainval', test()='train',
val()='val', the reference's own mapping) via PIL. Otherwise a
synthetic fallback: rectangle objects of class-coloured texture on
background, masks exactly consistent with images.
"""
from __future__ import annotations

import io
import os
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

N_CLASSES = 21
SIZE = 64
TRAIN_SIZE = 256
TEST_SIZE = 32


def _reader(n, seed_name):
    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            img = rng.rand(3, SIZE, SIZE).astype(np.float32) * 0.2
            mask = np.zeros((SIZE, SIZE), np.int64)
            for _ in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, N_CLASSES))
                y0, x0 = rng.randint(0, SIZE - 16, size=2)
                h, w = rng.randint(8, 16, size=2)
                colour = common.synthetic_rng(f"voc-c{cls}").rand(3, 1, 1)
                img[:, y0:y0 + h, x0:x0 + w] = colour + 0.05 * rng.rand(3, h, w)
                mask[y0:y0 + h, x0:x0 + w] = cls
            yield np.clip(img, 0, 1), mask

    return reader


_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def _real_path():
    p = os.path.join(common.DATA_HOME, "voc2012",
                     "VOCtrainval_11-May-2012.tar")
    return p if os.path.exists(p) else None


def _real_reader(sub_name):
    # one tar open + member index, shared across epochs (the reference
    # builds name2mem once in reader_creator)
    tf = tarfile.open(_real_path())
    members = {m.name: m for m in tf.getmembers()}

    def reader():
        from PIL import Image

        sets = tf.extractfile(members[_SET_FILE.format(sub_name)])
        for line in sets:
            name = line.decode("utf-8").strip()
            if not name:
                continue
            data = tf.extractfile(members[_DATA_FILE.format(name)]).read()
            label = tf.extractfile(
                members[_LABEL_FILE.format(name)]).read()
            # the module contract (same as the synthetic path): image
            # float32 [3, H, W] in [0, 1], mask int64 [H, W]
            img = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"),
                             np.float32).transpose(2, 0, 1) / 255.0
            mask = np.asarray(Image.open(io.BytesIO(label)),
                              np.int64)
            yield img, mask

    return reader


def train():
    if _real_path():
        return _real_reader("trainval")  # the reference's own mapping
    return _reader(TRAIN_SIZE, "voc2012-train")


def test():
    if _real_path():
        return _real_reader("train")
    return _reader(TEST_SIZE, "voc2012-test")


def val():
    if _real_path():
        return _real_reader("val")
    return _reader(TEST_SIZE, "voc2012-val")

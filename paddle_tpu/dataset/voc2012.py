"""PASCAL VOC2012 segmentation (reference v2/dataset/voc2012.py API).

``train()``/``test()``/``val()`` yield ``(image, label_mask)``: image
float32[3, H, W], mask int64[H, W] with 21 classes — the reference's
(image, label) segmentation pairs. Synthetic fallback: rectangle objects of
class-coloured texture on background, masks exactly consistent with images.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

N_CLASSES = 21
SIZE = 64
TRAIN_SIZE = 256
TEST_SIZE = 32


def _reader(n, seed_name):
    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            img = rng.rand(3, SIZE, SIZE).astype(np.float32) * 0.2
            mask = np.zeros((SIZE, SIZE), np.int64)
            for _ in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, N_CLASSES))
                y0, x0 = rng.randint(0, SIZE - 16, size=2)
                h, w = rng.randint(8, 16, size=2)
                colour = common.synthetic_rng(f"voc-c{cls}").rand(3, 1, 1)
                img[:, y0:y0 + h, x0:x0 + w] = colour + 0.05 * rng.rand(3, h, w)
                mask[y0:y0 + h, x0:x0 + w] = cls
            yield np.clip(img, 0, 1), mask

    return reader


def train():
    return _reader(TRAIN_SIZE, "voc2012-train")


def test():
    return _reader(TEST_SIZE, "voc2012-test")


def val():
    return _reader(TEST_SIZE, "voc2012-val")

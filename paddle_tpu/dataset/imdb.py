"""IMDB movie-review sentiment (reference python/paddle/v2/dataset/imdb.py).

``word_dict()`` -> {word: idx}; ``train(word_idx)``/``test(word_idx)`` yield
``(ids, 0|1)`` — the reference's tokenized-to-ids interface. Synthetic
fallback: two sentiment "topics" with disjoint high-probability word sets so
conv/LSTM classifiers genuinely learn the signal.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["word_dict", "train", "test"]

VOCAB_SIZE = 2048
TRAIN_SIZE = 2048
TEST_SIZE = 256


def word_dict():
    """{word: idx}; last index is <unk> like the reference build_dict."""
    return {f"w{i}": i for i in range(VOCAB_SIZE)}


def _synthetic_reader(n, seed_name, word_idx):
    v = len(word_idx)
    pos_words = np.arange(0, v // 4)
    neg_words = np.arange(v // 4, v // 2)
    common_words = np.arange(v // 2, v)

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 64))
            topic = pos_words if label else neg_words
            n_topic = max(1, length // 4)
            ids = np.concatenate([
                rng.choice(topic, size=n_topic),
                rng.choice(common_words, size=length - n_topic),
            ])
            rng.shuffle(ids)
            yield ids.astype(np.int64).tolist(), label

    return reader


def train(word_idx):
    return _synthetic_reader(TRAIN_SIZE, "imdb-train", word_idx)


def test(word_idx):
    return _synthetic_reader(TEST_SIZE, "imdb-test", word_idx)

"""IMDB movie-review sentiment (reference python/paddle/v2/dataset/imdb.py).

``word_dict()`` -> {word: idx}; ``train(word_idx)``/``test(word_idx)`` yield
``(ids, 0|1)`` — the reference's tokenized-to-ids interface. When the real
``aclImdb_v1.tar.gz`` corpus is present in the cache dir it is parsed with
the reference's own pipeline (punctuation-stripped lowercase tokenization,
frequency-cutoff dictionary with ``<unk>``, pos=0 / neg=1 — imdb.py:37-126);
otherwise a deterministic synthetic set with two disjoint sentiment "topics"
so conv/LSTM classifiers genuinely learn the signal.
"""
from __future__ import annotations

import collections
import os
import re
import string
import tarfile

import numpy as np

from . import common

__all__ = ["word_dict", "build_dict", "train", "test"]

VOCAB_SIZE = 2048
TRAIN_SIZE = 2048
TEST_SIZE = 256

_TAR = "aclImdb_v1.tar.gz"
_PUNCT = str.maketrans("", "", string.punctuation)


def _real_path():
    p = os.path.join(common.DATA_HOME, "imdb", _TAR)
    return p if os.path.exists(p) else None


def _tokenize(pattern):
    """Tokenized docs for member files matching ``pattern`` (reference
    imdb.py:37 tokenize — sequential tarfile.next access)."""
    with tarfile.open(_real_path()) as tarf:
        tf = tarf.next()
        while tf is not None:
            if pattern.match(tf.name):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="ignore")
                yield text.rstrip("\n\r").translate(_PUNCT).lower().split()
            tf = tarf.next()


def build_dict(pattern, cutoff):
    """{word: id} from the real corpus: keep words with freq > cutoff,
    ordered by (-freq, word), then append <unk> (reference imdb.py:60)."""
    word_freq = collections.defaultdict(int)
    for doc in _tokenize(pattern):
        for word in doc:
            word_freq[word] += 1
    kept = sorted(((w, f) for w, f in word_freq.items() if f > cutoff),
                  key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def word_dict():
    """{word: idx}; real corpus dictionary when present (cutoff 150, the
    reference's), else the synthetic vocabulary."""
    if _real_path():
        return build_dict(
            re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
            150)
    return {f"w{i}": i for i in range(VOCAB_SIZE)}


def _real_reader(pos_pattern, neg_pattern, word_idx, seed_name):
    unk = word_idx["<unk>"]
    cache = []  # built on first pass (reference builds INS at creator
    # time; lazy here so creating a reader stays free of tarball IO)

    def reader():
        if not cache:
            for pattern, label in ((pos_pattern, 0), (neg_pattern, 1)):
                for doc in _tokenize(pattern):
                    cache.append(([word_idx.get(w, unk) for w in doc],
                                  label))
        # the reference random.shuffles; deterministic here
        order = common.synthetic_rng(seed_name).permutation(len(cache))
        for i in order:
            yield cache[i]

    return reader


def _synthetic_reader(n, seed_name, word_idx):
    v = len(word_idx)
    pos_words = np.arange(0, v // 4)
    neg_words = np.arange(v // 4, v // 2)
    common_words = np.arange(v // 2, v)

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 64))
            topic = pos_words if label else neg_words
            n_topic = max(1, length // 4)
            ids = np.concatenate([
                rng.choice(topic, size=n_topic),
                rng.choice(common_words, size=length - n_topic),
            ])
            rng.shuffle(ids)
            yield ids.astype(np.int64).tolist(), label

    return reader


def train(word_idx):
    if _real_path():
        return _real_reader(re.compile(r"aclImdb/train/pos/.*\.txt$"),
                            re.compile(r"aclImdb/train/neg/.*\.txt$"),
                            word_idx, "imdb-train-order")
    return _synthetic_reader(TRAIN_SIZE, "imdb-train", word_idx)


def test(word_idx):
    if _real_path():
        return _real_reader(re.compile(r"aclImdb/test/pos/.*\.txt$"),
                            re.compile(r"aclImdb/test/neg/.*\.txt$"),
                            word_idx, "imdb-test-order")
    return _synthetic_reader(TEST_SIZE, "imdb-test", word_idx)

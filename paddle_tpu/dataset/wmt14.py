"""WMT-14 FR->EN translation (reference v2/dataset/wmt14.py API).

``train(dict_size)``/``test(dict_size)`` yield ``(src_ids, trg_ids,
trg_next_ids)`` with <s>/<e>/<unk> at ids 0/1/2 (wmt14.py START/END/UNK).
Synthetic fallback: the "translation" is a deterministic word-for-word map
with local reordering — a seq2seq model can genuinely learn it.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test"]

START = 0  # <s>
END = 1    # <e>
UNK = 2    # <unk>
TRAIN_SIZE = 2048
TEST_SIZE = 256


def _word_map(dict_size):
    rng = common.synthetic_rng("wmt14-map")
    # bijective map over the content vocabulary [3, dict_size)
    content = np.arange(3, dict_size)
    perm = content.copy()
    rng.shuffle(perm)
    table = np.arange(dict_size)
    table[content] = perm
    return table


def _reader(n, seed_name, dict_size):
    table = _word_map(dict_size)

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            length = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, size=length)
            trg = table[src]
            # local reordering: swap adjacent pairs deterministically
            for i in range(0, length - 1, 2):
                if src[i] % 2 == 0:
                    trg[i], trg[i + 1] = trg[i + 1], trg[i]
            src_ids = src.astype(np.int64).tolist()
            trg_in = [START] + trg.astype(np.int64).tolist()
            trg_next = trg.astype(np.int64).tolist() + [END]
            yield src_ids, trg_in, trg_next

    return reader


def train(dict_size):
    return _reader(TRAIN_SIZE, "wmt14-train", dict_size)


def test(dict_size):
    return _reader(TEST_SIZE, "wmt14-test", dict_size)

"""WMT-14 FR->EN translation (reference v2/dataset/wmt14.py API).

``train(dict_size)``/``test(dict_size)`` yield ``(src_ids, trg_ids,
trg_next_ids)`` with <s>/<e>/<unk> at ids 0/1/2 (wmt14.py START/END/UNK).
When the real ``wmt14.tgz`` shrunk corpus is present in the cache dir it
is parsed with the reference's rules (src.dict/trg.dict truncated to
dict_size, tab-separated parallel lines, >80-token pairs dropped,
<s>/<e> framing — wmt14.py:45-103); otherwise a synthetic fallback whose
"translation" is a deterministic word-for-word map with local reordering
— a seq2seq model can genuinely learn it.
"""
from __future__ import annotations

import os
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "get_dict"]

START = 0  # <s>
END = 1    # <e>
UNK = 2    # <unk>
TRAIN_SIZE = 2048
TEST_SIZE = 256


def _word_map(dict_size):
    rng = common.synthetic_rng("wmt14-map")
    # bijective map over the content vocabulary [3, dict_size)
    content = np.arange(3, dict_size)
    perm = content.copy()
    rng.shuffle(perm)
    table = np.arange(dict_size)
    table[content] = perm
    return table


def _reader(n, seed_name, dict_size):
    table = _word_map(dict_size)

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            length = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, size=length)
            trg = table[src]
            # local reordering: swap adjacent pairs deterministically
            for i in range(0, length - 1, 2):
                if src[i] % 2 == 0:
                    trg[i], trg[i + 1] = trg[i + 1], trg[i]
            src_ids = src.astype(np.int64).tolist()
            trg_in = [START] + trg.astype(np.int64).tolist()
            trg_next = trg.astype(np.int64).tolist() + [END]
            yield src_ids, trg_in, trg_next

    return reader


def _real_path():
    p = os.path.join(common.DATA_HOME, "wmt14", "wmt14.tgz")
    return p if os.path.exists(p) else None


def _read_to_dict(tar_file, dict_size):
    """First dict_size lines of the in-tar src.dict/trg.dict files
    (reference wmt14.py:45 __read_to_dict__)."""
    def to_dict(fd, size):
        out = {}
        for line_count, line in enumerate(fd):
            if line_count >= size:
                break
            out[line.decode("utf-8").strip()] = line_count
        return out

    with tarfile.open(tar_file, mode="r") as f:
        src_name, = [m.name for m in f if m.name.endswith("src.dict")]
        src_dict = to_dict(f.extractfile(src_name), dict_size)
        trg_name, = [m.name for m in f if m.name.endswith("trg.dict")]
        trg_dict = to_dict(f.extractfile(trg_name), dict_size)
    return src_dict, trg_dict


def _real_reader(tar_file, file_name, dict_size):
    def reader():
        src_dict, trg_dict = _read_to_dict(tar_file, dict_size)
        start, end = "<s>", "<e>"
        with tarfile.open(tar_file, mode="r") as f:
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    src_ids = [src_dict.get(w, UNK)
                               for w in [start] + src_words + [end]]
                    trg_words = parts[1].split()
                    trg_ids = [trg_dict.get(w, UNK) for w in trg_words]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    yield (src_ids, [trg_dict[start]] + trg_ids,
                           trg_ids + [trg_dict[end]])

    return reader


def get_dict(dict_size, reverse=False):
    """(src_dict, trg_dict) — real in-tar dicts when present, else the
    synthetic id-named vocabulary (reference wmt14.py get_dict)."""
    if _real_path():
        src_dict, trg_dict = _read_to_dict(_real_path(), dict_size)
    else:
        src_dict = {("<s>" if i == 0 else "<e>" if i == 1 else
                     "<unk>" if i == 2 else f"w{i}"): i
                    for i in range(dict_size)}
        trg_dict = dict(src_dict)
    if reverse:
        return ({v: k for k, v in src_dict.items()},
                {v: k for k, v in trg_dict.items()})
    return src_dict, trg_dict


def train(dict_size):
    if _real_path():
        return _real_reader(_real_path(), "train/train", dict_size)
    return _reader(TRAIN_SIZE, "wmt14-train", dict_size)


def test(dict_size):
    if _real_path():
        return _real_reader(_real_path(), "test/test", dict_size)
    return _reader(TEST_SIZE, "wmt14-test", dict_size)

"""UCI housing regression dataset (reference v2/dataset/uci_housing.py API).

Samples: (features float32[13], price float32[1]). Synthetic fallback draws
features then prices from a fixed linear model + noise, so fit_a_line-style
book tests converge deterministically.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

TRAIN_SIZE = 404
TEST_SIZE = 102


def _synthetic(n, seed_name):
    w_rng = common.synthetic_rng("uci-weights")
    true_w = w_rng.randn(13, 1).astype(np.float32)

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            x = rng.rand(13).astype(np.float32)
            y = (x @ true_w).astype(np.float32) + rng.normal(0, 0.05, 1).astype(np.float32)
            yield x, y

    return reader


def train():
    return _synthetic(TRAIN_SIZE, "uci-train")


def test():
    return _synthetic(TEST_SIZE, "uci-test")

"""UCI housing regression dataset (reference v2/dataset/uci_housing.py API).

Samples: (features float32[13], price float32[1]). When the real
``housing.data`` is present in the cache dir it is parsed with the
reference's rules (whitespace floats, 14 cols, per-feature
(x-avg)/(max-min) normalization, 80/20 split — uci_housing.py:60
load_data); otherwise a synthetic fallback draws features then prices
from a fixed linear model + noise, so fit_a_line-style book tests
converge deterministically.
"""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

TRAIN_SIZE = 404
TEST_SIZE = 102


def _synthetic(n, seed_name):
    w_rng = common.synthetic_rng("uci-weights")
    true_w = w_rng.randn(13, 1).astype(np.float32)

    def reader():
        rng = common.synthetic_rng(seed_name)
        for _ in range(n):
            x = rng.rand(13).astype(np.float32)
            y = (x @ true_w).astype(np.float32) + rng.normal(0, 0.05, 1).astype(np.float32)
            yield x, y

    return reader


def _real_path():
    p = os.path.join(common.DATA_HOME, "uci_housing", "housing.data")
    return p if os.path.exists(p) else None


def _load_real(ratio=0.8):
    data = np.fromfile(_real_path(), sep=" ").astype(np.float64)
    data = data.reshape(data.shape[0] // 14, 14)
    maxs, mins = data.max(axis=0), data.min(axis=0)
    avgs = data.mean(axis=0)
    for i in range(13):
        data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
    offset = int(data.shape[0] * ratio)
    return data[:offset], data[offset:]


def _real_reader(is_test):
    def reader():
        train_rows, test_rows = _load_real()
        for row in (test_rows if is_test else train_rows):
            yield (row[:13].astype(np.float32),
                   row[13:].astype(np.float32))

    return reader


def train():
    if _real_path():
        return _real_reader(is_test=False)
    return _synthetic(TRAIN_SIZE, "uci-train")


def test():
    if _real_path():
        return _real_reader(is_test=True)
    return _synthetic(TEST_SIZE, "uci-test")

"""Runtime flag registry — the gflags plane of the reference.

The reference centralizes runtime knobs as gflags
(/root/reference/paddle/utils/Flags.h:19-44: --use_gpu, --trainer_count,
--port, --log_period, ...; per-file DEFINE_* like executor.cc:25
--check_nan_inf), parsed in initMain / framework::InitGflags. The TPU-native
equivalent keeps the same three entry points:

- ``define_*`` at module scope registers a typed flag with a default;
- environment overrides: ``PADDLE_TPU_<NAME>`` is read at definition time
  (the cluster-launcher path — the reference reads gflags' FLAGS_* env);
- ``parse_flags(argv)`` consumes ``--name=value`` / ``--name value`` /
  ``--noname`` tokens (script path), returning unrecognized tokens.

Access is via the ``FLAGS`` namespace: ``flags.FLAGS.check_nan_inf``.
Components read their defaults from FLAGS so a flag flip affects every
instance created afterwards (constructor args still win).
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

_ENV_PREFIX = "PADDLE_TPU_"


class FlagError(ValueError):
    pass


class _Flag:
    __slots__ = ("name", "default", "value", "help", "parser", "type_name")

    def __init__(self, name, default, help_str, parser, type_name):
        self.name = name
        self.default = default
        self.help = help_str
        self.parser = parser
        self.type_name = type_name
        self.value = default


class _Namespace:
    """Attribute view over the registry (gflags' FLAGS object)."""

    def __getattr__(self, name: str) -> Any:
        try:
            return _registry[name].value
        except KeyError:
            raise AttributeError(f"unknown flag {name!r}; defined flags: "
                                 f"{sorted(_registry)}") from None

    def __setattr__(self, name: str, value: Any) -> None:
        flag = _registry.get(name)
        if flag is None:
            raise FlagError(f"unknown flag {name!r}")
        flag.value = flag.parser(value)


_registry: Dict[str, _Flag] = {}
FLAGS = _Namespace()


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    raise FlagError(f"not a boolean: {v!r}")


def _define(name: str, default: Any, help_str: str,
            parser: Callable[[Any], Any], type_name: str) -> None:
    if name in _registry:
        raise FlagError(f"flag {name!r} already defined")
    flag = _Flag(name, default, help_str, parser, type_name)
    env = os.environ.get(_ENV_PREFIX + name.upper())
    if env is not None:
        flag.value = parser(env)
    _registry[name] = flag


def define_bool(name, default, help_str=""):
    _define(name, default, help_str, _parse_bool, "bool")


def define_int32(name, default, help_str=""):
    _define(name, default, help_str, lambda v: int(str(v), 0), "int32")


def define_float(name, default, help_str=""):
    _define(name, default, help_str, float, "float")


def define_string(name, default, help_str=""):
    _define(name, default, help_str, str, "string")


def get_flag(name: str) -> Any:
    return getattr(FLAGS, name)


def set_flags(values: Dict[str, Any]) -> None:
    """Bulk set, fluid's paddle.set_flags analogue."""
    for k, v in values.items():
        setattr(FLAGS, k, v)


def flags_registered() -> List[str]:
    return sorted(_registry)


def reset_flags() -> None:
    """Restore every flag to its registered default (tests)."""
    for flag in _registry.values():
        flag.value = flag.default


def parse_flags(argv: List[str]) -> List[str]:
    """Consume --name=value / --name value / --noname tokens from argv;
    returns the tokens that are not recognized flags (positional args and
    foreign options), matching gflags' remove_flags behaviour."""
    rest: List[str] = []
    i = 0
    while i < len(argv):
        tok = argv[i]
        if not tok.startswith("--"):
            rest.append(tok)
            i += 1
            continue
        body = tok[2:]
        name, eq, val = body.partition("=")
        if name in _registry:
            flag = _registry[name]
            if eq:
                flag.value = flag.parser(val)
            elif flag.type_name == "bool":
                flag.value = True
            elif i + 1 < len(argv):
                flag.value = flag.parser(argv[i + 1])
                i += 1
            else:
                raise FlagError(f"flag --{name} expects a value")
        elif name.startswith("no") and name[2:] in _registry \
                and _registry[name[2:]].type_name == "bool" and not eq:
            _registry[name[2:]].value = False
        else:
            rest.append(tok)
        i += 1
    return rest


def print_flags() -> str:
    lines = []
    for name in sorted(_registry):
        f = _registry[name]
        mark = "" if f.value == f.default else "  (set)"
        lines.append(f"--{name}={f.value!r}  [{f.type_name}] {f.help}{mark}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Core flags (the load-bearing subset of Flags.h:19-44 + per-file DEFINEs,
# translated to what exists on TPU).
# ---------------------------------------------------------------------------
define_bool("check_nan_inf", False,
            "scan fetched outputs and updated state for NaN/Inf each run "
            "(executor.cc:25 --check_nan_inf)")
define_bool("use_amp", False,
            "default bf16-compute/f32-master mixed precision for new "
            "programs (TPU analogue of the float16 plane)")
define_string("mxu_precision", "default",
              "MXU contraction precision: default | high | highest")
define_bool("fused_conv_epilogue", False,
            "lower NHWC 1x1/stride-1 conv+BN(+relu)(+residual) chains in "
            "models as the fused conv1x1_bn_act op (Pallas forward that "
            "computes BN stats in the conv pass and folds the epilogue "
            "into the output tile; ops/fusion_ops.py). Default off until "
            "the chip A/B lands (tools/chip_session_r5.py)")
define_string("compilation_cache_dir", "",
              "persist XLA compilations here (jax persistent cache): "
              "repeat runs of the same program skip the 20-40s "
              "first-compile; empty = in-memory only. Pair with a "
              "warmup manifest (core.manifest / tools/warmup.py) for "
              "zero-fresh-compile boots")
define_bool("verify_restored_donation", True,
            "verify donated-state write-back the first time an "
            "executable RESTORED from --compilation_cache_dir executes "
            "(vs its no-donation twin), falling back to the twin on "
            "mismatch — guards the jaxlib defect where deserialized CPU "
            "executables read freed donated buffers and NaN training "
            "state; the verdict persists in the cache dir so a fleet "
            "pays the check once per backend")
define_int32("warmup_concurrency", 4,
             "thread-pool width for AOT manifest replay "
             "(core.manifest.replay): XLA compilation is host-side and "
             "releases the GIL, so boot-time signature compiles overlap")
define_int32("seed", 0,
             "global graph RNG seed used when a program sets no "
             "random_seed of its own (ThreadLocalRand analogue); runs "
             "are deterministic for a fixed seed")
define_int32("log_period", 100,
             "default trainer log cadence in batches (Flags.h --log_period)")
define_bool("op_callsite", True,
            "record user file:line on every appended op for error "
            "reports (CustomStackTrace analogue); disable to shave "
            "graph-build time")
define_int32("trace_level", 0,
             "span-tracing level seeding trace.get_tracer() at import: "
             "0 off, 1 executor/serving/trainer spans, 2 additionally "
             "per-op interpret-mode debug runs (Executor.run walks the "
             "block op-by-op, locating NaN/Inf producers). Runtime flips "
             "go through trace.enable(level)")
define_bool("verify_program", False,
            "run the paddle_tpu.analysis program verifier + whole-program "
            "shape/dtype checker around every transpiler pass "
            "(PassManager verify_each — the pass that breaks a program "
            "is named), and on the programs the trainer, "
            "save_inference_model, and the serving engines are about to "
            "compile. Build-time cost only; on in CI")
define_bool("reduce_peak_memory", False,
            "append the memory-aware op-scheduling pass "
            "(transpiler.ReducePeakMemory) to the inference/deployment "
            "pipelines: topologically reorders ops to shrink the static "
            "peak-HBM watermark (bit-exact outputs; analysis.memory "
            "computes the watermark)")
define_string("fault_plan", "",
              "deterministic chaos plan for manual resilience drills, "
              "e.g. 'preempt@5,torn_checkpoint@3': kind@step entries "
              "(resilience/faults.py FAULT_KINDS) injected once each "
              "into the next SGD.train run; empty = no injection")
define_float("trace_sample_rate", 1.0,
             "fraction of trace roots kept by the span tracer "
             "(deterministic counter-based sampling, no RNG)")
define_int32("trace_buffer", 16384,
             "span ring-buffer capacity; oldest completed spans fall "
             "off — bounds tracing memory on long-lived servers")

"""Compactor/feeder: sealed joined segments -> master-queue task descs.

The last hop before training: each sealed ``joined-*.ptlog`` becomes ONE
task desc (``ctrlog:<records>:<path>``) whose :func:`task_reader`
re-reads the sealed file deterministically — the same
replay-on-reserve contract as ``dataset/ctr.py`` descs (a desc alone
regenerates its rows, so master requeue-on-timeout and elastic
skip-if-covered semantics hold unchanged). Rows come out in the ctr
feed shape ``(ids int64[SLOTS], dense float32[DENSE_DIM],
label float32[1])`` so the existing CTR topology trains on them as-is.

Enqueue protocol — the C++ master's ``set_dataset`` REPLACES the queue
(native/master.cc), so the compactor only feeds when the queue is fully
drained (todo == pending == 0), and records what it fed in an atomic
``enqueued.json`` manifest next to the segments: a restarted compactor
never re-feeds a segment, so a training example enters the master queue
at most once per feed decision.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, List, Optional

import numpy as np

from .log import read_records, sealed_segments, segment_meta

DESC_PREFIX = "ctrlog"


def task_desc(path: str, records: int) -> str:
    return f"{DESC_PREFIX}:{int(records)}:{path}"


def task_reader(desc: str):
    """Rows of one sealed joined segment, ctr-feed-shaped. A desc is
    self-sufficient: re-reading the sealed file yields the identical
    row stream every time (master requeue replays exactly)."""
    prefix, records, path = desc.split(":", 2)
    if prefix != DESC_PREFIX:
        raise ValueError(f"not a {DESC_PREFIX} desc: {desc!r}")
    n = int(records)
    for idx, ex in read_records(path):
        if idx >= n:
            break
        feats = ex.get("features") or {}
        ids = np.asarray(feats.get("ids", []), np.int64).reshape(-1)
        dense = np.asarray(feats.get("dense", []),
                           np.float32).reshape(-1)
        label = np.asarray([ex.get("label", 0.0)], np.float32)
        yield ids, dense, label


class Compactor:
    """Feed sealed joined segments to a master queue, exactly once.

    joined_dir:  the :class:`~paddle_tpu.feedback.join.OutcomeJoiner`
                 output directory.
    state_path:  the durable fed-segment manifest (default
                 ``<joined_dir>/enqueued.json``; atomic tmp+rename).
    """

    def __init__(self, joined_dir: str, *,
                 state_path: Optional[str] = None,
                 clock: Callable[[], float] = time.time):
        self.joined_dir = str(joined_dir)
        self.state_path = state_path or os.path.join(
            self.joined_dir, "enqueued.json")
        self.clock = clock
        self.segments_enqueued = 0
        self.examples_enqueued = 0
        self.last_enqueue_t: Optional[float] = None
        self._enqueued = set()
        self._load_state()

    def _load_state(self) -> None:
        try:
            with open(self.state_path) as fh:
                state = json.load(fh)
        except (OSError, ValueError):
            return
        self._enqueued = set(state.get("segments", []))
        self.segments_enqueued = len(self._enqueued)
        self.examples_enqueued = int(state.get("examples", 0))
        self.last_enqueue_t = state.get("t")

    def _save_state(self) -> None:
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"segments": sorted(self._enqueued),
                       "examples": self.examples_enqueued,
                       "t": self.last_enqueue_t}, fh)
        os.rename(tmp, self.state_path)

    # -- feeding -------------------------------------------------------
    def pending_descs(self) -> List[str]:
        descs = []
        for path in sealed_segments(self.joined_dir):
            if os.path.basename(path).startswith("joined-") \
                    and path not in self._enqueued:
                try:
                    n = int(segment_meta(path)["records"])
                except (OSError, ValueError, KeyError):
                    n = sum(1 for _ in read_records(path))
                if n:
                    descs.append(task_desc(path, n))
        return descs

    def enqueue(self, client, *, require_drained: bool = True
                ) -> List[str]:
        """Feed every not-yet-fed sealed segment as one dataset
        (set_dataset REPLACES the queue — only safe on a drained one).
        Returns the descs fed ([] when nothing new or not drained)."""
        if require_drained:
            counts = client.counts()
            if counts.get("todo", 0) or counts.get("pending", 0):
                return []
        descs = self.pending_descs()
        if not descs:
            return []
        client.set_dataset(descs)
        for d in descs:
            _, n, path = d.split(":", 2)
            self._enqueued.add(path)
            self.examples_enqueued += int(n)
        self.segments_enqueued = len(self._enqueued)
        self.last_enqueue_t = self.clock()
        self._save_state()
        return descs

    def stats(self) -> dict:
        return {"segments_enqueued": self.segments_enqueued,
                "examples_enqueued": self.examples_enqueued,
                "backlog_segments": len(self.pending_descs()),
                "last_enqueue_t": self.last_enqueue_t}


def loop_status(log_dir: str, joined_dir: str,
                ckpt_dir: Optional[str] = None,
                clock: Callable[[], float] = time.time) -> dict:
    """One offline snapshot of loop lag, stage by stage — what
    ``tools/loopctl.py`` prints and the loop-lag gauges sample:

    - log_lag_s:     age of the newest sealed impression segment
    - join_lag_s:    age of the newest sealed joined segment
    - train_lag_s:   age of the newest checkpoint generation
    - backlog:       sealed-but-unfed segments awaiting the compactor
    """
    now = clock()

    def _newest_seal(dirname):
        ts = []
        for p in sealed_segments(dirname):
            try:
                ts.append(float(segment_meta(p).get("t_sealed") or 0))
            except (OSError, ValueError):
                ts.append(os.path.getmtime(p))
        return max(ts) if ts else None

    status = {"t": now}
    t_log = _newest_seal(log_dir)
    status["log_lag_s"] = None if t_log is None else round(now - t_log, 3)
    t_join = _newest_seal(joined_dir)
    status["join_lag_s"] = (None if t_join is None
                            else round(now - t_join, 3))
    comp = Compactor(joined_dir)
    status["backlog_segments"] = len(comp.pending_descs())
    status["examples_enqueued"] = comp.examples_enqueued
    if ckpt_dir:
        from .. import checkpoint as ckpt_mod

        step = ckpt_mod.latest_step(ckpt_dir)
        status["trained_step"] = step
        if step is not None:
            info = ckpt_mod._step_info(ckpt_dir, f"ckpt-{step}.npz") or {}
            t_ck = info.get("timestamp")
            status["train_lag_s"] = (None if not t_ck
                                     else round(now - float(t_ck), 3))
    return status

"""paddle_tpu.feedback: the serving fleet as the online plane's data
source — serve -> log -> join-outcome -> train -> publish as ONE loop.

- :mod:`.log` — the impression log: a crash-safe, segmented,
  length-prefixed record log written by a serving-side hook on ``/v1/*``
  (:class:`FeedbackHook`); bounded buffer + drop counters keep the hook
  off the serving hot path.
- :mod:`.join` — the outcome joiner: ``POST /v1/outcome`` keyed by
  request id, windowed join with TTL'd pending state emitting labeled
  click/no-click examples; restart-safe by replaying sealed segments
  against the sealed-output coverage map (never a duplicate example).
- :mod:`.compact` — the compactor/feeder: sealed joined segments become
  ``dataset/ctr.py``-format task descs on the master queue, so elastic
  :class:`~paddle_tpu.online.StreamingTrainer`\\ s train on what the
  fleet actually served and :class:`~paddle_tpu.online.Publisher` ships
  the update back.
"""
from .compact import Compactor, loop_status, task_desc, task_reader
from .join import OutcomeJoiner
from .log import FeedbackHook, ImpressionLog, read_records, sealed_segments

__all__ = [
    "ImpressionLog", "FeedbackHook", "read_records", "sealed_segments",
    "OutcomeJoiner", "Compactor", "task_desc", "task_reader",
    "loop_status",
]

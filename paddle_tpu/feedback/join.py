"""Outcome joiner: windowed impression/outcome join with TTL'd state.

Impressions stream in from the :mod:`.log` sealed segments; outcomes
arrive via ``POST /v1/outcome`` (-> :meth:`OutcomeJoiner.post_outcome`),
keyed by request id. Each impression emits EXACTLY ONE labeled example:

- outcome inside the window  -> positive (the outcome's label),
- window expiry              -> negative (click/no-click semantics),
- outcome before impression  -> parked with its own TTL, joined the
  moment the impression lands (out-of-order HTTP arrival is normal),
- duplicate outcome          -> first wins, counted.

Durability — examples write to ``joined-%06d`` segments in the log.py
format; ONLY sealed segments are real. Every sealed joined meta carries
``source``: the exact per-impression-segment record indexes its
examples cover. On restart the joiner rebuilds coverage from sealed
metas, discards any ``.open`` joined tail (counted), and re-ingests
precisely the uncovered impressions — coverage is committed atomically
with the examples it describes, so an example can never be emitted
twice.

The in-memory join WINDOW is durable too: every pending impression,
parked outcome, and removal appends one length-prefixed record to a
``window.spill`` sidecar (same format as the segments, flushed per
write). A restart replays the sidecar — pending impressions keep their
ORIGINAL deadlines and parked outcomes their TTLs, so a joiner crash
no longer turns in-window outcomes into false negatives. The sidecar
is compacted (atomic tmp+rename of the live entries) whenever drops
dominate and at ``seal()``/``close()``.
"""
from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from .log import (OPEN_SUFFIX, SEALED_SUFFIX, read_records,
                  scan_segment, sealed_segments, segment_meta,
                  write_record)


class OutcomeJoiner:
    def __init__(self, log_dir: str, out_dir: str, *,
                 window_s: float = 30.0,
                 park_ttl_s: Optional[float] = None,
                 segment_records: int = 256,
                 negative_label: float = 0.0,
                 clock: Callable[[], float] = time.time):
        self.log_dir = str(log_dir)
        self.out_dir = str(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self.window_s = float(window_s)
        self.park_ttl_s = (2.0 * self.window_s if park_ttl_s is None
                           else float(park_ttl_s))
        self.segment_records = int(segment_records)
        self.negative_label = float(negative_label)
        self.clock = clock
        # counters
        self.ingested = 0
        self.joined = 0              # outcome met impression in-window
        self.parked_joins = 0        # ... where the outcome came first
        self.expired_negatives = 0
        self.duplicate_outcomes = 0
        self.orphan_outcomes = 0     # parked outcomes whose TTL lapsed
        self.replayed = 0            # re-ingested after restart
        self.discarded_open_examples = 0
        self.torn_source_bytes = 0
        self.window_spilled = 0      # window ops appended to the sidecar
        self.window_replayed = 0     # window entries restored on restart
        self.spill_errors = 0        # sidecar writes that failed (shed)
        # state
        self._lock = threading.RLock()
        #: rid -> (segment_name, record_idx, record, deadline)
        self._pending: Dict[str, Tuple[str, int, dict, float]] = {}
        self._parked: Dict[str, Tuple[dict, float]] = {}
        self._emitted_rids = set()
        #: segment_name -> set(record_idx) already durably emitted
        self._covered: Dict[str, set] = {}
        self._open_fh = None
        self._open_path: Optional[str] = None
        self._open_records = 0
        self._open_source: Dict[str, list] = {}
        self._next_seg = 0
        # crash-safe window sidecar (see module docstring)
        self._spill_path = os.path.join(self.out_dir, "window.spill")
        self._spill_fh = None
        self._spill_drops = 0
        self._recover()
        self._replay_window()

    # -- restart safety ------------------------------------------------
    def _recover(self) -> None:
        for sealed in sorted(glob.glob(
                os.path.join(self.out_dir, "joined-*" + SEALED_SUFFIX))):
            stem = os.path.basename(sealed)[
                len("joined-"):-len(SEALED_SUFFIX)]
            self._next_seg = max(self._next_seg, int(stem) + 1)
            try:
                src = segment_meta(sealed).get("source", {})
            except (OSError, ValueError):
                # sealed payload without its meta (crash between the two
                # renames): its source coverage is unknown — replaying
                # those impressions would DUPLICATE examples, so recover
                # coverage from the records themselves
                src = {}
                for _, rec in read_records(sealed):
                    src.setdefault(rec.get("source_segment", ""),
                                   []).append(rec.get("source_idx", -1))
            for seg, idxs in src.items():
                self._covered.setdefault(seg, set()).update(idxs)
        for torn in sorted(glob.glob(
                os.path.join(self.out_dir, "joined-*" + OPEN_SUFFIX))):
            records, _, lost = scan_segment(torn)
            stem = os.path.basename(torn)[len("joined-"):-len(OPEN_SUFFIX)]
            self._next_seg = max(self._next_seg, int(stem) + 1)
            # unsealed examples never reached the training plane: drop
            # them (counted); their source impressions stay uncovered
            # and re-ingest, so they are emitted exactly once
            self.discarded_open_examples += records
            self.torn_source_bytes += lost
            os.remove(torn)

    # -- window durability (the spill sidecar) -------------------------
    def _replay_window(self) -> None:
        """Rebuild the pending/parked window from ``window.spill``:
        replay ops in append order (last op per rid wins), skip
        anything coverage says was already durably emitted, keep the
        ORIGINAL deadlines — a restart continues the window, it does
        not restart it."""
        if not os.path.exists(self._spill_path):
            return
        pend: Dict[str, Tuple[str, int, dict, float]] = {}
        park: Dict[str, Tuple[dict, float]] = {}
        for _, op in read_records(self._spill_path):
            rid, kind = op.get("rid"), op.get("op")
            if rid is None:
                continue
            if kind == "pending":
                pend[rid] = (op["seg"], int(op["idx"]), op["rec"],
                             float(op["deadline"]))
                park.pop(rid, None)
            elif kind == "parked":
                park[rid] = (op["outcome"], float(op["deadline"]))
                pend.pop(rid, None)
            elif kind == "drop":
                pend.pop(rid, None)
                park.pop(rid, None)
        for rid, (seg, idx, rec, deadline) in pend.items():
            if idx in self._covered.get(seg, set()):
                continue  # its example is already sealed
            self._pending[rid] = (seg, idx, rec, deadline)
            self.window_replayed += 1
        for rid, (out, deadline) in park.items():
            self._parked[rid] = (out, deadline)
            self.window_replayed += 1
        self._compact_spill()

    def _spill(self, op: dict) -> None:
        """One flushed length-prefixed append; failures shed (counted)
        — durability of the window must never block the join path."""
        try:
            if self._spill_fh is None:
                self._spill_fh = open(self._spill_path, "ab")
            write_record(self._spill_fh, op)
            self._spill_fh.flush()
        except OSError:
            self.spill_errors += 1
            return
        self.window_spilled += 1
        if op.get("op") == "drop":
            self._spill_drops += 1
            live = len(self._pending) + len(self._parked)
            if self._spill_drops > 2 * live + 64:
                self._compact_spill()

    def _spill_drop(self, rid: str) -> None:
        self._spill({"op": "drop", "rid": rid})

    def _compact_spill(self) -> None:
        """Rewrite the sidecar as just the LIVE window (atomic
        tmp+rename, like every other commit in the feedback plane)."""
        if self._spill_fh is not None:
            self._spill_fh.close()
            self._spill_fh = None
        tmp = self._spill_path + ".tmp"
        try:
            with open(tmp, "wb") as fh:
                for rid, (seg, idx, rec, d) in self._pending.items():
                    write_record(fh, {"op": "pending", "rid": rid,
                                      "seg": seg, "idx": idx,
                                      "rec": rec, "deadline": d})
                for rid, (out, d) in self._parked.items():
                    write_record(fh, {"op": "parked", "rid": rid,
                                      "outcome": out, "deadline": d})
            os.replace(tmp, self._spill_path)
        except OSError:
            self.spill_errors += 1
            return
        self._spill_drops = 0

    # -- outcome ingress -----------------------------------------------
    def post_outcome(self, request_id: str, outcome) -> str:
        """'joined' | 'parked' | 'duplicate'. ``outcome`` is a label
        number or a dict with a ``label`` field (extra keys ride into
        the example)."""
        if isinstance(outcome, dict):
            label = float(outcome.get("label", 1.0))
            extra = {k: v for k, v in outcome.items() if k != "label"}
        else:
            label = 1.0 if outcome is None else float(outcome)
            extra = {}
        with self._lock:
            if request_id in self._emitted_rids \
                    or request_id in self._parked:
                self.duplicate_outcomes += 1
                return "duplicate"
            hit = self._pending.pop(request_id, None)
            if hit is not None:
                seg, idx, rec, _ = hit
                self._emit(seg, idx, rec, label, extra,
                           t_outcome=self.clock())
                self._spill_drop(request_id)
                self.joined += 1
                return "joined"
            entry = ({"label": label, "extra": extra, "t": self.clock()},
                     self.clock() + self.park_ttl_s)
            self._parked[request_id] = entry
            self._spill({"op": "parked", "rid": request_id,
                         "outcome": entry[0], "deadline": entry[1]})
            return "parked"

    # -- impression ingress --------------------------------------------
    def poll_once(self) -> dict:
        """Ingest new sealed impression segments, then run expiries.
        Returns a stats snapshot (what loopctl prints)."""
        with self._lock:
            for path in sealed_segments(self.log_dir):
                seg = os.path.basename(path)
                covered = self._covered.get(seg, set())
                for idx, rec in read_records(path):
                    if idx in covered:
                        continue
                    rid = rec.get("rid")
                    if rid is None or rid in self._emitted_rids \
                            or rid in self._pending:
                        continue
                    self.ingested += 1
                    if covered:
                        # this segment already has durable coverage: we
                        # are re-walking it after a restart
                        self.replayed += 1
                    park = self._parked.pop(rid, None)
                    if park is not None:
                        out, _ = park
                        self._emit(seg, idx, rec, out["label"],
                                   out["extra"], t_outcome=out["t"])
                        self._spill_drop(rid)
                        self.joined += 1
                        self.parked_joins += 1
                        continue
                    deadline = self.clock() + self.window_s
                    self._pending[rid] = (seg, idx, rec, deadline)
                    self._spill({"op": "pending", "rid": rid, "seg": seg,
                                 "idx": idx, "rec": rec,
                                 "deadline": deadline})
            self._expire()
        return self.stats()

    def _expire(self) -> None:
        now = self.clock()
        for rid in [r for r, (_, _, _, d) in self._pending.items()
                    if d <= now]:
            seg, idx, rec, _ = self._pending.pop(rid)
            self._emit(seg, idx, rec, self.negative_label, {},
                       t_outcome=None)
            self._spill_drop(rid)
            self.expired_negatives += 1
        for rid in [r for r, (_, d) in self._parked.items()
                    if d <= now]:
            self._parked.pop(rid)
            self._spill_drop(rid)
            self.orphan_outcomes += 1

    # -- example egress ------------------------------------------------
    def _emit(self, seg: str, idx: int, rec: dict, label: float,
              extra: dict, t_outcome: Optional[float]) -> None:
        rid = rec.get("rid")
        self._emitted_rids.add(rid)
        example = {
            "rid": rid, "label": float(label),
            "features": rec.get("features"),
            "served": rec.get("served"),
            "model": rec.get("model"),
            "weights_version": rec.get("weights_version"),
            "t_impression": rec.get("t"), "t_outcome": t_outcome,
            "source_segment": seg, "source_idx": idx,
        }
        if extra:
            example["outcome"] = extra
        if self._open_fh is None:
            self._open_path = os.path.join(
                self.out_dir, f"joined-{self._next_seg:06d}{OPEN_SUFFIX}")
            self._next_seg += 1
            self._open_fh = open(self._open_path, "wb")
            self._open_records = 0
            self._open_source = {}
        write_record(self._open_fh, example)
        self._open_fh.flush()
        self._open_records += 1
        self._open_source.setdefault(seg, []).append(idx)
        self._covered.setdefault(seg, set()).add(idx)
        if self._open_records >= self.segment_records:
            self._seal_open()

    def _seal_open(self) -> None:
        fh, self._open_fh = self._open_fh, None
        if fh is None:
            return
        fh.close()
        path, self._open_path = self._open_path, None
        sealed = path[:-len(OPEN_SUFFIX)] + SEALED_SUFFIX
        meta = {"records": self._open_records,
                "bytes": os.path.getsize(path),
                "source": {k: sorted(v)
                           for k, v in self._open_source.items()},
                "t_sealed": self.clock()}
        tmp = sealed[:-len(SEALED_SUFFIX)] + ".json.tmp"
        with open(tmp, "w") as out:
            json.dump(meta, out)
        os.rename(path, sealed)          # the commit point
        os.rename(tmp, sealed[:-len(SEALED_SUFFIX)] + ".json")
        self._open_records = 0
        self._open_source = {}

    def seal(self) -> None:
        """Seal the open joined segment so the compactor can feed it;
        compacts the window sidecar down to the live entries too."""
        with self._lock:
            self._seal_open()
            self._compact_spill()

    def close(self) -> None:
        self.seal()
        with self._lock:
            if self._spill_fh is not None:
                self._spill_fh.close()
                self._spill_fh = None

    # -- observability -------------------------------------------------
    def oldest_pending_s(self) -> float:
        with self._lock:
            if not self._pending:
                return 0.0
            now = self.clock()
            return max(0.0, now - min(
                d - self.window_s
                for (_, _, _, d) in self._pending.values()))

    def stats(self) -> dict:
        with self._lock:
            return {
                "ingested": self.ingested, "joined": self.joined,
                "parked_joins": self.parked_joins,
                "expired_negatives": self.expired_negatives,
                "duplicate_outcomes": self.duplicate_outcomes,
                "orphan_outcomes": self.orphan_outcomes,
                "replayed": self.replayed,
                "window_spilled": self.window_spilled,
                "window_replayed": self.window_replayed,
                "spill_errors": self.spill_errors,
                "discarded_open_examples":
                    self.discarded_open_examples,
                "pending": len(self._pending),
                "parked": len(self._parked),
                "oldest_pending_s": round(self.oldest_pending_s(), 6),
            }

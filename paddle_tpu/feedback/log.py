"""Impression log: crash-safe segmented record log + the serving hook.

Format — one directory of segments. The active segment is
``seg-%06d.open``: a stream of length-prefixed records (4-byte
little-endian payload length, then UTF-8 JSON). Sealing renames it
atomically to ``seg-%06d.ptlog`` and writes a ``seg-%06d.json`` meta
sidecar (record count, byte size, wall-clock bounds) — readers treat
ONLY sealed segments as durable, exactly like the checkpoint plane's
payload+meta commit protocol. A crash mid-write leaves a torn ``.open``
tail; recovery walks complete records and seals them, counting the
discarded bytes (the checkpoint walk-back, applied to logs).

Latency contract — :meth:`ImpressionLog.append` is one deque append
behind a lock: the serving thread never touches the disk. A background
writer drains the bounded buffer; when the buffer is full the record is
DROPPED and counted (``dropped``), never blocked on. The
bench_feedback_loop A/B pins the hook under 1% of serve cost.
"""
from __future__ import annotations

import glob
import io
import json
import os
import struct
import threading
import time
from collections import deque
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

_LEN = struct.Struct("<I")
OPEN_SUFFIX = ".open"
SEALED_SUFFIX = ".ptlog"


def _jsonable(obj):
    """Records may carry numpy arrays/scalars straight off the serving
    path (the hook defers conversion to the writer thread)."""
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return obj


def write_record(fh: io.BufferedWriter, record: dict) -> int:
    payload = json.dumps(_jsonable(record),
                         separators=(",", ":")).encode("utf-8")
    fh.write(_LEN.pack(len(payload)))
    fh.write(payload)
    return _LEN.size + len(payload)


def read_records(path: str) -> Iterator[Tuple[int, dict]]:
    """Yield ``(index, record)`` from a segment, stopping cleanly at a
    torn tail (short length word, short payload, or broken JSON)."""
    with open(path, "rb") as fh:
        i = 0
        while True:
            head = fh.read(_LEN.size)
            if len(head) < _LEN.size:
                return
            (n,) = _LEN.unpack(head)
            payload = fh.read(n)
            if len(payload) < n:
                return
            try:
                rec = json.loads(payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                return
            yield i, rec
            i += 1


def scan_segment(path: str) -> Tuple[int, int, int]:
    """(complete_records, complete_bytes, torn_bytes) — the walk-back
    probe recovery and the joiner's torn-tail accounting share."""
    total = os.path.getsize(path)
    records = clean = 0
    for _ in read_records(path):
        records += 1
    # recompute clean byte length by re-walking lengths only
    with open(path, "rb") as fh:
        for _ in range(records):
            (n,) = _LEN.unpack(fh.read(_LEN.size))
            fh.seek(n, os.SEEK_CUR)
        clean = fh.tell()
    return records, clean, total - clean


def sealed_segments(dirname: str) -> List[str]:
    return sorted(glob.glob(os.path.join(dirname, "*" + SEALED_SUFFIX)))


def segment_meta(path: str) -> dict:
    with open(os.path.splitext(path)[0] + ".json") as fh:
        return json.load(fh)


class ImpressionLog:
    """Bounded-buffer, background-written, segmented impression log.

    append() -> deque (never blocks; drops + counts past
    ``buffer_records``); the writer thread drains to the ``.open``
    segment and seals every ``segment_records`` records. On open, a
    leftover ``.open`` tail from a crashed writer is recovered: complete
    records re-seal as a ``torn=True`` segment, the ragged tail bytes
    are counted and discarded (``torn_lost_bytes``) — bounded, counted
    loss; never a corrupt read downstream.
    """

    def __init__(self, dirname: str, *, segment_records: int = 256,
                 buffer_records: int = 4096, flush_s: float = 0.02,
                 clock: Callable[[], float] = time.time):
        self.dirname = str(dirname)
        os.makedirs(self.dirname, exist_ok=True)
        self.segment_records = int(segment_records)
        self.flush_s = float(flush_s)
        self.clock = clock
        self.logged = 0            # accepted into the buffer
        self.written = 0           # on disk (open or sealed)
        self.dropped = 0           # buffer-full shed, counted not blocked
        self.sealed_count = 0
        self.torn_recovered = 0    # records saved from a crashed .open
        self.torn_lost_bytes = 0
        self._buf: deque = deque(maxlen=None)
        self._buffer_records = int(buffer_records)
        self._lock = threading.Lock()      # buffer + counters (hot path)
        self._io_lock = threading.Lock()   # segment file ops only
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._fh: Optional[io.BufferedWriter] = None
        self._open_path: Optional[str] = None
        self._open_records = 0
        self._open_t0: Optional[float] = None
        self._next_seg = 0
        self._recover()
        self._thread = threading.Thread(
            target=self._drain_loop, name="paddle-tpu-impression-log",
            daemon=True)
        self._thread.start()

    # -- recovery ------------------------------------------------------
    def _recover(self) -> None:
        for sealed in sealed_segments(self.dirname):
            stem = os.path.basename(sealed)[len("seg-"):-len(SEALED_SUFFIX)]
            self._next_seg = max(self._next_seg, int(stem) + 1)
            self.sealed_count += 1
        for torn in sorted(glob.glob(
                os.path.join(self.dirname, "seg-*" + OPEN_SUFFIX))):
            records, clean, lost = scan_segment(torn)
            stem = os.path.basename(torn)[len("seg-"):-len(OPEN_SUFFIX)]
            self._next_seg = max(self._next_seg, int(stem) + 1)
            if records == 0:
                os.remove(torn)
                self.torn_lost_bytes += lost
                continue
            if lost:
                with open(torn, "rb+") as fh:
                    fh.truncate(clean)
            self._seal_file(torn, records, torn=bool(lost),
                            lost_bytes=lost)
            self.torn_recovered += records
            self.torn_lost_bytes += lost

    # -- hot path ------------------------------------------------------
    def append(self, record: dict) -> bool:
        """Non-blocking: True when buffered, False (counted) when shed."""
        with self._lock:
            if len(self._buf) >= self._buffer_records:
                self.dropped += 1
                return False
            self._buf.append(record)
            self.logged += 1
        self._wake.set()
        return True

    # -- writer thread -------------------------------------------------
    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_s)
            self._wake.clear()
            self._drain()
        self._drain()

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._buf:
                    return
                rec = self._buf.popleft()
            with self._io_lock:
                self._write(rec)

    def _write(self, rec: dict) -> None:
        if self._fh is None:
            self._open_path = os.path.join(
                self.dirname, f"seg-{self._next_seg:06d}{OPEN_SUFFIX}")
            self._next_seg += 1
            self._fh = open(self._open_path, "wb")
            self._open_records = 0
            self._open_t0 = self.clock()
        write_record(self._fh, rec)
        self._fh.flush()
        self._open_records += 1
        self.written += 1
        if self._open_records >= self.segment_records:
            self._seal_open()

    def _seal_open(self) -> None:
        fh, self._fh = self._fh, None
        if fh is None:
            return
        fh.close()
        path, self._open_path = self._open_path, None
        self._seal_file(path, self._open_records, t0=self._open_t0)
        self._open_records = 0

    def _seal_file(self, path: str, records: int, *, torn: bool = False,
                   lost_bytes: int = 0,
                   t0: Optional[float] = None) -> None:
        sealed = path[:-len(OPEN_SUFFIX)] + SEALED_SUFFIX
        meta = {"records": records, "bytes": os.path.getsize(path),
                "torn": torn, "lost_bytes": lost_bytes,
                "t_first": t0, "t_sealed": self.clock()}
        tmp = sealed[:-len(SEALED_SUFFIX)] + ".json.tmp"
        with open(tmp, "w") as fh:
            json.dump(meta, fh)
        os.rename(path, sealed)          # the commit point
        os.rename(tmp, sealed[:-len(SEALED_SUFFIX)] + ".json")
        self.sealed_count += 1

    # -- control -------------------------------------------------------
    def flush(self, timeout: float = 5.0) -> None:
        """Wait until every buffered record is on disk."""
        deadline = time.monotonic() + timeout
        self._wake.set()
        while time.monotonic() < deadline:
            with self._lock:
                if not self._buf:
                    return
            time.sleep(0.002)

    def seal(self, timeout: float = 5.0) -> None:
        """Drain the buffer and seal the open segment (no-op if empty).
        Runs on the caller's thread after the writer drained, so the
        rename is ordered after every write."""
        self.flush(timeout)
        # brief settle: the writer may hold one popped record
        deadline = time.monotonic() + timeout
        while self._wake.is_set() and time.monotonic() < deadline:
            time.sleep(0.002)
        with self._io_lock:
            self._seal_open()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10.0)
        with self._io_lock:
            self._seal_open()

    def stats(self) -> dict:
        with self._lock:
            return {"logged": self.logged, "written": self.written,
                    "dropped": self.dropped, "buffered": len(self._buf),
                    "sealed_segments": self.sealed_count,
                    "torn_recovered": self.torn_recovered,
                    "torn_lost_bytes": self.torn_lost_bytes}

    def __enter__(self) -> "ImpressionLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class FeedbackHook:
    """The serving-side tap: one object a Server/MultiTenantServer/Fleet
    attaches (``attach_feedback``) to start producing impressions.

    ``on_served`` builds the impression record (request features, served
    outputs, model/tenant, weights_version from ``version_source``,
    trace id) and hands it to the log's non-blocking append — the whole
    hot-path cost is a deque append. ``joiner`` (optional) is what the
    ``POST /v1/outcome`` endpoint routes into.
    """

    def __init__(self, log: ImpressionLog, joiner=None,
                 version_source: Optional[Callable[[], object]] = None,
                 clock: Callable[[], float] = time.time):
        self.log = log
        self.joiner = joiner
        self.version_source = version_source
        self.clock = clock
        self._rid_lock = threading.Lock()
        self._rid_n = 0
        self._rid_prefix = f"r{os.getpid():x}-{int(clock() * 1e3) & 0xffffff:x}"

    def new_request_id(self) -> str:
        with self._rid_lock:
            self._rid_n += 1
            return f"{self._rid_prefix}-{self._rid_n}"

    def on_served(self, request_id: str, payload, result, *,
                  model: Optional[str] = None,
                  trace_id: Optional[str] = None) -> bool:
        version = None
        if self.version_source is not None:
            try:
                version = self.version_source()
            except Exception:  # noqa: BLE001 - never fail the request
                version = None
        return self.log.append({
            "rid": request_id, "t": self.clock(), "model": model,
            "weights_version": version, "trace": trace_id,
            "features": payload, "served": result})

    def stats(self) -> dict:
        s = self.log.stats()
        if self.joiner is not None:
            s["joiner"] = self.joiner.stats()
        return s

"""Profiling & scoped-timer observability.

Three reference subsystems in one TPU-native module (SURVEY.md §5.1):
- fluid profiler (/root/reference/paddle/platform/profiler.h:25-107,
  python/paddle/v2/fluid/profiler.py): ``profiler()`` context +
  ``RecordEvent``-style scoped events, reported as a per-name table
  (calls/total/min/max/avg ms).
- legacy Stat timers (/root/reference/paddle/utils/Stat.h:63-242
  REGISTER_TIMER + globalStat.printAllStatus): ``timer()`` accumulates into
  a process-global StatSet, dumped by ``print_all_status()`` — the trainer
  calls it at pass end like Trainer.cpp:449.
- nvprof hook (/root/reference/paddle/platform/cuda_profiler.h,
  fluid/profiler.py:19 cuda_profiler): ``xprof_trace`` wraps
  ``jax.profiler.trace`` — the TPU-native equivalent writes an xplane
  trace viewable in TensorBoard/XProf.

Timing on an async accelerator: default is host wall-time of the dispatch
(cheap; right for spotting python-side overhead). For device-inclusive
times pass the step's outputs as ``block_on`` — they are
block_until_ready'd before the clock stops, playing the role of the
reference's CUDA-event stream synchronisation.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Optional

_local = threading.local()


class _Stat:
    __slots__ = ("calls", "total", "min", "max", "kind")

    def __init__(self):
        self.calls = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        # "time" (seconds, displayed as ms) or "count" (exact raw
        # numbers). Fixed by the first sample; later samples of the
        # other kind are converted into this entry's display plane so
        # min/max stay in one unit.
        self.kind = None

    def add(self, v):
        self.calls += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    @property
    def scale(self):
        return 1.0 if self.kind == "count" else 1e3


class StatSet:
    """Named wall-time + count accumulators (the legacy globalStat).

    Two first-class kinds share the table: timers (seconds in, ms out)
    and counts (op-count deltas, sizes — exact numbers in AND out, no
    unit scaling). A name's kind is set by its first sample; the
    table/as_dict column shape is identical for both, so consumers that
    pin it (transpiler tests, serving /metrics) read counts from the
    ms-named columns as raw values."""

    def __init__(self):
        self._stats = defaultdict(_Stat)
        self._lock = threading.Lock()

    def add(self, name, dt):
        with self._lock:
            s = self._stats[name]
            if s.kind is None:
                s.kind = "time"
            # a timer sample on a count-kind name lands as ms (that
            # entry's display unit) instead of polluting min/max with
            # second-scaled values
            s.add(dt if s.kind == "time" else dt * 1e3)

    def add_count(self, name, n):
        """Record a unitless count (op-count deltas, sizes) as a
        first-class count entry: exact values, no ms scaling on
        readback. On a name already carrying timers the count is
        converted to that entry's ms plane (reads back as ``n``)."""
        with self._lock:
            s = self._stats[name]
            if s.kind is None:
                s.kind = "count"
            s.add(n if s.kind == "count" else n / 1e3)

    def reset(self):
        with self._lock:
            self._stats.clear()

    def kind_of(self, name):
        """'time' | 'count' | None (unknown name)."""
        with self._lock:
            s = self._stats.get(name)
            return s.kind if s else None

    def table(self):
        """Rows of (name, calls, total, min, max, avg) — ms for time
        entries, raw exact values for count entries."""
        with self._lock:
            rows = [
                (name, s.calls, s.total * s.scale, s.min * s.scale,
                 s.max * s.scale, s.total / s.calls * s.scale)
                for name, s in sorted(self._stats.items(),
                                      key=lambda kv: -kv[1].total
                                      * kv[1].scale)
            ]
        return rows

    def as_dict(self, prefix: str = ""):
        """JSON-safe export of the timer table (name -> calls/total/min/
        max/avg ms + kind), optionally filtered to names starting with
        ``prefix`` — how the serving /metrics endpoint surfaces its
        engine timers (serving/metrics.py merge_timer_dict). Count-kind
        entries read back exactly through the ms-named keys (the pinned
        shape)."""
        with self._lock:
            kinds = {name: s.kind for name, s in self._stats.items()}
        return {
            name: {"calls": calls, "total_ms": total, "min_ms": mn,
                   "max_ms": mx, "avg_ms": avg,
                   "kind": kinds.get(name, "time")}
            for name, calls, total, mn, mx, avg in self.table()
            if name.startswith(prefix)
        }

    def format(self):
        rows = self.table()
        if not rows:
            return "(no timers recorded)"
        head = f"{'name':<40}{'calls':>8}{'total ms':>12}{'min ms':>10}" \
               f"{'max ms':>10}{'avg ms':>10}"
        lines = [head, "-" * len(head)]
        for name, calls, total, mn, mx, avg in rows:
            lines.append(f"{name:<40}{calls:>8}{total:>12.3f}{mn:>10.3f}"
                         f"{mx:>10.3f}{avg:>10.3f}")
        return "\n".join(lines)


global_stat = StatSet()


def _device_sync(block_on):
    """Wait for device work: block on the given arrays (the reliable way —
    jit dispatch is async and there is no global device barrier for pure
    computations). ``block_on`` may be a zero-arg callable resolved at exit
    time, so a with-block can reference outputs it assigns inside:

        with timer("step", block_on=lambda: outs):
            outs = train_step()
    """
    import jax

    if callable(block_on):
        block_on = block_on()
    if block_on is not None:
        jax.block_until_ready(block_on)
    else:
        jax.effects_barrier()  # awaits effectful ops only


@contextlib.contextmanager
def timer(name: str, stat_set: Optional[StatSet] = None, sync: bool = False,
          block_on=None):
    """Scoped timer accumulating into the global StatSet (REGISTER_TIMER).

    Async-dispatch caveat: by default this measures host wall-time of the
    dispatch. To include device time, pass the step's output arrays — or a
    zero-arg callable returning them, e.g. ``block_on=lambda: outs`` where
    the with-block assigns ``outs`` — they are block_until_ready'd before
    the clock stops. ``sync=True`` without ``block_on`` only awaits
    effectful computations.
    """
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if sync or block_on is not None:
            _device_sync(block_on)
        (stat_set or global_stat).add(name, time.perf_counter() - t0)


def print_all_status(stat_set: Optional[StatSet] = None):
    print((stat_set or global_stat).format())


# ---------------------------------------------------------------------------
# Event profiler (fluid profiler parity)
# ---------------------------------------------------------------------------
class _Profile:
    def __init__(self, sync):
        self.stats = StatSet()
        self.sync = sync


def _active() -> Optional[_Profile]:
    return getattr(_local, "profile", None)


@contextlib.contextmanager
def record_event(name: str, block_on=None):
    """RAII event (platform/profiler.h:97 RecordEvent): no-op unless inside
    a ``profiler()`` context. Pass the step's outputs as ``block_on`` to
    include device time (see ``timer``)."""
    p = _active()
    if p is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if p.sync or block_on is not None:
            _device_sync(block_on)
        p.stats.add(name, time.perf_counter() - t0)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             sync: bool = False, print_report: bool = True):
    """Collect record_event timings and print the table on exit (mirrors
    fluid.profiler.profiler / EnableProfiler+DisableProfiler)."""
    p = _Profile(sync)
    prev = _active()
    _local.profile = p
    try:
        yield p
    finally:
        _local.profile = prev  # restore outer profiler when nested
        if print_report:
            print(p.stats.format())


@contextlib.contextmanager
def xprof_trace(logdir: str):
    """TPU hardware trace via jax.profiler (the nvprof/cuda_profiler
    analogue): writes an XProf/TensorBoard trace to ``logdir``."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def framework_op_stats(logdir: str, top: Optional[int] = None):
    """Parse an ``xprof_trace`` capture into per-op rows (the tooling
    behind PERF.md's breakdowns, made first-class): returns a list of
    dicts with name/type/occurrences/total_self_us/flop_rate/
    memory_bw_gbs/operational_intensity/bound_by, sorted by self time.

    Uses the XProf converter when present; raises a clear error
    otherwise (the trace itself is still viewable in TensorBoard).
    """
    import glob
    import json
    import os

    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                          "python")
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except Exception as exc:  # pragma: no cover - env-dependent
        raise RuntimeError(
            "framework_op_stats needs the xprof converter "
            "(pip package 'xprof'); the raw trace in "
            f"{logdir!r} is still viewable in TensorBoard") from exc
    planes = sorted(glob.glob(
        os.path.join(logdir, "plugins/profile/*/*.xplane.pb")))
    if not planes:
        raise FileNotFoundError(f"no xplane capture under {logdir!r}")
    data, _ = rtd.xspace_to_tool_data([planes[-1]], "framework_op_stats",
                                      {})
    table = json.loads(data)
    table = table[1] if isinstance(table, list) and len(table) > 1 else table
    cols = [c["label"] for c in table["cols"]]

    def col(row, label, default=None):
        try:
            return row[cols.index(label)]
        except (ValueError, IndexError):
            return default

    rows = []
    for r in table["rows"]:
        vals = [c.get("v") for c in r["c"]]
        rows.append({
            "name": col(vals, "Operation Name"),
            "type": col(vals, "Operation Type"),
            "occurrences": col(vals, "#Occurrences"),
            "total_self_us": col(vals, "Total self-time (us)"),
            "flop_rate_gflops": col(vals, "Model FLOP Rate (GFLOP/s)"),
            "memory_bw_gbs": col(vals, "Measured Memory BW (GBytes/Sec)"),
            "operational_intensity": col(vals,
                                         "Operational Intensity "
                                         "(FLOPs/Byte)"),
            "bound_by": col(vals, "Bound by"),
        })
    rows.sort(key=lambda d: -(d["total_self_us"] or 0.0))
    return rows[:top] if top else rows

"""Checkpoint & inference-model persistence.

Parity surface of /root/reference/python/paddle/v2/fluid/io.py:32-218
(save_vars/save_params/save_persistables, load_*, save_inference_model,
load_inference_model) and the save/load ops
(/root/reference/paddle/operators/save_op.cc, load_op.cc).

The TPU-native design difference: the reference emits save/load ops into a
program and runs them through the per-op executor; here persistence is a
host-side operation on the scope (device->host DMA + npz/pickle), since
serialisation is not compute and does not belong in an XLA computation.
Program serialisation uses a stable JSON-encodable dict (the analogue of the
ProgramDesc protobuf) so saved models are portable across processes.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.program import (Block, Operator, Parameter, Program, Variable,
                           default_main_program)
from .core.scope import Scope, global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "read_inference_model_meta",
    "program_to_dict", "program_from_dict", "prune_program",
    "transpile_saved_model", "quantize_inference_model",
]


# --------------------------------------------------------------------------
# Program (de)serialisation — ProgramDesc-protobuf equivalent
# --------------------------------------------------------------------------
def program_to_dict(program: Program) -> dict:
    blocks = []
    for b in program.blocks:
        blocks.append({
            "idx": b.idx,
            "parent_idx": b.parent_idx,
            "vars": [
                {
                    "name": v.name,
                    "shape": list(v.shape) if v.shape is not None else None,
                    "dtype": str(v.dtype),
                    "persistable": v.persistable,
                    "stop_gradient": v.stop_gradient,
                    "lod_level": v.lod_level,
                    "is_data": v.is_data,
                    "is_parameter": isinstance(v, Parameter),
                }
                for v in b.vars.values()
            ],
            "ops": [
                {"type": op.type, "inputs": op.inputs, "outputs": op.outputs,
                 "attrs": op.attrs}
                for op in b.ops
            ],
        })
    return {"blocks": blocks, "version": 1}


def program_from_dict(d: dict) -> Program:
    p = Program()
    p.blocks = []
    for bd in d["blocks"]:
        b = Block(p, bd["idx"], bd["parent_idx"])
        for vd in bd["vars"]:
            cls = Parameter if vd.get("is_parameter") else Variable
            v = cls(b, vd["name"], shape=vd["shape"], dtype=vd["dtype"],
                    persistable=vd["persistable"],
                    stop_gradient=vd["stop_gradient"],
                    lod_level=vd.get("lod_level", 0),
                    is_data=vd.get("is_data", False))
            b.vars[vd["name"]] = v
        for od in bd["ops"]:
            b.ops.append(Operator(b, od["type"], od["inputs"], od["outputs"],
                                  od["attrs"]))
        p.blocks.append(b)
    return p


# --------------------------------------------------------------------------
# Variable persistence
# --------------------------------------------------------------------------
def _is_persistable(v: Variable) -> bool:
    return v.persistable


def _is_parameter(v: Variable) -> bool:
    return isinstance(v, Parameter)


def save_vars(executor, dirname: str, main_program: Optional[Program] = None,
              vars: Optional[Sequence[Variable]] = None, predicate=None,
              scope: Optional[Scope] = None):
    """Save selected scope variables to ``dirname`` (one .npy per var +
    manifest), mirroring io.py save_vars semantics."""
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = [v for v in program.list_vars() if predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    manifest = []
    for v in vars:
        if not scope.has(v.name):
            continue
        arr = scope.get_numpy(v.name)
        fname = v.name.replace("/", "__")
        entry = {"name": v.name, "file": fname + ".npy"}
        if arr.dtype.kind == "V":
            # ml_dtypes (bf16/fp8) round-trip through np.save as raw void
            # ('|V2') and come back unreadable — store the integer bit
            # view and the logical dtype in the manifest instead.
            entry["dtype"] = str(arr.dtype)
            arr = arr.view(np.dtype(f"u{arr.dtype.itemsize}"))
        np.save(os.path.join(dirname, fname + ".npy"), arr)
        manifest.append(entry)
    with open(os.path.join(dirname, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f, indent=1)


def save_params(executor, dirname, main_program=None, scope=None):
    return save_vars(executor, dirname, main_program, None, _is_parameter, scope)


def save_persistables(executor, dirname, main_program=None, scope=None):
    return save_vars(executor, dirname, main_program, None, _is_persistable, scope)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              scope=None):
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = [v for v in program.list_vars() if predicate(v)]
    with open(os.path.join(dirname, "MANIFEST.json")) as f:
        manifest = {e["name"]: e for e in json.load(f)}
    import jax.numpy as jnp

    for v in vars:
        if v.name not in manifest:
            continue
        entry = manifest[v.name]
        arr = np.load(os.path.join(dirname, entry["file"]))
        if entry.get("dtype"):
            import ml_dtypes  # noqa: F401 — registers bfloat16/fp8 names

            arr = arr.view(np.dtype(entry["dtype"]))
        scope.set(v.name, jnp.asarray(arr))


def load_params(executor, dirname, main_program=None, scope=None):
    return load_vars(executor, dirname, main_program, None, _is_parameter, scope)


def load_persistables(executor, dirname, main_program=None, scope=None):
    return load_vars(executor, dirname, main_program, None, _is_persistable, scope)


# --------------------------------------------------------------------------
# Inference model: program pruning + save
# --------------------------------------------------------------------------
def prune_program(program: Program, feed_names: List[str],
                  fetch_names: List[str], for_test: bool = True) -> Program:
    """Slice the program to the subgraph producing ``fetch_names`` from
    ``feed_names`` (the reference's prune.cc / inference_optimize).

    Runs the transpiler's ``prune_pipeline`` on a clone: composite
    ``seg_fwd`` recompute segments flatten back to plain forward ops
    (checkpointing only matters when training, and a flat op list keeps
    the saved artifact consumable by every backend including the native
    C machine), ``for_test`` canonicalizes every ``is_test`` attr, and
    dead-op elimination takes the backward slice from the fetches."""
    from .transpiler import prune_pipeline

    return prune_pipeline(for_test=for_test).run(
        program.clone(), feed_names, fetch_names)


def save_inference_model(dirname: str, feeded_var_names: List[str],
                         target_vars: List[Variable], executor,
                         main_program: Optional[Program] = None, scope=None,
                         transpile: bool = True):
    """Prune to the inference subgraph, run the transpiler's inference
    pipeline (dropout→scale, constant folding, BN folding, fused-kernel
    rewrites — ``transpile=False`` restores the plain prune), and persist
    program + params (reference io.py:165 save_inference_model).

    Weight-rewriting passes write NEW names into a child scope; the
    caller's scope is never mutated."""
    program = main_program or default_main_program()
    fetch_names = [v.name if hasattr(v, "name") else v for v in target_vars]
    pruned = prune_program(program, feeded_var_names, fetch_names)
    save_scope = scope or global_scope()
    if transpile:
        from .transpiler import inference_pipeline

        work_scope = Scope(parent=save_scope)
        pruned = inference_pipeline().run(
            pruned, feeded_var_names, fetch_names, scope=work_scope)
        save_scope = work_scope
    from .flags import FLAGS

    if FLAGS.verify_program:
        # never persist an artifact the verifier rejects: the saved model
        # is the contract every serving replica loads
        from . import analysis

        analysis.check_program(pruned, feeded_var_names, fetch_names,
                               scope=save_scope, annotate=False)
    os.makedirs(dirname, exist_ok=True)
    _drop_stale_manifest(dirname)
    with open(os.path.join(dirname, "__model__.json"), "w") as f:
        json.dump({
            "program": program_to_dict(pruned),
            "feed_names": feeded_var_names,
            "fetch_names": fetch_names,
        }, f)
    save_vars(executor, os.path.join(dirname, "params"),
              main_program=pruned, predicate=_is_persistable,
              scope=save_scope)


def _drop_stale_manifest(dirname: str) -> None:
    """Re-saving an artifact invalidates its warmup manifest: the old
    signatures reference the previous program's digest, and leaving them
    would make every future boot skip-replay (or merge-accumulate stale
    records forever). The next warmup writes a fresh one."""
    from .core.manifest import MANIFEST_NAME

    try:
        os.remove(os.path.join(dirname, MANIFEST_NAME))
    except OSError:
        pass


def _load_saved_params(dirname: str) -> Scope:
    """Load a saved model's params/ directory into a fresh host Scope
    (numpy arrays; no executor involved) for offline transpilation."""
    scope = Scope()
    with open(os.path.join(dirname, "params", "MANIFEST.json")) as f:
        manifest = json.load(f)
    for entry in manifest:
        arr = np.load(os.path.join(dirname, "params", entry["file"]))
        if entry.get("dtype"):
            import ml_dtypes  # noqa: F401 — registers bfloat16/fp8 names

            arr = arr.view(np.dtype(entry["dtype"]))
        scope.set(entry["name"], arr)
    return scope


def transpile_saved_model(dirname: str, out_dirname: str, pipeline=None):
    """Re-run a transpile pipeline over an already-saved inference model,
    writing a new saved-model directory. Defaults to the transpiler's
    ``deployment_pipeline`` — the portable form with fused ops lowered
    back to folded conv2d + bias add, which is what int8 weight
    quantization and the native C machine want. Returns the PassManager
    (``.stats()`` has the per-pass numbers)."""
    from .transpiler import deployment_pipeline

    with open(os.path.join(dirname, "__model__.json")) as f:
        payload = json.load(f)
    program = program_from_dict(payload["program"])
    scope = _load_saved_params(dirname)
    pm = pipeline or deployment_pipeline()
    program = pm.run(program, payload["feed_names"],
                     payload["fetch_names"], scope=scope)
    os.makedirs(out_dirname, exist_ok=True)
    with open(os.path.join(out_dirname, "__model__.json"), "w") as f:
        json.dump({
            "program": program_to_dict(program),
            "feed_names": payload["feed_names"],
            "fetch_names": payload["fetch_names"],
        }, f)
    save_vars(None, os.path.join(out_dirname, "params"),
              main_program=program, predicate=_is_persistable, scope=scope)
    return pm


def quantize_inference_model(dirname: str, out_dirname: str,
                             min_elems: int = 1024,
                             transpile: bool = True) -> List[str]:
    """Weight-only per-output-channel int8 quantization of a saved
    inference model, for the C machine (beyond-reference; the reference
    era predates int8 deployment).

    Eligible weights (>= ``min_elems`` f32 elements) are per-output-
    channel symmetric int8 (scale = max|w over channel| / 127), recorded
    in ``__quant__.json`` sidecars; everything else copies through:
    - 2-D params used EXCLUSIVELY as ``mul`` right-hand sides (fc / qkv
      / head projections, the bulk of LM bytes): the C machine keeps the
      int8 bytes resident and folds the scales into the matmul epilogue
      — ~4x serving memory AND artifact size;
    - 4-D params used exclusively as ``conv2d`` filters (one consistent
      data_format): int8 in the artifact, dequantized once at load
      (filters are small next to activations — the win is the shipped
      bytes).
    Weights with any other/shared use stay f32. The quantized directory
    is C-machine-only (the Python executor load path expects the f32
    manifest).

    ``transpile`` (default) first runs the transpiler's deployment
    pipeline over the saved model: batch_norm folds into the preceding
    conv/mul weights and fused ``conv1x1_bn_act`` ops lower to plain
    folded conv2d — so weights that were locked up in BN-adjacent or
    fused forms become int8-eligible (strictly more parameter bytes
    quantize on conv+BN models)."""
    import shutil
    import tempfile

    tmpdir = None
    if transpile:
        tmpdir = tempfile.mkdtemp(prefix="quant_transpile_")
        transpile_saved_model(dirname, tmpdir)
        dirname = tmpdir
    try:
        return _quantize_saved_model(dirname, out_dirname, min_elems)
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def _quantize_saved_model(dirname: str, out_dirname: str,
                          min_elems: int) -> List[str]:
    import shutil

    with open(os.path.join(dirname, "__model__.json")) as f:
        payload = json.load(f)
    # a param is eligible only if EVERY reference to it is mul's Y slot
    # (int8 stays resident) or conv2d's Filter slot with one consistent
    # data_format (int8 on disk, dequantized once at load)
    usage: dict = {}
    for op in payload["program"]["blocks"][0]["ops"]:
        for slot, names in op["inputs"].items():
            for n in names:
                if op["type"] == "mul" and slot == "Y":
                    kind = "mul"
                elif op["type"] == "conv2d" and slot == "Filter":
                    kind = "conv:" + op["attrs"].get("data_format",
                                                     "NCHW")
                else:
                    kind = "no"
                prev = usage.setdefault(n, kind)
                if prev != kind:
                    usage[n] = "no"
    os.makedirs(os.path.join(out_dirname, "params"), exist_ok=True)
    shutil.copyfile(os.path.join(dirname, "__model__.json"),
                    os.path.join(out_dirname, "__model__.json"))
    with open(os.path.join(dirname, "params", "MANIFEST.json")) as f:
        manifest = json.load(f)
    kept, quant, quantized = [], [], []
    for entry in manifest:
        arr = None
        kind = usage.get(entry["name"], "no")
        if "dtype" in entry or kind == "no":
            eligible = False  # bf16 bit-view / shared or unknown use
        else:
            arr = np.load(os.path.join(dirname, "params", entry["file"]))
            want_ndim = 2 if kind == "mul" else 4
            eligible = (arr.dtype == np.float32
                        and arr.ndim == want_ndim
                        and arr.size >= min_elems)
        if not eligible:
            shutil.copyfile(os.path.join(dirname, "params", entry["file"]),
                            os.path.join(out_dirname, "params",
                                         entry["file"]))
            kept.append(entry)
            continue
        if kind == "mul":
            reduce_axes, out_axis = (0,), 1
        else:  # conv filters: OIHW for NCHW, HWIO for NHWC
            out_axis = 0 if kind.endswith("NCHW") else 3
            reduce_axes = tuple(a for a in range(4) if a != out_axis)
        scales = np.maximum(np.abs(arr).max(axis=reduce_axes),
                            1e-12) / 127.0
        bshape = tuple(-1 if a == out_axis else 1 for a in range(arr.ndim))
        q = np.clip(np.round(arr / scales.reshape(bshape)), -127,
                    127).astype(np.int8)
        base = entry["file"][:-4]
        qfile, sfile = base + ".int8.bin", base + ".scale.bin"
        q.tofile(os.path.join(out_dirname, "params", qfile))
        scales.astype(np.float32).tofile(
            os.path.join(out_dirname, "params", sfile))
        rec = {"name": entry["name"], "qfile": qfile, "sfile": sfile,
               "kind": "mul" if kind == "mul" else "conv",
               "shape": [int(d) for d in arr.shape],
               "out_axis": out_axis}
        if kind == "mul":
            rec["rows"], rec["cols"] = int(arr.shape[0]), int(arr.shape[1])
        quant.append(rec)
        quantized.append(entry["name"])
    with open(os.path.join(out_dirname, "params", "MANIFEST.json"),
              "w") as f:
        json.dump(kept, f, indent=1)
    with open(os.path.join(out_dirname, "__quant__.json"), "w") as f:
        json.dump(quant, f, indent=1)
    return quantized


def read_inference_model_meta(dirname: str) -> dict:
    """Read a saved inference model's metadata WITHOUT loading parameters:
    returns ``{"program": <program dict>, "feed_names": [...],
    "fetch_names": [...]}``. The serving engines use this to derive
    shape buckets and decode hyperparameters (attrs + var shapes live in
    the program dict) before deciding how to place the weights."""
    with open(os.path.join(dirname, "__model__.json")) as f:
        return json.load(f)


def load_inference_model(dirname: str, executor, scope=None):
    """Returns (program, feed_names, fetch_names); parameters are loaded into
    the scope (reference io.py load_inference_model)."""
    with open(os.path.join(dirname, "__model__.json")) as f:
        payload = json.load(f)
    program = program_from_dict(payload["program"])
    load_vars(executor, os.path.join(dirname, "params"),
              main_program=program, predicate=_is_persistable, scope=scope)
    return program, payload["feed_names"], payload["fetch_names"]

"""Fused 1x1-conv + BN-epilogue Pallas kernels.

PERF.md's ResNet-50 roofline: the bs256 train step is HBM-bound, with
~8 GB/step of bare elementwise traffic (residual adds) and the BN
normalize reading/writing every conv output around the dot. The
reference runs these as separate cudnn conv + BN + eltwise kernels
(/root/reference/paddle/operators/conv_cudnn_op.cu.cc,
batch_norm_op.cc, elementwise_add_op.cc); XLA fuses better than cudnn
but still materializes the raw conv output around the training-mode BN
reduction. These kernels attack the structure directly:

- ``conv1x1_stats``: one pass computing y_raw = x @ W while
  accumulating the per-channel sum and sum-of-squares in VMEM across
  the R grid — the BN statistics come out of the SAME pass that writes
  the conv output, removing the separate stats-reduce read of y_raw.
- ``scale_shift_act``: one elementwise pass y = act(y*scale+shift+res)
  applying the folded BN affine, the residual add, and the activation
  in a single read/write — where XLA's scheduler leaves the residual
  fork as its own kernel (the measured 11.2 ms/step), this folds it.
- ``conv1x1_epilogue``: the inference-mode full fusion — running stats
  are known up front, so the affine+act+residual ride in the dot
  kernel's output tile and the raw conv output NEVER touches HBM.

Everything falls back to plain XLA ops when shapes don't tile or the
backend is not TPU (CPU tests run the pallas path in interpret mode).
The backward stays XLA: the fused-linear-backward tombstone (PERF.md)
showed hand-written backward contractions lose under the 16 MB
scoped-vmem limit; forward epilogue fusion does not fight that wall
because the weight tile is small and the accumulator is [2, O].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM_BUDGET = 12 * 1024 * 1024


def _pick_block_r(R: int, I: int, O: int, itemsize: int) -> int:
    """Largest R tile dividing R that fits the VMEM budget (0 = none)."""
    fixed = I * O * itemsize + 2 * O * 4  # weight tile + stats accum
    if fixed > _VMEM_BUDGET:
        return 0
    for b in (1024, 512, 256, 128):
        if R % b:
            continue
        tiles = b * I * itemsize * 2 + 2 * b * O * itemsize
        if fixed + tiles <= _VMEM_BUDGET:
            return b
    return 0


def _stats_kernel(x_ref, w_ref, y_ref, stat_ref, acc_ref, *, nsteps,
                  precision):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    y = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    acc_ref[0, :] += jnp.sum(y, axis=0)
    acc_ref[1, :] += jnp.sum(y * y, axis=0)

    @pl.when(step == nsteps - 1)
    def _done():
        stat_ref[...] = acc_ref[...]


def conv1x1_stats(x2, w, precision=None, interpret=False):
    """y_raw = x2 @ w plus per-channel (sum, sumsq) in one pass.

    x2: [R, I]; w: [I, O]. Returns (y_raw [R, O] in x2.dtype,
    stats [2, O] f32). Falls back to XLA when the shape doesn't tile.
    """
    R, I = x2.shape
    O = w.shape[1]
    block_r = _pick_block_r(R, I, O, x2.dtype.itemsize)
    on_tpu = jax.default_backend() == "tpu"
    if block_r == 0 or not (on_tpu or interpret):
        y = jax.lax.dot_general(x2, w, (((1,), (0,)), ((), ())),
                                precision=precision,
                                preferred_element_type=jnp.float32)
        stats = jnp.stack([jnp.sum(y, axis=0), jnp.sum(y * y, axis=0)])
        return y.astype(x2.dtype), stats
    nsteps = R // block_r
    y, stats = pl.pallas_call(
        functools.partial(_stats_kernel, nsteps=nsteps,
                          precision=precision),
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((block_r, I), lambda i: (i, 0)),
            pl.BlockSpec((I, O), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_r, O), lambda i: (i, 0)),
            pl.BlockSpec((2, O), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, O), x2.dtype),
            jax.ShapeDtypeStruct((2, O), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((2, O), jnp.float32)],
        interpret=interpret,
    )(x2, w)
    return y, stats


def _epilogue_kernel(x_ref, w_ref, sc_ref, sh_ref, res_ref, o_ref, *,
                     act, precision):
    y = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32)
    y = y * sc_ref[...] + sh_ref[...]
    if res_ref is not None:
        y = y + res_ref[...].astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


def conv1x1_epilogue(x2, w, scale, shift, residual=None, act=None,
                     precision=None, interpret=False):
    """Inference-mode full fusion: act((x2 @ w) * scale + shift [+ res]).

    The raw conv output never reaches HBM. scale/shift are the folded
    BN affine ([O] f32): scale = gamma*rsqrt(var+eps),
    shift = beta - mean*scale.
    """
    R, I = x2.shape
    O = w.shape[1]
    block_r = _pick_block_r(R, I, O, x2.dtype.itemsize)
    on_tpu = jax.default_backend() == "tpu"
    if block_r == 0 or not (on_tpu or interpret):
        y = jax.lax.dot_general(x2, w, (((1,), (0,)), ((), ())),
                                precision=precision,
                                preferred_element_type=jnp.float32)
        y = y * scale + shift
        if residual is not None:
            y = y + residual.astype(jnp.float32)
        if act == "relu":
            y = jnp.maximum(y, 0.0)
        return y.astype(x2.dtype)
    nsteps = R // block_r
    ins = [x2, w, scale.reshape(1, O).astype(jnp.float32),
           shift.reshape(1, O).astype(jnp.float32)]
    in_specs = [
        pl.BlockSpec((block_r, I), lambda i: (i, 0)),
        pl.BlockSpec((I, O), lambda i: (0, 0)),
        pl.BlockSpec((1, O), lambda i: (0, 0)),
        pl.BlockSpec((1, O), lambda i: (0, 0)),
    ]
    if residual is not None:
        ins.append(residual)
        in_specs.append(pl.BlockSpec((block_r, O), lambda i: (i, 0)))
        kern = functools.partial(_epilogue_kernel, act=act,
                                 precision=precision)
    else:
        def kern(x_ref, w_ref, sc_ref, sh_ref, o_ref):
            return _epilogue_kernel(x_ref, w_ref, sc_ref, sh_ref, None,
                                    o_ref, act=act, precision=precision)
    return pl.pallas_call(
        kern,
        grid=(nsteps,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_r, O), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, O), x2.dtype),
        interpret=interpret,
    )(*ins)


def _apply_kernel(y_ref, sc_ref, sh_ref, res_ref, o_ref, *, act):
    y = y_ref[...].astype(jnp.float32) * sc_ref[...] + sh_ref[...]
    if res_ref is not None:
        y = y + res_ref[...].astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


def scale_shift_act(y_raw, scale, shift, residual=None, act=None,
                    interpret=False):
    """One elementwise pass: act(y_raw*scale + shift [+ residual]).

    Folds the BN affine, the residual fork, and the activation into a
    single read/write of the [R, O] activation.
    """
    R, O = y_raw.shape
    block_r = 0
    itemsize = y_raw.dtype.itemsize
    # Mirror _pick_block_r's accounting: every R-streamed tile (y_raw in,
    # y out, optional residual in) is DOUBLE-BUFFERED by Pallas while the
    # grid walks R — 2 streams without a residual, 3 with one, i.e.
    # ~4-6x b*O*itemsize resident, not the single-copy 3x the old
    # estimate assumed (which overshot the budget and silently fell back
    # to XLA at sizes that actually fit, and vice versa near the edge).
    streams = 3 if residual is not None else 2
    fixed = 2 * O * 4  # scale + shift f32 rows, revisited (not streamed)
    for b in (2048, 1024, 512, 256, 128):
        if R % b == 0 and (2 * streams * b * O * itemsize + fixed) \
                <= _VMEM_BUDGET:
            block_r = b
            break
    on_tpu = jax.default_backend() == "tpu"
    if block_r == 0 or not (on_tpu or interpret):
        y = y_raw.astype(jnp.float32) * scale + shift
        if residual is not None:
            y = y + residual.astype(jnp.float32)
        if act == "relu":
            y = jnp.maximum(y, 0.0)
        return y.astype(y_raw.dtype)
    nsteps = R // block_r
    ins = [y_raw, scale.reshape(1, O).astype(jnp.float32),
           shift.reshape(1, O).astype(jnp.float32)]
    in_specs = [
        pl.BlockSpec((block_r, O), lambda i: (i, 0)),
        pl.BlockSpec((1, O), lambda i: (0, 0)),
        pl.BlockSpec((1, O), lambda i: (0, 0)),
    ]
    if residual is not None:
        ins.append(residual)
        in_specs.append(pl.BlockSpec((block_r, O), lambda i: (i, 0)))
        kern = functools.partial(_apply_kernel, act=act)
    else:
        def kern(y_ref, sc_ref, sh_ref, o_ref):
            return _apply_kernel(y_ref, sc_ref, sh_ref, None, o_ref,
                                 act=act)
    return pl.pallas_call(
        kern,
        grid=(nsteps,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_r, O), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(y_raw.shape, y_raw.dtype),
        interpret=interpret,
    )(*ins)

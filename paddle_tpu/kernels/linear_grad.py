"""Fused backward for linear / 1x1-conv layers: one Pallas pass per R-tile
computing BOTH input and weight gradients.

    dX = dY @ W^T        [R, O] x [I, O]^T -> [R, I]
    dW = X^T @ dY        [R, I]^T x [R, O] -> [I, O]   (f32 VMEM accumulator)

Why this kernel exists: XLA emits the two gradient contractions of a linear
layer as separate kernels, each re-streaming dY from HBM and laying the
weight-grad contraction (over the huge R = batch*spatial axis) out with
physical relayouts. On a v5e these backward contractions are the single
largest consumer of HBM bandwidth in ResNet-class training (43 ms of a 104 ms
bs256 step, running at 90% of HBM peak — PERF.md round 3). Fusing them reads
X and dY exactly once, keeps the [I, O] weight-grad accumulator resident in
VMEM across the R-grid in f32, and never materialises a transpose.

The reference hits the same structure with cuBLAS GEMMs per gradient
(/root/reference/paddle/operators/mul_op.cc grad kernels,
conv_cudnn_op.cu.cc backward-data/backward-filter); the TPU-native answer is
one Mosaic kernel per layer rather than two library calls.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _linear_bwd_kernel(x_ref, dy_ref, w_ref, dx_ref, dw_ref, acc_ref, *,
                       nsteps, precision):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    dy = dy_ref[...]
    # dX tile: contract dY's O axis with W's O axis -> [block_r, I].
    dx_ref[...] = jax.lax.dot_general(
        dy, w_ref[...], (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32).astype(dx_ref.dtype)
    # dW: contract the R axis of this tile; accumulate across the grid.
    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], dy, (((0,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)

    @pl.when(step == nsteps - 1)
    def _done():
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


# VMEM the kernel may claim. The binding constraint is NOT the chip's
# 128 MB VMEM but XLA's scoped-vmem allocation limit for custom calls
# (16 MB by default — exceeding it is a hard compile error: "Scoped
# allocation ... exceeded scoped vmem limit", measured on chip). Stay
# under it with headroom; shapes that don't fit (e.g. FFN-sized [I, O]
# weight-resident accumulators) fall back to the two XLA dots.
_VMEM_BUDGET = 14 * 1024 * 1024


def _pick_block(R: int, I: int, O: int, xb: int, yb: int, wb: int) -> int:
    """Largest R tile that divides R and fits the VMEM budget; 0 = none
    (weight-resident footprint alone too big, or R untileable)."""
    # weight-resident cost: w block + dw block + f32 accumulator
    fixed = I * O * (wb + wb + 4)
    if fixed > _VMEM_BUDGET:
        return 0
    for b in (1024, 512, 256, 128):
        if R % b:
            continue
        # x, dy in (double-buffered), dx out
        tiles = b * I * xb * 2 + b * O * yb * 2 + b * I * xb
        if fixed + tiles <= _VMEM_BUDGET:
            return b
    return 0


def linear_bwd(x, dy, w, precision=None):
    """(dX, dW) for y = x @ w.  x: [R, I], dy: [R, O], w: [I, O].

    Falls back to two XLA dots when shapes don't tile (non-128 R multiples)
    or the weight-resident VMEM footprint doesn't fit (e.g. vocab-sized
    heads, where XLA's own tiling over O is the right schedule anyway).
    """
    from ..flags import FLAGS

    R, I = x.shape
    O = w.shape[1]
    use_pallas = FLAGS.fused_linear_grad and jax.default_backend() == "tpu"
    block_r = (_pick_block(R, I, O, x.dtype.itemsize, dy.dtype.itemsize,
                           w.dtype.itemsize)
               if use_pallas else 0)
    if block_r == 0:
        dx = jax.lax.dot_general(dy, w, (((1,), (1,)), ((), ())),
                                 precision=precision)
        dw = jax.lax.dot_general(x, dy, (((0,), (0,)), ((), ())),
                                 precision=precision)
        return dx.astype(x.dtype), dw.astype(w.dtype)
    nsteps = R // block_r
    dx, dw = pl.pallas_call(
        functools.partial(_linear_bwd_kernel, nsteps=nsteps,
                          precision=precision),
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((block_r, I), lambda i: (i, 0)),
            pl.BlockSpec((block_r, O), lambda i: (i, 0)),
            pl.BlockSpec((I, O), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_r, I), lambda i: (i, 0)),
            pl.BlockSpec((I, O), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, I), x.dtype),
            jax.ShapeDtypeStruct((I, O), w.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((I, O), jnp.float32)],
    )(x, dy, w)
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def linear2d(x, w, precision=None):
    """y = x @ w with the fused Pallas backward. x: [R, I], w: [I, O]."""
    return jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                               precision=precision)


def _linear2d_fwd(x, w, precision):
    return linear2d(x, w, precision), (x, w)


def _linear2d_bwd(precision, res, g):
    x, w = res
    dx, dw = linear_bwd(x, g.astype(x.dtype), w, precision=precision)
    return dx, dw


linear2d.defvjp(_linear2d_fwd, _linear2d_bwd)

"""Flash attention: Pallas TPU kernel with online softmax.

The long-context workhorse. The reference framework predates Transformers
(SURVEY.md §5.7) — its closest analogues are the fused CUDA cell kernels
(/root/reference/paddle/cuda/src/hl_cuda_lstm.cu) whose role (keep the hot
loop's working set on-chip instead of round-tripping HBM) this kernel plays
for attention: O(T^2) scores never materialise in HBM; each (batch*head,
q-block) grid cell streams K/V blocks through VMEM, maintaining the running
max/denominator of the softmax (the standard online-softmax recurrence), so
HBM traffic is O(T*d) instead of O(T^2).

On non-TPU backends (the CPU test mesh) ``flash_attention`` falls back to a
pure-jnp reference — same semantics, XLA-fused — for both passes. On TPU
the BACKWARD is also Pallas (``_flash_dq_kernel`` / ``_flash_dkv_kernel``):
p-tiles are recomputed from the forward's saved logsumexp per block, so the
backward's HBM traffic stays O(T*d) like the forward's. (The earlier
jnp-recompute backward materialised the [T, T] probabilities and made
transformer training HBM-bound — 180 GB/step at d1024/L8/T2048 — see
PERF.md.)
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# Large blocks amortise the per-iteration VPU work (masking, exp, online
# rescale) over more MXU work — the d=64 head dim makes the matmuls thin,
# so the block sizes carry the efficiency. Device-traced sweep at
# bs8/h16/T2048/d64 fwd+bwd: 512x512 7.9 ms, 256x512 9.0, 512x256 10.5,
# 256x256 12.1 (PERF.md).
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _pick_block(t, preferred):
    b = min(preferred, t)
    while t % b:
        b //= 2
    return max(b, 1)


def rotary(x, pos0=0, base=10000.0):
    """Rotary position embedding over [B, H, T, D] heads, positions
    pos0..pos0+T-1 (RoFormer pairing: (x[2i], x[2i+1]) rotates by
    pos * base^(-2i/D)). The single source of truth for RoPE math — the
    per-layer encoder op and the stacked/decode path both call it; the
    offset form serves incremental decode. ``pos0`` may be a [B] array
    of PER-ROW offsets (the slot-decode path, where every batch row sits
    at its own sequence position)."""
    D = x.shape[-1]
    T = x.shape[2]
    half = D // 2
    inv = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos0 = jnp.asarray(pos0, jnp.float32)
    if pos0.ndim:  # per-row offsets: [B] -> angles [B, T, half]
        pos = pos0[:, None] + jnp.arange(T, dtype=jnp.float32)[None, :]
        ang = pos[:, :, None] * inv[None, None, :]
        cos = jnp.cos(ang)[:, None].astype(x.dtype)  # [B, 1, T, half]
        sin = jnp.sin(ang)[:, None].astype(x.dtype)
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        r1 = x1 * cos - x2 * sin
        r2 = x1 * sin + x2 * cos
        return jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    pos = pos0 + jnp.arange(T, dtype=jnp.float32)
    ang = pos[:, None] * inv[None, :]  # [T, half]
    cos = jnp.cos(ang)[None, None].astype(x.dtype)
    sin = jnp.sin(ang)[None, None].astype(x.dtype)
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape)


def reference_attention(q, k, v, lengths=None, causal=False, sm_scale=None,
                        q_pos0=0):
    """Pure-jnp attention over [B, H, T, D]; the semantic ground truth.

    K/V may carry Hkv < H head planes (grouped-query attention, query
    head h reading kv head h // (H//Hkv)): the group structure stays in
    the einsum — no [B, H, T, D] expansion is ever materialised, which is
    the point of the smaller cache on the decode hot path.

    ``q_pos0`` offsets the queries' GLOBAL positions for causal masking —
    a window of w queries starting at cache position p attends key j iff
    j <= p + i (the block-causal mask incremental verify needs). It may
    be a [B] array of PER-ROW offsets (the paged chunked-prefill path,
    where every batch row resumes at its own context length)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    H, Hkv = q.shape[1], k.shape[1]
    if H != Hkv:
        if H % Hkv:
            raise ValueError(f"query heads {H} not a multiple of kv heads "
                             f"{Hkv}")
        rep = H // Hkv
        qg = q.reshape(q.shape[0], Hkv, rep, q.shape[2], q.shape[3])
        s = jnp.einsum("bgrqd,bgkd->bgrqk", qg, k).astype(jnp.float32)             * sm_scale
        s = s.reshape(q.shape[0], H, q.shape[2], k.shape[2])
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)             * sm_scale
    T = q.shape[2], k.shape[2]
    if causal:
        p0 = jnp.asarray(q_pos0)
        if p0.ndim:  # per-row offsets: [B] -> mask [B, 1, Tq, Tk]
            qi = p0[:, None] + jnp.arange(T[0])[None, :]
            kj = jnp.arange(T[1])
            s = jnp.where(qi[:, None, :, None] >= kj[None, None, None, :],
                          s, -jnp.inf)
        else:
            qi = q_pos0 + jnp.arange(T[0])[:, None]
            kj = jnp.arange(T[1])[None, :]
            s = jnp.where(qi >= kj, s, -jnp.inf)
    if lengths is not None:
        kj = jnp.arange(T[1])[None, None, None, :]
        s = jnp.where(kj < lengths[:, None, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (padding queries) produce NaN-free zeros
    p = jnp.where(jnp.isnan(p), 0.0, p)
    p = p.astype(v.dtype)
    if H != Hkv:
        pg = p.reshape(p.shape[0], Hkv, rep, p.shape[2], p.shape[3])
        og = jnp.einsum("bgrqk,bgkd->bgrqd", pg, v)
        return og.reshape(q.shape)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _flash_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k,
                  causal, sm_scale, kv_len):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    q = q_ref[0]  # [bq, d] — native dtype (bf16 under AMP): MXU-fast dots
    # lengths arrive via scalar prefetch (rank-1 SMEM blocks of size 1 do
    # not lower on Mosaic); index by the batch*head grid position
    length = len_ref[pl.program_id(0)]

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    n_blocks = kv_len // block_k
    if causal:
        # blocks fully above the diagonal contribute nothing — skip them
        last = (qb + 1) * block_q  # exclusive bound on visible columns
        n_live = (last + block_k - 1) // block_k
        ub = jnp.minimum(n_blocks, n_live)
    else:
        ub = n_blocks

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < length
        if causal:
            mask &= q_pos >= k_pos
        s = jnp.where(mask, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # keep -inf rows stable (fully masked so far)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        alpha = jnp.where(jnp.isfinite(m), alpha, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, ub, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # logsumexp residual for the flash backward; fully-masked rows get +inf
    # so exp(s - lse) is exactly 0 for them in the backward recompute.
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), jnp.inf)
    lse_ref[0, 0] = lse[:, 0]


def _flash_forward(q, k, v, lengths, causal, sm_scale, block_q, block_k,
                   interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    q3 = q.reshape(BH, Tq, D)
    k3 = k.reshape(BH, Tk, D)
    v3 = v.reshape(BH, Tk, D)
    if lengths is None:
        lens = jnp.full((B,), Tk, jnp.int32)
    else:
        lens = lengths.astype(jnp.int32)
    lens_bh = jnp.repeat(lens, H)  # [BH]

    block_q = _pick_block(Tq, block_q)
    block_k = _pick_block(Tk, block_k)
    grid = (BH, Tq // block_q)

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               sm_scale=sm_scale, kv_len=Tk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # lens_bh, available before the body runs
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, lens: (b, i, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, i, lens: (b, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, i, lens: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, lens: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, lens: (b, 0, i)),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
                   jax.ShapeDtypeStruct((BH, 1, Tq), jnp.float32)],
        interpret=interpret,
    )(lens_bh, q3, k3, v3)
    return out.reshape(B, H, Tq, D), lse


def _flash_dq_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                     dq_ref, *, block_k, causal, sm_scale, kv_len):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    q = q_ref[0]                              # [bq, d] native dtype
    do = do_ref[0]                            # [bq, d]
    lse = lse_ref[0, 0][:, None]              # [bq, 1]
    dd = dd_ref[0, 0][:, None]                # [bq, 1] rowsum(dO * O)
    length = len_ref[pl.program_id(0)]

    n_blocks = kv_len // block_k
    if causal:
        last = (qb + 1) * block_q
        ub = jnp.minimum(n_blocks, (last + block_k - 1) // block_k)
    else:
        ub = n_blocks
    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, acc):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < length
        if causal:
            mask &= q_pos >= k_pos
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)     # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - dd)
        return acc + jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    acc = jax.lax.fori_loop(
        0, ub, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (acc * sm_scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                      dk_ref, dv_ref, *, block_q, causal, sm_scale, q_len):
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    block_k, d = k_ref.shape[1], k_ref.shape[2]
    k = k_ref[0]                              # [bk, d] native dtype
    v = v_ref[0]                              # [bk, d]
    length = len_ref[pl.program_id(0)]

    n_blocks = q_len // block_q
    lb = (kb * block_k) // block_q if causal else 0
    k_pos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        dd = dd_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        mask = k_pos < length
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask &= q_pos >= k_pos
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)          # [bq, bk]
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, d]
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        ds = p * (dp - dd)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, d]
        return dk_acc, dv_acc

    z = jnp.zeros((block_k, d), jnp.float32)
    dk_acc, dv_acc = jax.lax.fori_loop(lb, n_blocks, body, (z, z))
    dk_ref[0] = (dk_acc * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, lengths, g, causal, sm_scale, block_q,
                    block_k, interpret):
    """Blockwise flash backward: recomputes p tiles from the saved
    logsumexp instead of materialising [T, T] — HBM stays O(T*d), matching
    the forward's memory story (the whole point of the kernel)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    q3, k3, v3 = (t.reshape(BH, -1, D) for t in (q, k, v))
    do3 = g.reshape(BH, Tq, D)
    # D_i = rowsum(dO * O): one cheap fused elementwise+reduce in XLA
    dd = jnp.sum(do3.astype(jnp.float32)
                 * o.reshape(BH, Tq, D).astype(jnp.float32),
                 axis=-1)[:, None, :]          # [BH, 1, Tq]
    if lengths is None:
        lens = jnp.full((B,), Tk, jnp.int32)
    else:
        lens = lengths.astype(jnp.int32)
    lens_bh = jnp.repeat(lens, H)

    bq = _pick_block(Tq, block_q)
    bk = _pick_block(Tk, block_k)

    dq_kernel = functools.partial(_flash_dq_kernel, block_k=bk,
                                  causal=causal, sm_scale=sm_scale,
                                  kv_len=Tk)
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, Tq // bq),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i, lens: (b, i, 0)),
                pl.BlockSpec((1, Tk, D), lambda b, i, lens: (b, 0, 0)),
                pl.BlockSpec((1, Tk, D), lambda b, i, lens: (b, 0, 0)),
                pl.BlockSpec((1, bq, D), lambda b, i, lens: (b, i, 0)),
                pl.BlockSpec((1, 1, bq), lambda b, i, lens: (b, 0, i)),
                pl.BlockSpec((1, 1, bq), lambda b, i, lens: (b, 0, i)),
            ],
            out_specs=pl.BlockSpec((1, bq, D), lambda b, i, lens: (b, i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
        interpret=interpret,
    )(lens_bh, q3, k3, v3, do3, lse, dd)

    dkv_kernel = functools.partial(_flash_dkv_kernel, block_q=bq,
                                   causal=causal, sm_scale=sm_scale,
                                   q_len=Tq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, Tk // bk),
            in_specs=[
                pl.BlockSpec((1, Tq, D), lambda b, j, lens: (b, 0, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j, lens: (b, j, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j, lens: (b, j, 0)),
                pl.BlockSpec((1, Tq, D), lambda b, j, lens: (b, 0, 0)),
                pl.BlockSpec((1, 1, Tq), lambda b, j, lens: (b, 0, 0)),
                pl.BlockSpec((1, 1, Tq), lambda b, j, lens: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D), lambda b, j, lens: (b, j, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j, lens: (b, j, 0)),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct((BH, Tk, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, Tk, D), v.dtype)],
        interpret=interpret,
    )(lens_bh, q3, k3, v3, do3, lse, dd)
    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


def _pad_to_lanes(q, k, v, lengths):
    """Zero-pad the T axes up to 128-lane multiples so the kernels' block
    slicing is Mosaic-aligned for ANY sequence length. K padding becomes
    masked columns (lengths caps at the true Tk); padded Q rows compute
    garbage that callers slice away — and contribute nothing to dk/dv
    because their incoming gradient is zero-padded."""
    Tq, Tk = q.shape[2], k.shape[2]
    pq = (-Tq) % 128
    pk = (-Tk) % 128
    if pq == 0 and pk == 0:
        return q, k, v, lengths, Tq
    if pq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pq), (0, 0)))
    if pk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pk), (0, 0)))
    if lengths is None:
        lengths = jnp.full((q.shape[0],), Tk, jnp.int32)
    return q, k, v, lengths, Tq


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _attention(q, k, v, lengths, causal, sm_scale):
    if jax.default_backend() == "tpu":
        qp, kp, vp, lens, Tq = _pad_to_lanes(q, k, v, lengths)
        out, _ = _flash_forward(qp, kp, vp, lens, causal, sm_scale,
                                DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                                interpret=False)
        return out[:, :, :Tq]
    return reference_attention(q, k, v, lengths, causal, sm_scale)


def _attention_fwd(q, k, v, lengths, causal, sm_scale):
    if jax.default_backend() == "tpu":
        qp, kp, vp, lens, Tq = _pad_to_lanes(q, k, v, lengths)
        out, lse = _flash_forward(qp, kp, vp, lens, causal, sm_scale,
                                  DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                                  interpret=False)
        return out[:, :, :Tq], (qp, kp, vp, out, lse, lens,
                                (Tq, k.shape[2]))
    return (reference_attention(q, k, v, lengths, causal, sm_scale),
            (q, k, v, None, None, lengths, None))


def _attention_bwd(causal, sm_scale, res, g):
    q, k, v, o, lse, lengths, orig = res
    if lse is not None:
        Tq, Tk = orig
        if g.shape[2] != q.shape[2]:
            g = jnp.pad(g, ((0, 0), (0, 0),
                            (0, q.shape[2] - g.shape[2]), (0, 0)))
        dq, dk, dv = _flash_backward(q, k, v, o, lse, lengths, g, causal,
                                     sm_scale, DEFAULT_BLOCK_Q,
                                     DEFAULT_BLOCK_K, interpret=False)
        return dq[:, :, :Tq], dk[:, :, :Tk], dv[:, :, :Tk], None

    def f(q, k, v):
        return reference_attention(q, k, v, lengths, causal, sm_scale)

    _, vjp = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_attention.defvjp(_attention_fwd, _attention_bwd)


def flash_attention(q, k, v, lengths=None, causal=False, sm_scale=None):
    """Scaled-dot-product attention over [B, H, T, D] tensors.

    Pallas flash kernel on TPU, jnp reference elsewhere; differentiable via
    recompute. ``lengths`` [B] masks K/V padding columns.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _attention(q, k, v, lengths, causal, float(sm_scale))

"""Flash attention: Pallas TPU kernel with online softmax.

The long-context workhorse. The reference framework predates Transformers
(SURVEY.md §5.7) — its closest analogues are the fused CUDA cell kernels
(/root/reference/paddle/cuda/src/hl_cuda_lstm.cu) whose role (keep the hot
loop's working set on-chip instead of round-tripping HBM) this kernel plays
for attention: O(T^2) scores never materialise in HBM; each (batch*head,
q-block) grid cell streams K/V blocks through VMEM, maintaining the running
max/denominator of the softmax (the standard online-softmax recurrence), so
HBM traffic is O(T*d) instead of O(T^2).

On non-TPU backends (the CPU test mesh) ``flash_attention`` falls back to a
pure-jnp reference — same semantics, XLA-fused. The backward pass always
uses the recompute-based jnp formulation via ``jax.custom_vjp``: XLA fuses
it well, and it keeps the Pallas surface forward-only.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _pick_block(t, preferred):
    b = min(preferred, t)
    while t % b:
        b //= 2
    return max(b, 1)


def reference_attention(q, k, v, lengths=None, causal=False, sm_scale=None):
    """Pure-jnp attention over [B, H, T, D]; the semantic ground truth."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    T = q.shape[2], k.shape[2]
    if causal:
        qi = jnp.arange(T[0])[:, None]
        kj = jnp.arange(T[1])[None, :]
        s = jnp.where(qi >= kj, s, -jnp.inf)
    if lengths is not None:
        kj = jnp.arange(T[1])[None, None, None, :]
        s = jnp.where(kj < lengths[:, None, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (padding queries) produce NaN-free zeros
    p = jnp.where(jnp.isnan(p), 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _flash_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_k, causal,
                  sm_scale, kv_len):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [bq, d]
    # lengths arrive via scalar prefetch (rank-1 SMEM blocks of size 1 do
    # not lower on Mosaic); index by the batch*head grid position
    length = len_ref[pl.program_id(0)]

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    n_blocks = kv_len // block_k
    if causal:
        # blocks fully above the diagonal contribute nothing — skip them
        last = (qb + 1) * block_q  # exclusive bound on visible columns
        n_live = (last + block_k - 1) // block_k
        ub = jnp.minimum(n_blocks, n_live)
    else:
        ub = n_blocks

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < length
        if causal:
            mask &= q_pos >= k_pos
        s = jnp.where(mask, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # keep -inf rows stable (fully masked so far)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        alpha = jnp.where(jnp.isfinite(m), alpha, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, ub, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, lengths, causal, sm_scale, block_q, block_k,
                   interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    q3 = q.reshape(BH, Tq, D)
    k3 = k.reshape(BH, Tk, D)
    v3 = v.reshape(BH, Tk, D)
    if lengths is None:
        lens = jnp.full((B,), Tk, jnp.int32)
    else:
        lens = lengths.astype(jnp.int32)
    lens_bh = jnp.repeat(lens, H)  # [BH]

    block_q = _pick_block(Tq, block_q)
    block_k = _pick_block(Tk, block_k)
    grid = (BH, Tq // block_q)

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               sm_scale=sm_scale, kv_len=Tk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # lens_bh, available before the body runs
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, lens: (b, i, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, i, lens: (b, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, i, lens: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda b, i, lens: (b, i, 0)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
        interpret=interpret,
    )(lens_bh, q3, k3, v3)
    return out.reshape(B, H, Tq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _attention(q, k, v, lengths, causal, sm_scale):
    if jax.default_backend() == "tpu":
        return _flash_forward(q, k, v, lengths, causal, sm_scale,
                              DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                              interpret=False)
    return reference_attention(q, k, v, lengths, causal, sm_scale)


def _attention_fwd(q, k, v, lengths, causal, sm_scale):
    return _attention(q, k, v, lengths, causal, sm_scale), (q, k, v, lengths)


def _attention_bwd(causal, sm_scale, res, g):
    q, k, v, lengths = res

    def f(q, k, v):
        return reference_attention(q, k, v, lengths, causal, sm_scale)

    _, vjp = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_attention.defvjp(_attention_fwd, _attention_bwd)


def flash_attention(q, k, v, lengths=None, causal=False, sm_scale=None):
    """Scaled-dot-product attention over [B, H, T, D] tensors.

    Pallas flash kernel on TPU, jnp reference elsewhere; differentiable via
    recompute. ``lengths`` [B] masks K/V padding columns.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _attention(q, k, v, lengths, causal, float(sm_scale))

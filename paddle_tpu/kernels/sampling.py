"""Per-row token selection for the decode platform.

One batched computation selects the next token for EVERY decode row at
once, with each row carrying its OWN sampling policy as device scalars:
temperature (0 = greedy argmax), top-k (0 = off), top-p (1.0 = off), a
per-row seed, and the row's sampling step. Randomness derives from
``fold_in(PRNGKey(seed), step)`` alone — never from a shared stream — so
a row's token is a pure function of (logits, policy, seed, step),
invariant to batch composition, tick interleaving, and which other
requests happen to be co-scheduled. That is the property that makes
mixed greedy/sampled continuous batches safe under one compile and lets
hedged fleet attempts reproduce each other's tokens.

``masked_logprobs``/``top_logprobs`` are the beam-search twins: the
per-row log-softmax (mask applied first) and its top-K — computed inside
the same decode computation so a beam fork never re-runs the model.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30  # large-negative instead of -inf: keeps softmax NaN-free
# on rows whose mask bans everything (the host validates masks, but the
# device math must not poison the batch if one slips through)


def apply_mask(logits, mask):
    """Ban tokens where ``mask`` <= 0 (mask is [rows, V] float, 1 = allowed).
    None = no constraint."""
    if mask is None:
        return logits
    return jnp.where(mask > 0, logits, _NEG_INF)


def masked_logprobs(logits, mask=None):
    """Per-row log-softmax with the token mask applied first — the
    scoring plane beam search expands on."""
    z = apply_mask(logits.astype(jnp.float32), mask)
    return jax.nn.log_softmax(z, axis=-1)


def top_logprobs(logits, k: int, mask=None):
    """(values [rows, k], ids [rows, k]) — each row's top-k masked
    log-probs, descending (lax.top_k tie-break: lower token id wins)."""
    lp = masked_logprobs(logits, mask)
    vals, ids = jax.lax.top_k(lp, k)
    return vals, ids.astype(jnp.int32)


def _topk_filter(z, top_k):
    """Per-row top-k: keep each row's k largest logits (k = 0 disables).
    Rows carry DIFFERENT k, so the static lax.top_k is replaced by a
    sort + per-row threshold."""
    V = z.shape[-1]
    kk = jnp.where(top_k <= 0, V, jnp.clip(top_k, 1, V)).astype(jnp.int32)
    sorted_desc = -jnp.sort(-z, axis=-1)
    kth = jnp.take_along_axis(sorted_desc, (kk - 1)[:, None], axis=-1)
    return jnp.where(z >= kth, z, _NEG_INF)


def _topp_filter(z, top_p):
    """Per-row nucleus filter over the (already temperature-scaled,
    top-k-filtered) logits: keep the smallest prefix of the descending
    distribution whose probability mass reaches top_p (always >= 1
    token). top_p >= 1 disables."""
    sorted_desc = -jnp.sort(-z, axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs  # exclusive prefix mass
    keep = cum_excl < jnp.clip(top_p, 0.0, 1.0)[:, None]
    keep = keep.at[:, 0].set(True)
    # threshold: the smallest kept logit per row
    kept_min = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1)
    filt = jnp.where(z >= kept_min[:, None], z, _NEG_INF)
    return jnp.where((top_p >= 1.0)[:, None], z, filt)


def sample_rows(logits, temperature, top_k, top_p, seed, step, mask=None):
    """Select one token per row.

    logits [rows, V] f32; temperature [rows] f32; top_k [rows] i32;
    top_p [rows] f32; seed [rows] u32/i32; step [rows] i32 (tokens this
    request has sampled so far); mask [rows, V] f32 or None. Returns
    ids [rows] i32. temperature == 0 rows take the masked argmax (no
    randomness consumed); sampled rows draw from the temperature-scaled,
    top-k- then top-p-filtered distribution with key
    ``fold_in(PRNGKey(seed), step)``.
    """
    z = apply_mask(logits.astype(jnp.float32), mask)
    greedy = jnp.argmax(z, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(temperature.astype(jnp.float32), 1e-6)
    zs = z / temp[:, None]
    zs = _topk_filter(zs, top_k.astype(jnp.int32))
    zs = _topp_filter(zs, top_p.astype(jnp.float32))

    def draw(seed_r, step_r, z_r):
        key = jax.random.fold_in(
            jax.random.PRNGKey(seed_r.astype(jnp.uint32)),
            step_r.astype(jnp.uint32))
        return jax.random.categorical(key, z_r)

    sampled = jax.vmap(draw)(seed, step, zs).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)

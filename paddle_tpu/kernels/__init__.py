"""Hand-written Pallas TPU kernels for ops where XLA fusion is not enough
(SURVEY.md §7: the fused-kernel tier replacing paddle/cuda's hl_* CUDA
kernels)."""
from . import flash_attention  # noqa: F401

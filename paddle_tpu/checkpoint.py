"""Checkpoint/resume: atomic, self-describing training snapshots.

Mirrors the reference's checkpoint designs:
- Go pserver: UUID-named payload + md5/timestamp meta, atomic replace, old
  checkpoint removal (/root/reference/go/pserver/service.go:346-420,
  doc/design/cluster_train/checkpointing.md).
- Legacy trainer: per-pass param dirs (--save_dir, trainer/ParamUtil.h:58)
  with --init_model_path/--start_pass resume (TrainerMain.cpp:25-27).

A checkpoint captures EVERYTHING persistable in the scope — parameters,
optimizer slots (momentum/adam moments live in the scope like any state),
batch-norm running stats, evaluator accumulators, the RNG key — so resume
is bit-exact. Written as one .npz + a JSON meta with md5, then atomically
renamed; ``max_keep`` old checkpoints are pruned.

Multi-process (DCN) runs are first-class: values whose shards this process
can fully cover (replicated, or sharded only on intra-process axes) go in
the main payload, written by process 0 alone; values sharded ACROSS
processes (e.g. ZeRO accumulators on a cross-slice dp axis) are saved by
EVERY process as its local shards + index metadata in a per-process
``.shard{i}.npz`` sidecar, and load stitches them back on a shared
filesystem — the analogue of the pserver fleet checkpointing its parameter
blocks in parallel (/root/reference/go/pserver/service.go:346-420; each
pserver saved ITS slice, exactly like a shard sidecar here).
"""
from __future__ import annotations

import hashlib
import json
import os
import time
import warnings
from typing import List, Optional, Tuple

import numpy as np

from .core.program import RNG_VAR
from .core.scope import global_scope

META_NAME = "checkpoint.meta"
PIN_NAME = "publisher.pin"


def pin_generation(dirname: str, step: Optional[int]) -> None:
    """Pin generation ``step`` against retention GC (the Publisher pins
    what the serving fleet is CURRENTLY serving, so a replica restart
    can always re-load it). ``step=None`` removes the pin. Atomic."""
    path = os.path.join(dirname, PIN_NAME)
    if step is None:
        try:
            os.remove(path)
        except FileNotFoundError:
            pass
        return
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"step": int(step)}, f)
    os.replace(tmp, path)


def pinned_step(dirname: str) -> Optional[int]:
    """The GC-pinned generation step, or None."""
    try:
        with open(os.path.join(dirname, PIN_NAME)) as f:
            return int(json.load(f)["step"])
    except (FileNotFoundError, ValueError, KeyError,
            json.JSONDecodeError):
        return None


def _process_info():
    """(process_index, process_count) without forcing a backend when jax
    was never imported (plain single-process users)."""
    import sys

    if "jax" not in sys.modules:
        return 0, 1
    import jax

    try:
        return jax.process_index(), jax.process_count()
    except Exception:  # backend not initialized
        return 0, 1


def _sync_processes(nproc, tag):
    """Barrier across the jax.distributed fleet: every process's files are
    durably renamed before anyone proceeds past a save."""
    if nproc <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def _covers_locally(v):
    """Metadata-only: can this process's shards reconstruct the full
    value? (No device->host transfer — shard indices suffice.)"""
    import sys

    if "jax" not in sys.modules:
        return True
    import jax

    if not isinstance(v, jax.Array) or v.is_fully_addressable:
        return True
    seen = np.zeros(v.shape, bool)
    for sh in v.addressable_shards:
        seen[sh.index] = True
    return bool(seen.all())


def _local_cover(v):
    """Full numpy value from this process's shards (caller must have
    checked _covers_locally)."""
    import sys

    if "jax" not in sys.modules:
        return np.asarray(v)
    import jax

    if not isinstance(v, jax.Array) or v.is_fully_addressable:
        return np.asarray(v)
    out = np.zeros(v.shape, v.dtype)
    for sh in v.addressable_shards:
        out[sh.index] = np.asarray(sh.data)
    return out


def _index_to_json(index, shape):
    """A shard's tuple-of-slices index as [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _store(arrays, dtypes, name, arr):
    """Record ``arr`` under ``name`` with the bf16/fp8 raw-bits trick."""
    dtypes[name] = str(arr.dtype)
    if arr.dtype.kind == "V":
        arr = arr.view(np.dtype(f"u{arr.dtype.itemsize}"))
    arrays[name] = arr


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_checkpoint(dirname: str, scope=None, step: int = 0,
                    max_keep: int = 3, extra: Optional[dict] = None) -> str:
    """Snapshot the whole scope into ``dirname``; returns the payload path."""
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    pid, nproc = _process_info()
    arrays, dtypes = {}, {}
    shard_arrays, shard_dtypes, shard_meta = {}, {}, {}
    for name in scope.keys():
        value = scope.get(name)
        if _covers_locally(value):
            # payload values are process 0's job; other processes never
            # materialize them (a metadata check, not a device fetch)
            if pid == 0:
                _store(arrays, dtypes, name, _local_cover(value))
            continue
        # sharded ACROSS processes: save this process's shards + indices
        pieces = []
        for i, sh in enumerate(value.addressable_shards):
            key = f"{name}@shard{i}"
            _store(shard_arrays, shard_dtypes, key,
                   np.asarray(sh.data))
            pieces.append(_index_to_json(sh.index, value.shape))
        shard_meta[name] = {"shape": list(value.shape),
                            "indices": pieces}

    payload = os.path.join(dirname, f"ckpt-{step}.npz")
    written = payload
    if shard_arrays:
        shard_arrays["__shards__"] = np.frombuffer(json.dumps(
            {"meta": shard_meta, "dtypes": shard_dtypes}).encode(),
            dtype=np.uint8)
        spath = os.path.join(dirname, f"ckpt-{step}.shard{pid}.npz")
        stmp = spath + f".tmp{os.getpid()}"
        with open(stmp, "wb") as f:
            np.savez(f, **shard_arrays)
        os.replace(stmp, spath)
        if pid != 0:
            written = spath
    if pid != 0:
        # only process 0 writes the payload + meta; everyone synchronizes
        # below so no process can read a half-written checkpoint
        _sync_processes(nproc, f"ckpt-{step}")
        return written
    arrays["__dtypes__"] = np.frombuffer(
        json.dumps(dtypes).encode(), dtype=np.uint8)
    tmp = payload + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, payload)  # atomic

    # stale sidecars from a previous, larger fleet at this step would
    # otherwise be globbed and stitched OVER fresh data on load
    for f in os.listdir(dirname):
        if f.startswith(f"ckpt-{step}.shard") and f.endswith(".npz"):
            try:
                idx = int(f.split(".shard")[1][:-4])
            except ValueError:
                continue
            if idx >= nproc:
                os.remove(os.path.join(dirname, f))
    step_meta = {
        "step": step,
        "md5": _md5(payload),
        "timestamp": time.time(),
        "shard_files": nproc if shard_arrays else 0,
        "shard_values": sorted(shard_meta),
        "extra": extra or {},
    }
    # Per-step meta sidecar (ckpt-N.json): the single META_NAME file only
    # records the LATEST checkpoint's md5/extra, but torn-latest fallback
    # (load_checkpoint/latest_step walking back to an older intact
    # checkpoint) needs integrity + resume position for older steps too.
    sj_tmp = payload[:-4] + f".json.tmp{os.getpid()}"
    with open(sj_tmp, "w") as f:
        json.dump(step_meta, f)
    os.replace(sj_tmp, payload[:-4] + ".json")
    meta = {"latest": os.path.basename(payload), **step_meta}
    meta_tmp = os.path.join(dirname, META_NAME + f".tmp{os.getpid()}")
    with open(meta_tmp, "w") as f:
        json.dump(meta, f)
    os.replace(meta_tmp, os.path.join(dirname, META_NAME))

    # prune old checkpoints: keep the newest max_keep by step, but the one
    # just written (what meta['latest'] points to) always survives even if
    # its step is lower than leftovers from an abandoned longer run — and
    # so does the Publisher-pinned generation (the one the serving fleet
    # is live on), however old: endless-pass online training GCs its
    # history without ever deleting what production serves
    cks = sorted(
        (p for p in os.listdir(dirname)
         if p.startswith("ckpt-") and p.endswith(".npz")
         and ".shard" not in p),
        key=lambda p: int(p[5:-4]))
    keep = max(int(max_keep), 1)
    keep_set = set(cks[max(len(cks) - keep, 0):]) | {os.path.basename(payload)}
    pin = pinned_step(dirname)
    if pin is not None:
        keep_set.add(f"ckpt-{pin}.npz")
    for old in cks:
        if old not in keep_set:
            os.remove(os.path.join(dirname, old))
            base = old[:-4]
            for sf in os.listdir(dirname):
                if sf.startswith(base + ".shard") \
                        or sf == base + ".json":
                    os.remove(os.path.join(dirname, sf))
    _sync_processes(nproc, f"ckpt-{step}")
    return payload


class _Stage:
    """Staging target for a restore: values land here first so a load
    that fails mid-way never leaves the real scope half-written.

    ``commit(scope, plan=...)`` is the reshard-on-restore half: staged
    values are FULL host values by construction (main payload entries,
    or sidecar shards stitched through their global index metadata), so
    re-placing them is one ``device_put`` per value onto the new plan's
    PartitionSpec — a checkpoint saved under mesh/plan A restores under
    mesh/plan B (different axis split, fewer devices) bitwise."""

    def __init__(self):
        self._vars = {}

    def set(self, name, value):
        self._vars[name] = value

    def commit(self, scope, plan=None):
        if plan is None:
            for name, value in self._vars.items():
                scope.set(name, value)
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        replicated = NamedSharding(plan.mesh, PartitionSpec())
        for name, value in self._vars.items():
            if name == RNG_VAR:
                scope.set(name, jax.device_put(value, replicated))
                continue
            arr = np.asarray(value) if not hasattr(value, "ndim") else value
            try:
                sharding = plan.state_sharding(name, arr.ndim,
                                               shape=arr.shape)
                scope.set(name, jax.device_put(value, sharding))
            except Exception:  # noqa: BLE001 - plan misfit (e.g. an
                # evaluator accumulator no rule covers): restore the raw
                # host value; the executor re-places it at the next step
                scope.set(name, value)


def _step_of(payload_name: str) -> int:
    return int(payload_name[5:-4])  # "ckpt-<step>.npz"


def _step_info(dirname: str, payload_name: str) -> Optional[dict]:
    """The per-step meta sidecar (md5/extra/shard manifest), or None for
    checkpoints written before sidecars existed."""
    try:
        with open(os.path.join(dirname, payload_name[:-4] + ".json")) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def generation_info(dirname: str, step: int) -> Optional[dict]:
    """Public view of one generation's per-step meta (md5, timestamp,
    ``extra`` — including an elastic trainer's lineage manifest), or
    None when the step has no sidecar."""
    return _step_info(dirname, f"ckpt-{int(step)}.npz")


def _candidates(dirname: str, meta: dict) -> List[str]:
    """Payload names to try, newest-first: the meta's latest, then every
    OLDER step (a leftover higher-step file from an abandoned longer run
    is not a fallback target — meta deliberately points below it)."""
    latest = meta["latest"]
    latest_step_no = _step_of(latest)
    older = sorted(
        (p for p in os.listdir(dirname)
         if p.startswith("ckpt-") and p.endswith(".npz")
         and ".shard" not in p and p != latest
         and _step_of(p) < latest_step_no),
        key=_step_of, reverse=True)
    return [latest] + older


def _restore_payload(dirname: str, payload_name: str, scope,
                     verify: bool, expect_md5: Optional[str],
                     expect_files, expect_values) -> None:
    """Verify + load one payload (and its shard sidecars) into ``scope``
    (any object with ``set``). Raises on any integrity problem."""
    payload = os.path.join(dirname, payload_name)
    if verify and expect_md5 is not None and _md5(payload) != expect_md5:
        raise ValueError(f"checkpoint {payload} md5 mismatch (corrupt)")
    _load_shard_sidecars(dirname, payload_name[:-4], scope,
                         expect_files=expect_files,
                         expect_values=expect_values)
    with np.load(payload) as data:
        dtypes = {}
        if "__dtypes__" in data.files:
            dtypes = json.loads(bytes(data["__dtypes__"]).decode())
        for key in data.files:
            if key == "__dtypes__":
                continue
            arr = data[key]
            want = dtypes.get(key)
            if want and str(arr.dtype) != want:
                import ml_dtypes  # noqa: F401 — registers bfloat16/fp8

                arr = arr.view(np.dtype(want))
            if key == RNG_VAR:
                import jax

                scope.set(key, jax.numpy.asarray(arr))
            else:
                scope.set(key, arr)


def load_checkpoint(dirname: str, scope=None, verify: bool = True,
                    strict: bool = False, plan=None,
                    accept=None) -> dict:
    """Restore the latest *intact* checkpoint into the scope; returns its
    meta dict. Raises FileNotFoundError if none exists.

    When the latest checkpoint is torn (md5 mismatch, unreadable npz,
    missing shard sidecars), the default walks BACK to the newest older
    intact ``ckpt-*.npz`` — warning, and recording ``fallback``/
    ``fallback_from``/``fallback_errors`` in the returned meta — because
    an auto-resuming job must survive the checkpoint that was being
    written when it died. ``strict=True`` keeps the hard ValueError (the
    reference's ErrCheckpointNotFound path). If NO intact checkpoint
    remains, the latest's original error is raised either way. A restore
    stages into a buffer first, so the scope is never left half-written.

    ``plan`` (a :class:`paddle_tpu.parallel.ShardingPlan`) RESHARDS on
    restore: staged values — full host values, whether they came from
    the main payload or from stitching ``.shard{i}.npz`` sidecars
    through their global index metadata — commit as device arrays
    sharded by the plan's PartitionSpecs, so a checkpoint saved under
    ``dp=8`` restores bitwise into a scope lowered under ``dp=4×mp=2``
    or onto a smaller mesh (the elastic mesh-shape-change path).

    ``accept`` (callable ``meta -> bool``) filters candidates by their
    meta/lineage BEFORE any bytes are read: a generation the predicate
    rejects (e.g. one whose lineage is inconsistent with the master's
    queue state) is skipped exactly like a torn one, walking back to the
    newest acceptable intact generation."""
    scope = scope or global_scope()
    meta_path = os.path.join(dirname, META_NAME)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no checkpoint meta in {dirname}")
    with open(meta_path) as f:
        meta = json.load(f)
    errors: List[Tuple[str, BaseException]] = []
    for payload_name in _candidates(dirname, meta):
        is_latest = payload_name == meta["latest"]
        info = meta if is_latest else _step_info(dirname, payload_name)
        if accept is not None:
            cand = dict(info or {})
            cand.setdefault("step", _step_of(payload_name))
            cand.setdefault("extra", {})
            if not accept(cand):
                exc = ValueError(
                    f"checkpoint {payload_name} rejected by accept "
                    "predicate (lineage inconsistent)")
                errors.append((payload_name, exc))
                if strict:
                    raise exc
                continue
        stage = _Stage()
        try:
            _restore_payload(
                dirname, payload_name, stage, verify,
                expect_md5=(info or {}).get("md5"),
                expect_files=(info or {}).get("shard_files"),
                expect_values=(info or {}).get("shard_values"))
        except Exception as exc:  # noqa: BLE001 - walk back per candidate
            errors.append((payload_name, exc))
            if strict:
                raise
            continue
        stage.commit(scope, plan=plan)
        if is_latest:
            return meta
        out = dict(info or {})
        out.setdefault("step", _step_of(payload_name))
        out.setdefault("extra", {})
        out["latest"] = payload_name
        out["fallback"] = True
        out["fallback_from"] = meta["latest"]
        out["fallback_errors"] = [f"{n}: {e}" for n, e in errors]
        warnings.warn(
            f"checkpoint {meta['latest']} in {dirname} is not usable "
            f"({errors[0][1]}); fell back to intact {payload_name} "
            f"(step {out['step']})", RuntimeWarning, stacklevel=2)
        return out
    raise errors[0][1]


def _load_shard_sidecars(dirname: str, base: str, scope,
                         expect_files=None, expect_values=None) -> None:
    """Stitch cross-process shard sidecars (``{base}.shard*.npz``) back
    into full values; requires shared storage holding every process's
    file. Raises if sidecars are missing/extra vs the meta manifest or if
    the union of shards leaves holes."""
    import glob

    files = sorted(glob.glob(os.path.join(dirname, base + ".shard*.npz")))
    if expect_files is not None and len(files) != expect_files:
        raise ValueError(
            f"checkpoint expects {expect_files} shard sidecar files for "
            f"{base!r} but found {len(files)} — values "
            f"{expect_values or []} were saved as per-process shards and "
            "cannot be restored without every process's file")
    if not files:
        return
    full, seen, dtypes = {}, {}, {}
    for path in files:
        with np.load(path) as data:
            info = json.loads(bytes(data["__shards__"]).decode())
            dtypes.update(info["dtypes"])
            for name, m in info["meta"].items():
                if name not in full:
                    first = data[f"{name}@shard0"]                         if f"{name}@shard0" in data.files else None
                    dt = first.dtype if first is not None else np.float32
                    full[name] = np.zeros(m["shape"], dt)
                    seen[name] = np.zeros(m["shape"], bool)
                for i, idx in enumerate(m["indices"]):
                    key = f"{name}@shard{i}"
                    if key not in data.files:
                        continue
                    sl = tuple(slice(a, b) for a, b in idx)
                    full[name][sl] = data[key]
                    seen[name][sl] = True
    for name, arr in full.items():
        if not seen[name].all():
            raise ValueError(
                f"checkpoint value {name!r} has uncovered shards — are "
                "all processes' .shard files on this filesystem?")
        want = dtypes.get(f"{name}@shard0")
        if want and str(arr.dtype) != want:
            import ml_dtypes  # noqa: F401

            arr = arr.view(np.dtype(want))
        scope.set(name, arr)


def _looks_intact(dirname: str, payload_name: str,
                  expect_md5: Optional[str]) -> bool:
    """Cheap integrity probe: md5 when the per-step sidecar recorded one,
    else an npz directory read (a truncated zip fails to open)."""
    payload = os.path.join(dirname, payload_name)
    try:
        if expect_md5 is not None:
            return _md5(payload) == expect_md5
        with np.load(payload) as data:
            list(data.files)
        return True
    except Exception:  # noqa: BLE001 - any failure means not intact
        return False


def save_manifest(dirname: str, executor) -> Optional[str]:
    """Persist ``executor``'s recorded compile signatures next to the
    checkpoints (``warmup_manifest.json``) so a resuming process can
    AOT-replay them before its first step — the cold-start half of the
    resume contract (``SGD.train`` does both ends automatically when a
    ``CheckpointConfig`` is passed). Returns the path, or None when
    nothing compiled yet."""
    if len(executor.manifest) == 0:
        return None
    return executor.manifest.save(dirname)


def load_manifest(dirname: str):
    """Load the checkpoint directory's warmup manifest (a
    ``core.manifest.SignatureManifest``), or None when absent; raises
    ``ManifestError`` naming the file on an unreadable version. Replay
    it with ``core.manifest.replay(executor, [program], scope=...)``."""
    from .core import manifest as manifest_mod

    return manifest_mod.try_load(dirname)


def latest_step(dirname: str, verify: bool = True,
                accept=None) -> Optional[int]:
    """The step of the latest INTACT checkpoint, or None. A torn latest
    is skipped the same way ``load_checkpoint`` falls back; pass
    ``verify=False`` for the raw meta value. ``accept`` applies the same
    meta/lineage predicate ``load_checkpoint`` takes, so a Publisher can
    watch for the newest generation *consistent with the queue state*."""
    try:
        with open(os.path.join(dirname, META_NAME)) as f:
            meta = json.load(f)
        if not verify and accept is None:
            return meta["step"]
        for payload_name in _candidates(dirname, meta):
            is_latest = payload_name == meta["latest"]
            info = meta if is_latest else _step_info(dirname, payload_name)
            if accept is not None:
                cand = dict(info or {})
                cand.setdefault("step", _step_of(payload_name))
                cand.setdefault("extra", {})
                if not accept(cand):
                    continue
            if not verify or _looks_intact(dirname, payload_name,
                                           (info or {}).get("md5")):
                return meta["step"] if is_latest else _step_of(payload_name)
        return None
    except (FileNotFoundError, KeyError, json.JSONDecodeError, ValueError):
        return None

"""Checkpoint/resume: atomic, self-describing training snapshots.

Mirrors the reference's checkpoint designs:
- Go pserver: UUID-named payload + md5/timestamp meta, atomic replace, old
  checkpoint removal (/root/reference/go/pserver/service.go:346-420,
  doc/design/cluster_train/checkpointing.md).
- Legacy trainer: per-pass param dirs (--save_dir, trainer/ParamUtil.h:58)
  with --init_model_path/--start_pass resume (TrainerMain.cpp:25-27).

A checkpoint captures EVERYTHING persistable in the scope — parameters,
optimizer slots (momentum/adam moments live in the scope like any state),
batch-norm running stats, evaluator accumulators, the RNG key — so resume
is bit-exact. Written as one .npz + a JSON meta with md5, then atomically
renamed; ``max_keep`` old checkpoints are pruned.

Multi-process (DCN) runs are first-class: values whose shards this process
can fully cover (replicated, or sharded only on intra-process axes) go in
the main payload, written by process 0 alone; values sharded ACROSS
processes (e.g. ZeRO accumulators on a cross-slice dp axis) are saved by
EVERY process as its local shards + index metadata in a per-process
``.shard{i}.npz`` sidecar, and load stitches them back on a shared
filesystem — the analogue of the pserver fleet checkpointing its parameter
blocks in parallel (/root/reference/go/pserver/service.go:346-420; each
pserver saved ITS slice, exactly like a shard sidecar here).
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

import numpy as np

from .core.program import RNG_VAR
from .core.scope import global_scope

META_NAME = "checkpoint.meta"


def _process_info():
    """(process_index, process_count) without forcing a backend when jax
    was never imported (plain single-process users)."""
    import sys

    if "jax" not in sys.modules:
        return 0, 1
    import jax

    try:
        return jax.process_index(), jax.process_count()
    except Exception:  # backend not initialized
        return 0, 1


def _sync_processes(nproc, tag):
    """Barrier across the jax.distributed fleet: every process's files are
    durably renamed before anyone proceeds past a save."""
    if nproc <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def _covers_locally(v):
    """Metadata-only: can this process's shards reconstruct the full
    value? (No device->host transfer — shard indices suffice.)"""
    import sys

    if "jax" not in sys.modules:
        return True
    import jax

    if not isinstance(v, jax.Array) or v.is_fully_addressable:
        return True
    seen = np.zeros(v.shape, bool)
    for sh in v.addressable_shards:
        seen[sh.index] = True
    return bool(seen.all())


def _local_cover(v):
    """Full numpy value from this process's shards (caller must have
    checked _covers_locally)."""
    import sys

    if "jax" not in sys.modules:
        return np.asarray(v)
    import jax

    if not isinstance(v, jax.Array) or v.is_fully_addressable:
        return np.asarray(v)
    out = np.zeros(v.shape, v.dtype)
    for sh in v.addressable_shards:
        out[sh.index] = np.asarray(sh.data)
    return out


def _index_to_json(index, shape):
    """A shard's tuple-of-slices index as [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _store(arrays, dtypes, name, arr):
    """Record ``arr`` under ``name`` with the bf16/fp8 raw-bits trick."""
    dtypes[name] = str(arr.dtype)
    if arr.dtype.kind == "V":
        arr = arr.view(np.dtype(f"u{arr.dtype.itemsize}"))
    arrays[name] = arr


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_checkpoint(dirname: str, scope=None, step: int = 0,
                    max_keep: int = 3, extra: Optional[dict] = None) -> str:
    """Snapshot the whole scope into ``dirname``; returns the payload path."""
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    pid, nproc = _process_info()
    arrays, dtypes = {}, {}
    shard_arrays, shard_dtypes, shard_meta = {}, {}, {}
    for name in scope.keys():
        value = scope.get(name)
        if _covers_locally(value):
            # payload values are process 0's job; other processes never
            # materialize them (a metadata check, not a device fetch)
            if pid == 0:
                _store(arrays, dtypes, name, _local_cover(value))
            continue
        # sharded ACROSS processes: save this process's shards + indices
        pieces = []
        for i, sh in enumerate(value.addressable_shards):
            key = f"{name}@shard{i}"
            _store(shard_arrays, shard_dtypes, key,
                   np.asarray(sh.data))
            pieces.append(_index_to_json(sh.index, value.shape))
        shard_meta[name] = {"shape": list(value.shape),
                            "indices": pieces}

    payload = os.path.join(dirname, f"ckpt-{step}.npz")
    written = payload
    if shard_arrays:
        shard_arrays["__shards__"] = np.frombuffer(json.dumps(
            {"meta": shard_meta, "dtypes": shard_dtypes}).encode(),
            dtype=np.uint8)
        spath = os.path.join(dirname, f"ckpt-{step}.shard{pid}.npz")
        stmp = spath + f".tmp{os.getpid()}"
        with open(stmp, "wb") as f:
            np.savez(f, **shard_arrays)
        os.replace(stmp, spath)
        if pid != 0:
            written = spath
    if pid != 0:
        # only process 0 writes the payload + meta; everyone synchronizes
        # below so no process can read a half-written checkpoint
        _sync_processes(nproc, f"ckpt-{step}")
        return written
    arrays["__dtypes__"] = np.frombuffer(
        json.dumps(dtypes).encode(), dtype=np.uint8)
    tmp = payload + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, payload)  # atomic

    # stale sidecars from a previous, larger fleet at this step would
    # otherwise be globbed and stitched OVER fresh data on load
    for f in os.listdir(dirname):
        if f.startswith(f"ckpt-{step}.shard") and f.endswith(".npz"):
            try:
                idx = int(f.split(".shard")[1][:-4])
            except ValueError:
                continue
            if idx >= nproc:
                os.remove(os.path.join(dirname, f))
    meta = {
        "latest": os.path.basename(payload),
        "step": step,
        "md5": _md5(payload),
        "timestamp": time.time(),
        "shard_files": nproc if shard_arrays else 0,
        "shard_values": sorted(shard_meta),
        "extra": extra or {},
    }
    meta_tmp = os.path.join(dirname, META_NAME + f".tmp{os.getpid()}")
    with open(meta_tmp, "w") as f:
        json.dump(meta, f)
    os.replace(meta_tmp, os.path.join(dirname, META_NAME))

    # prune old checkpoints: keep the newest max_keep by step, but the one
    # just written (what meta['latest'] points to) always survives even if
    # its step is lower than leftovers from an abandoned longer run
    cks = sorted(
        (p for p in os.listdir(dirname)
         if p.startswith("ckpt-") and p.endswith(".npz")
         and ".shard" not in p),
        key=lambda p: int(p[5:-4]))
    keep = max(int(max_keep), 1)
    keep_set = set(cks[max(len(cks) - keep, 0):]) | {os.path.basename(payload)}
    for old in cks:
        if old not in keep_set:
            os.remove(os.path.join(dirname, old))
            base = old[:-4]
            for sf in os.listdir(dirname):
                if sf.startswith(base + ".shard"):
                    os.remove(os.path.join(dirname, sf))
    _sync_processes(nproc, f"ckpt-{step}")
    return payload


def load_checkpoint(dirname: str, scope=None, verify: bool = True) -> dict:
    """Restore the latest checkpoint into the scope. Returns the meta dict.
    Raises FileNotFoundError if none exists; ValueError on md5 mismatch
    (torn/corrupt file — the reference's ErrCheckpointNotFound path)."""
    scope = scope or global_scope()
    meta_path = os.path.join(dirname, META_NAME)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no checkpoint meta in {dirname}")
    with open(meta_path) as f:
        meta = json.load(f)
    payload = os.path.join(dirname, meta["latest"])
    if verify and _md5(payload) != meta["md5"]:
        raise ValueError(f"checkpoint {payload} md5 mismatch (corrupt)")
    _load_shard_sidecars(dirname, meta["latest"][:-4], scope,
                         expect_files=meta.get("shard_files"),
                         expect_values=meta.get("shard_values"))
    with np.load(payload) as data:
        dtypes = {}
        if "__dtypes__" in data.files:
            dtypes = json.loads(bytes(data["__dtypes__"]).decode())
        for key in data.files:
            if key == "__dtypes__":
                continue
            arr = data[key]
            want = dtypes.get(key)
            if want and str(arr.dtype) != want:
                import ml_dtypes  # noqa: F401 — registers bfloat16/fp8

                arr = arr.view(np.dtype(want))
            if key == RNG_VAR:
                import jax

                scope.set(key, jax.numpy.asarray(arr))
            else:
                scope.set(key, arr)
    return meta


def _load_shard_sidecars(dirname: str, base: str, scope,
                         expect_files=None, expect_values=None) -> None:
    """Stitch cross-process shard sidecars (``{base}.shard*.npz``) back
    into full values; requires shared storage holding every process's
    file. Raises if sidecars are missing/extra vs the meta manifest or if
    the union of shards leaves holes."""
    import glob

    files = sorted(glob.glob(os.path.join(dirname, base + ".shard*.npz")))
    if expect_files is not None and len(files) != expect_files:
        raise ValueError(
            f"checkpoint expects {expect_files} shard sidecar files for "
            f"{base!r} but found {len(files)} — values "
            f"{expect_values or []} were saved as per-process shards and "
            "cannot be restored without every process's file")
    if not files:
        return
    full, seen, dtypes = {}, {}, {}
    for path in files:
        with np.load(path) as data:
            info = json.loads(bytes(data["__shards__"]).decode())
            dtypes.update(info["dtypes"])
            for name, m in info["meta"].items():
                if name not in full:
                    first = data[f"{name}@shard0"]                         if f"{name}@shard0" in data.files else None
                    dt = first.dtype if first is not None else np.float32
                    full[name] = np.zeros(m["shape"], dt)
                    seen[name] = np.zeros(m["shape"], bool)
                for i, idx in enumerate(m["indices"]):
                    key = f"{name}@shard{i}"
                    if key not in data.files:
                        continue
                    sl = tuple(slice(a, b) for a, b in idx)
                    full[name][sl] = data[key]
                    seen[name][sl] = True
    for name, arr in full.items():
        if not seen[name].all():
            raise ValueError(
                f"checkpoint value {name!r} has uncovered shards — are "
                "all processes' .shard files on this filesystem?")
        want = dtypes.get(f"{name}@shard0")
        if want and str(arr.dtype) != want:
            import ml_dtypes  # noqa: F401

            arr = arr.view(np.dtype(want))
        scope.set(name, arr)


def latest_step(dirname: str) -> Optional[int]:
    """The step of the latest checkpoint, or None."""
    try:
        with open(os.path.join(dirname, META_NAME)) as f:
            return json.load(f)["step"]
    except (FileNotFoundError, KeyError, json.JSONDecodeError):
        return None

"""Checkpoint/resume: atomic, self-describing training snapshots.

Mirrors the reference's checkpoint designs:
- Go pserver: UUID-named payload + md5/timestamp meta, atomic replace, old
  checkpoint removal (/root/reference/go/pserver/service.go:346-420,
  doc/design/cluster_train/checkpointing.md).
- Legacy trainer: per-pass param dirs (--save_dir, trainer/ParamUtil.h:58)
  with --init_model_path/--start_pass resume (TrainerMain.cpp:25-27).

A checkpoint captures EVERYTHING persistable in the scope — parameters,
optimizer slots (momentum/adam moments live in the scope like any state),
batch-norm running stats, evaluator accumulators, the RNG key — so resume
is bit-exact. Written as one .npz + a JSON meta with md5, then atomically
renamed; ``max_keep`` old checkpoints are pruned. In multi-trainer runs
only one process should save (the reference elects via master
RequestSaveModel, go/master/service.go:474-481 — here: save when
``trainer_id == 0``).
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

import numpy as np

from .core.program import RNG_VAR
from .core.scope import global_scope

META_NAME = "checkpoint.meta"


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_checkpoint(dirname: str, scope=None, step: int = 0,
                    max_keep: int = 3, extra: Optional[dict] = None) -> str:
    """Snapshot the whole scope into ``dirname``; returns the payload path."""
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    arrays, dtypes = {}, {}
    for name in scope.keys():
        arr = np.asarray(scope.get(name))
        dtypes[name] = str(arr.dtype)
        if arr.dtype.kind == "V":
            # extension dtypes (bfloat16, fp8): store raw bits; the dtype
            # map restores the view on load
            arr = arr.view(np.dtype(f"u{arr.dtype.itemsize}"))
        arrays[name] = arr
    arrays["__dtypes__"] = np.frombuffer(
        json.dumps(dtypes).encode(), dtype=np.uint8)
    payload = os.path.join(dirname, f"ckpt-{step}.npz")
    tmp = payload + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, payload)  # atomic

    meta = {
        "latest": os.path.basename(payload),
        "step": step,
        "md5": _md5(payload),
        "timestamp": time.time(),
        "extra": extra or {},
    }
    meta_tmp = os.path.join(dirname, META_NAME + f".tmp{os.getpid()}")
    with open(meta_tmp, "w") as f:
        json.dump(meta, f)
    os.replace(meta_tmp, os.path.join(dirname, META_NAME))

    # prune old checkpoints: keep the newest max_keep by step, but the one
    # just written (what meta['latest'] points to) always survives even if
    # its step is lower than leftovers from an abandoned longer run
    cks = sorted(
        (p for p in os.listdir(dirname)
         if p.startswith("ckpt-") and p.endswith(".npz")),
        key=lambda p: int(p[5:-4]))
    keep = max(int(max_keep), 1)
    keep_set = set(cks[max(len(cks) - keep, 0):]) | {os.path.basename(payload)}
    for old in cks:
        if old not in keep_set:
            os.remove(os.path.join(dirname, old))
    return payload


def load_checkpoint(dirname: str, scope=None, verify: bool = True) -> dict:
    """Restore the latest checkpoint into the scope. Returns the meta dict.
    Raises FileNotFoundError if none exists; ValueError on md5 mismatch
    (torn/corrupt file — the reference's ErrCheckpointNotFound path)."""
    scope = scope or global_scope()
    meta_path = os.path.join(dirname, META_NAME)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no checkpoint meta in {dirname}")
    with open(meta_path) as f:
        meta = json.load(f)
    payload = os.path.join(dirname, meta["latest"])
    if verify and _md5(payload) != meta["md5"]:
        raise ValueError(f"checkpoint {payload} md5 mismatch (corrupt)")
    with np.load(payload) as data:
        dtypes = {}
        if "__dtypes__" in data.files:
            dtypes = json.loads(bytes(data["__dtypes__"]).decode())
        for key in data.files:
            if key == "__dtypes__":
                continue
            arr = data[key]
            want = dtypes.get(key)
            if want and str(arr.dtype) != want:
                import ml_dtypes  # noqa: F401 — registers bfloat16/fp8

                arr = arr.view(np.dtype(want))
            if key == RNG_VAR:
                import jax

                scope.set(key, jax.numpy.asarray(arr))
            else:
                scope.set(key, arr)
    return meta


def latest_step(dirname: str) -> Optional[int]:
    """The step of the latest checkpoint, or None."""
    try:
        with open(os.path.join(dirname, META_NAME)) as f:
            return json.load(f)["step"]
    except (FileNotFoundError, KeyError, json.JSONDecodeError):
        return None

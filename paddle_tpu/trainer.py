"""Event-driven training loop, parity with the v2 SGD trainer
(/root/reference/python/paddle/v2/trainer.py:24,124-202) on top of the
whole-block XLA executor.

Differences from the reference, all TPU-motivated:
- No parameter/updater objects: the optimizer appends its update ops into
  the program (fluid-style) and the whole step — forward, backward,
  update — is one compiled XLA computation per batch signature.
- Distribution is an argument (mesh + ShardingPlan), not a different
  updater class: the same loop runs single-chip or SPMD over a slice.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import numpy as np

from . import event as evt
from . import io as io_mod
from . import profiler
from .core.executor import Executor, TPUPlace
from .core.program import (Program, Variable, default_main_program,
                           default_startup_program)
from .core.scope import Scope, global_scope
from .data_feeder import DataFeeder


class SGD:
    """``SGD(cost, optimizer, feed_list).train(reader, ...)``.

    ``metrics`` maps display names to program variables (e.g. the output of
    layers.accuracy) fetched and averaged alongside the cost — the analogue
    of the reference's in-loop Evaluators (TrainerInternal.cpp:140-153).
    """

    def __init__(self, cost: Variable, optimizer, feed_list: Sequence[Variable],
                 place: Optional[TPUPlace] = None, mesh=None, plan=None,
                 metrics: Optional[Dict[str, Variable]] = None,
                 scope: Optional[Scope] = None,
                 check_nan_inf: Optional[bool] = None,
                 transpile: bool = False,
                 pad_to_multiple: Optional[int] = None):
        self.cost = cost
        self.metrics = dict(metrics or {})
        self.main_program: Program = cost.block.program
        self.startup_program = default_startup_program()
        # Inference/test clone is taken BEFORE optimizer ops are appended
        # and flips is_test (fluid's Program.clone(for_test=True)).
        self.test_program = self.main_program.clone(for_test=True)
        if transpile:
            # Training rewrites must land BEFORE minimize appends the
            # backward: grad ops reference the op list they were derived
            # from, and the fused replacements carry their own grad_fns.
            # Per-pass wall time / op deltas go to the profiler StatSet
            # (profiler.print_all_status shows them next to step timers).
            from .transpiler import training_pipeline, prune_pipeline

            feeds = [v.name for v in feed_list]
            fetches = [cost.name] + [v.name for v in self.metrics.values()]
            training_pipeline().run(self.main_program, feeds, fetches,
                                    scope=scope or global_scope())
            prune_pipeline().run(self.test_program, feeds, fetches)
        optimizer.minimize(cost, startup_program=self.startup_program)
        from .flags import FLAGS

        if FLAGS.verify_program:
            # static backstop before the first compile: structural verify
            # + whole-program shape/dtype inference over the FULL step
            # program (forward, backward, optimizer updates) — a broken
            # layer/rewrite fails here naming op/callsite/slot, not as a
            # JAX trace error inside jit
            from . import analysis

            feeds = [v.name for v in feed_list]
            fetches = [cost.name] + [v.name for v in self.metrics.values()]
            analysis.check_program(self.main_program, feeds, fetches,
                                   scope=scope or global_scope())
            analysis.check_program(self.startup_program)
        # pad_to_multiple: bucket ragged columns (data_feeder.py) so varlen
        # training pads to a bounded set of compile signatures.
        self.feeder = DataFeeder(feed_list, pad_to_multiple=pad_to_multiple)
        self._feed_names = [v.name for v in feed_list]
        self.scope = scope or global_scope()
        if mesh is None and plan is not None:
            mesh = plan.mesh
        self.exe = Executor(place or TPUPlace(0), check_nan_inf=check_nan_inf,
                            mesh=mesh, plan=plan)
        self._initialized = False
        if plan is not None:
            self._apply_plan(plan)

    # ------------------------------------------------------------------
    def _apply_plan(self, plan):
        """One sharding plane: run the ShardProgram pass over the step,
        test, and startup programs (every var annotated with its
        plan-resolved PartitionSpec; located ShardingPlanError on a rule
        set that cannot fit) and point the executor at the plan's mesh —
        parameters then INITIALIZE sharded (the startup run lands each
        shard on its device; no replicated staging copy) and every step
        lowers through ``jax.jit(in_shardings/out_shardings,
        donate_argnums)`` with GSPMD inserting the collectives."""
        from .transpiler import shard_program

        fetches = [self.cost.name] + [v.name for v in
                                      self.metrics.values()]
        for prog in (self.main_program, self.test_program,
                     self.startup_program):
            shard_program(prog, plan, self._feed_names, fetches,
                          scope=self.scope)
        self.exe.mesh = plan.mesh
        self.exe.plan = plan

    def _init_params(self):
        if not self._initialized:
            self.exe.run(self.startup_program, scope=self.scope)
            self._initialized = True

    def _fetch_list(self):
        return [self.cost] + list(self.metrics.values())

    def _split(self, fetched):
        cost = float(np.asarray(fetched[0]))
        names = list(self.metrics.keys())
        vals = {n: float(np.mean(np.asarray(v)))
                for n, v in zip(names, fetched[1:])}
        return cost, vals

    # ------------------------------------------------------------------
    def train(self, reader: Callable, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              test_reader: Optional[Callable] = None,
              run_log=None, async_depth: int = 1,
              checkpoint=None, mem_budget: Optional[float] = None,
              plan=None, goodput=None):
        """Run ``num_passes`` over ``reader`` (a batched reader: yields
        minibatches of rows ordered like ``feed_list``).

        Without an ``event_handler``, batch cost is logged every
        ``--log_period`` batches (flags.py), the reference trainer's
        default output (TrainerInternal.cpp log_period path).

        ``run_log`` (a :class:`paddle_tpu.trace.RunLog` or any event
        callable) receives every event IN ADDITION to ``event_handler``:
        per-iteration cost/metrics/examples-per-sec land in its JSONL
        journal and the global StatSet is dumped at EndPass — the
        Trainer.cpp:449 stat dump, machine-readable.

        ``checkpoint`` (a :class:`paddle_tpu.resilience.CheckpointConfig`)
        makes the run preemption-safe: the scope (params, optimizer
        slots, RNG stream) plus the training position is checkpointed
        every ``every_n_steps`` completed steps (serialized off the
        critical path with ``background=True``), SIGTERM/SIGINT drains
        in-flight work, writes a final checkpoint and exits after
        ``EndPass(interrupted=True)``, and the next ``train`` call
        auto-resumes from the latest intact checkpoint — skipping the
        already-consumed batches of the interrupted pass (unless the
        reader is master-backed, whose task queue already tracks
        consumption) so the end state is bit-identical to an
        uninterrupted run.

        ``mem_budget`` (bytes) gates the step program on the static
        peak-HBM estimate (paddle_tpu.analysis.memory): at the first
        batch — when the batch size is known but BEFORE the first
        compile — the whole step program (forward, backward, optimizer)
        is analyzed against the budget, and a
        :class:`~paddle_tpu.analysis.MemoryBudgetError` naming the peak
        live set and the remat advisor's suggestions is raised instead
        of letting XLA OOM at compile or first run.

        ``plan`` (a :class:`paddle_tpu.parallel.ShardingPlan`) turns the
        run SPMD over the plan's mesh: the ShardProgram pass annotates
        every program var with its PartitionSpec, parameters initialize
        sharded, and the whole step lowers through one
        ``jax.jit(in_shardings/out_shardings, donate_argnums)`` — dp, tp
        (and sp/ep through the mesh-aware op kernels) compose on ONE
        mesh. Equivalent to constructing ``SGD(..., plan=plan)``; must
        be given before the first step initializes parameters.

        ``async_depth`` > 1 pipelines the loop: batch stacking +
        host->device transfer run on a background thread
        (reader.device_prefetch machinery), each step is dispatched with
        ``Executor.run_async`` while up to ``async_depth`` prior steps
        are still in flight, and cost/metrics resolve with that lag —
        ``EndIteration`` fires (in batch order) when a step's fetches
        RESOLVE, with a full drain before ``EndPass``, so a
        ``BeginIteration`` for step k+1 can precede step k's
        ``EndIteration``. Numerics are unchanged: the same programs run
        in the same order on the same device state (async-vs-sync parity
        is pinned bitwise by tests/test_async_training.py). The default
        ``async_depth=1`` is the fully synchronous reference loop.

        ``goodput`` controls the training observatory
        (:class:`paddle_tpu.trace.GoodputMeter`): the default ``None``
        creates a fresh meter so every second of the run decomposes into
        the goodput/badput buckets and ``EndIteration`` events carry
        host/device walls + live MFU; pass an existing meter to share
        accounting (the elastic ``StreamingTrainer`` does), or ``False``
        to run the bare uninstrumented loop (the bench A/B off-leg).
        The active meter is exposed as ``self.goodput``."""
        user_handler = event_handler or _default_log_handler()
        if run_log is not None:
            def event_handler(e, _h=user_handler, _r=run_log):
                _h(e)
                _r(e)
        else:
            event_handler = user_handler
        if plan is not None:
            # a mid-life plan swap is legal: params already initialized
            # under the previous layout are resharded by the executor's
            # device_put at the next step
            self._apply_plan(plan)
        self._init_params()
        self._mem_budget = mem_budget
        self._mem_checked = False
        from .trace.goodput import GoodputMeter

        if goodput is False:
            meter = None
        elif goodput is None or goodput is True:
            meter = GoodputMeter()
        else:
            meter = goodput
        self.goodput = meter
        self._flops_priced = meter is None
        rs = None
        from .flags import FLAGS
        from .resilience import TrainResilience, faults
        if (checkpoint is not None or FLAGS.fault_plan
                or faults.active_plan() is not None):
            # reshard-on-restore: the executor's plan rides into the
            # restore, so a checkpoint saved under a different mesh/plan
            # re-places bitwise through THIS plan's PartitionSpecs
            rs = TrainResilience(checkpoint, scope=self.scope,
                                 plan=self.exe.plan)
            rs.resume()  # restores scope + position from the latest ckpt
            if checkpoint is not None and getattr(checkpoint, "dirname",
                                                  None):
                # cold-start replay: AOT-compile the step signatures the
                # previous run recorded next to its checkpoints, BEFORE
                # the first batch — with --compilation_cache_dir these
                # are disk restores and resume pays zero fresh compiles
                self._replay_manifest(checkpoint.dirname)
        import contextlib

        from .trace.flight import get_recorder

        # live trainer state rides every flight bundle (position,
        # goodput snapshot, recent step walls); WeakMethod-held so a
        # dropped SGD never pins memory
        from collections import deque

        self._flight_pos = {"pass_id": None, "batch_id": None}
        self._step_walls = deque(maxlen=32)
        recorder = get_recorder()
        recorder.add_source("trainer", self._flight_state)
        ctx = rs.signal_context() if rs is not None \
            else contextlib.nullcontext()
        try:
            self._train_passes(ctx, rs, reader, num_passes, event_handler,
                               test_reader, async_depth, meter)
        except BaseException as exc:
            if rs is not None:
                # join (never mask) an in-flight background save so no
                # thread keeps mutating the ckpt dir after the crash
                rs.abort()
            # black box for the postmortem: throttled bundle capturing
            # the exact position/goodput state at the failure
            recorder.auto_dump("trainer_error", error=exc)
            raise
        if rs is not None:
            rs.finalize()
            if checkpoint is not None and getattr(checkpoint, "dirname",
                                                  None):
                self._save_manifest(checkpoint.dirname)

    def _replay_manifest(self, dirname: str):
        """Resume-time warmup: AOT-replay the signature manifest saved
        next to the checkpoints (see core.manifest). A missing manifest
        is a normal first boot; a version-rejected one warns and falls
        back to compile-on-first-step — resume must never die on a
        warmup artifact."""
        import warnings

        from . import trace
        from .core import manifest as manifest_mod

        try:
            manifest = manifest_mod.try_load(dirname)
        except manifest_mod.ManifestError as exc:
            warnings.warn(f"ignoring warmup manifest: {exc}",
                          RuntimeWarning, stacklevel=2)
            return None
        if manifest is None:
            return None
        with trace.span("trainer/manifest_replay", dirname=dirname) as sp:
            stats = manifest_mod.replay(
                self.exe, [self.main_program, self.test_program],
                scope=self.scope, manifest=manifest)
            if sp is not None:
                sp.set_attrs(**stats)
        self._last_replay = stats
        return stats

    def _save_manifest(self, dirname: str) -> None:
        """Persist the compile signatures of this run next to the
        checkpoints so the next resume replays them."""
        if len(self.exe.manifest) == 0:
            return
        try:
            self.exe.manifest.save(dirname)
        except OSError:
            pass  # checkpoint volume gone: the run itself still succeeded

    def _train_passes(self, ctx, rs, reader, num_passes, event_handler,
                      test_reader, async_depth, meter=None):
        import time as time_mod

        with ctx:
            if meter is not None:
                # the residual anchor carries ACROSS passes: event
                # dispatch, reader setup, and the EndPass->BeginPass gap
                # all belong to the decomposition, not just the step loop
                t_anchor = time_mod.perf_counter()
                acc0 = meter.total_seconds()
            for pass_id in range(rs.start_pass if rs else 0, num_passes):
                event_handler(evt.BeginPass(pass_id))
                skip_n = rs.skip_for_pass(pass_id, reader) if rs else 0
                if async_depth > 1:
                    pass_costs, pass_metrics = self._run_pass_async(
                        pass_id, reader, event_handler, int(async_depth),
                        rs=rs, skip_n=skip_n, meter=meter)
                else:
                    pass_costs, pass_metrics = self._run_pass_sync(
                        pass_id, reader, event_handler, rs=rs,
                        skip_n=skip_n, meter=meter)
                if meter is not None:
                    # the residual (event handlers, splits, loop
                    # bookkeeping) closes the decomposition: bucket
                    # seconds sum to the measured pass wall
                    wall = time_mod.perf_counter() - t_anchor
                    meter.account("host_dispatch",
                                  wall - (meter.total_seconds() - acc0))
                    meter.publish_stats(profiler.global_stat)
                    t_anchor = time_mod.perf_counter()
                    acc0 = meter.total_seconds()
                summary = _mean_metrics(pass_metrics)
                summary["cost"] = float(np.mean(pass_costs)) \
                    if pass_costs else 0.0
                if rs is not None and rs.interrupted:
                    # graceful preemption: the final checkpoint is
                    # already on disk (commit with wait=True); no test
                    # pass on the way out
                    event_handler(evt.EndPass(pass_id, metrics=summary,
                                              interrupted=True))
                    break
                if test_reader is not None:
                    result = self.test(test_reader)
                    event_handler(evt.EndPass(pass_id, metrics=summary))
                    event_handler(result)
                else:
                    event_handler(evt.EndPass(pass_id, metrics=summary))

    def _maybe_check_mem_budget(self, feed):
        """One-shot build-time budget gate, run at the first batch (batch
        size now known) BEFORE the first compile/dispatch."""
        if getattr(self, "_mem_budget", None) is None or self._mem_checked:
            return
        self._mem_checked = True
        from . import analysis

        batch = 1
        for v in feed.values():
            shape = getattr(v, "shape", None)
            if shape:
                batch = int(shape[0])
                break
        fetches = [self.cost.name] + [v.name for v in
                                      self.metrics.values()]
        analysis.check_memory_budget(
            self.main_program, list(feed), fetches, self._mem_budget,
            scope=self.scope, batch_size=batch,
            what="SGD.train step program", plan=self.exe.plan)

    def _maybe_price_flops(self, feed, meter):
        """One-shot MFU numerator: price the step program through the
        calibrated cost model at the first batch (batch size now known).
        Unpriceable programs simply leave MFU off."""
        if meter is None or getattr(self, "_flops_priced", True):
            return
        self._flops_priced = True
        from .trace.goodput import program_flops

        batch = 1
        for v in feed.values():
            shape = getattr(v, "shape", None)
            if shape:
                batch = int(shape[0])
                break
        # the static analysis costs ~10ms — cache per batch size so
        # repeated train() calls on one trainer price it once
        cached = getattr(self, "_flops_cache", None)
        if cached is not None and cached[0] == batch:
            meter.set_program_flops(cached[1])
            return
        fetches = [self.cost.name] + [v.name for v in
                                      self.metrics.values()]
        flops = program_flops(
            self.main_program, self._feed_names, fetches,
            scope=self.scope, batch_size=batch, plan=self.exe.plan)
        self._flops_cache = (batch, flops)
        meter.set_program_flops(flops)

    def _flight_state(self):
        """Live-state source for the flight recorder: where the run is,
        its goodput waterfall, and the last-N step walls."""
        meter = getattr(self, "goodput", None)
        return {
            "position": dict(getattr(self, "_flight_pos", {}) or {}),
            "goodput": meter.snapshot() if meter is not None else None,
            "recent_step_walls_s": [
                round(w, 6) for w in getattr(self, "_step_walls", [])],
        }

    def _run_pass_sync(self, pass_id, reader, event_handler, rs=None,
                       skip_n=0, meter=None):
        import time as time_mod

        from . import trace

        m = meter
        perf = time_mod.perf_counter
        exe = self.exe
        pass_costs, pass_metrics = [], []
        it = enumerate(reader())
        while True:
            # the reader pull is the data-wait bucket; a master-backed
            # reader (StreamingTrainer) accounts its queue idle +
            # rollback time into the shared meter DURING next(), so
            # those inner seconds are re-attributed out of data_wait
            if m is not None:
                inner0 = (m.bucket_seconds("master_wait")
                          + m.bucket_seconds("recovery_rollback"))
                t_read0 = perf()
            try:
                batch_id, batch = next(it)
                while batch_id < skip_n:
                    # consumed before the interrupt (resume replay)
                    batch_id, batch = next(it)
            except StopIteration:
                if m is not None:
                    inner = (m.bucket_seconds("master_wait")
                             + m.bucket_seconds("recovery_rollback")
                             - inner0)
                    m.account("data_wait", perf() - t_read0 - inner)
                break
            if m is not None:
                inner = (m.bucket_seconds("master_wait")
                         + m.bucket_seconds("recovery_rollback")
                         - inner0)
                m.account("data_wait", perf() - t_read0 - inner)
                t_step0 = perf()
                self._flight_pos["pass_id"] = pass_id
                self._flight_pos["batch_id"] = batch_id
            if rs is not None:
                # transient-fault retries (backoff included) are
                # recovery, not compute
                if m is not None:
                    with m.measure("recovery_rollback"):
                        rs.before_step()
                else:
                    rs.before_step()
            event_handler(evt.BeginIteration(pass_id, batch_id))
            # REGISTER_TIMER("TrainBatch") parity: the step timer
            # accumulates in the global StatSet, which RunLog dumps
            # (and print_all_status prints) at pass end
            device_dt = step_mfu = None
            with trace.span("trainer/iteration", pass_id=pass_id,
                            batch_id=batch_id) as sp, \
                    profiler.timer("trainer/step"):
                if m is not None:
                    t_feed0 = perf()
                feed = self.feeder.feed(batch)
                if m is not None:
                    m.account("data_wait", perf() - t_feed0)
                self._maybe_check_mem_budget(feed)
                self._maybe_price_flops(feed, m)
                if m is not None:
                    fc0 = exe.fresh_compile_seconds
                    t_run0 = perf()
                fetched = exe.run(self.main_program, feed=feed,
                                  fetch_list=self._fetch_list(),
                                  scope=self.scope)
                if m is not None:
                    run_dt = perf() - t_run0
                    fc_dt = min(exe.fresh_compile_seconds - fc0, run_dt)
                    device_dt = run_dt - fc_dt
                    m.account("fresh_compile", fc_dt)
                    m.account("device_compute", device_dt)
                    step_mfu = m.note_step(device_dt)
                cost, mvals = self._split(fetched)
                if sp is not None:
                    sp.set_attr("cost", cost)
            pass_costs.append(cost)
            pass_metrics.append(mvals)
            try:
                bs = len(batch)
            except TypeError:
                bs = None
            host_dt = None
            if m is not None:
                step_wall = perf() - t_step0
                host_dt = max(0.0, step_wall - (device_dt or 0.0))
                self._step_walls.append(step_wall)
            event_handler(evt.EndIteration(pass_id, batch_id, cost,
                                           mvals, batch_size=bs,
                                           host_wall_s=host_dt,
                                           device_wall_s=device_dt,
                                           mfu=step_mfu))
            if rs is not None:
                # a due/periodic save stalls the loop right here
                if m is not None:
                    with m.measure("checkpoint_stall"):
                        stop = rs.after_step(pass_id, batch_id, bs)
                else:
                    stop = rs.after_step(pass_id, batch_id, bs)
                if stop:
                    break  # graceful interrupt: checkpoint written
        return pass_costs, pass_metrics

    def _run_pass_async(self, pass_id, reader, event_handler, depth,
                        rs=None, skip_n=0, meter=None):
        """The overlapped pipeline: a background feeder stage keeps
        device-resident batches ready, the dispatch loop enqueues step
        k+1 while step k executes (bounded at ``depth`` in flight), and
        the oldest step resolves — one host sync — only when the window
        is full. Iteration spans split into ``trainer/dispatch`` and
        ``trainer/resolve`` phases carrying a ``queue_depth`` attr, so
        tools/trace_summary.py --pipeline shows host gap vs device
        time."""
        import time as time_mod
        from collections import deque

        import jax

        from . import trace
        from .reader.decorator import background_stage

        feeder = self.feeder
        dev = None if self.exe.mesh is not None \
            else self.exe.place.device()

        def feed_source():
            for batch_id, batch in enumerate(reader()):
                if batch_id < skip_n:
                    continue  # consumed before the interrupt (resume)
                try:
                    bs = len(batch)
                except TypeError:
                    bs = None
                yield batch_id, bs, feeder.feed(batch)

        def to_device(item):
            batch_id, bs, feed = item
            if dev is None:  # mesh runs: the executor shards feeds itself
                return batch_id, bs, feed
            return batch_id, bs, {k: (jax.device_put(v, dev)
                                      if not isinstance(v, jax.Array)
                                      else v)
                                  for k, v in feed.items()}

        m = meter
        perf = time_mod.perf_counter
        exe = self.exe
        pending = deque()  # (batch_id, batch_size, RunHandle, host_wall)
        pass_costs, pass_metrics = [], []
        # device wall per step on the overlapped path = the
        # resolve-ordered interval (EndIteration k-1 -> EndIteration k):
        # with the window full the device is the bottleneck, so that
        # interval IS the step's device time — the MFU denominator and
        # the runlog's examples/sec base
        last_resolve = [None]

        def resolve_oldest():
            batch_id, bs, handle, host_dt = pending.popleft()
            if m is not None:
                t0 = perf()
            with trace.span("trainer/resolve", pass_id=pass_id,
                            batch_id=batch_id,
                            queue_depth=len(pending) + 1) as sp, \
                    profiler.timer("trainer/resolve"):
                cost, mvals = self._split(handle.result())
                if sp is not None:
                    sp.set_attr("cost", cost)
            device_dt = step_mfu = None
            if m is not None:
                now = perf()
                # host blocked on device results: the goodput numerator
                m.account("device_compute", now - t0)
                if last_resolve[0] is not None:
                    device_dt = now - last_resolve[0]
                    step_mfu = m.note_step(device_dt)
                    self._step_walls.append(device_dt)
                last_resolve[0] = now
            pass_costs.append(cost)
            pass_metrics.append(mvals)
            event_handler(evt.EndIteration(pass_id, batch_id, cost,
                                           mvals, batch_size=bs,
                                           host_wall_s=host_dt,
                                           device_wall_s=device_dt,
                                           mfu=step_mfu))
            if rs is not None:
                # defer: a snapshot here would race the in-flight window
                # (donated state) — the dispatch loop drains, then
                # commits at the safe point
                rs.after_step(pass_id, batch_id, bs, defer=True)

        stream = background_stage(feed_source, depth=depth,
                                  transform=to_device)
        stopped = False
        try:
            sit = iter(stream())
            while True:
                # blocked on the background feed stage = data wait
                if m is not None:
                    t_read0 = perf()
                try:
                    batch_id, bs, feed = next(sit)
                except StopIteration:
                    if m is not None:
                        m.account("data_wait", perf() - t_read0)
                    break
                if m is not None:
                    m.account("data_wait", perf() - t_read0)
                    self._flight_pos["pass_id"] = pass_id
                    self._flight_pos["batch_id"] = batch_id
                if rs is not None:
                    if m is not None:
                        with m.measure("recovery_rollback"):
                            rs.before_step()
                    else:
                        rs.before_step()
                self._maybe_check_mem_budget(feed)
                self._maybe_price_flops(feed, m)
                event_handler(evt.BeginIteration(pass_id, batch_id))
                host_dt = None
                if m is not None:
                    fc0 = exe.fresh_compile_seconds
                    t_disp0 = perf()
                with trace.span("trainer/dispatch", pass_id=pass_id,
                                batch_id=batch_id,
                                queue_depth=len(pending)), \
                        profiler.timer("trainer/dispatch"):
                    handle = exe.run_async(self.main_program, feed=feed,
                                           fetch_list=self._fetch_list(),
                                           scope=self.scope)
                if m is not None:
                    host_dt = perf() - t_disp0
                    fc_dt = min(exe.fresh_compile_seconds - fc0, host_dt)
                    m.account("fresh_compile", fc_dt)
                    m.account("host_dispatch", host_dt - fc_dt)
                pending.append((batch_id, bs, handle, host_dt))
                while len(pending) >= depth:
                    resolve_oldest()
                if rs is not None and rs.pause_requested:
                    # checkpoint due / shutdown: drain the whole window so
                    # resolved == dispatched == scope state, then save
                    while pending:
                        resolve_oldest()
                    if m is not None:
                        with m.measure("checkpoint_stall"):
                            stop = rs.commit(pass_id)
                    else:
                        stop = rs.commit(pass_id)
                    if stop:
                        stopped = True
                        break
            while pending:  # drain: every EndIteration precedes EndPass
                resolve_oldest()
            if not stopped and rs is not None and rs.pause_requested:
                if m is not None:
                    with m.measure("checkpoint_stall"):
                        rs.commit(pass_id)
                else:
                    rs.commit(pass_id)
        except BaseException:
            # In-flight steps' state writes have already landed in the
            # scope; drain their handles (costs/metrics + EndIteration
            # per step) so the event stream stays consistent with the
            # scope before propagating. If the drain itself keeps
            # failing (e.g. the handler raises), at least block the
            # remaining handles instead of abandoning them mid-flight.
            while pending:
                try:
                    resolve_oldest()
                except BaseException:
                    for _, _, h, _ in pending:
                        try:
                            h.block()
                        except Exception:
                            pass
                    pending.clear()
            raise
        return pass_costs, pass_metrics

    def test(self, reader: Callable) -> "evt.TestResult":
        self._init_params()
        costs, metrics = [], []
        for batch in reader():
            feed = self.feeder.feed(batch)
            fetched = self.exe.run(self.test_program, feed=feed,
                                   fetch_list=self._fetch_list(),
                                   scope=self.scope)
            cost, mvals = self._split(fetched)
            costs.append(cost)
            metrics.append(mvals)
        return evt.TestResult(float(np.mean(costs)) if costs else 0.0,
                              _mean_metrics(metrics))

    # ------------------------------------------------------------------
    def save_params(self, dirname: str):
        io_mod.save_params(self.exe, dirname, self.main_program,
                           scope=self.scope)

    def load_params(self, dirname: str):
        self._init_params()
        io_mod.load_params(self.exe, dirname, self.main_program,
                           scope=self.scope)


def _default_log_handler():
    from .flags import FLAGS

    period = max(int(FLAGS.log_period), 1)

    def handler(e):
        if isinstance(e, evt.EndIteration) and e.batch_id % period == 0:
            extra = "".join(f" {k}={v:.4f}" for k, v in
                            (e.metrics or {}).items())
            print(f"pass {e.pass_id} batch {e.batch_id} "
                  f"cost={e.cost:.6f}{extra}", flush=True)
        elif isinstance(e, evt.EndPass):
            print(f"pass {e.pass_id} done: "
                  + " ".join(f"{k}={v:.6f}" for k, v in
                             (e.metrics or {}).items()), flush=True)

    return handler


def _mean_metrics(per_batch):
    out: Dict[str, float] = {}
    if per_batch:
        for key in per_batch[0]:
            out[key] = float(np.mean([m[key] for m in per_batch]))
    return out

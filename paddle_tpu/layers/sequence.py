"""Sequence & recurrent layer functions.

Parity targets: the reference's fluid sequence layers
(/root/reference/python/paddle/v2/fluid/layers/nn.py: sequence_pool,
sequence_conv, dynamic_lstm, dynamic_gru, sequence_expand, sequence_first/
last_step) and the v1 helpers they wrap.

Sequence-ness here is a build-time property: a Variable carries a
``seq_len`` pointer to its companion int32 ``[batch]`` lengths Variable
(created by ``layers.data(..., lod_level>0)`` — the dense+mask replacement
for the reference's LoD, SURVEY.md §5.7). Layer functions thread it from
inputs to outputs, so masked ops always see the right lengths without the
user plumbing them by hand.
"""
from __future__ import annotations

import numpy as np

from ..initializer import XavierInitializer
from .layer_helper import LayerHelper


def get_seq_len(var):
    """The lengths Variable travelling with ``var`` (or None)."""
    return getattr(var, "seq_len", None)


def _len_input(var):
    sl = get_seq_len(var)
    return {"Length": [sl]} if sl is not None else {}


def sequence_pool(input, pool_type="average", main_program=None,
                  startup_program=None):
    helper = LayerHelper("sequence_pool", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op(
        "sequence_pool", {"X": [input], **_len_input(input)},
        {"pool_type": pool_type})


def sequence_first_step(input, **kw):
    return sequence_pool(input, "first", **kw)


def sequence_last_step(input, **kw):
    return sequence_pool(input, "last", **kw)


def sequence_softmax(input, main_program=None, startup_program=None):
    helper = LayerHelper("sequence_softmax", main_program=main_program,
                         startup_program=startup_program)
    y = helper.simple_op("sequence_softmax",
                         {"X": [input], **_len_input(input)})
    y.seq_len = get_seq_len(input)
    return y


def sequence_expand(x, y, main_program=None, startup_program=None):
    """Broadcast each row of ``x`` across ``y``'s time axis (reference
    sequence_expand with y's LoD)."""
    helper = LayerHelper("sequence_expand", main_program=main_program,
                         startup_program=startup_program)
    o = helper.simple_op(
        "sequence_expand", {"X": [x], "Y": [y], **_len_input(y)})
    o.seq_len = get_seq_len(y)
    return o


def sequence_reverse(input, main_program=None, startup_program=None):
    helper = LayerHelper("sequence_reverse", main_program=main_program,
                         startup_program=startup_program)
    outs, _ = helper.append_op(
        "sequence_reverse", {"X": [input], **_len_input(input)}, ["Y"])
    y = outs["Y"][0]
    y.seq_len = get_seq_len(input)
    return y


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  main_program=None, startup_program=None):
    """Context-window conv over a sequence (reference nn.py sequence_conv)."""
    if filter_stride != 1:
        # The reference op enforces contextStride == 1 too
        # (sequence_conv_op.cc PADDLE_ENFORCE).
        raise ValueError("sequence_conv only supports filter_stride=1")
    if padding is not None:
        raise NotImplementedError(
            "trainable context padding (PaddingData) is not supported; "
            "out-of-range context rows are zero-padded")
    helper = LayerHelper("sequence_conv", main_program=main_program,
                         startup_program=startup_program)
    d = input.shape[-1]
    filter_shape = [filter_size * d, num_filters]
    filt = helper.create_parameter(
        param_attr, shape=filter_shape, dtype=input.dtype,
        default_initializer=XavierInitializer())
    pre_bias = helper.simple_op(
        "sequence_conv",
        {"X": [input], "Filter": [filt], **_len_input(input)},
        {"contextLength": filter_size, "contextStart": -int(filter_size // 2),
         "contextStride": filter_stride})
    pre_act = helper.append_bias_op(pre_bias, bias_attr, num_filters,
                                    dim_start=2)
    o = helper.append_activation(pre_act, act)
    o.seq_len = get_seq_len(input)
    return o


def row_conv(input, future_context_size, param_attr=None, act=None,
             main_program=None, startup_program=None):
    helper = LayerHelper("row_conv", main_program=main_program,
                         startup_program=startup_program)
    d = input.shape[-1]
    filt = helper.create_parameter(
        param_attr, shape=[future_context_size, d], dtype=input.dtype,
        default_initializer=XavierInitializer())
    o = helper.simple_op(
        "row_conv", {"X": [input], "Filter": [filt], **_len_input(input)})
    o.seq_len = get_seq_len(input)
    return helper.append_activation(o, act)


def sequence_concat(inputs, main_program=None, startup_program=None):
    helper = LayerHelper("sequence_concat", main_program=main_program,
                         startup_program=startup_program)
    lens = [get_seq_len(v) for v in inputs]
    ins = {"X": list(inputs)}
    if all(l is not None for l in lens):
        ins["Length"] = lens
    outs, _ = helper.append_op("sequence_concat", ins, ["Out", "OutLength"])
    o = outs["Out"][0]
    o.seq_len = outs["OutLength"][0]
    return o


def dynamic_lstm(input, size, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", param_attr=None, bias_attr=None,
                 h0=None, c0=None, main_program=None, startup_program=None):
    """LSTM over a pre-projected sequence (reference nn.py dynamic_lstm /
    lstm_op.cc). ``input`` is [b, T, size] with size = 4*hidden; returns
    (hidden_seq, cell_seq)."""
    helper = LayerHelper("lstm", main_program=main_program,
                         startup_program=startup_program)
    hidden = size // 4
    w = helper.create_parameter(
        param_attr, shape=[hidden, 4 * hidden], dtype=input.dtype,
        default_initializer=XavierInitializer())
    bias_cols = 7 * hidden if use_peepholes else 4 * hidden
    bias = None if bias_attr is False else helper.create_parameter(
        bias_attr, shape=[1, bias_cols], dtype=input.dtype, is_bias=True)
    ins = {"Input": [input], "Weight": [w], **_len_input(input)}
    if bias is not None:
        ins["Bias"] = [bias]
    if h0 is not None:
        ins["H0"] = [h0]
    if c0 is not None:
        ins["C0"] = [c0]
    outs, _ = helper.append_op(
        "lstm", ins, ["Hidden", "Cell", "LastH", "LastC"],
        {"use_peepholes": use_peepholes, "is_reverse": is_reverse,
         "gate_activation": gate_activation,
         "cell_activation": cell_activation,
         "candidate_activation": candidate_activation})
    h_seq, c_seq = outs["Hidden"][0], outs["Cell"][0]
    h_seq.seq_len = get_seq_len(input)
    c_seq.seq_len = get_seq_len(input)
    return h_seq, c_seq


def dynamic_gru(input, size, is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", param_attr=None, bias_attr=None,
                h0=None, main_program=None, startup_program=None):
    """GRU over a pre-projected sequence (reference gru_op.cc): ``input`` is
    [b, T, 3*size], returns hidden sequence [b, T, size]."""
    helper = LayerHelper("gru", main_program=main_program,
                         startup_program=startup_program)
    w = helper.create_parameter(
        param_attr, shape=[size, 3 * size], dtype=input.dtype,
        default_initializer=XavierInitializer())
    bias = None if bias_attr is False else helper.create_parameter(
        bias_attr, shape=[1, 3 * size], dtype=input.dtype, is_bias=True)
    ins = {"Input": [input], "Weight": [w], **_len_input(input)}
    if bias is not None:
        ins["Bias"] = [bias]
    if h0 is not None:
        ins["H0"] = [h0]
    outs, _ = helper.append_op(
        "gru", ins, ["Hidden", "LastH"],
        {"is_reverse": is_reverse, "gate_activation": gate_activation,
         "activation": candidate_activation})
    h_seq = outs["Hidden"][0]
    h_seq.seq_len = get_seq_len(input)
    return h_seq


def simple_rnn(input, size=None, is_reverse=False, activation="tanh",
               param_attr=None, bias_attr=None, h0=None,
               main_program=None, startup_program=None):
    """Plain RNN over a sequence already at hidden width (the v1
    ``recurrent_layer``, reference gserver/layers/RecurrentLayer.cpp):
    out_t = act(in_t + out_{t-1} @ W + b). ``input`` is [b, T, h]."""
    helper = LayerHelper("simple_rnn", main_program=main_program,
                         startup_program=startup_program)
    hdim = int(size or input.shape[-1])
    w = helper.create_parameter(
        param_attr, shape=[hdim, hdim], dtype=input.dtype,
        default_initializer=XavierInitializer())
    bias = None if bias_attr is False else helper.create_parameter(
        bias_attr, shape=[1, hdim], dtype=input.dtype, is_bias=True)
    ins = {"Input": [input], "Weight": [w], **_len_input(input)}
    if bias is not None:
        ins["Bias"] = [bias]
    if h0 is not None:
        ins["H0"] = [h0]
    outs, _ = helper.append_op(
        "simple_rnn", ins, ["Hidden", "LastH"],
        {"is_reverse": is_reverse, "activation": activation})
    h_seq = outs["Hidden"][0]
    h_seq.seq_len = get_seq_len(input)
    return h_seq


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, main_program=None,
              startup_program=None):
    """One LSTM step from raw inputs (reference nn.py lstm_unit): concat
    [x, h] -> fc to 4h -> lstm_unit op. Returns (h, c)."""
    from . import nn as nn_layers
    from . import tensor as tensor_layers

    helper = LayerHelper("lstm_unit", main_program=main_program,
                         startup_program=startup_program)
    size = cell_t_prev.shape[-1]
    concat = tensor_layers.concat([x_t, hidden_t_prev], axis=-1)
    gates = nn_layers.fc(concat, size=4 * size, param_attr=param_attr,
                         bias_attr=bias_attr,
                         main_program=helper.main_program,
                         startup_program=helper.startup_program)
    outs, _ = helper.append_op(
        "lstm_unit", {"X": [gates], "C_prev": [cell_t_prev]},
        ["C", "H"], {"forget_bias": forget_bias})
    return outs["H"][0], outs["C"][0]


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             main_program=None, startup_program=None):
    """One GRU step (reference nn.py gru_unit): ``input`` pre-projected
    [b, 3*h]; returns (new_hidden, gates, reset_hidden_prev)."""
    helper = LayerHelper("gru_unit", main_program=main_program,
                         startup_program=startup_program)
    hdim = size
    w = helper.create_parameter(
        param_attr, shape=[hdim, 3 * hdim], dtype=input.dtype,
        default_initializer=XavierInitializer())
    bias = None if bias_attr is False else helper.create_parameter(
        bias_attr, shape=[1, 3 * hdim], dtype=input.dtype, is_bias=True)
    ins = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias is not None:
        ins["Bias"] = [bias]
    outs, _ = helper.append_op(
        "gru_unit", ins, ["Hidden", "Gate", "ResetHiddenPrev"],
        {"activation": activation, "gate_activation": gate_activation})
    return outs["Hidden"][0], outs["Gate"][0], outs["ResetHiddenPrev"][0]


def warpctc(input, label, blank=0, norm_by_times=False,
            main_program=None, startup_program=None):
    """CTC loss layer (reference WarpCTCLayer.cpp / hl_warpctc_wrap.cc).

    ``input``: [b, T, C] unnormalized logits; ``label``: [b, L] int ids.
    Sequence lengths attached to either variable (data(..., lod_level=1) /
    upstream sequence ops) are used automatically. Returns Loss [b, 1].
    """
    helper = LayerHelper("warpctc", main_program=main_program,
                         startup_program=startup_program)
    ins = {"Logits": [input], "Label": [label]}
    ll = get_seq_len(input)
    tl = get_seq_len(label)
    if ll is not None:
        ins["LogitsLength"] = [ll]
    if tl is not None:
        ins["LabelLength"] = [tl]
    outs, _ = helper.append_op(
        "warpctc", ins, ["Loss"],
        {"blank": blank, "norm_by_times": norm_by_times})
    return outs["Loss"][0]


def ctc_greedy_decoder(input, blank=0, main_program=None,
                       startup_program=None):
    """Best-path CTC decoding (collapse repeats, drop blanks); returns
    (decoded [b, T] padded with blank, lengths [b, 1])."""
    helper = LayerHelper("ctc_greedy_decoder", main_program=main_program,
                         startup_program=startup_program)
    ins = {"Logits": [input]}
    ll = get_seq_len(input)
    if ll is not None:
        ins["LogitsLength"] = [ll]
    outs, _ = helper.append_op("ctc_greedy_decode", ins,
                               ["Out", "OutLength"], {"blank": blank})
    dec, n = outs["Out"][0], outs["OutLength"][0]
    dec.seq_len = n
    return dec, n

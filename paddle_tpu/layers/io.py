"""Data-entry layer (fluid layers.data parity)."""
from __future__ import annotations

from ..core.program import default_main_program
from .layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         main_program=None, stop_gradient=True):
    """Declare a feed variable.

    Matches /root/reference/python/paddle/v2/fluid/layers (data): by default a
    -1 batch dimension is prepended; the executor concretises it from the
    actual feed and re-jits per batch-shape signature.
    """
    helper = LayerHelper("data", main_program=main_program)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.block.create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True,
    )

"""Data-entry layer (fluid layers.data parity)."""
from __future__ import annotations

from ..core.program import default_main_program
from .layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         main_program=None, stop_gradient=True):
    """Declare a feed variable.

    Matches /root/reference/python/paddle/v2/fluid/layers (data): by default a
    -1 batch dimension is prepended; the executor concretises it from the
    actual feed and re-jits per batch-shape signature.
    """
    helper = LayerHelper("data", main_program=main_program)
    shape = list(shape)
    if lod_level > 0:
        # Dense+mask sequence feed (SURVEY.md §5.7): the tensor is padded to
        # [batch, T, *shape] and a companion int32 ``<name>@len`` [batch]
        # carries true lengths — the feeder (data_feeder.py) emits both. The
        # reference instead packs rows and threads LoD offsets
        # (/root/reference/paddle/framework/lod_tensor.h:43-58).
        import numpy as _np
        is_ids = (len(shape) == 1 and shape[0] == 1
                  and _np.issubdtype(_np.dtype(dtype), _np.integer))
        shape = [-1, -1] + ([] if is_ids else shape)
        var = helper.block.create_var(
            name=name, shape=shape, dtype=dtype, lod_level=lod_level,
            stop_gradient=stop_gradient, is_data=True,
        )
        len_var = helper.block.create_var(
            name=f"{name}@len", shape=[-1], dtype="int32",
            stop_gradient=True, is_data=True,
        )
        # companion feeds are emitted by the DataFeeder alongside their
        # owner column; they are not reader columns of their own
        len_var.is_companion = True
        var.seq_len = len_var
        return var
    if append_batch_size:
        shape = [-1] + shape
    return helper.block.create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True,
    )

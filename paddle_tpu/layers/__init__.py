"""User-facing layer functions (fluid layers package parity)."""
from .io import data
from .nn import (accuracy, batch_norm, conv2d, cross_entropy, dropout,
                 embedding, fc, layer_norm, lrn, pool2d, square_error_cost,
                 softmax_with_cross_entropy, topk)
from .ops import *  # noqa: F401,F403  (auto-generated unary/binary wrappers)
from .ops import __all__ as _ops_all
from .sequence import (dynamic_gru, dynamic_lstm, gru_unit, lstm_unit,
                       row_conv, sequence_concat, sequence_conv,
                       sequence_expand, sequence_first_step,
                       sequence_last_step, sequence_pool, sequence_reverse,
                       sequence_softmax)
from .tensor import (argmax, assign, cast, concat, create_global_var,
                     fill_constant, mean, one_hot, reshape, scale, split,
                     sums, transpose)

__all__ = (
    ["data", "fc", "embedding", "conv2d", "pool2d", "batch_norm", "layer_norm",
     "dropout", "lrn", "cross_entropy", "softmax_with_cross_entropy",
     "square_error_cost", "accuracy", "topk",
     "fill_constant", "create_global_var", "cast", "concat", "sums", "assign",
     "mean", "scale", "reshape", "transpose", "split", "one_hot", "argmax",
     "sequence_pool", "sequence_first_step", "sequence_last_step",
     "sequence_softmax", "sequence_expand", "sequence_reverse",
     "sequence_conv", "sequence_concat", "row_conv",
     "dynamic_lstm", "dynamic_gru", "lstm_unit", "gru_unit"]
    + list(_ops_all)
)

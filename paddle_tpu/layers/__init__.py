"""User-facing layer functions (fluid layers package parity)."""
from .io import data
from .nn import (accuracy, batch_norm, chunk_eval, clip, conv1x1_bn_act,
                 conv2d, conv2d_transpose, cos_sim, crf_decoding, mul,
                 cross_entropy, dropout, embedding, fc,
                 fused_head_cross_entropy, layer_norm,
                 linear_chain_crf, lrn, pool2d, rms_norm,
                 sigmoid_cross_entropy_with_logits, square_error_cost,
                 softmax_with_cross_entropy, topk)
from .attention import (multi_head_attention, pipelined_transformer_stack,
                        switch_moe, transformer_encoder_layer)
from .control_flow import (DynamicRNN, StaticRNN, While, array_length,
                           array_read, array_write, beam_search_decoder,
                           create_array, increment)
from .control_flow import beam_search_decode
from .ops import *  # noqa: F401,F403  (auto-generated unary/binary wrappers)
from .ops import __all__ as _ops_all
from .sequence import (ctc_greedy_decoder, dynamic_gru, dynamic_lstm,
                       gru_unit, lstm_unit, row_conv, simple_rnn,
                       sequence_concat,
                       sequence_conv, sequence_expand, sequence_first_step,
                       sequence_last_step, sequence_pool, sequence_reverse,
                       sequence_softmax, warpctc)
from .detection import (bilinear_interp, box_coder, hsigmoid,
                        iou_similarity, multibox_loss, prior_box)
from .legacy import (addto, dot_prod, factorization_machine, gated_unit,
                     interpolation, kmax_seq_score, l2_distance, linear_comb,
                     multiplex, out_prod, power, repeat, resize, rotate,
                     row_l2_norm, sampling_id, scale_shift, scaling,
                     sequence_reshape, slope_intercept, sum_to_one_norm)
from . import math_op_patch  # noqa: F401 - patches +,-,*,/ onto Variable
from .tensor import (argmax, assign, cast, concat, create_global_var,
                     create_tensor, fill_constant,
                     fill_constant_batch_size_like, ones, zeros,
                     gaussian_random_batch_size_like, matmul,
                     mean, one_hot, reduce_max, reduce_mean, reduce_min,
                     reduce_sum, reshape, scale, split, sums, transpose)

__all__ = (
    ["data", "fc", "embedding", "conv2d", "pool2d", "batch_norm", "layer_norm",
     "rms_norm", "dropout", "lrn", "cross_entropy", "conv1x1_bn_act",
     "fused_head_cross_entropy",
     "softmax_with_cross_entropy",
     "sigmoid_cross_entropy_with_logits",
     "square_error_cost", "accuracy", "topk",
     "linear_chain_crf", "crf_decoding", "chunk_eval",
     "fill_constant", "fill_constant_batch_size_like",
     "gaussian_random_batch_size_like",
     "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
     "create_global_var", "cast", "concat", "sums", "assign",
     "matmul", "mean", "scale", "reshape", "transpose", "split", "one_hot", "argmax",
     "sequence_pool", "sequence_first_step", "sequence_last_step",
     "sequence_softmax", "sequence_expand", "sequence_reverse",
     "sequence_conv", "sequence_concat", "row_conv",
     "dynamic_lstm", "dynamic_gru", "simple_rnn", "lstm_unit", "gru_unit",
     "warpctc", "ctc_greedy_decoder",
     "StaticRNN", "DynamicRNN", "While", "create_array", "array_write",
     "array_read", "array_length", "increment", "beam_search_decoder",
     "beam_search_decode", "cos_sim", "mul", "clip", "conv2d_transpose",
     "create_tensor", "ones", "zeros",
     "multi_head_attention", "transformer_encoder_layer", "switch_moe",
     "pipelined_transformer_stack",
     "interpolation", "scaling", "power", "slope_intercept", "addto",
     "sum_to_one_norm", "row_l2_norm", "scale_shift", "linear_comb",
     "dot_prod", "out_prod", "l2_distance", "repeat", "resize", "rotate",
     "multiplex", "kmax_seq_score", "sequence_reshape", "sampling_id",
     "factorization_machine", "gated_unit",
     "prior_box", "iou_similarity", "box_coder", "multibox_loss",
     "bilinear_interp", "hsigmoid"]
    + list(_ops_all)
)

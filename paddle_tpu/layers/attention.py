"""Attention & transformer layers (capability extension beyond the
reference — SURVEY.md §5.7; the reference's sequence modelling tops out at
LSTM/GRU + RecurrentGradientMachine).

TPU-first design notes: weights are fused (one qkv projection = one MXU
matmul), heads live in a [B, H, T, D] layout whose last dim maps to lanes,
attention is the flash kernel, and everything between the matmuls fuses
under the whole-block XLA compile.
"""
from __future__ import annotations

from ..initializer import NormalInitializer, XavierInitializer
from .layer_helper import LayerHelper
from .sequence import get_seq_len


def multi_head_attention(queries, keys=None, values=None, d_model=None,
                         num_heads=8, num_kv_heads=None, causal=False,
                         use_rope=False, sequence_parallel=False,
                         param_attr=None,
                         main_program=None, startup_program=None):
    """Multi-head attention over [b, T, d_model] sequences; self-attention
    when keys/values are omitted. Returns [b, T, d_model].

    ``num_kv_heads`` < num_heads gives grouped-query / multi-query
    attention (smaller KV projections and caches — the long-context
    serving trade); ``use_rope`` applies rotary position embedding to
    q/k heads in place of learned positions."""
    from . import tensor as T

    helper = LayerHelper("multi_head_attention", main_program=main_program,
                         startup_program=startup_program)
    keys = queries if keys is None else keys
    values = keys if values is None else values
    d_model = d_model or queries.shape[-1]
    if d_model % num_heads:
        raise ValueError(f"d_model {d_model} not divisible by heads "
                         f"{num_heads}")
    num_kv_heads = num_kv_heads or num_heads
    if num_heads % num_kv_heads:
        raise ValueError(f"num_heads {num_heads} not a multiple of "
                         f"num_kv_heads {num_kv_heads}")
    head_d = d_model // num_heads
    d_kv = head_d * num_kv_heads
    self_attn = keys is queries

    def proj(x, width, name):
        # each projection gets its own parameter: suffix a user-provided
        # name so qkv/out never collapse onto one shared weight
        from ..param_attr import ParamAttr

        attr = ParamAttr.to_attr(param_attr)
        if attr is not None and attr.name:
            import copy

            attr = copy.copy(attr)
            attr.name = f"{attr.name}.{name}"
        w = helper.create_parameter(
            attr, shape=[x.shape[-1], width], dtype=x.dtype,
            default_initializer=XavierInitializer())
        return helper.simple_op("mul", {"X": [x], "Y": [w]},
                                {"x_num_col_dims": 2})

    mp, sp = helper.main_program, helper.startup_program
    if self_attn:
        qkv = proj(queries, d_model + 2 * d_kv, "qkv")  # ONE fused matmul
        q, k, v = T.split(qkv, [d_model, d_kv, d_kv], dim=2,
                          main_program=mp, startup_program=sp)
    else:
        q = proj(queries, d_model, "q")
        k = proj(keys, d_kv, "k")
        v = proj(values, d_kv, "v")

    def heads(x, Tlen, n):
        x = T.reshape(x, [-1, Tlen, n, head_d], main_program=mp,
                      startup_program=sp)
        return T.transpose(x, [0, 2, 1, 3], main_program=mp,
                           startup_program=sp)

    tq, tk = queries.shape[1], keys.shape[1]
    qh = heads(q, tq, num_heads)
    kh = heads(k, tk, num_kv_heads)
    vh = heads(v, tk, num_kv_heads)
    if use_rope:
        qh = helper.simple_op("rotary_embed", {"X": [qh]})
        kh = helper.simple_op("rotary_embed", {"X": [kh]})
    ins = {"Q": [qh], "K": [kh], "V": [vh]}
    sl = get_seq_len(keys)
    if sl is not None:
        ins["Length"] = [sl]
    ctx = helper.simple_op("scaled_dot_product_attention", ins,
                           {"causal": causal,
                            "sequence_parallel": sequence_parallel})
    ctx = T.transpose(ctx, [0, 2, 1, 3], main_program=mp, startup_program=sp)
    ctx = T.reshape(ctx, [-1, tq, d_model], main_program=mp,
                    startup_program=sp)
    o = proj(ctx, d_model, "out")
    o.seq_len = get_seq_len(queries)
    return o


def transformer_encoder_layer(x, num_heads, d_ff, causal=False,
                              num_kv_heads=None, use_rope=False,
                              dropout_prob=0.0, sequence_parallel=False,
                              moe_experts=0, norm_type="layer_norm",
                              main_program=None,
                              startup_program=None):
    """Pre-LN transformer block: x + MHA(LN(x)); x + FFN(LN(x)).
    ``sequence_parallel`` routes attention through the ring kernel when the
    executor mesh has an 'sp' axis; ``moe_experts`` > 0 swaps the dense FFN
    for a Switch MoE (returns (out, aux_loss) in that case);
    ``norm_type="rms_norm"`` swaps both pre-norms for RMSNorm (single
    reduction, no shift — the modern LM convention)."""
    from . import nn as N

    if norm_type not in ("layer_norm", "rms_norm"):
        raise ValueError(f"norm_type must be 'layer_norm' or 'rms_norm', "
                         f"got {norm_type!r}")

    def pre_norm(t, **kw2):
        if norm_type == "rms_norm":
            return N.rms_norm(t, begin_norm_axis=2, **kw2)
        return N.layer_norm(t, begin_norm_axis=2, **kw2)

    kw = dict(main_program=main_program, startup_program=startup_program)
    d_model = x.shape[-1]
    h = pre_norm(x, **kw)
    h.seq_len = get_seq_len(x)
    attn = multi_head_attention(h, num_heads=num_heads, causal=causal,
                                num_kv_heads=num_kv_heads,
                                use_rope=use_rope,
                                sequence_parallel=sequence_parallel, **kw)
    helper = LayerHelper("transformer", **kw)
    x = helper.simple_op("elementwise_add", {"X": [x], "Y": [attn]})
    h2 = pre_norm(x, **kw)
    if moe_experts:
        ff, aux = switch_moe(h2, num_experts=moe_experts, d_ff=d_ff, **kw)
        o = helper.simple_op("elementwise_add", {"X": [x], "Y": [ff]})
        o.seq_len = get_seq_len(x)
        return o, aux
    ff = N.fc(h2, size=d_ff, num_flatten_dims=2, act="gelu", **kw)
    if dropout_prob:
        ff = N.dropout(ff, dropout_prob, **kw)
    ff = N.fc(ff, size=d_model, num_flatten_dims=2, **kw)
    o = helper.simple_op("elementwise_add", {"X": [x], "Y": [ff]})
    o.seq_len = get_seq_len(x)
    return o


def make_stack_params(helper, base, L, d_model, d_ff, dtype="float32",
                      num_heads=None, num_kv_heads=None, param_attr=None):
    """Create (or rejoin by name) the stacked [L, ...] block weights for
    ``pipelined_transformer_stack`` / ``transformer_stack_generate``:
    returns the op-input dict keyed by slot name. Names follow
    ``{base}.stack_{suffix}`` so sharding plans and sibling programs
    (training vs generation) address the same tensors."""
    from ..initializer import ConstantInitializer
    from ..param_attr import ParamAttr

    def mk(suffix, shape, bias=False, fan=None, init=None):
        import copy

        attr = (ParamAttr.to_attr(param_attr) if param_attr is not None
                else ParamAttr())
        attr = copy.copy(attr)
        attr.name = f"{base}.stack_{suffix}"
        if init is None and not bias:
            init = XavierInitializer(fan_in=fan[0], fan_out=fan[1])
        return helper.create_parameter(
            attr, shape=shape, dtype=dtype, is_bias=bias,
            default_initializer=init)

    one = ConstantInitializer(1.0)
    # GQA: KV planes carry num_kv_heads < num_heads head groups
    d_kv = (d_model if not (num_heads and num_kv_heads)
            else d_model // num_heads * num_kv_heads)
    qkv_width = d_model + 2 * d_kv
    return {
        "Ln1S": [mk("ln1_s", [L, d_model], bias=True, init=one)],
        "Ln1B": [mk("ln1_b", [L, d_model], bias=True)],
        "QkvW": [mk("qkv_w", [L, d_model, qkv_width],
                    fan=(d_model, qkv_width))],
        "OutW": [mk("out_w", [L, d_model, d_model],
                    fan=(d_model, d_model))],
        "Ln2S": [mk("ln2_s", [L, d_model], bias=True, init=one)],
        "Ln2B": [mk("ln2_b", [L, d_model], bias=True)],
        "FfW1": [mk("ff_w1", [L, d_model, d_ff], fan=(d_model, d_ff))],
        "FfB1": [mk("ff_b1", [L, d_ff], bias=True)],
        "FfW2": [mk("ff_w2", [L, d_ff, d_model], fan=(d_ff, d_model))],
        "FfB2": [mk("ff_b2", [L, d_model], bias=True)],
    }


def pipelined_transformer_stack(x, n_layers, num_heads, d_ff=None,
                                num_kv_heads=None, use_rope=False,
                                causal=True, n_microbatches=None,
                                pipe_axis="pp", data_axis="dp", remat=False,
                                param_attr=None, main_program=None,
                                startup_program=None):
    """L pre-LN transformer blocks with stacked [L, ...] weights — the
    scan-over-layers form of ``transformer_encoder_layer``. One compiled
    block body regardless of depth, and the layer axis doubles as the
    pipeline-stage axis: under a mesh with a ``pp`` axis (see
    ``parallel.pipeline_plan``) the stack runs the GPipe microbatch
    schedule across stages. Names carry a ``.stack_`` marker so the plan
    can shard every stacked tensor's leading dim on ``pp``."""
    from ..param_attr import ParamAttr

    if get_seq_len(x) is not None:
        raise NotImplementedError(
            "pipelined_transformer_stack assumes full-length sequences; "
            "padded variable-length batches should use the per-layer "
            "transformer_encoder_layer path (which masks via Length)")
    helper = LayerHelper("pipelined_transformer_stack",
                         main_program=main_program,
                         startup_program=startup_program)
    d_model = x.shape[-1]
    if d_model % num_heads:
        raise ValueError(f"d_model {d_model} not divisible by heads "
                         f"{num_heads}")
    d_ff = d_ff or 4 * d_model
    L = n_layers
    from ..param_attr import ParamAttr as _PA

    _given = _PA.to_attr(param_attr)
    base = (_given.name if _given is not None and _given.name
            else helper.main_program.unique_name("pipe"))

    if num_kv_heads and num_heads % num_kv_heads:
        raise ValueError(f"num_heads {num_heads} not a multiple of "
                         f"num_kv_heads {num_kv_heads}")
    ins = {"X": [x]}
    ins.update(make_stack_params(helper, base, L, d_model, d_ff,
                                 dtype=x.dtype, num_heads=num_heads,
                                 num_kv_heads=num_kv_heads,
                                 param_attr=param_attr))
    o = helper.simple_op(
        "pipelined_transformer_stack", ins,
        {"num_heads": num_heads, "num_kv_heads": num_kv_heads,
         "use_rope": use_rope, "causal": causal,
         "n_microbatches": n_microbatches, "pipe_axis": pipe_axis,
         "data_axis": data_axis, "remat": remat})
    return o


def switch_moe(x, num_experts, d_ff=None, capacity_factor=1.25,
               param_attr=None, main_program=None, startup_program=None):
    """Switch-Transformer MoE FFN (top-1 routing, capacity-dropped tokens).
    Expert weights are [E, ...]-major so an 'ep' mesh axis shards experts
    (see ops/moe_ops.py). Returns (out, aux_loss) — add
    ``alpha * aux_loss`` to the training objective for load balance."""
    from ..param_attr import ParamAttr

    helper = LayerHelper("switch_moe", main_program=main_program,
                         startup_program=startup_program)
    d_model = x.shape[-1]
    d_ff = d_ff or 4 * d_model
    E = num_experts
    base = helper.main_program.unique_name("moe")

    def mk(suffix, shape, bias=False):
        # explicit names: ".expert_" marks [E, ...]-major tensors so
        # expert_parallel_plan can shard dim 0 on the 'ep' mesh axis
        attr = (ParamAttr.to_attr(param_attr) if param_attr is not None
                else ParamAttr())
        import copy

        attr = copy.copy(attr)
        attr.name = f"{base}.{suffix}"
        return helper.create_parameter(
            attr, shape=shape, dtype=x.dtype, is_bias=bias,
            default_initializer=None if bias else XavierInitializer())

    wg = mk("gate", [d_model, E])
    w1 = mk("expert_w1", [E, d_model, d_ff])
    b1 = mk("expert_b1", [E, d_ff], bias=True)
    w2 = mk("expert_w2", [E, d_ff, d_model])
    b2 = mk("expert_b2", [E, d_model], bias=True)
    outs, _ = helper.append_op(
        "switch_moe",
        {"X": [x], "Gate": [wg], "W1": [w1], "B1": [b1], "W2": [w2],
         "B2": [b2]},
        ["Out", "AuxLoss"], {"capacity_factor": capacity_factor})
    y = outs["Out"][0]
    y.seq_len = get_seq_len(x)
    return y, outs["AuxLoss"][0]

"""LayerHelper: shared machinery for layer functions.

Mirrors /root/reference/python/paddle/v2/fluid/layer_helper.py — creates
parameters (with startup-program init ops), creates shape-inferred temporary
variables, and appends ops. Build-time shape inference is derived from the
op kernels themselves via jax.eval_shape (see core/registry.infer_outputs)
instead of per-op C++ InferShape implementations.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core.enforce import EnforceError, format_input_sigs
from ..core.program import (BATCH_DIM_SENTINEL, Program, default_main_program,
                            default_startup_program)
from ..core.registry import get_op, infer_outputs
from ..core.types import to_dtype
from ..initializer import ConstantInitializer, XavierInitializer
from ..param_attr import ParamAttr


def _abstract(var):
    shape = tuple(BATCH_DIM_SENTINEL if d == -1 else d for d in (var.shape or ()))
    return jax.ShapeDtypeStruct(shape, var.dtype)


def _concrete_to_build_shape(shape):
    return tuple(-1 if d == BATCH_DIM_SENTINEL else d for d in shape)


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        self.main_program: Program = kwargs.get("main_program") or default_main_program()
        self.startup_program: Program = (
            kwargs.get("startup_program") or default_startup_program()
        )

    @property
    def block(self):
        return self.main_program.current_block()

    @property
    def name(self) -> str:
        return self.main_program.unique_name(self.layer_type)

    # -- parameters --------------------------------------------------------
    def create_parameter(
        self,
        attr,
        shape: Sequence[int],
        dtype,
        is_bias: bool = False,
        default_initializer=None,
    ):
        attr = ParamAttr.to_attr(attr)
        if attr is None:
            return None
        name = attr.name or self.main_program.unique_name(self.layer_type + ".w")
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
        block = self.main_program.global_block
        if name in block.vars:
            return block.vars[name]
        param = block.create_parameter(
            name=name, shape=shape, dtype=dtype, trainable=attr.trainable,
            initializer={"lr": attr.learning_rate,
                         "regularizer": attr.regularizer},
        )
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        param.regularizer = attr.regularizer
        param.gradient_clip = getattr(attr, "gradient_clip", None)
        param.update_hooks = list(getattr(attr, "update_hooks", ()) or ())
        # Mirror into the startup program with its init op.
        sb = self.startup_program.global_block
        sv = sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True)
        init(sv, sb)
        return param

    # -- variables ---------------------------------------------------------
    def create_tmp_variable(self, dtype, shape=None, stop_gradient=False):
        return self.block.create_var(
            name=self.main_program.unique_name(self.layer_type + ".tmp"),
            dtype=dtype, shape=shape, stop_gradient=stop_gradient,
        )

    def create_global_variable(self, name=None, shape=None, dtype="float32",
                               persistable=True):
        return self.main_program.global_block.create_var(
            name=name or self.main_program.unique_name(self.layer_type + ".gv"),
            shape=shape, dtype=dtype, persistable=persistable,
        )

    # -- op + shape-inferred outputs ---------------------------------------
    def append_op(self, op_type: str, inputs: Dict[str, list], outputs,
                  attrs: Optional[dict] = None):
        """Append an op; ``outputs`` maps slot -> list of Variables (or a
        list of slot names to auto-create shape-inferred tmp vars)."""
        attrs = attrs or {}
        in_names = {
            slot: [v.name if hasattr(v, "name") else str(v) for v in vs]
            for slot, vs in inputs.items() if vs
        }
        if isinstance(outputs, (list, tuple)):
            out_slots = list(outputs)
            try:
                abstract_ins = {
                    slot: [_abstract(self.block.var(n)) for n in names]
                    for slot, names in in_names.items()
                }
            except KeyError as exc:
                # The classic build mistake: a handle from program A fed to
                # a layer built while program B is current (e.g. a layer
                # call on a `return` line after `with program_guard(...)`
                # exited). Name the likely cause instead of a bare KeyError.
                for slot, names in in_names.items():
                    for n in names:
                        if not self.block.has_var(n):
                            for v in inputs.get(slot, []):
                                if (getattr(v, "name", None) == n
                                        and getattr(v, "block", None)
                                        is not None
                                        and v.block.program
                                        is not self.main_program):
                                    raise EnforceError(
                                        f"layer {self.layer_type!r}: input "
                                        f"{n!r} belongs to a DIFFERENT "
                                        "Program than the one currently "
                                        "being built — layers must be "
                                        "called inside the program_guard "
                                        "that owns their inputs"
                                    ) from exc
                            raise EnforceError(
                                f"layer {self.layer_type!r}: input {n!r} is "
                                "not defined in the current program"
                            ) from exc
                raise
            try:
                inferred = infer_outputs(op_type, attrs, abstract_ins)
            except EnforceError:
                raise
            except Exception as exc:
                # Build-time InferShape failure: report like the
                # reference's PADDLE_ENFORCE in an op's InferShape, with
                # the declared (-1 = batch) input shapes.
                shapes = format_input_sigs({
                    slot: [jax.ShapeDtypeStruct(
                        _concrete_to_build_shape(a.shape), a.dtype)
                        for a in arrs]
                    for slot, arrs in abstract_ins.items()})
                raise EnforceError(
                    f"op {op_type!r} shape inference failed\n"
                    f"  inputs: {shapes}\n"
                    f"  cause: {type(exc).__name__}: {exc}") from exc
            outputs = {}
            for slot in out_slots:
                vars_for_slot = []
                for sds in inferred.get(slot, []):
                    v = self.block.create_var(
                        name=self.main_program.unique_name(
                            f"{self.layer_type}.{slot.lower()}"),
                        shape=_concrete_to_build_shape(sds.shape),
                        dtype=sds.dtype,
                    )
                    vars_for_slot.append(v)
                outputs[slot] = vars_for_slot
        out_names = {
            slot: [v.name if hasattr(v, "name") else str(v) for v in vs]
            for slot, vs in outputs.items() if vs
        }
        self.block.append_op(op_type, inputs=in_names, outputs=out_names,
                             attrs=attrs)
        flat = [v for slot in sorted(outputs) for v in outputs[slot]]
        return outputs, flat

    def simple_op(self, op_type: str, inputs: Dict[str, list], attrs=None,
                  out_slot: str = "Out"):
        """Common case: one auto-created output variable in ``out_slot``."""
        outputs, _ = self.append_op(op_type, inputs, [out_slot], attrs)
        result = outputs[out_slot][0]
        # Thread sequence lengths through shape-preserving ops (elementwise,
        # activations, per-timestep fc): if any input carries a seq_len and
        # the output keeps the [batch, time] leading dims, propagate it.
        for vs in inputs.values():
            for v in vs:
                sl = getattr(v, "seq_len", None)
                if (sl is not None and result.shape is not None
                        and v.shape is not None
                        and result.shape[:2] == v.shape[:2]):
                    result.seq_len = sl
                    return result
        return result

    # -- activation sugar --------------------------------------------------
    def append_activation(self, var, act: Optional[str]):
        if act is None:
            return var
        if isinstance(act, dict):
            act_type = act.pop("type")
            attrs = act
        else:
            act_type, attrs = act, {}
        helper = LayerHelper(act_type, main_program=self.main_program,
                             startup_program=self.startup_program)
        return helper.simple_op(act_type, {"X": [var]}, attrs)

    def append_bias_op(self, var, bias_attr, size, dim_start=1):
        attr = ParamAttr.to_attr(bias_attr) if bias_attr is not False else None
        if attr is None:
            return var
        b = self.create_parameter(attr, shape=[size], dtype=var.dtype, is_bias=True)
        return self.simple_op("elementwise_add", {"X": [var], "Y": [b]},
                              {"axis": dim_start})


def kw_helper(layer_type: str, kw: dict) -> "LayerHelper":
    """Helper for builders taking **kw with optional main_program/
    startup_program (legacy.py, detection.py)."""
    return LayerHelper(layer_type, main_program=kw.get("main_program"),
                       startup_program=kw.get("startup_program"))

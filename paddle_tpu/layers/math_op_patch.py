"""Arithmetic operators on build-time Variables.

The reference lets config authors write ``pred - label`` directly: its v1
DSL patches ``__add__``/``__sub__``/``__mul__`` onto LayerOutput
(/root/reference/python/paddle/trainer_config_helpers/layer_math.py:73-90),
folding scalar operands into a slope_intercept layer. Same contract here:
scalar operands lower to a single ``scale`` op, Variable operands to the
matching ``elementwise_*`` op.

Only arithmetic is patched — comparisons stay Python defaults so Variables
remain hashable and usable as dict keys (``layers.equal``/``less_than``
cover the graph-side predicates).
"""
from __future__ import annotations

import numbers

from ..core.program import Variable


def _scale(x, k=1.0, b=0.0):
    from .tensor import scale

    return scale(x, scale=float(k), bias=float(b))


def _elementwise(op_name, x, y):
    from . import ops

    return getattr(ops, op_name)(x, y)


def _add(self, other):
    if isinstance(other, numbers.Number):
        return _scale(self, 1.0, other)
    return _elementwise("elementwise_add", self, other)


def _sub(self, other):
    if isinstance(other, numbers.Number):
        return _scale(self, 1.0, -other)
    return _elementwise("elementwise_sub", self, other)


def _rsub(self, other):
    if isinstance(other, numbers.Number):
        return _scale(self, -1.0, other)
    return _elementwise("elementwise_sub", other, self)


def _mul(self, other):
    if isinstance(other, numbers.Number):
        return _scale(self, other)
    return _elementwise("elementwise_mul", self, other)


def _truediv(self, other):
    if isinstance(other, numbers.Number):
        return _scale(self, 1.0 / other)
    return _elementwise("elementwise_div", self, other)


def _rtruediv(self, other):
    if isinstance(other, numbers.Number):
        from . import ops

        return _scale(ops.reciprocal(self), other)
    return _elementwise("elementwise_div", other, self)


def _neg(self):
    return _scale(self, -1.0)


def monkey_patch_variable():
    Variable.__add__ = _add
    Variable.__radd__ = _add
    Variable.__sub__ = _sub
    Variable.__rsub__ = _rsub
    Variable.__mul__ = _mul
    Variable.__rmul__ = _mul
    Variable.__truediv__ = _truediv
    Variable.__rtruediv__ = _rtruediv
    Variable.__neg__ = _neg


monkey_patch_variable()

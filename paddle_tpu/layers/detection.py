"""Detection layer builders: the SSD stack + hierarchical sigmoid
(v1 DSL: priorbox_layer, multibox_loss_layer, detection_output_layer,
bilinear_interp_layer, hsigmoid — trainer_config_helpers/layers.py)."""
from __future__ import annotations

from ..param_attr import ParamAttr
from .layer_helper import kw_helper as _h


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variances=(0.1, 0.1, 0.2, 0.2), clip=False, **kw):
    """SSD anchors for one feature map (priorbox_layer). Returns
    (boxes, variances), each [H, W, num_priors, 4]."""
    h = _h("prior_box", kw)
    outs, _ = h.append_op(
        "prior_box", {"Input": [input], "Image": [image]},
        ["Boxes", "Variances"],
        {"min_sizes": list(min_sizes), "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios or []),
         "variances": list(variances), "clip": clip})
    return outs["Boxes"][0], outs["Variances"][0]


def iou_similarity(x, y, **kw):
    h = _h("iou_similarity", kw)
    return h.simple_op("iou_similarity", {"X": [x], "Y": [y]}, {})


def box_coder(prior_box, target_box, prior_variance=None,
              code_type="encode_center_size", **kw):
    h = _h("box_coder", kw)
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_variance is not None:
        ins["Variance"] = [prior_variance]
    return h.simple_op("box_coder", ins, {"code_type": code_type},
                       out_slot="OutputBox")


def multibox_loss(prior_boxes, prior_variances, loc_pred, conf_pred,
                  gt_boxes, gt_classes, gt_length=None,
                  overlap_threshold=0.5, neg_pos_ratio=3.0, **kw):
    """SSD training loss (multibox_loss_layer): per-image loss [b, 1]."""
    h = _h("multibox_loss", kw)
    ins = {"PriorBoxes": [prior_boxes], "PriorVariances": [prior_variances],
           "LocPred": [loc_pred], "ConfPred": [conf_pred],
           "GtBoxes": [gt_boxes], "GtClasses": [gt_classes]}
    if gt_length is not None:
        ins["GtLength"] = [gt_length]
    return h.simple_op("multibox_loss", ins,
                       {"overlap_threshold": overlap_threshold,
                        "neg_pos_ratio": neg_pos_ratio}, out_slot="Loss")


def bilinear_interp(input, out_h, out_w, **kw):
    """Bilinear upsampling of NHWC maps (bilinear_interp_layer)."""
    h = _h("bilinear_interp", kw)
    return h.simple_op("bilinear_interp", {"X": [input]},
                       {"out_h": out_h, "out_w": out_w})


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             **kw):
    """Hierarchical sigmoid loss [b, 1] over a complete binary class tree
    (hsigmoid, HierarchicalSigmoidLayer.cpp)."""
    h = _h("hsigmoid", kw)
    w = h.create_parameter(param_attr or ParamAttr(),
                           [num_classes - 1, int(input.shape[-1])],
                           input.dtype)
    ins = {"X": [input], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = h.create_parameter(bias_attr or ParamAttr(), [num_classes - 1],
                               input.dtype, is_bias=True)
        ins["Bias"] = [b]
    return h.simple_op("hsigmoid", ins, {"num_classes": num_classes})

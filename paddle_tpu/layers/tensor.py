"""Tensor-manipulation layers (fluid layers/tensor.py + parts of ops.py)."""
from __future__ import annotations

from .layer_helper import LayerHelper


def _helper(name, main_program=None, startup_program=None):
    return LayerHelper(name, main_program=main_program,
                       startup_program=startup_program)


def fill_constant(shape, dtype, value, main_program=None, startup_program=None):
    h = _helper("fill_constant", main_program, startup_program)
    return h.simple_op("fill_constant", {},
                       {"shape": list(shape), "dtype": str(dtype), "value": value})


def create_global_var(shape, value, dtype, persistable=True, name=None,
                      main_program=None, startup_program=None):
    """A persistable var initialised in the startup program (reference
    tensor.py create_global_var) — used for learning rates, counters."""
    h = _helper("global_var", main_program, startup_program)
    var = h.create_global_variable(name=name, shape=shape, dtype=dtype,
                                   persistable=persistable)
    sb = h.startup_program.global_block
    sv = sb.create_var(name=var.name, shape=shape, dtype=dtype, persistable=True)
    sb.append_op("fill_constant", outputs={"Out": [sv.name]},
                 attrs={"shape": list(shape), "dtype": str(sv.dtype),
                        "value": value})
    return var


def cast(x, dtype, main_program=None, startup_program=None):
    h = _helper("cast", main_program, startup_program)
    return h.simple_op("cast", {"X": [x]}, {"out_dtype": str(dtype)})


def concat(input, axis=0, main_program=None, startup_program=None):
    h = _helper("concat", main_program, startup_program)
    return h.simple_op("concat", {"X": list(input)}, {"axis": axis})


def sums(input, main_program=None, startup_program=None):
    h = _helper("sum", main_program, startup_program)
    return h.simple_op("sum", {"X": list(input)})


def assign(input, output=None, main_program=None, startup_program=None):
    h = _helper("assign", main_program, startup_program)
    if output is None:
        return h.simple_op("assign", {"X": [input]})
    h.append_op("assign", {"X": [input]}, {"Out": [output]}, {})
    return output


def mean(x, main_program=None, startup_program=None):
    h = _helper("mean", main_program, startup_program)
    return h.simple_op("mean", {"X": [x]})


def scale(x, scale=1.0, bias=0.0, main_program=None, startup_program=None):
    h = _helper("scale", main_program, startup_program)
    return h.simple_op("scale", {"X": [x]}, {"scale": scale, "bias": bias})


def reshape(x, shape, main_program=None, startup_program=None):
    h = _helper("reshape", main_program, startup_program)
    return h.simple_op("reshape", {"X": [x]}, {"shape": list(shape)})


def transpose(x, perm, main_program=None, startup_program=None):
    h = _helper("transpose", main_program, startup_program)
    return h.simple_op("transpose", {"X": [x]}, {"axis": list(perm)})


def split(x, num_or_sections, dim=0, main_program=None, startup_program=None):
    h = _helper("split", main_program, startup_program)
    if isinstance(num_or_sections, int):
        attrs = {"num": num_or_sections, "axis": dim}
        n = num_or_sections
    else:
        attrs = {"sections": list(num_or_sections), "axis": dim}
        n = len(num_or_sections)
    outs, _ = h.append_op("split", {"X": [x]}, ["Out"], attrs)
    return outs["Out"]


def one_hot(input, depth, main_program=None, startup_program=None):
    h = _helper("one_hot", main_program, startup_program)
    return h.simple_op("one_hot", {"X": [input]}, {"depth": depth})


def argmax(x, axis=-1, main_program=None, startup_program=None):
    h = _helper("argmax", main_program, startup_program)
    return h.simple_op("argmax", {"X": [x]}, {"axis": axis})


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  main_program=None, startup_program=None):
    """Constant tensor whose batch dim copies ``input``'s
    (fill_constant_batch_size_like_op.cc) — the standard way to make
    batch-shaped initial RNN states."""
    helper = _helper("fill_constant_batch_size_like", main_program,
                     startup_program)
    return helper.simple_op(
        "fill_constant_batch_size_like", {"Input": [input]},
        {"shape": list(shape), "dtype": str(dtype), "value": value,
         "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx})


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    dtype="float32", input_dim_idx=0,
                                    output_dim_idx=0, main_program=None,
                                    startup_program=None):
    """Gaussian noise with a batch dim copied from ``input``
    (gaussian_random_batch_size_like_op.cc); gradients do not flow into
    it — the reparameterization-trick noise leaf."""
    helper = _helper("gaussian_random_batch_size_like", main_program,
                     startup_program)
    return helper.simple_op(
        "gaussian_random_batch_size_like", {"Input": [input]},
        {"shape": list(shape), "dtype": str(dtype), "mean": mean,
         "std": std, "input_dim_idx": input_dim_idx,
         "output_dim_idx": output_dim_idx})


def _reduce_layer(op_type):
    def layer(x, dim=None, keep_dim=False, main_program=None,
              startup_program=None):
        h = _helper(op_type, main_program, startup_program)
        return h.simple_op(op_type, {"X": [x]},
                           {"dim": dim, "keep_dim": keep_dim,
                            "reduce_all": dim is None})

    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           main_program=None, startup_program=None):
    """Batched matmul (matmul_op.cc): used for attention score/context
    products over [b, T, d] sequence tensors."""
    helper = _helper("matmul", main_program, startup_program)
    return helper.simple_op(
        "matmul", {"X": [x], "Y": [y]},
        {"transpose_X": transpose_x, "transpose_Y": transpose_y,
         "alpha": alpha})


def create_tensor(dtype="float32", name=None, main_program=None,
                  startup_program=None):
    """fluid tensor.py create_tensor: an empty named variable to assign
    into (While loop counters etc.)."""
    from .layer_helper import LayerHelper

    helper = LayerHelper("create_tensor", main_program=main_program,
                         startup_program=startup_program)
    return helper.block.create_var(
        name=name or helper.main_program.unique_name("tensor"),
        shape=[1], dtype=dtype)


def ones(shape, dtype="float32", main_program=None, startup_program=None):
    """fluid tensor.py ones."""
    return fill_constant(shape=shape, value=1.0, dtype=dtype,
                         main_program=main_program,
                         startup_program=startup_program)


def zeros(shape, dtype="float32", main_program=None,
          startup_program=None):
    """fluid tensor.py zeros."""
    return fill_constant(shape=shape, value=0.0, dtype=dtype,
                         main_program=main_program,
                         startup_program=startup_program)

"""Layer builders for the legacy gserver layer-type tail (ops/legacy_ops.py).

Completes the v1 trainer_config_helpers DSL surface
(/root/reference/python/paddle/trainer_config_helpers/layers.py) in fluid
style: combinator layers take Variables, parameterized ones (scale_shift,
factorization_machine, gated_unit) create their weights via LayerHelper.
"""
from __future__ import annotations

from ..param_attr import ParamAttr
from .layer_helper import kw_helper as _h


def interpolation(x, y, weight, **kw):
    """w*x + (1-w)*y, per-row scalar weight (interpolation_layer)."""
    h = _h("interpolation", kw)
    return h.simple_op("interpolation",
                       {"X": [x], "Y": [y], "W": [weight]}, {})


def scaling(x, weight, **kw):
    """Per-row scalar times row (scaling_layer)."""
    h = _h("scaling", kw)
    return h.simple_op("scaling", {"X": [x], "W": [weight]}, {})


def power(x, weight, **kw):
    """x ** w with per-row scalar exponent (power_layer)."""
    h = _h("power", kw)
    return h.simple_op("power", {"X": [x], "W": [weight]}, {})


def slope_intercept(x, slope=1.0, intercept=0.0, **kw):
    h = _h("slope_intercept", kw)
    return h.simple_op("slope_intercept", {"X": [x]},
                       {"slope": slope, "intercept": intercept})


def addto(inputs, bias=None, act=None, **kw):
    """Elementwise sum of same-shaped layers (addto_layer)."""
    h = _h("addto", kw)
    ins = {"X": list(inputs)}
    if bias is not None:
        ins["Bias"] = [bias]
    y = h.simple_op("addto", ins, {})
    return h.append_activation(y, act) if act else y


def sum_to_one_norm(x, **kw):
    h = _h("sum_to_one_norm", kw)
    return h.simple_op("sum_to_one_norm", {"X": [x]}, {})


def row_l2_norm(x, **kw):
    h = _h("row_l2_norm", kw)
    return h.simple_op("row_l2_norm", {"X": [x]}, {})


def scale_shift(x, param_attr=None, bias_attr=None, **kw):
    """y = w*x + b with learned SCALAR w, b (scale_shift_layer)."""
    h = _h("scale_shift", kw)
    w = h.create_parameter(param_attr or ParamAttr(), [1], x.dtype)
    ins = {"X": [x], "Scale": [w]}
    if bias_attr is not False:
        b = h.create_parameter(bias_attr or ParamAttr(), [1], x.dtype,
                               is_bias=True)
        ins["Bias"] = [b]
    return h.simple_op("scale_shift", ins, {})


def linear_comb(weights, vectors, **kw):
    """Weighted sum of m d-dim sub-vectors (linear_comb_layer)."""
    h = _h("linear_comb", kw)
    return h.simple_op("linear_comb", {"W": [weights], "X": [vectors]}, {})


def dot_prod(x, y, **kw):
    h = _h("dot_prod", kw)
    return h.simple_op("dot_prod", {"X": [x], "Y": [y]}, {})


def out_prod(x, y, **kw):
    h = _h("out_prod", kw)
    return h.simple_op("out_prod", {"X": [x], "Y": [y]}, {})


def l2_distance(x, y, **kw):
    h = _h("l2_distance", kw)
    return h.simple_op("l2_distance", {"X": [x], "Y": [y]}, {})


def repeat(x, num_repeats, as_row_vector=True, **kw):
    h = _h("repeat", kw)
    return h.simple_op("repeat", {"X": [x]},
                       {"num_repeats": num_repeats,
                        "as_row_vector": as_row_vector})


def resize(x, size, **kw):
    h = _h("resize", kw)
    # The kernel folds the batch dim ([b, d] -> [b*d/size, size]), which
    # abstract shape inference cannot evaluate against the symbolic batch
    # sentinel — declare the [-1, size] output shape directly instead.
    out_var = h.create_tmp_variable(x.dtype, shape=[-1, size])
    h.append_op("resize", {"X": [x]}, {"Out": [out_var]}, {"size": size})
    return out_var


def rotate(x, height, width, **kw):
    h = _h("rotate", kw)
    return h.simple_op("rotate", {"X": [x]},
                       {"height": height, "width": width})


def multiplex(inputs, index, **kw):
    """Row-wise select among candidate tensors (multiplex_op.cc)."""
    h = _h("multiplex", kw)
    return h.simple_op("multiplex", {"X": list(inputs), "Ids": [index]}, {})


def kmax_seq_score(scores, beam_size=1, **kw):
    h = _h("kmax_seq_score", kw)
    from .sequence import get_seq_len

    ins = {"X": [scores]}
    sl = get_seq_len(scores)
    if sl is not None:
        ins["Length"] = [sl]
    return h.simple_op("kmax_seq_score", ins, {"beam_size": beam_size})


def sequence_reshape(x, new_dim, **kw):
    h = _h("sequence_reshape", kw)
    return h.simple_op("sequence_reshape", {"X": [x]}, {"new_dim": new_dim})


def sampling_id(probs, **kw):
    h = _h("sampling_id", kw)
    return h.simple_op("sampling_id", {"X": [probs]}, {})


def factorization_machine(x, factor_size, param_attr=None, **kw):
    """FM second-order interaction term (factorization_machine layer)."""
    h = _h("factorization_machine", kw)
    v = h.create_parameter(param_attr or ParamAttr(),
                           [int(x.shape[-1]), factor_size], x.dtype)
    return h.simple_op("factorization_machine", {"X": [x], "V": [v]}, {})


def gated_unit(x, size, act="tanh", param_attr=None, gate_attr=None, **kw):
    """out = act(x Wp) * sigmoid(x Wg) (gated_unit_layer)."""
    from .nn import fc

    p = fc(x, size=size, param_attr=param_attr, bias_attr=None,
           main_program=kw.get("main_program"),
           startup_program=kw.get("startup_program"))
    g = fc(x, size=size, param_attr=gate_attr, bias_attr=None,
           main_program=kw.get("main_program"),
           startup_program=kw.get("startup_program"))
    h = _h("gated_unit", kw)
    return h.simple_op("gated_unit", {"P": [p], "G": [g]}, {"act": act})

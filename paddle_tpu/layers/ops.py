"""Auto-generated thin layer wrappers for elementwise/unary ops.

Mirrors the reference's registry-generated layer functions
(/root/reference/python/paddle/v2/fluid/layers/ops.py + registry.py): every
simple X->Out op gets a layer function of the same name.
"""
from __future__ import annotations

import sys

from .layer_helper import LayerHelper

_UNARY = [
    "relu", "sigmoid", "logsigmoid", "tanh", "exp", "log", "sqrt", "rsqrt",
    "abs", "ceil", "floor", "round", "reciprocal", "square", "softplus",
    "softsign", "gelu", "sin", "cos", "tanh_shrink", "softmax", "log_softmax",
]

_UNARY_ATTRS = {
    "softshrink": ("lambda",),
    "hard_shrink": ("threshold",),
    "brelu": ("t_min", "t_max"),
    "relu6": ("threshold",),
    "leaky_relu": ("alpha",),
    "elu": ("alpha",),
    "pow": ("factor",),
    "stanh": ("scale_a", "scale_b"),
    "hard_sigmoid": ("slope", "offset"),
    "thresholded_relu": ("threshold",),
    "swish": ("beta",),
}

_BINARY = [
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_xor",
]

_module = sys.modules[__name__]


def _make_unary(op_type, attr_names=()):
    def layer(x, main_program=None, startup_program=None, **kwargs):
        h = LayerHelper(op_type, main_program=main_program,
                        startup_program=startup_program)
        attrs = {k: v for k, v in kwargs.items() if k in attr_names or not attr_names}
        return h.simple_op(op_type, {"X": [x]}, attrs)

    layer.__name__ = op_type
    return layer


def _make_binary(op_type):
    def layer(x, y, axis=-1, main_program=None, startup_program=None):
        h = LayerHelper(op_type, main_program=main_program,
                        startup_program=startup_program)
        return h.simple_op(op_type, {"X": [x], "Y": [y]}, {"axis": axis})

    layer.__name__ = op_type
    return layer


for _op in _UNARY:
    setattr(_module, _op, _make_unary(_op))
for _op, _attrs in _UNARY_ATTRS.items():
    setattr(_module, _op, _make_unary(_op, _attrs))
for _op in _BINARY:
    setattr(_module, _op, _make_binary(_op))

__all__ = _UNARY + list(_UNARY_ATTRS) + _BINARY

"""Core NN layers — the fluid layers/nn.py parity surface.

Each function builds ops into the default (or given) program via LayerHelper;
shapes are inferred from the kernels themselves. Citations:
/root/reference/python/paddle/v2/fluid/layers/nn.py (fc, embedding, conv2d,
pool2d, batch_norm, dropout, cross_entropy, accuracy, ...).
"""
from __future__ import annotations

import numpy as np

from ..core.types import to_dtype
from ..initializer import ConstantInitializer, NormalInitializer, XavierInitializer
from .layer_helper import LayerHelper


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None, main_program=None, startup_program=None):
    """Fully-connected layer (reference nn.py fc): mul per input + sum + bias
    + activation. Multiple inputs each get their own weight.
    ``num_flatten_dims`` may be a list (one value per input) — for inputs
    of different ranks feeding the same fc. Each input's mul output keeps
    its leading ``num_flatten_dims`` dims plus the size axis, so every
    entry must produce the SAME output rank (e.g. a [b, T, d] input with
    nfd=2 combines with another [b, T, d2] at nfd=2, rank 3 + 3; a
    [b, d2] input at nfd=1 yields rank 2 and cannot be summed with it —
    rejected at build time rather than failing inside XLA broadcasting)."""
    helper = LayerHelper("fc", main_program=main_program,
                         startup_program=startup_program)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    nfds = (list(num_flatten_dims)
            if isinstance(num_flatten_dims, (list, tuple))
            else [num_flatten_dims] * len(inputs))
    if len(nfds) != len(inputs):
        raise ValueError(
            f"fc: num_flatten_dims list has {len(nfds)} entries for "
            f"{len(inputs)} inputs")
    out_ranks = {nfd + 1 for nfd in nfds}
    if len(out_ranks) > 1:
        raise ValueError(
            "fc: per-input num_flatten_dims produce MIXED partial-sum "
            f"ranks {sorted(nfd + 1 for nfd in nfds)} (each input "
            "contributes a [*leading_dims, size] partial of rank "
            "num_flatten_dims+1, and the partials are summed "
            "elementwise) — use num_flatten_dims values whose outputs "
            "share one rank, or reshape the lower-rank inputs first")
    mul_results = []
    for inp, nfd in zip(inputs, nfds):
        in_shape = inp.shape
        fan_in = int(np.prod(in_shape[nfd:]))
        w = helper.create_parameter(
            param_attr, shape=[fan_in, size], dtype=inp.dtype,
            default_initializer=XavierInitializer())
        mul_results.append(
            helper.simple_op("mul", {"X": [inp], "Y": [w]},
                             {"x_num_col_dims": nfd,
                              "y_num_col_dims": 1}))
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.simple_op("sum", {"X": mul_results})
    if bias_attr is False:
        pre_act = pre_bias
    else:
        pre_act = helper.append_bias_op(pre_bias, bias_attr, size,
                                        dim_start=nfds[0])
    return helper.append_activation(pre_act, act)


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32", main_program=None, startup_program=None):
    """Embedding lookup (reference nn.py embedding / lookup_table_op.cc).

    With ``is_sparse`` the gradient is a SelectedRows (row ids + row grads,
    no [V, D] buffer — lookup_table_op.cc:59) and the optimizer applies a
    lazy row-granular update; required for large vocabularies (CTR).
    Regularization on a sparse embedding densifies the grad and defeats the
    point — leave param_attr.regularizer unset for is_sparse weights."""
    helper = LayerHelper("embedding", main_program=main_program,
                         startup_program=startup_program)
    w = helper.create_parameter(
        param_attr, shape=list(size), dtype=dtype,
        default_initializer=XavierInitializer())
    if padding_idx is not None and padding_idx < 0:
        # fluid semantics: negative padding_idx counts from the vocab end
        padding_idx = int(size[0]) + int(padding_idx)
    return helper.simple_op(
        "lookup_table", {"W": [w], "Ids": [input]},
        {"padding_idx": padding_idx, "is_sparse": bool(is_sparse)})


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None, main_program=None,
           startup_program=None):
    helper = LayerHelper("conv2d", main_program=main_program,
                         startup_program=startup_program)
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    channel_axis = 1 if data_format == "NCHW" else 3
    num_channels = input.shape[channel_axis]
    if data_format == "NCHW":
        filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    else:  # HWIO for NHWC
        filter_shape = list(filter_size) + [num_channels // groups, num_filters]
    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(
        param_attr, shape=filter_shape, dtype=input.dtype,
        default_initializer=NormalInitializer(0.0, std))
    pre_bias = helper.simple_op(
        "conv2d", {"Input": [input], "Filter": [w]},
        {"strides": stride, "paddings": padding, "dilations": dilation,
         "groups": groups, "data_format": data_format},
        out_slot="Output")
    pre_act = helper.append_bias_op(pre_bias, bias_attr,
                                    num_filters, dim_start=channel_axis)
    return helper.append_activation(pre_act, act)


def pool2d(input, pool_size=2, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, data_format="NCHW",
           main_program=None, startup_program=None):
    """``ceil_mode`` selects the legacy v1 output-size rule
    (ceil((I+2p-F)/S)+1, reference config_parser.py cnn_output_size with
    caffe_mode=False); fluid's default is floor."""
    helper = LayerHelper("pool2d", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op(
        "pool2d", {"X": [input]},
        {"pooling_type": pool_type, "ksize": pool_size,
         "strides": pool_stride, "paddings": pool_padding,
         "global_pooling": global_pooling, "ceil_mode": bool(ceil_mode),
         "data_format": data_format})


def _bn_state(helper, channels, param_attr, bias_attr):
    """Shared BN affine+running-stats setup (batch_norm and the fused
    conv1x1_bn_act): scale/bias params, persistable .mean/.var state in
    BOTH programs (init ops in startup, state in main — the '.mean'/
    '.var' suffix is what the executor's state-threading keys on), and
    the saved-stat tmp outputs. Returns (scale, bias, mean, variance,
    saved_mean, saved_var)."""
    dtype = "float32"  # stats/affine in f32 even under bf16 compute
    scale = helper.create_parameter(
        param_attr, shape=[channels], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(
        bias_attr, shape=[channels], dtype=dtype, is_bias=True)
    mean_name = scale.name + ".mean"
    var_name = scale.name + ".var"
    block = helper.main_program.global_block
    mean = block.create_var(name=mean_name, shape=[channels], dtype=dtype,
                            persistable=True, stop_gradient=True)
    variance = block.create_var(name=var_name, shape=[channels], dtype=dtype,
                                persistable=True, stop_gradient=True)
    sb = helper.startup_program.global_block
    for name, value in ((mean_name, 0.0), (var_name, 1.0)):
        v = sb.create_var(name=name, shape=[channels], dtype=dtype,
                          persistable=True)
        ConstantInitializer(value)(v, sb)
    saved_mean = helper.create_tmp_variable(dtype, shape=[channels],
                                            stop_gradient=True)
    saved_var = helper.create_tmp_variable(dtype, shape=[channels],
                                           stop_gradient=True)
    return scale, bias, mean, variance, saved_mean, saved_var


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               main_program=None, startup_program=None):
    """Batch normalisation (reference nn.py batch_norm / batch_norm_op.cc).

    Mean/Variance are persistable running stats; MeanOut/VarianceOut alias
    them so the executor's functional state-threading updates them in place.
    """
    helper = LayerHelper("batch_norm", main_program=main_program,
                         startup_program=startup_program)
    if data_layout == "NCHW":
        channels = input.shape[1]
    else:
        channels = input.shape[-1]
    scale, bias, mean, variance, saved_mean, saved_var = _bn_state(
        helper, channels, param_attr, bias_attr)
    y = helper.create_tmp_variable(input.dtype, shape=input.shape)
    helper.append_op(
        "batch_norm",
        {"X": [input], "Scale": [scale], "Bias": [bias],
         "Mean": [mean], "Variance": [variance]},
        {"Y": [y], "MeanOut": [mean], "VarianceOut": [variance],
         "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout},
    )
    return helper.append_activation(y, act)


def conv1x1_bn_act(input, num_filters, residual=None, act=None,
                   is_test=False, momentum=0.9, epsilon=1e-5,
                   param_attr=None, bn_param_attr=None, bn_bias_attr=None,
                   main_program=None, startup_program=None):
    """Fused NHWC 1x1-conv + batch_norm + activation (+ residual add)
    as one op (ops/fusion_ops.py): the epilogue-fusion form of the
    conv2d->batch_norm->elementwise_add->relu chain that bounds the
    ResNet roofline (PERF.md). Enabled from models via
    --fused_conv_epilogue."""
    if act not in (None, "", "relu"):
        raise ValueError(
            f"conv1x1_bn_act supports act None or 'relu' (the fused "
            f"kernels implement exactly these), got {act!r}")
    helper = LayerHelper("conv1x1_bn_act", main_program=main_program,
                         startup_program=startup_program)
    channels_in = int(input.shape[-1])
    filt = helper.create_parameter(
        param_attr, shape=[1, 1, channels_in, num_filters],
        dtype=input.dtype,
        default_initializer=NormalInitializer(  # match conv2d's init
            0.0, (2.0 / channels_in) ** 0.5))
    scale, bias, mean, variance, saved_mean, saved_var = _bn_state(
        helper, num_filters, bn_param_attr, bn_bias_attr)
    out_shape = list(input.shape[:-1]) + [num_filters]
    y = helper.create_tmp_variable(input.dtype, shape=out_shape)
    conv_out = helper.create_tmp_variable(
        input.dtype, shape=[1, 1] if is_test else out_shape,
        stop_gradient=True)
    ins = {"X": [input], "Filter": [filt], "Scale": [scale],
           "Bias": [bias], "Mean": [mean], "Variance": [variance]}
    if residual is not None:
        ins["Residual"] = [residual]
    helper.append_op(
        "conv1x1_bn_act", ins,
        {"Y": [y], "MeanOut": [mean], "VarianceOut": [variance],
         "SavedMean": [saved_mean], "SavedVariance": [saved_var],
         "ConvOut": [conv_out]},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "act": act or ""})
    return y


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, main_program=None,
               startup_program=None):
    helper = LayerHelper("layer_norm", main_program=main_program,
                         startup_program=startup_program)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape, dtype="float32",
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape, dtype="float32",
                                    is_bias=True)
        inputs["Bias"] = [b]
    outs, _ = helper.append_op("layer_norm", inputs, ["Y", "Mean", "Variance"],
                               {"epsilon": epsilon,
                                "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(outs["Y"][0], act)


def fused_head_cross_entropy(input, label, num_classes, chunk=8192,
                             param_attr=None, main_program=None,
                             startup_program=None, *,
                             label_smoothing=0.0,
                             vocab_parallel=False, model_axis="mp",
                             data_axis="dp"):
    """LM-head projection + softmax cross-entropy in one chunked op: the
    [tokens, num_classes] logits tensor never materializes (online
    logsumexp over vocab chunks — ops/loss_ops.py). Use in place of
    ``fc(x, num_classes)`` + ``softmax_with_cross_entropy`` when the
    vocabulary is large. Returns the per-row Loss [.., 1]; the head
    weight is a normal [d, num_classes] parameter.

    ``vocab_parallel=True``: when the executor compiles with a mesh whose
    ``model_axis`` has size > 1, the head computes Megatron-style — each
    device scans only its vocab shard and three per-row collectives
    combine the statistics (parallel/vocab_parallel_loss.py). Pair it
    with a plan rule sharding this weight's LAST dim over ``model_axis``;
    the same program still runs unchanged on one device."""
    if not 0.0 <= float(label_smoothing) < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}")
    helper = LayerHelper("fused_head_cross_entropy",
                         main_program=main_program,
                         startup_program=startup_program)
    d = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[int(d), int(num_classes)],
                                dtype="float32")
    outs, _ = helper.append_op(
        "fused_head_cross_entropy",
        {"X": [input], "W": [w], "Label": [label]},
        ["Loss", "LSE"], {"chunk": int(chunk),
                          "label_smoothing": float(label_smoothing),
                          "vocab_parallel": bool(vocab_parallel),
                          "model_axis": model_axis,
                          "data_axis": data_axis})
    outs["LSE"][0].stop_gradient = True
    return outs["Loss"][0]


def rms_norm(input, scale=True, shift=False, begin_norm_axis=1,
             epsilon=1e-6, param_attr=None, bias_attr=None, act=None,
             main_program=None, startup_program=None):
    """RMSNorm (beyond-reference; see ops/nn_ops.py rms_norm). Defaults
    follow the modern LM convention: learned scale, no shift."""
    helper = LayerHelper("rms_norm", main_program=main_program,
                         startup_program=startup_program)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape,
                                    dtype="float32",
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape,
                                    dtype="float32", is_bias=True)
        inputs["Bias"] = [b]
    outs, _ = helper.append_op("rms_norm", inputs, ["Y", "InvRms"],
                               {"epsilon": epsilon,
                                "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(outs["Y"][0], act)


def dropout(x, dropout_prob=0.5, is_test=False, main_program=None,
            startup_program=None):
    helper = LayerHelper("dropout", main_program=main_program,
                         startup_program=startup_program)
    outs, _ = helper.append_op("dropout", {"X": [x]}, ["Out", "Mask"],
                               {"dropout_prob": dropout_prob, "is_test": is_test})
    return outs["Out"][0]


def lrn(input, n=5, k=2.0, alpha=1e-4, beta=0.75, data_format="NCHW",
        main_program=None, startup_program=None):
    helper = LayerHelper("lrn", main_program=main_program,
                         startup_program=startup_program)
    outs, _ = helper.append_op("lrn", {"X": [input]}, ["Out", "MidOut"],
                               {"n": n, "k": k, "alpha": alpha, "beta": beta,
                                "data_format": data_format})
    return outs["Out"][0]


# --- losses -----------------------------------------------------------------
def sigmoid_cross_entropy_with_logits(x, label, main_program=None,
                                      startup_program=None):
    """Elementwise binary cross-entropy on logits (fluid layers.nn parity)."""
    helper = LayerHelper("sigmoid_cross_entropy_with_logits",
                         main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("sigmoid_cross_entropy_with_logits",
                            {"X": [x], "Label": [label]}, {})


def cross_entropy(input, label, soft_label=False, main_program=None,
                  startup_program=None):
    helper = LayerHelper("cross_entropy", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("cross_entropy", {"X": [input], "Label": [label]},
                            {"soft_label": soft_label}, out_slot="Y")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               label_smoothing=0.0,
                               main_program=None, startup_program=None):
    """``label_smoothing`` (hard labels only): train against the target
    (1-eps)*onehot + eps/V — beyond-reference, the seq2seq/ViT-era
    regularizer; the fused grad stays (softmax - target)."""
    helper = LayerHelper("softmax_with_cross_entropy",
                         main_program=main_program,
                         startup_program=startup_program)
    if soft_label and label_smoothing:
        raise ValueError("label_smoothing applies to hard labels only")
    if not 0.0 <= float(label_smoothing) < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}")
    outs, _ = helper.append_op(
        "softmax_with_cross_entropy", {"Logits": [logits], "Label": [label]},
        ["Softmax", "Loss"], {"soft_label": soft_label,
                              "label_smoothing": float(label_smoothing)})
    return outs["Loss"][0]


def square_error_cost(input, label, main_program=None, startup_program=None):
    helper = LayerHelper("square_error_cost", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("square_error_cost", {"X": [input], "Y": [label]})


# --- metrics ----------------------------------------------------------------
def topk(input, k, main_program=None, startup_program=None):
    helper = LayerHelper("top_k", main_program=main_program,
                         startup_program=startup_program)
    outs, _ = helper.append_op("top_k", {"X": [input]}, ["Out", "Indices"],
                               {"k": k})
    return outs["Out"][0], outs["Indices"][0]


def accuracy(input, label, k=1, main_program=None, startup_program=None):
    """Classification accuracy via top-k (reference nn.py accuracy)."""
    helper = LayerHelper("accuracy", main_program=main_program,
                         startup_program=startup_program)
    values, indices = topk(input, k, main_program=main_program,
                           startup_program=startup_program)
    outs, _ = helper.append_op(
        "accuracy", {"Out": [values], "Indices": [indices], "Label": [label]},
        ["Accuracy", "Correct", "Total"], {})
    return outs["Accuracy"][0]


def linear_chain_crf(input, label, param_attr=None, main_program=None,
                     startup_program=None):
    """Linear-chain CRF negative log-likelihood cost (reference fluid
    layers.linear_chain_crf / linear_chain_crf_op.cc). ``input`` is the
    padded emission sequence [b, T, n]; creates the [n+2, n] transition
    parameter (rows: start, end, pairwise). Returns the per-row NLL [b, 1];
    the transition variable is retrievable for crf_decoding via
    ``crf.transition``."""
    from .sequence import get_seq_len

    helper = LayerHelper("linear_chain_crf", main_program=main_program,
                         startup_program=startup_program)
    n = input.shape[-1]
    trans = helper.create_parameter(
        param_attr, shape=[n + 2, n], dtype=input.dtype,
        default_initializer=XavierInitializer())
    ins = {"Emission": [input], "Transition": [trans], "Label": [label]}
    sl = get_seq_len(input)
    if sl is not None:
        ins["Length"] = [sl]
    outs, _ = helper.append_op("linear_chain_crf", ins,
                               ["LogLikelihood", "Alpha"])
    nll = outs["LogLikelihood"][0]
    nll.transition = trans
    return nll


def crf_decoding(input, param_attr=None, transition=None, label=None,
                 main_program=None, startup_program=None):
    """Viterbi decode (crf_decoding_op.cc): pass ``transition`` (e.g.
    ``cost.transition`` from linear_chain_crf) or a param_attr naming the
    shared transition parameter."""
    from .sequence import get_seq_len

    helper = LayerHelper("crf_decoding", main_program=main_program,
                         startup_program=startup_program)
    if transition is None:
        n = input.shape[-1]
        transition = helper.create_parameter(
            param_attr, shape=[n + 2, n], dtype=input.dtype,
            default_initializer=XavierInitializer())
    ins = {"Emission": [input], "Transition": [transition]}
    sl = get_seq_len(input)
    if sl is not None:
        ins["Length"] = [sl]
    if label is not None:
        ins["Label"] = [label]
    outs, _ = helper.append_op("crf_decoding", ins, ["ViterbiPath"])
    path = outs["ViterbiPath"][0]
    path.seq_len = sl
    return path


def chunk_eval(input, label, chunk_scheme="IOB", num_chunk_types=1,
               main_program=None, startup_program=None):
    """Chunk precision/recall/F1 (chunk_eval_op.cc). Returns
    (precision, recall, f1, n_infer, n_label, n_correct)."""
    from .sequence import get_seq_len

    if chunk_scheme != "IOB":
        raise NotImplementedError("only the IOB chunk scheme is supported")
    helper = LayerHelper("chunk_eval", main_program=main_program,
                         startup_program=startup_program)
    ins = {"Inference": [input], "Label": [label]}
    sl = get_seq_len(input) or get_seq_len(label)
    if sl is not None:
        ins["Length"] = [sl]
    outs, _ = helper.append_op(
        "chunk_eval", ins,
        ["Precision", "Recall", "F1-Score", "NumInferChunks",
         "NumLabelChunks", "NumCorrectChunks"],
        {"chunk_scheme": chunk_scheme, "num_chunk_types": num_chunk_types})
    return (outs["Precision"][0], outs["Recall"][0], outs["F1-Score"][0],
            outs["NumInferChunks"][0], outs["NumLabelChunks"][0],
            outs["NumCorrectChunks"][0])


def cos_sim(X, Y, main_program=None, startup_program=None):
    """Cosine similarity rows of X vs Y (fluid nn.py cos_sim /
    cos_sim_op.cc)."""
    helper = LayerHelper("cos_sim", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("cos_sim", {"X": [X], "Y": [Y]}, {})


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, main_program=None,
        startup_program=None):
    """The raw mul op as a layer (fluid ops.py mul)."""
    helper = LayerHelper("mul", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("mul", {"X": [x], "Y": [y]},
                            {"x_num_col_dims": x_num_col_dims,
                             "y_num_col_dims": y_num_col_dims})


def clip(x, min, max, main_program=None, startup_program=None):  # noqa: A002
    """Elementwise clamp (fluid ops.py clip / clip_op.cc)."""
    helper = LayerHelper("clip", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("clip", {"X": [x]},
                            {"min": float(min), "max": float(max)})


def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", main_program=None,
                     startup_program=None):
    """Transposed convolution (fluid nn.py conv2d_transpose /
    conv2d_transpose_op.cc)."""
    helper = LayerHelper("conv2d_transpose", main_program=main_program,
                         startup_program=startup_program)
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    channel_axis = 1 if data_format == "NCHW" else 3
    cin = input.shape[channel_axis]
    # filter layout per the op contract: IOHW (NCHW) / HWIO (NHWC)
    shape = ([cin, num_filters] + list(filter_size)
             if data_format == "NCHW"
             else list(filter_size) + [cin, num_filters])
    w = helper.create_parameter(
        param_attr, shape=shape, dtype=input.dtype,
        default_initializer=NormalInitializer(
            0.0, (2.0 / (cin * filter_size[0] * filter_size[1])) ** 0.5))
    o = helper.simple_op(
        "conv2d_transpose", {"Input": [input], "Filter": [w]},
        {"strides": stride, "paddings": padding,
         "data_format": data_format}, out_slot="Output")
    o = helper.append_bias_op(o, bias_attr, num_filters,
                              dim_start=channel_axis)
    return helper.append_activation(o, act)

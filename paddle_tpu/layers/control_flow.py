"""Control-flow builders: StaticRNN, While, tensor arrays, beam decode.

Parity with the reference's fluid control_flow layer API
(/root/reference/python/paddle/v2/fluid/layers/control_flow.py: StaticRNN,
While, array_write/array_read/increment, DynamicRNN) and the decode stack
(beam_search + beam_search_decode ops,
/root/reference/python/paddle/v2/fluid/tests/book/test_machine_translation.py).

Builder mechanics: entering ``rnn.step()`` / ``while.block()`` pushes a
sub-block on the program; layers called inside append ops there as usual. On
exit the builder SERIALIZES the sub-block's ops into the parent ``static_rnn``
/ ``while`` op's attrs (plain data) — see ops/control_flow_ops.py for how the
kernel re-materialises them under lax.scan / lax.while_loop. External
variables referenced by the body (weights created by fc etc.) are collected
automatically into the op's Param input slot.

DynamicRNN is subsumed: the reference needs lod_rank_table +
shrink_rnn_memory to batch variable-length rows (control_flow.py:609 area);
here StaticRNN takes the sequence's Length and applies the same
freeze-memory/zero-output masking in one scan.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..core.program import Variable, default_main_program
from .layer_helper import LayerHelper
from .sequence import get_seq_len


def _collect_body(block, bound_names):
    """Serialize a sub-block's ops; classify external reads as params."""
    body_ops = []
    produced = set(bound_names)
    params: List[str] = []
    for op in block.ops:
        for slot, names in op.inputs.items():
            for n in names:
                if n not in produced and n not in params:
                    params.append(n)
        for n in op.output_names():
            produced.add(n)
        body_ops.append({
            "type": op.type,
            "inputs": {s: list(ns) for s, ns in op.inputs.items()},
            "outputs": {s: list(ns) for s, ns in op.outputs.items()},
            "attrs": dict(op.attrs),
        })
    return body_ops, params


class StaticRNN:
    """Scan-based user-defined recurrence (fluid StaticRNN,
    control_flow.py; reference runtime recurrent_op.cc:222).

    with rnn.step():
        xt = rnn.step_input(seq)         # [b, d] slice of [b, T, d]
        h  = rnn.memory(init=h0)         # loop-carried
        new_h = some_layers(xt, h)
        rnn.update_memory(h, new_h)
        rnn.step_output(new_h)
    out, = rnn()                          # [b, T, ...]
    """

    def __init__(self, name=None, main_program=None, startup_program=None):
        self.helper = LayerHelper("static_rnn", main_program=main_program,
                                  startup_program=startup_program)
        self.seq_vars: List[Variable] = []
        self.x_vars: List[Variable] = []
        self.mem_init: List[Variable] = []
        self.mem_vars: List[Variable] = []
        self.mem_out: Dict[str, Optional[str]] = {}
        self.out_vars: List[Variable] = []
        self.step_block = None
        self._len_var = None

    # -- context ----------------------------------------------------------
    class _Step:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            prog = self.rnn.helper.main_program
            self.rnn.step_block = prog.create_block()
            return self.rnn

        def __exit__(self, exc_type, *a):
            prog = self.rnn.helper.main_program
            prog.rollback()
            if exc_type is None:
                self.rnn._complete()

    def step(self):
        return StaticRNN._Step(self)

    # -- body API ---------------------------------------------------------
    def step_input(self, seq: Variable) -> Variable:
        """Register a [b, T, ...] sequence; returns its per-step [b, ...]
        view usable inside the body."""
        prog = self.helper.main_program
        if self._len_var is None:
            self._len_var = get_seq_len(seq)
        shape = (seq.shape[0],) + tuple(seq.shape[2:])
        xt = self.step_block.create_var(
            name=prog.unique_name("static_rnn.x"), shape=shape,
            dtype=seq.dtype)
        self.seq_vars.append(seq)
        self.x_vars.append(xt)
        return xt

    def memory(self, init: Variable) -> Variable:
        """Loop-carried state seeded from ``init`` ([b, ...])."""
        prog = self.helper.main_program
        mem = self.step_block.create_var(
            name=prog.unique_name("static_rnn.mem"), shape=init.shape,
            dtype=init.dtype)
        self.mem_init.append(init)
        self.mem_vars.append(mem)
        self.mem_out[mem.name] = None
        return mem

    def update_memory(self, mem: Variable, new: Variable):
        self.mem_out[mem.name] = new.name

    def step_output(self, o: Variable):
        self.out_vars.append(o)

    output = step_output

    # -- completion -------------------------------------------------------
    def _complete(self):
        for m, tgt in self.mem_out.items():
            if tgt is None:
                raise ValueError(f"memory {m!r} was never update_memory()'d")
        bound = [v.name for v in self.x_vars] + [v.name for v in self.mem_vars]
        body_ops, params = _collect_body(self.step_block, bound)
        ins = {
            "X": self.seq_vars,
            "MemInit": self.mem_init,
            "Param": [self.helper.block.var(n) if self.helper.block.has_var(n)
                      else n for n in params],
        }
        if self._len_var is not None:
            ins["Length"] = [self._len_var]
        attrs = {
            "body_ops": body_ops,
            "x_names": [v.name for v in self.x_vars],
            "mem_names": [v.name for v in self.mem_vars],
            "mem_out_names": [self.mem_out[v.name] for v in self.mem_vars],
            "out_names": [v.name for v in self.out_vars],
            "param_names": params,
            "seq_len_static": (self.seq_vars[0].shape[1]
                               if self.seq_vars else 0),
        }
        outs, _ = self.helper.append_op("static_rnn", ins,
                                        ["Out", "LastMem"], attrs)
        self._outputs = outs["Out"]
        self._last_mems = outs["LastMem"]
        for o in self._outputs:
            o.seq_len = self._len_var

    def __call__(self):
        outs = self._outputs
        return outs[0] if len(outs) == 1 else outs


def _static_scalar_value(blocks, name):
    """The value of ``name`` if its producer is a static fill_constant."""
    for blk in blocks:
        producer = None
        for op in blk.ops:
            if name in op.output_names():
                producer = op  # keep the LAST producer (current version)
        if producer is not None:
            if producer.type == "fill_constant":
                return producer.attrs.get("value")
            return None
    return None


# Refuse to unroll absurdly long loops into a masked scan — a sentinel
# limit like less_than(i, 1e9) must keep the dynamic lowering.
_MAX_INFERRED_TRIP = 10_000


def _producer_through_assigns(sub, name):
    """The body op producing ``name``'s final value, assign chains
    followed."""
    writer = None
    for op in sub.ops:
        if name in op.output_names():
            writer = op
    seen = 0
    while writer is not None and writer.type == "assign" and seen < 16:
        seen += 1
        src = (writer.inputs.get("X") or [None])[0]
        writer = None
        for op in sub.ops:
            if src in op.output_names():
                writer = op
    return writer


def _counter_step(sub, name) -> Optional[float]:
    """If ``name`` is a verified loop counter — the body reassigns it to
    increment(name, step) with step >= 1 (possibly through assigns) —
    return the step; else None."""
    writer = _producer_through_assigns(sub, name)
    if (writer is not None and writer.type == "increment"
            and (writer.inputs.get("X") or [None])[0] == name):
        step = float(writer.attrs.get("step", 1.0))
        if step >= 1.0:
            return step
    return None


def _infer_trip_bound(sub, outer, cond_name) -> Optional[int]:
    """Derive a static trip-count bound for a while body, the analogue of
    the reference reading extents off the lod_rank_table when it
    differentiates a dynamic while sub-block
    (/root/reference/paddle/framework/backward.cc:415 + lod_rank_table.h).

    Inference is deliberately conservative: it only fires when the
    condition is ``less_than/less_equal(i, n)`` where ``i`` is a VERIFIED
    counter (the body reassigns it to ``increment(i, step>=1)``) with a
    static, non-negative start AND ``n`` is a static fill_constant — then
    the bound is exactly ceil((n - start) / step), so the masked scan runs
    the same trips the dynamic loop would. Anything else (runtime limits,
    non-counter conditions, sentinel limits past _MAX_INFERRED_TRIP) keeps
    the dynamic ``lax.while_loop`` lowering: a merely plausible bound
    (e.g. a tensor-array extent) could silently truncate a loop whose
    runtime limit runs longer.
    """
    import math

    cond_op = _producer_through_assigns(sub, cond_name)
    if cond_op is None or cond_op.type not in ("less_than", "less_equal"):
        return None
    xname = (cond_op.inputs.get("X") or [None])[0]
    yname = (cond_op.inputs.get("Y") or [None])[0]
    if xname is None or yname is None:
        return None
    step = _counter_step(sub, xname)
    if step is None:
        return None
    start = _static_scalar_value((outer,), xname)
    if start is None or start < 0:
        return None
    limit = _static_scalar_value((sub, outer), yname)
    if limit is None:
        return None
    extra = 1 if cond_op.type == "less_equal" else 0
    trips = max(int(math.ceil((float(limit) - start) / step)) + extra, 0)
    return trips if trips <= _MAX_INFERRED_TRIP else None


class While:
    """Functional while loop (fluid layers.While / while_op.cc).

    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        ... body ops; every loop-carried var (including cond) must be
        written each iteration (use layers.assign(new, output=var)) ...
    Loop-carried vars are detected as body-written names that exist in the
    enclosing block.
    """

    def __init__(self, cond: Variable, max_iters=None, main_program=None,
                 startup_program=None):
        """``max_iters``: static trip-count bound. Setting it lowers the
        loop to a fixed-length masked scan, which makes the while
        reverse-differentiable (trainable) — see ops/control_flow_ops.py
        while_op. When left None, a bound is INFERRED from the loop
        structure (static `less_than` limits or tensor-array extents —
        _infer_trip_bound) so NMT-style decode-train loops differentiate
        without hand-passing one; pass ``max_iters=0`` to force the dynamic
        ``lax.while_loop`` lowering (true early exit, not trainable)."""
        self.helper = LayerHelper("while", main_program=main_program,
                                  startup_program=startup_program)
        self.cond = cond
        self.max_iters = max_iters
        self.sub_block = None

    class _Block:
        def __init__(self, w):
            self.w = w

        def __enter__(self):
            self.w.outer_block = self.w.helper.main_program.current_block()
            self.w.sub_block = self.w.helper.main_program.create_block()
            return self.w

        def __exit__(self, exc_type, *a):
            self.w.helper.main_program.rollback()
            if exc_type is None:
                self.w._complete()

    def block(self):
        return While._Block(self)

    def _complete(self):
        sub = self.sub_block
        outer = self.outer_block
        # Carried vars: body-written names resolvable in the OUTER scope.
        written = []
        for op in sub.ops:
            for n in op.output_names():
                if outer.has_var(n) and n not in written:
                    written.append(n)
        if self.cond.name not in written:
            raise ValueError(
                "While body must reassign the condition variable "
                f"{self.cond.name!r} (layers.assign(new_cond, output=cond))")
        carried = written
        body_ops, params = _collect_body(sub, carried)
        max_iters = self.max_iters
        if max_iters is None:
            max_iters = _infer_trip_bound(sub, outer, self.cond.name)
        elif max_iters == 0:
            max_iters = None  # explicit request for the dynamic lowering
        ins = {
            "Carried": [outer.var(n) for n in carried],
            "Param": [outer.var(n) if outer.has_var(n) else n
                      for n in params],
        }
        attrs = {
            "body_ops": body_ops,
            "carried_names": carried,
            "param_names": params,
            "cond_name": self.cond.name,
            "max_iters": max_iters,
        }
        # Outputs write back to the SAME outer variables (final loop state).
        outputs = {"Out": [outer.var(n) for n in carried]}
        self.helper.append_op("while", ins, outputs, attrs)


def create_array(element_shape, max_len, dtype="float32", main_program=None,
                 startup_program=None):
    """A [max_len, ...] zero buffer: the functional LoDTensorArray."""
    from . import tensor as tensor_layers

    return tensor_layers.fill_constant(
        shape=[max_len] + list(element_shape), dtype=dtype, value=0.0,
        main_program=main_program, startup_program=startup_program)


def array_write(x, i, array, main_program=None, startup_program=None):
    helper = LayerHelper("array_write", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("array_write",
                            {"X": [x], "I": [i], "Array": [array]})


def array_read(array, i, main_program=None, startup_program=None):
    helper = LayerHelper("array_read", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("array_read", {"Array": [array], "I": [i]})


def increment(x, value=1.0, main_program=None, startup_program=None):
    helper = LayerHelper("increment", main_program=main_program,
                         startup_program=startup_program)
    return helper.simple_op("increment", {"X": [x]}, {"step": value})


def beam_search_decoder(init_state, embedding_param, cell_params, out_params,
                        beam_size=4, max_len=32, bos_id=0, eos_id=1,
                        cell="gru", init_cell=None, main_program=None,
                        startup_program=None):
    """Fused beam-search generation (see ops/control_flow_ops.py
    beam_search_decoder for semantics and reference citations).

    cell_params = (weight_x, weight_h, bias_or_None);
    out_params = (weight_out, bias_or_None).
    Returns (ids [b, beam, max_len], scores [b, beam], lengths [b, beam]).
    """
    helper = LayerHelper("beam_search_decoder", main_program=main_program,
                         startup_program=startup_program)
    wx, wh, bias = cell_params
    w_out, b_out = out_params
    ins = {"InitState": [init_state], "Embedding": [embedding_param],
           "WeightX": [wx], "WeightH": [wh], "WeightOut": [w_out]}
    if bias is not None:
        ins["Bias"] = [bias]
    if b_out is not None:
        ins["OutBias"] = [b_out]
    if init_cell is not None:
        ins["InitCell"] = [init_cell]
    outs, _ = helper.append_op(
        "beam_search_decoder", ins, ["Ids", "SeqScores", "SeqLen"],
        {"beam_size": beam_size, "max_len": max_len, "bos_id": bos_id,
         "eos_id": eos_id, "cell": cell})
    return outs["Ids"][0], outs["SeqScores"][0], outs["SeqLen"][0]


def array_length(array, main_program=None, startup_program=None):
    """Length of a functional LoDTensorArray (fluid control_flow.py
    array_length): the [max_len, ...] buffer's leading extent, as a
    [1] int64 constant."""
    from . import tensor as tensor_layers

    return tensor_layers.fill_constant(
        shape=[1], value=int(array.shape[0]), dtype="int64",
        main_program=main_program, startup_program=startup_program)


class DynamicRNN(StaticRNN):
    """fluid DynamicRNN (control_flow.py DynamicRNN): user-defined
    recurrence over VARIABLE-length sequences. The reference sorts rows
    by length through a lod_rank_table and shrinks the batch as
    sequences end (recurrent_op StepScopes); the dense+mask plane makes
    that machinery unnecessary — this is StaticRNN whose scan carries
    each row's state through unchanged past its length (the static_rnn
    op masks on Length), so dynamic == static + mask, one lax.scan.

    API differences served: ``block()`` (the fluid name for the step
    context) and ``memory(init=... | shape/value zeros-boot)``.
    """

    def block(self):
        return self.step()

    def memory(self, init=None, shape=None, value=0.0, dtype="float32",
               **kw):
        if init is None:
            if not self.seq_vars:
                raise ValueError(
                    "DynamicRNN.memory(shape=...) needs a step_input "
                    "first (the zeros boot sizes its batch from it)")
            from . import tensor as tensor_layers

            prog = self.helper.main_program
            cur = prog.current_block_idx
            prog.current_block_idx = prog.blocks[cur].parent_idx
            try:
                init = tensor_layers.fill_constant_batch_size_like(
                    input=self.seq_vars[0],
                    shape=[-1] + list(shape or []), value=value,
                    dtype=dtype)
            finally:
                prog.current_block_idx = cur
        return super().memory(init)


def beam_search_decode(ids, scores, main_program=None,
                       startup_program=None):
    """fluid's beam_search_decode converts the While-loop beam arrays
    (LoDTensorArray ids/scores) into final sequences — machinery the
    fused in-graph decoder makes unnecessary."""
    raise NotImplementedError(
        "beam_search_decode (array-to-tensor conversion for the "
        "While-loop beam) is served by the fused in-graph decoders, "
        "which return finished sequences directly: "
        "layers.beam_search_decoder / models.transformer_lm_beam_search")

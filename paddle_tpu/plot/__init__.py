"""Training-curve plotting, v2 Ploter parity
(/root/reference/python/paddle/v2/plot/ploter.py).

The reference draws matplotlib curves in notebooks; here ``Ploter``
accumulates (step, value) series per title and renders either a PNG (when
matplotlib is importable and a path is given) or a terminal summary —
training scripts call the same append/plot API either way.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class Ploter:
    def __init__(self, *titles: str):
        self.titles = list(titles)
        self._data: Dict[str, List[Tuple[float, float]]] = {
            t: [] for t in titles}

    def append(self, title: str, step: float, value: float) -> None:
        if title not in self._data:
            raise KeyError(f"unknown series {title!r}; declared: "
                           f"{self.titles}")
        self._data[title].append((float(step), float(value)))

    def reset(self) -> None:
        for t in self._data:
            self._data[t] = []

    def series(self, title: str) -> List[Tuple[float, float]]:
        return list(self._data[title])

    def plot(self, path: Optional[str] = None) -> Optional[str]:
        """Write a PNG to ``path`` (matplotlib), else return a terminal
        summary string (also returned alongside the PNG)."""
        if path is not None:
            try:
                import matplotlib
                matplotlib.use("Agg")
                import matplotlib.pyplot as plt

                fig, ax = plt.subplots(figsize=(7, 4))
                for t in self.titles:
                    if self._data[t]:
                        xs, ys = zip(*self._data[t])
                        ax.plot(xs, ys, label=t)
                ax.set_xlabel("step")
                ax.legend()
                fig.tight_layout()
                fig.savefig(path)
                plt.close(fig)
            except ImportError:
                path = None  # fall through to the text summary
        parts = []
        for t in self.titles:
            pts = self._data[t]
            if not pts:
                parts.append(f"{t}: (empty)")
                continue
            ys = [y for _, y in pts]
            parts.append(f"{t}: n={len(ys)} last={ys[-1]:.6g} "
                         f"min={min(ys):.6g} max={max(ys):.6g}")
        return " | ".join(parts)

"""Helpers for steering which XLA backend a process (or child) uses.

The dev environment pins JAX at a single real TPU chip through a tunnel
plugin that intercepts backend initialization; multi-device work runs on a
virtual CPU mesh instead (``--xla_force_host_platform_device_count``).
These helpers centralize the env surgery so scripts (bench.py,
__graft_entry__.py) and tests agree on it.
"""
from __future__ import annotations

import os

_FORCE_FLAG = "--xla_force_host_platform_device_count"


def strip_host_device_flag(flags: str) -> str:
    """Remove any existing host-device-count flag (either '--flag=value' or
    '--flag value' spelling) from an XLA_FLAGS string."""
    toks = flags.split()
    kept, skip_next = [], False
    for i, t in enumerate(toks):
        if skip_next:
            skip_next = False
            continue
        if t.startswith(_FORCE_FLAG):
            # '--flag value' spelling: the bare flag followed by an integer
            if t == _FORCE_FLAG and i + 1 < len(toks) and toks[i + 1].isdigit():
                skip_next = True
            continue
        kept.append(t)
    return " ".join(kept)


def _strip_tunnel_shim(env: dict) -> None:
    """Drop the dev-tunnel site shim from PYTHONPATH for CPU children.

    JAX_PLATFORMS=cpu alone does not stop the tunnel plugin from
    initializing during backend discovery, and when the tunnel is down
    that initialization can HANG rather than fail — observed wedging even
    pure-CPU children for hours. CPU children must not load it at all."""
    pp = env.get("PYTHONPATH", "")
    kept = [p for p in pp.split(os.pathsep) if p and "axon" not in p]
    if kept:
        env["PYTHONPATH"] = os.pathsep.join(kept)
    else:
        env.pop("PYTHONPATH", None)


def cpu_mesh_env(base_env: dict, n_devices: int) -> dict:
    """Child-process env for an n-device virtual CPU mesh."""
    env = dict(base_env)
    flags = strip_host_device_flag(env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + f" {_FORCE_FLAG}={n_devices}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    _strip_tunnel_shim(env)
    return env


def cpu_env(base_env: dict) -> dict:
    """Child-process env pinned to the (single-device) CPU backend."""
    env = dict(base_env)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = strip_host_device_flag(env.get("XLA_FLAGS", ""))
    _strip_tunnel_shim(env)
    return env


def tpu_env(base_env: dict) -> dict:
    """Child-process env cleaned for real-TPU use: drop any CPU pin or
    virtual-device-count leakage so the platform plugin can claim the chip."""
    env = dict(base_env)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = strip_host_device_flag(env.get("XLA_FLAGS", ""))
    return env


def claim_cpu_mesh(n_devices: int) -> None:
    """Commit THIS process's (not-yet-initialized) JAX backend to an
    n-device virtual CPU mesh. Must run before any backend initialization;
    sets both the env vars and the live config (the tunnel plugin only
    respects the latter once jax is imported)."""
    os.environ.update(
        {k: v for k, v in cpu_mesh_env(os.environ, n_devices).items()
         if k in ("XLA_FLAGS", "JAX_PLATFORMS")})
    import jax

    jax.config.update("jax_platforms", "cpu")


def backend_initialized():
    """Whether a JAX backend has already been committed in this process:
    True / False, or None when it cannot be determined (the private
    registry moved in a jax upgrade). Callers pick their own safe side
    for None."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return None

"""paddle_tpu: a TPU-native deep-learning framework.

A from-scratch rebuild of PaddlePaddle's (~v0.11) capability set —
ProgramDesc-style graph capture, an op zoo with automatic backward,
optimizers-as-ops, feed/fetch execution, readers/datasets, checkpointing,
distributed data-parallel training — re-architected for JAX/XLA on TPU:
whole program blocks compile to single XLA computations, gradients come from
jax.vjp, and every distributed path is in-graph collectives over ICI/DCN
instead of parameter servers. See SURVEY.md at the repo root for the full
mapping onto the reference.
"""
from . import (analysis, checkpoint, clip, decoding, evaluator, event,
               initializer,
               layers, learning_rate_decay, master, models, nets, online,
               optimizer, parallel, profiler, regularizer, resilience,
               serving, trace, trainer, transpiler)
from . import flags
from .checkgrad import check_gradients
from .core.enforce import (EnforceError, enforce, enforce_eq, enforce_ge,
                           enforce_gt, enforce_le, enforce_lt, enforce_ne,
                           enforce_not_none)
from .flags import FLAGS, parse_flags, set_flags
from .data_feeder import DataFeeder
from .core import (CPUPlace, Executor, Program, RunHandle, Scope, TPUPlace,
                   recompute_guard,
                   default_main_program, default_startup_program, global_scope,
                   program_guard)
from .core.backward import append_backward
from .core.selected_rows import SelectedRows
from .param_attr import ParamAttr
from .ops.common import amp_enabled, set_amp, set_mxu_precision

# ops must be imported so kernels register before any program runs
from . import ops as _ops  # noqa: F401

__version__ = "0.1.0"

"""ResNet — the flagship/benchmark model family.

Parity with /root/reference/benchmark/paddle/image/resnet.py (ImageNet
ResNet-50/101/152, bottleneck blocks) and the fluid book CIFAR variant
(/root/reference/python/paddle/v2/fluid/tests/book/
test_image_classification_train.py resnet_cifar10).

TPU notes: NHWC layout so the channel dim lands on the MXU lanes; batch-norm
in f32 with conv compute dtypes following the input (bf16 under the bench
harness); identity shortcuts use projection convs only on shape change, as in
the reference. With ``recompute=True`` each residual block becomes a
recompute segment (core.program.recompute_guard): only conv outputs and BN
stats survive to the backward, cutting peak activation memory ~2x for deep
variants at large batch. It is off by default because on-chip measurement
shows it trades ~45% step time for that memory (the barriered
rematerialization adds HBM traffic rather than removing it — see PERF.md
"recompute segments").
"""

from ..core.program import maybe_recompute

from .. import layers


_maybe_recompute = maybe_recompute


def _fuse_epilogue(filter_size, stride, padding, data_format):
    from ..flags import FLAGS

    return (FLAGS.fused_conv_epilogue and filter_size == 1 and stride == 1
            and padding == 0 and data_format == "NHWC")


def _conv_bn(x, num_filters, filter_size, stride=1, padding=0, act="relu",
             data_format="NHWC", is_test=False):
    if _fuse_epilogue(filter_size, stride, padding, data_format):
        return layers.conv1x1_bn_act(x, num_filters, act=act,
                                     is_test=is_test)
    conv = layers.conv2d(x, num_filters=num_filters, filter_size=filter_size,
                         stride=stride, padding=padding, bias_attr=False,
                         data_format=data_format)
    return layers.batch_norm(conv, act=act, is_test=is_test,
                             data_layout=data_format)


def _shortcut(x, ch_out, stride, data_format, is_test):
    ch_axis = 3 if data_format == "NHWC" else 1
    if x.shape[ch_axis] != ch_out or stride != 1:
        return _conv_bn(x, ch_out, 1, stride, act=None,
                        data_format=data_format, is_test=is_test)
    return x


def _bottleneck(x, ch_mid, stride, data_format, is_test, recompute=False):
    """1x1 → 3x3 → 1x1(×4) bottleneck (reference resnet.py bottleneck)."""
    ch_out = ch_mid * 4
    with _maybe_recompute(recompute):
        short = _shortcut(x, ch_out, stride, data_format, is_test)
        y = _conv_bn(x, ch_mid, 1, 1, 0, data_format=data_format,
                     is_test=is_test)
        y = _conv_bn(y, ch_mid, 3, stride, 1, data_format=data_format,
                     is_test=is_test)
        if _fuse_epilogue(1, 1, 0, data_format):
            # residual add + relu ride the final 1x1 conv's output tile
            return layers.conv1x1_bn_act(y, ch_out, residual=short,
                                         act="relu", is_test=is_test)
        y = _conv_bn(y, ch_out, 1, 1, 0, act=None, data_format=data_format,
                     is_test=is_test)
        added = layers.elementwise_add(y, short)
        return layers.relu(added)


def _basicblock(x, ch_out, stride, data_format, is_test, recompute=False):
    with _maybe_recompute(recompute):
        short = _shortcut(x, ch_out, stride, data_format, is_test)
        y = _conv_bn(x, ch_out, 3, stride, 1, data_format=data_format,
                     is_test=is_test)
        y = _conv_bn(y, ch_out, 3, 1, 1, act=None, data_format=data_format,
                     is_test=is_test)
        added = layers.elementwise_add(y, short)
        return layers.relu(added)


def resnet_imagenet(images, num_classes=1000, depth=50, data_format="NHWC",
                    is_test=False, recompute=False):
    """ResNet-50/101/152 for 224x224 ImageNet (reference resnet.py:8)."""
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
    assert depth in cfg, f"resnet_imagenet depth must be one of {sorted(cfg)}, got {depth}"
    counts = cfg[depth]
    x = _conv_bn(images, 64, 7, stride=2, padding=3, data_format=data_format,
                 is_test=is_test)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      data_format=data_format)
    for stage, (ch_mid, n) in enumerate(zip([64, 128, 256, 512], counts)):
        for block in range(n):
            stride = 2 if block == 0 and stage > 0 else 1
            x = _bottleneck(x, ch_mid, stride, data_format, is_test,
                            recompute=recompute)
    x = layers.pool2d(x, pool_type="avg", global_pooling=True,
                      data_format=data_format)
    return layers.fc(x, size=num_classes)


def resnet_cifar10(images, num_classes=10, depth=32, data_format="NHWC",
                   is_test=False, recompute=False):
    """CIFAR ResNet with basic blocks, depth = 6n+2 (book test parity)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    x = _conv_bn(images, 16, 3, 1, 1, data_format=data_format,
                 is_test=is_test)
    for stage, ch in enumerate([16, 32, 64]):
        for block in range(n):
            stride = 2 if block == 0 and stage > 0 else 1
            x = _basicblock(x, ch, stride, data_format, is_test,
                            recompute=recompute)
    x = layers.pool2d(x, pool_type="avg", global_pooling=True,
                      data_format=data_format)
    return layers.fc(x, size=num_classes)

"""VGG-16/19 — parity with /root/reference/benchmark/paddle/image/vgg.py."""
from .. import layers
from ..nets import img_conv_group


def vgg(images, num_classes=1000, depth=19, data_format="NHWC",
        is_test=False):
    """VGG-16 or VGG-19 (reference vgg.py:24 selects conv counts by depth)."""
    assert depth in (16, 19), f"vgg depth must be 16 or 19, got {depth}"
    nums = [2, 2, 3, 3, 3] if depth == 16 else [2, 2, 4, 4, 4]
    x = images
    for filters, n in zip([64, 128, 256, 512, 512], nums):
        x = img_conv_group(x, [filters] * n, conv_filter_size=3,
                           conv_act="relu", data_format=data_format)
    fc1 = layers.fc(x, size=4096, act="relu")
    fc1 = layers.dropout(fc1, 0.5, is_test=is_test)
    fc2 = layers.fc(fc1, size=4096, act="relu")
    fc2 = layers.dropout(fc2, 0.5, is_test=is_test)
    return layers.fc(fc2, size=num_classes)

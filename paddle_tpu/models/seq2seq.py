"""Transformer NMT (encoder-decoder with cross-attention).

The decoder side IS the stacked LM: it reuses ``transformer_lm``'s
shared-by-name weight contract (tok_emb / pos_emb / lm_stack.* /
final_ln.* / lm_head.w — here the TARGET embedding/stack/head) extended
with per-layer cross-attention weights (``xattn.stack_*``); the encoder
carries its own stack (``enc_stack.*`` / src_emb / src_pos_emb /
enc_ln.*). One scope therefore serves training (the teacher-forced
``transformer_encdec_teacher`` op), the admission-time encoder pass, and
the paged cross-attention decode — the GAN-demo sibling-programs
pattern, seq2seq-shaped.
"""
from __future__ import annotations

from ..initializer import ConstantInitializer
from ..layers.layer_helper import LayerHelper
from ..param_attr import ParamAttr
from .transformer import _shared_lm_params


def _cross_params(helper, n_layers, d_model, d_kv):
    """The stacked cross-attention weights (xattn.stack_*): per-layer
    pre-LN + query/out projections for the decoder, plus the K/V
    projection the ENCODE op applies to the encoder memory."""
    one = ConstantInitializer(1.0)

    def mk(suffix, shape, bias=False, init=None):
        return helper.create_parameter(
            ParamAttr(name=f"xattn.stack_{suffix}"), shape=shape,
            dtype="float32", is_bias=bias, default_initializer=init)

    return {
        "XLnS": [mk("ln_s", [n_layers, d_model], bias=True, init=one)],
        "XLnB": [mk("ln_b", [n_layers, d_model], bias=True)],
        "XQW": [mk("q_w", [n_layers, d_model, d_model])],
        "XOutW": [mk("out_w", [n_layers, d_model, d_model])],
        "XKvW": [mk("kv_w", [n_layers, d_model, 2 * d_kv])],
    }


def _encoder_params(helper, src_vocab_size, d_model, d_ff, max_src_len,
                    n_layers, num_heads, num_kv_heads):
    from ..layers.attention import make_stack_params

    one = ConstantInitializer(1.0)
    ins = {
        "SrcTokEmb": [helper.create_parameter(
            ParamAttr(name="src_emb"), shape=[src_vocab_size, d_model],
            dtype="float32")],
        "SrcPosEmb": [helper.create_parameter(
            ParamAttr(name="src_pos_emb"), shape=[max_src_len, d_model],
            dtype="float32")],
        "EncLnS": [helper.create_parameter(
            ParamAttr(name="enc_ln.scale"), shape=[d_model],
            dtype="float32", default_initializer=one)],
        "EncLnB": [helper.create_parameter(
            ParamAttr(name="enc_ln.bias"), shape=[d_model],
            dtype="float32", is_bias=True)],
    }
    enc = make_stack_params(helper, "enc_stack", n_layers, d_model, d_ff,
                            num_heads=num_heads,
                            num_kv_heads=num_kv_heads)
    ins.update({f"Enc{slot}": v for slot, v in enc.items()})
    return ins


def shared_nmt_params(helper, src_vocab_size, tgt_vocab_size, d_model,
                      d_ff, max_src_len, max_tgt_len, n_layers,
                      num_heads, num_kv_heads=None):
    """Every weight the NMT op family shares, keyed by op slot — build
    (or rejoin by name) in any program that needs the model."""
    d_kv = (d_model if not (num_heads and num_kv_heads)
            else d_model // num_heads * num_kv_heads)
    ins = _shared_lm_params(helper, tgt_vocab_size, d_model, d_ff,
                            max_tgt_len, n_layers, num_heads,
                            num_kv_heads)
    ins.update(_cross_params(helper, n_layers, d_model, d_kv))
    ins.update(_encoder_params(helper, src_vocab_size, d_model, d_ff,
                               max_src_len, n_layers, num_heads,
                               num_kv_heads))
    return ins


def transformer_nmt_teacher(src_ids, src_len, tgt_in, src_vocab_size,
                            tgt_vocab_size, d_model=256, n_layers=4,
                            num_heads=8, d_ff=None, num_kv_heads=None,
                            max_src_len=128, max_tgt_len=128,
                            main_program=None, startup_program=None):
    """Teacher-forced NMT training forward: src_ids [b, Ts] int64 +
    src_len [b] int32 + tgt_in [b, Tt] int64 -> logits [b, Tt, Vt].
    Wrap with softmax_with_cross_entropy against tgt_next for the loss;
    the trained scope serves through
    :class:`paddle_tpu.decoding.Seq2SeqGenerationEngine` token-exact."""
    kw = dict(main_program=main_program, startup_program=startup_program)
    d_ff = d_ff or 4 * d_model
    helper = LayerHelper("transformer_nmt", **kw)
    ins = {"SrcIds": [src_ids], "SrcLen": [src_len], "TgtIn": [tgt_in]}
    ins.update(shared_nmt_params(helper, src_vocab_size, tgt_vocab_size,
                                 d_model, d_ff, max_src_len, max_tgt_len,
                                 n_layers, num_heads, num_kv_heads))
    logits = helper.simple_op(
        "transformer_encdec_teacher", ins,
        {"num_heads": num_heads, "num_kv_heads": num_kv_heads},
        out_slot="Logits")
    return logits

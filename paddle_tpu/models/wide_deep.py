"""Wide&Deep CTR model with high-dimensional sparse embeddings.

The BASELINE.json flagship config #5 ("Wide&Deep CTR with high-dim sparse
embeddings, distributed pserver -> ICI all-reduce"). The reference served
this workload with the sparse parameter-server path — row-sparse gradients
(/root/reference/paddle/operators/lookup_table_op.cc:59 SelectedRows grad,
/root/reference/paddle/math/SparseRowMatrix.h) shipped to pservers sharded
by parameter block (/root/reference/paddle/pserver/ParameterServer2.h:94).

TPU-native redesign: ``is_sparse=True`` embeddings produce SelectedRows
gradients consumed by lazy row-granular optimizer updates (never a [V, D]
buffer), and the vocabulary dimension shards over the model axis of the
device mesh (parallel.vocab_sharded_plan) so the embedding table scales
with the slice — GSPMD turns lookups and row updates into ICI traffic.
"""
from __future__ import annotations

from ..layers.layer_helper import LayerHelper
from .. import layers


def wide_deep(sparse_ids, dense_input, vocab_size, embed_dim=16,
              hidden_sizes=(64, 32), is_sparse=True,
              main_program=None, startup_program=None):
    """Build the Wide&Deep CTR tower; returns the [b, 1] logit.

    sparse_ids:  int [b, S] — S categorical slots, ids pre-offset into a
                 shared vocabulary of ``vocab_size`` (the usual CTR layout).
    dense_input: float [b, Dd] continuous features (may be None).

    wide  = sum over slots of a per-id scalar weight (an embedding of dim 1
            — the linear-over-one-hot part) [+ linear in dense features]
    deep  = MLP over the concatenated [b, S*embed_dim] slot embeddings
            [+ dense features]
    logit = wide + deep head
    """
    kw = dict(main_program=main_program, startup_program=startup_program)
    b_s = sparse_ids.shape
    num_slots = b_s[-1]

    # -- wide: linear part over sparse ids ------------------------------
    wide_emb = layers.embedding(
        sparse_ids, size=[vocab_size, 1], is_sparse=is_sparse, **kw)
    helper = LayerHelper("wide_deep", **kw)
    wide = helper.simple_op(
        "reduce_sum", {"X": [wide_emb]}, {"dim": [1], "keep_dim": False})

    # -- deep: embeddings + MLP ----------------------------------------
    deep_emb = layers.embedding(
        sparse_ids, size=[vocab_size, embed_dim], is_sparse=is_sparse, **kw)
    deep = layers.reshape(deep_emb, [-1, num_slots * embed_dim], **kw)
    if dense_input is not None:
        deep = layers.concat([deep, dense_input], axis=1, **kw)
        wide = layers.elementwise_add(
            wide, layers.fc(dense_input, size=1, **kw), **kw)
    for size in hidden_sizes:
        deep = layers.fc(deep, size=size, act="relu", **kw)
    deep_logit = layers.fc(deep, size=1, **kw)

    return layers.elementwise_add(wide, deep_logit, **kw)


def wide_deep_loss(logit, label, main_program=None, startup_program=None):
    """Mean sigmoid cross-entropy CTR loss; returns (loss, probability)."""
    kw = dict(main_program=main_program, startup_program=startup_program)
    helper = LayerHelper("wide_deep", **kw)
    ce = helper.simple_op(
        "sigmoid_cross_entropy_with_logits",
        {"X": [logit], "Label": [label]}, {})
    prob = layers.sigmoid(logit, **kw)
    return layers.mean(ce, **kw), prob

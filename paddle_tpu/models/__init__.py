"""Model zoo: the reference's benchmark/demo model families, rebuilt on the
paddle_tpu layer API.

Reference configs: /root/reference/benchmark/paddle/image/{alexnet,googlenet,
resnet,vgg,smallnet_mnist_cifar}.py and /root/reference/v1_api_demo/mnist
(LeNet). The RNN/LSTM families land with the sequence machinery.

All builders take a data Variable and append ops to the default (or given)
program; they return the logits variable. ``data_format`` defaults to NHWC —
the TPU-native layout (channels-last maps directly onto the MXU's lane
dimension) — whereas the reference hardcodes NCHW for cuDNN.
"""
from .lenet import lenet5
from .alexnet import alexnet
from .vgg import vgg
from .resnet import resnet_imagenet, resnet_cifar10
from .googlenet import googlenet
from .mobilenet import mobilenet
from .smallnet import smallnet_mnist_cifar
from .seq2seq import shared_nmt_params, transformer_nmt_teacher
from .transformer import (transformer_lm, transformer_lm_beam_search,
                          transformer_lm_generate,
                          transformer_lm_speculative_generate)
from .wide_deep import wide_deep, wide_deep_loss

__all__ = [
    "transformer_lm", "transformer_lm_beam_search", "transformer_lm_generate",
    "transformer_lm_speculative_generate", "wide_deep", "wide_deep_loss",
    "shared_nmt_params", "transformer_nmt_teacher",
    "lenet5", "alexnet", "vgg", "resnet_imagenet", "resnet_cifar10",
    "googlenet", "mobilenet", "smallnet_mnist_cifar",
]

"""AlexNet — parity with /root/reference/benchmark/paddle/image/alexnet.py."""
from .. import layers


def alexnet(images, num_classes=1000, data_format="NHWC", is_test=False):
    """images: [N, 224, 224, 3] NHWC (or NCHW) → logits.

    Structure follows the reference config: conv11/s4 → lrn → pool → conv5 →
    lrn → pool → conv3 ×3 → pool → fc4096 ×2 (dropout .5) → fc classes.
    """
    conv1 = layers.conv2d(images, num_filters=96, filter_size=11, stride=4,
                          padding=1, act="relu", data_format=data_format)
    norm1 = layers.lrn(conv1, n=5, alpha=1e-4, beta=0.75,
                       data_format=data_format)
    pool1 = layers.pool2d(norm1, pool_size=3, pool_stride=2,
                          data_format=data_format)
    conv2 = layers.conv2d(pool1, num_filters=256, filter_size=5, padding=2,
                          groups=1, act="relu", data_format=data_format)
    norm2 = layers.lrn(conv2, n=5, alpha=1e-4, beta=0.75,
                       data_format=data_format)
    pool2 = layers.pool2d(norm2, pool_size=3, pool_stride=2,
                          data_format=data_format)
    conv3 = layers.conv2d(pool2, num_filters=384, filter_size=3, padding=1,
                          act="relu", data_format=data_format)
    conv4 = layers.conv2d(conv3, num_filters=384, filter_size=3, padding=1,
                          groups=1, act="relu", data_format=data_format)
    conv5 = layers.conv2d(conv4, num_filters=256, filter_size=3, padding=1,
                          groups=1, act="relu", data_format=data_format)
    pool5 = layers.pool2d(conv5, pool_size=3, pool_stride=2,
                          data_format=data_format)
    fc6 = layers.fc(pool5, size=4096, act="relu")
    fc6 = layers.dropout(fc6, 0.5, is_test=is_test)
    fc7 = layers.fc(fc6, size=4096, act="relu")
    fc7 = layers.dropout(fc7, 0.5, is_test=is_test)
    return layers.fc(fc7, size=num_classes)

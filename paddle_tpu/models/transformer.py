"""Transformer language model (decoder-only, causal).

Capability extension beyond the reference (which predates Transformers);
the flagship long-context model: flash attention on one chip,
ring-attention sequence parallelism across chips
(parallel/ring_attention.py) when T outgrows a single device.
"""
from __future__ import annotations

import numpy as np

from .. import layers
from ..layers.layer_helper import LayerHelper
from ..param_attr import ParamAttr


def transformer_lm(ids, vocab_size, d_model=256, n_layers=4, num_heads=8,
                   d_ff=None, num_kv_heads=None, use_rope=False,
                   max_len=2048, norm_type="layer_norm",
                   pipeline_stack=False, n_microbatches=None, remat=False,
                   include_head=True,
                   main_program=None, startup_program=None):
    """ids [b, T] int64 -> logits [b, T, vocab]. Pre-LN GPT-style blocks,
    learned positional embedding, weight-tied-free output head.

    ``pipeline_stack=True`` builds the blocks as one stacked-weight layer
    (scan over layers; pipeline-parallel under a 'pp' mesh axis with
    ``parallel.pipeline_plan`` — see layers.pipelined_transformer_stack).
    ``include_head=False`` returns the final-norm hidden states [b, T, d]
    instead of logits, for use with
    ``layers.fused_head_cross_entropy`` (chunked large-vocab loss that
    never materializes the logits)."""
    # validate BEFORE building anything: a raise must not leave orphan
    # embedding ops/parameters in the caller's program
    if norm_type != "layer_norm" and pipeline_stack:
        raise ValueError(
            "pipeline_stack=True supports norm_type='layer_norm' only "
            "(the stacked-weight layout and its generation/serving "
            "siblings share fixed LN parameter planes)")
    if not include_head and pipeline_stack:
        raise ValueError(
            "pipeline_stack=True requires include_head=True: the "
            "generation/serving siblings rejoin the trained head by its "
            "fixed name (lm_head.w), which only the built-in head "
            "creates — a fused_head_cross_entropy head would train "
            "under a different parameter name and serving would "
            "silently run an untrained head")
    kw = dict(main_program=main_program, startup_program=startup_program)
    d_ff = d_ff or 4 * d_model
    tok = layers.embedding(ids, size=[vocab_size, d_model],
                           param_attr=ParamAttr(name="tok_emb"), **kw)
    tok.seq_len = getattr(ids, "seq_len", None)
    T = ids.shape[1]
    helper = LayerHelper("transformer_lm", **kw)
    if use_rope:
        # positions live in the attention rotation — no learned table
        x = tok
    else:
        pos_table = helper.create_parameter(
            ParamAttr(name="pos_emb"), shape=[max_len, d_model],
            dtype="float32")
        # slice the first T rows; T is static under the whole-block compile
        pos = helper.simple_op("slice", {"X": [pos_table]},
                               {"axes": [0], "starts": [0], "ends": [T]})
        x = helper.simple_op("elementwise_add", {"X": [tok], "Y": [pos]})
        x.seq_len = tok.seq_len
    ln_attr = ln_bias = head_attr = None
    if pipeline_stack:
        # stable parameter names so a generation program (which rebuilds
        # these layers) shares the trained weights by name; one stacked
        # LM per program — the fixed names would otherwise silently alias
        if "lm_stack.stack_qkv_w" in helper.main_program.global_block.vars:
            raise ValueError(
                "transformer_lm(pipeline_stack=True) may be built only "
                "once per program: its parameter names (lm_stack.*, "
                "final_ln.*, lm_head.w) are fixed so generation programs "
                "can rejoin them, and a second stacked LM in the same "
                "program would silently share weights")
        x = layers.pipelined_transformer_stack(
            x, n_layers=n_layers, num_heads=num_heads, d_ff=d_ff,
            num_kv_heads=num_kv_heads, use_rope=use_rope, causal=True,
            n_microbatches=n_microbatches, remat=remat,
            param_attr=ParamAttr(name="lm_stack"), **kw)
        ln_attr = ParamAttr(name="final_ln.scale")
        ln_bias = ParamAttr(name="final_ln.bias")
        head_attr = ParamAttr(name="lm_head.w")
    else:
        from ..core.program import maybe_recompute

        for _ in range(n_layers):
            # remat: each block becomes one recompute segment — only its
            # matmul outputs survive to the backward (the norms'
            # grad_fn_is_optimization keeps them segment-eligible), the
            # deep-stack activation-memory lever for the per-layer path
            with maybe_recompute(remat, main_program):
                x = layers.transformer_encoder_layer(
                    x, num_heads=num_heads, d_ff=d_ff,
                    num_kv_heads=num_kv_heads, use_rope=use_rope,
                    causal=True, norm_type=norm_type, **kw)
    if norm_type == "rms_norm":
        x = layers.rms_norm(x, begin_norm_axis=2, **kw)
    else:
        x = layers.layer_norm(x, begin_norm_axis=2, param_attr=ln_attr,
                              bias_attr=ln_bias, **kw)
    if not include_head:
        return x
    logits = layers.fc(x, size=vocab_size, num_flatten_dims=2,
                       param_attr=head_attr, bias_attr=False, **kw)
    return logits


def _shared_lm_params(helper, vocab_size, d_model, d_ff, max_len,
                      n_layers, num_heads=None, num_kv_heads=None,
                      use_rope=False):
    """The weights-shared-by-name contract with transformer_lm
    (pipeline_stack=True), in ONE place: rebuild tok_emb/pos_emb/
    final_ln/lm_head/lm_stack.* so a generation-family program rejoins
    the trained tensors. Returns the op-input dict (minus Prompt)."""
    from ..initializer import ConstantInitializer
    from ..layers.attention import make_stack_params

    if num_heads and num_kv_heads and num_heads % num_kv_heads:
        raise ValueError(f"num_heads {num_heads} not a multiple of "
                         f"num_kv_heads {num_kv_heads}")
    tok = helper.create_parameter(ParamAttr(name="tok_emb"),
                                  shape=[vocab_size, d_model],
                                  dtype="float32")
    pos = None if use_rope else helper.create_parameter(
        ParamAttr(name="pos_emb"), shape=[max_len, d_model],
        dtype="float32")
    ln_s = helper.create_parameter(
        ParamAttr(name="final_ln.scale"), shape=[d_model], dtype="float32",
        default_initializer=ConstantInitializer(1.0))
    ln_b = helper.create_parameter(ParamAttr(name="final_ln.bias"),
                                   shape=[d_model], dtype="float32",
                                   is_bias=True)
    head_w = helper.create_parameter(ParamAttr(name="lm_head.w"),
                                     shape=[d_model, vocab_size],
                                     dtype="float32")
    ins = {"TokEmb": [tok], "FinalLnS": [ln_s],
           "FinalLnB": [ln_b], "HeadW": [head_w]}
    if pos is not None:
        ins["PosEmb"] = [pos]
    ins.update(make_stack_params(helper, "lm_stack", n_layers, d_model,
                                 d_ff, num_heads=num_heads,
                                 num_kv_heads=num_kv_heads))
    return ins


def transformer_lm_generate(prompt, vocab_size, d_model=256, n_layers=4,
                            num_heads=8, d_ff=None, num_kv_heads=None,
                            use_rope=False, max_len=2048,
                            max_new_tokens=32, temperature=0.0, top_k=0,
                            main_program=None, startup_program=None):
    """Generation program for a ``transformer_lm(pipeline_stack=True)``
    model: KV-cache incremental decoding
    (ops/pipeline_ops.transformer_stack_generate) — greedy by default,
    temperature/top-k sampling through the RNG plane when
    ``temperature`` > 0.

    Rebuilds the SAME named parameters (tok_emb, pos_emb, lm_stack.*,
    final_ln.*, lm_head.w) so running this program in the training scope
    serves the trained weights — do not run its startup program (that
    would re-initialize them; the pattern is the GAN demo's shared-weight
    sibling programs). prompt: [b, Tp] int64 -> [b, Tp + max_new_tokens].
    """
    kw = dict(main_program=main_program, startup_program=startup_program)
    d_ff = d_ff or 4 * d_model
    helper = LayerHelper("transformer_lm_generate", **kw)
    ins = {"Prompt": [prompt]}
    ins.update(_shared_lm_params(helper, vocab_size, d_model, d_ff,
                                 max_len, n_layers, num_heads,
                                 num_kv_heads, use_rope))
    o = helper.simple_op("transformer_stack_generate", ins,
                         {"num_heads": num_heads,
                          "num_kv_heads": num_kv_heads,
                          "use_rope": use_rope,
                          "max_new_tokens": max_new_tokens,
                          "temperature": float(temperature),
                          "top_k": int(top_k)})
    o.stop_gradient = True
    return o


def transformer_lm_beam_search(prompt, vocab_size, d_model=256, n_layers=4,
                               num_heads=8, d_ff=None, num_kv_heads=None,
                               use_rope=False, max_len=2048,
                               max_new_tokens=32, beam_size=4,
                               length_penalty=0.0, eos_id=None,
                               main_program=None, startup_program=None):
    """Beam-search generation for a ``transformer_lm(pipeline_stack=True)``
    model (ops/pipeline_ops.transformer_stack_beam_search). Same
    shared-parameter contract as ``transformer_lm_generate``. Returns
    (ids [b, K, Tp+N] best-first, scores [b, K])."""
    kw = dict(main_program=main_program, startup_program=startup_program)
    d_ff = d_ff or 4 * d_model
    helper = LayerHelper("transformer_lm_beam_search", **kw)
    ins = {"Prompt": [prompt]}
    ins.update(_shared_lm_params(helper, vocab_size, d_model, d_ff,
                                 max_len, n_layers, num_heads,
                                 num_kv_heads, use_rope))
    outs, _ = helper.append_op(
        "transformer_stack_beam_search", ins, ["Out", "Scores"],
        {"num_heads": num_heads, "num_kv_heads": num_kv_heads,
         "use_rope": use_rope,
         "max_new_tokens": max_new_tokens,
         "beam_size": beam_size, "length_penalty": float(length_penalty),
         "eos_id": -1 if eos_id is None else int(eos_id)})
    ids = outs["Out"][0]
    scores = outs["Scores"][0]
    ids.stop_gradient = True
    scores.stop_gradient = True
    return ids, scores


def transformer_lm_speculative_generate(prompt, vocab_size, d_model=256,
                                        n_layers=4, num_heads=8, d_ff=None,
                                        num_kv_heads=None, use_rope=False,
                                        max_len=2048, max_new_tokens=32,
                                        draft_layers=None, gamma=4,
                                        main_program=None,
                                        startup_program=None):
    """Self-speculative greedy decoding for a
    ``transformer_lm(pipeline_stack=True)`` model: the first
    ``draft_layers`` of the SAME stack plus a small draft head
    (draft_ln.*, draft_head.w — train it separately, e.g. on the frozen
    stack) propose ``gamma`` tokens per round, and the full stack verifies
    them in one block-causal pass. Output is EXACTLY the plain greedy
    decode (acceptance keeps only tokens the full stack argmaxes); the
    draft only buys fewer full-stack passes. Returns (ids [b, Tp+N],
    rounds [1] — plain decode would take N).

    EXPERIMENTAL (status, PERF.md "speculative decoding"): correctness is
    pinned (tests/test_generate.py) and a trained draft head cuts verify
    rounds well below N on the CPU mesh, but the only wall-clock A/B on
    record (r3 chip, UNtrained model — zero acceptance) was a 2.4x
    slowdown. Until tools/chip_session_r5.py's trained-model A/B records
    a speedup > 1, prefer plain ``transformer_lm_generate`` in
    production."""
    from ..initializer import ConstantInitializer

    kw = dict(main_program=main_program, startup_program=startup_program)
    d_ff = d_ff or 4 * d_model
    draft_layers = draft_layers or max(1, n_layers // 2)
    helper = LayerHelper("transformer_lm_speculative_generate", **kw)
    ins = {"Prompt": [prompt]}
    ins.update(_shared_lm_params(helper, vocab_size, d_model, d_ff,
                                 max_len, n_layers, num_heads,
                                 num_kv_heads, use_rope))
    ins["DraftLnS"] = [helper.create_parameter(
        ParamAttr(name="draft_ln.scale"), shape=[d_model],
        dtype="float32", default_initializer=ConstantInitializer(1.0))]
    ins["DraftLnB"] = [helper.create_parameter(
        ParamAttr(name="draft_ln.bias"), shape=[d_model], dtype="float32",
        is_bias=True)]
    ins["DraftHeadW"] = [helper.create_parameter(
        ParamAttr(name="draft_head.w"), shape=[d_model, vocab_size],
        dtype="float32")]
    outs, _ = helper.append_op(
        "transformer_stack_speculative_generate", ins, ["Out", "Rounds"],
        {"num_heads": num_heads, "num_kv_heads": num_kv_heads,
         "use_rope": use_rope, "max_new_tokens": max_new_tokens,
         "draft_layers": int(draft_layers), "gamma": int(gamma)})
    ids = outs["Out"][0]
    rounds = outs["Rounds"][0]
    ids.stop_gradient = True
    rounds.stop_gradient = True
    return ids, rounds

"""Transformer language model (decoder-only, causal).

Capability extension beyond the reference (which predates Transformers);
the flagship long-context model: flash attention on one chip,
ring-attention sequence parallelism across chips
(parallel/ring_attention.py) when T outgrows a single device.
"""
from __future__ import annotations

import numpy as np

from .. import layers
from ..layers.layer_helper import LayerHelper
from ..param_attr import ParamAttr


def transformer_lm(ids, vocab_size, d_model=256, n_layers=4, num_heads=8,
                   d_ff=None, max_len=2048, pipeline_stack=False,
                   n_microbatches=None, main_program=None,
                   startup_program=None):
    """ids [b, T] int64 -> logits [b, T, vocab]. Pre-LN GPT-style blocks,
    learned positional embedding, weight-tied-free output head.

    ``pipeline_stack=True`` builds the blocks as one stacked-weight layer
    (scan over layers; pipeline-parallel under a 'pp' mesh axis with
    ``parallel.pipeline_plan`` — see layers.pipelined_transformer_stack)."""
    kw = dict(main_program=main_program, startup_program=startup_program)
    d_ff = d_ff or 4 * d_model
    tok = layers.embedding(ids, size=[vocab_size, d_model],
                           param_attr=ParamAttr(name="tok_emb"), **kw)
    tok.seq_len = getattr(ids, "seq_len", None)
    T = ids.shape[1]
    helper = LayerHelper("transformer_lm", **kw)
    pos_table = helper.create_parameter(
        ParamAttr(name="pos_emb"), shape=[max_len, d_model], dtype="float32")
    # slice the first T rows; T is static under the whole-block compile
    pos = helper.simple_op("slice", {"X": [pos_table]},
                           {"axes": [0], "starts": [0], "ends": [T]})
    x = helper.simple_op("elementwise_add", {"X": [tok], "Y": [pos]})
    x.seq_len = tok.seq_len
    if pipeline_stack:
        x = layers.pipelined_transformer_stack(
            x, n_layers=n_layers, num_heads=num_heads, d_ff=d_ff,
            causal=True, n_microbatches=n_microbatches, **kw)
    else:
        for _ in range(n_layers):
            x = layers.transformer_encoder_layer(x, num_heads=num_heads,
                                                 d_ff=d_ff, causal=True,
                                                 **kw)
    x = layers.layer_norm(x, begin_norm_axis=2, **kw)
    logits = layers.fc(x, size=vocab_size, num_flatten_dims=2,
                       bias_attr=False, **kw)
    return logits

"""MobileNet v1 — depthwise-separable convolution family.

The era-matching mobile deployment model (models-repo mobilenet config;
the reference tree carries the building block as depthwise conv support in
conv_op.cc groups==channels). Depthwise 3x3 + pointwise 1x1 stacks; the
pointwise convs take the 1x1-as-dot fast path (ops/nn_ops.py) with the
fused Pallas backward, and the depthwise stages exercise
``depthwise_conv2d``'s grouped lowering.

TPU note: depthwise convs are VPU-bound (no contraction feeds the MXU), so
this family trades MXU utilisation for parameter count exactly as it does
on mobile silicon — it is in the zoo for capability parity, not as an MFU
flagship.
"""
from .. import layers


def _conv_bn(x, num_filters, filter_size, stride, padding, data_format,
             is_test, groups=1):
    conv = layers.conv2d(x, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=padding, groups=groups, bias_attr=False,
                         data_format=data_format)
    return layers.batch_norm(conv, act="relu", is_test=is_test,
                             data_layout=data_format)


def _separable(x, ch_out, stride, data_format, is_test):
    ch_in = x.shape[3 if data_format == "NHWC" else 1]
    x = _conv_bn(x, ch_in, 3, stride, 1, data_format, is_test,
                 groups=ch_in)  # depthwise
    return _conv_bn(x, ch_out, 1, 1, 0, data_format, is_test)  # pointwise


def mobilenet(images, num_classes=1000, scale=1.0, data_format="NHWC",
              is_test=False):
    """MobileNet v1 for 224x224 inputs. ``scale`` is the width multiplier."""
    def c(ch):
        return max(8, int(ch * scale))

    x = _conv_bn(images, c(32), 3, 2, 1, data_format, is_test)
    for ch, stride in [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1),
                       (512, 2)] + [(512, 1)] * 5 + [(1024, 2), (1024, 1)]:
        x = _separable(x, c(ch), stride, data_format, is_test)
    x = layers.pool2d(x, pool_type="avg", global_pooling=True,
                      data_format=data_format)
    return layers.fc(x, size=num_classes)

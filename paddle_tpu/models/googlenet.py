"""GoogLeNet (Inception v1) — parity with
/root/reference/benchmark/paddle/image/googlenet.py."""
from .. import layers


def _inception(x, c1, c3r, c3, c5r, c5, proj, data_format):
    """Inception module: 1x1, 3x3(reduced), 5x5(reduced), pool-proj branches
    concatenated on the channel axis (reference googlenet.py inception)."""
    ch_axis = 3 if data_format == "NHWC" else 1
    b1 = layers.conv2d(x, num_filters=c1, filter_size=1, act="relu",
                       data_format=data_format)
    b3 = layers.conv2d(x, num_filters=c3r, filter_size=1, act="relu",
                       data_format=data_format)
    b3 = layers.conv2d(b3, num_filters=c3, filter_size=3, padding=1,
                       act="relu", data_format=data_format)
    b5 = layers.conv2d(x, num_filters=c5r, filter_size=1, act="relu",
                       data_format=data_format)
    b5 = layers.conv2d(b5, num_filters=c5, filter_size=5, padding=2,
                       act="relu", data_format=data_format)
    bp = layers.pool2d(x, pool_size=3, pool_stride=1, pool_padding=1,
                       data_format=data_format)
    bp = layers.conv2d(bp, num_filters=proj, filter_size=1, act="relu",
                       data_format=data_format)
    return layers.concat([b1, b3, b5, bp], axis=ch_axis)


def googlenet(images, num_classes=1000, data_format="NHWC", is_test=False):
    """images: [N, 224, 224, 3] → logits (main head only; the reference's
    two auxiliary heads are a training-era artifact and omitted)."""
    x = layers.conv2d(images, num_filters=64, filter_size=7, stride=2,
                      padding=3, act="relu", data_format=data_format)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, data_format=data_format)
    x = layers.conv2d(x, num_filters=64, filter_size=1, act="relu",
                      data_format=data_format)
    x = layers.conv2d(x, num_filters=192, filter_size=3, padding=1,
                      act="relu", data_format=data_format)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, data_format=data_format)
    x = _inception(x, 64, 96, 128, 16, 32, 32, data_format)
    x = _inception(x, 128, 128, 192, 32, 96, 64, data_format)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, data_format=data_format)
    x = _inception(x, 192, 96, 208, 16, 48, 64, data_format)
    x = _inception(x, 160, 112, 224, 24, 64, 64, data_format)
    x = _inception(x, 128, 128, 256, 24, 64, 64, data_format)
    x = _inception(x, 112, 144, 288, 32, 64, 64, data_format)
    x = _inception(x, 256, 160, 320, 32, 128, 128, data_format)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, data_format=data_format)
    x = _inception(x, 256, 160, 320, 32, 128, 128, data_format)
    x = _inception(x, 384, 192, 384, 48, 128, 128, data_format)
    x = layers.pool2d(x, pool_type="avg", global_pooling=True,
                      data_format=data_format)
    x = layers.dropout(x, 0.4, is_test=is_test)
    return layers.fc(x, size=num_classes)

"""LeNet-5 for MNIST — the minimum end-to-end slice.

Mirrors /root/reference/v1_api_demo/mnist/light_mnist.py and the fluid book
test /root/reference/python/paddle/v2/fluid/tests/book/
test_recognize_digits_conv.py (conv-pool ×2 + fc).
"""
from .. import layers


def lenet5(images, data_format="NHWC", num_classes=10):
    """images: [N, 28, 28, 1] (NHWC) or [N, 1, 28, 28] (NCHW) → logits."""
    conv1 = layers.conv2d(images, num_filters=20, filter_size=5, act="relu",
                          data_format=data_format)
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2,
                          data_format=data_format)
    conv2 = layers.conv2d(pool1, num_filters=50, filter_size=5, act="relu",
                          data_format=data_format)
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2,
                          data_format=data_format)
    fc1 = layers.fc(pool2, size=500, act="relu")
    logits = layers.fc(fc1, size=num_classes)
    return logits

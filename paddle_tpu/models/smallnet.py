"""SmallNet for MNIST/CIFAR quick benchmarks — parity with
/root/reference/benchmark/paddle/image/smallnet_mnist_cifar.py."""
from .. import layers


def smallnet_mnist_cifar(images, num_classes=10, data_format="NHWC"):
    """conv5x32 → pool → conv5x64 → pool → fc (reference smallnet config)."""
    x = layers.conv2d(images, num_filters=32, filter_size=5, padding=2,
                      act="relu", data_format=data_format)
    x = layers.pool2d(x, pool_size=2, pool_stride=2, data_format=data_format)
    x = layers.conv2d(x, num_filters=64, filter_size=5, padding=2,
                      act="relu", data_format=data_format)
    x = layers.pool2d(x, pool_size=2, pool_stride=2, data_format=data_format)
    return layers.fc(x, size=num_classes)

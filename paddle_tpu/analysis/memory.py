"""Liveness & peak-HBM analysis over program blocks.

The static answer to "will this program fit, and if not, which tensors
are holding the watermark" — computed at BUILD time from the checker's
inferred ``ShapeDtypeStruct``s (PR 6), before XLA ever sees the program
or a chip OOMs. The model follows the executor's actual residency rules:

- **resident** values — persistable vars (parameters, optimizer slots),
  scope state (KV caches), and the feeds — occupy HBM for the whole
  step;
- **transient** values live from their producing op to their last
  consumer (fetches live to the end of the block);
- **donation/aliasing**: the liveness map is keyed by NAME, so an op
  writing onto its own input (momentum's in-place param update,
  batch_norm's MeanOut onto Mean) replaces the buffer instead of
  double-counting it — exactly what ``donate_argnums`` buys at run time;
- **recompute segments** (``seg_fwd``/``grad_seg``): interior
  activations are freed as soon as the forward consumes them; only the
  checkpoint-policy residuals (matmul/conv outputs + ndim<=1 stats, the
  ``backward.SEGMENT_SAVE_OPS`` contract) stay live until the paired
  ``grad_seg``;
- **stacked scans** (``pipelined_transformer_stack``): the scan body's
  saved activation planes are ``[L, ...]``-shaped and invisible to
  name-level liveness — the op's cost handler sizes them per its
  ``remat`` policy (``residual_bytes``) and they are held live from the
  forward op to its paired grad op.

``check_memory_budget`` turns the analysis into a gate:
``SGD.train(mem_budget=...)`` and the serving engines raise a located
:class:`MemoryBudgetError` naming the peak set and the remat advisor's
suggestions instead of letting XLA OOM at compile.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np

from ..core.enforce import EnforceError
from ..core.program import (BATCH_DIM_SENTINEL, GRAD_SUFFIX, Block,
                            Operator, Program)
from ..core.registry import get_op, has_op, infer_outputs
from ..core.scope import Scope
from . import costmodel
from .checker import infer_program
from .costmodel import OpCost, V5E_HBM_BW, V5E_PEAK_FLOPS, op_cost


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.2f} TB"


@dataclasses.dataclass
class LiveTensor:
    """One entry of the live set at the peak: what it is, how big, and
    which op (and user line) produced it."""

    name: str
    bytes: float
    shape: tuple
    dtype: str
    kind: str  # "resident" | "activation" | "residual"
    producer_index: Optional[int] = None
    producer_type: Optional[str] = None
    callsite: Optional[str] = None

    def format(self) -> str:
        where = ""
        if self.producer_type is not None:
            where = f"  <- op #{self.producer_index} {self.producer_type!r}"
            if self.callsite:
                where += f" (created at {self.callsite})"
        return (f"{_fmt_bytes(self.bytes):>12}  {self.name}  "
                f"{tuple(self.shape)} {self.dtype} [{self.kind}]{where}")


@dataclasses.dataclass
class RematAdvice:
    """One candidate ``recompute_guard`` span, ranked by the peak bytes
    it would free against the extra HBM traffic + FLOPs the barriered
    backward recompute would re-stream (the PERF.md round-3 lesson:
    remat is a memory lever, NOT a bandwidth lever — the advisor prices
    both sides instead of leaving it folklore)."""

    start: int
    end: int
    op_types: List[str]
    bytes_saved: float
    extra_traffic_bytes: float
    extra_flops: float
    callsite: Optional[str] = None

    @property
    def net_memory_per_traffic(self) -> float:
        return self.bytes_saved / max(self.extra_traffic_bytes, 1.0)

    def format(self) -> str:
        kinds = ", ".join(self.op_types[:5])
        if len(self.op_types) > 5:
            kinds += ", ..."
        site = f" (around {self.callsite})" if self.callsite else ""
        return (f"recompute_guard ops #{self.start}..#{self.end} "
                f"[{kinds}]{site}: frees ~{_fmt_bytes(self.bytes_saved)} "
                f"of peak at +{_fmt_bytes(self.extra_traffic_bytes)} HBM "
                f"traffic / +{self.extra_flops / 1e9:.1f} GFLOP recompute")


class MemoryBudgetError(EnforceError):
    """The static peak-HBM estimate exceeds the configured budget.
    Raised at build time — before XLA compiles, allocates, or OOMs —
    with the peak live set and remat advice attached."""

    def __init__(self, message: str, *, peak_bytes: float,
                 budget_bytes: float, top: Sequence[LiveTensor] = (),
                 advice: Sequence[RematAdvice] = ()):
        super().__init__(message)
        self.peak_bytes = peak_bytes
        self.budget_bytes = budget_bytes
        self.top = list(top)
        self.advice = list(advice)


class MemoryAnalysis:
    """Result of :func:`analyze_memory`.

    With a sharding plan (``analyze_memory(plan=...)`` or a
    ShardProgram-annotated program) every byte figure is PER DEVICE:
    sharded dims divide each tensor by its mesh-axis product, and
    ``collectives`` prices the in-graph psum/all-gather traffic the plan
    implies (``mesh_axes`` records the mesh; both are None single-chip).
    """

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self.mesh_axes = None
        self.collectives = None  # analysis.sharding.ShardingCost
        self.resident_bytes: float = 0.0
        self.peak_bytes: float = 0.0
        self.peak_op_index: Optional[int] = None
        self.peak_op_type: Optional[str] = None
        self.peak_live: List[LiveTensor] = []
        # live bytes DURING each op (outputs allocated, dead inputs not
        # yet freed) — the watermark curve
        self.live_at_op: List[float] = []
        self.op_costs: List[Optional[OpCost]] = []
        self.op_types: List[str] = []
        self.total_cost: OpCost = OpCost()
        self.uncosted_ops: List[str] = []

    # -- summary -----------------------------------------------------------
    def top(self, n: int = 10) -> List[LiveTensor]:
        return sorted(self.peak_live, key=lambda t: -t.bytes)[:n]

    @property
    def total_flops(self) -> float:
        return self.total_cost.flops

    @property
    def total_hbm_bytes(self) -> float:
        return self.total_cost.bytes

    @property
    def intensity(self) -> float:
        return self.total_cost.intensity

    def estimated_step_seconds(self, peak_flops: float = V5E_PEAK_FLOPS,
                               hbm_bw: float = V5E_HBM_BW) -> float:
        """Sum of per-op roofline times — each op bound by compute or
        bandwidth, whichever binds it (PERF.md's per-op-group method)."""
        return sum(c.step_seconds(peak_flops, hbm_bw)
                   for c in self.op_costs if c is not None)

    def roofline_rows(self) -> List[dict]:
        """Per-op-type aggregate: FLOPs, bytes, intensity, bound, est ms
        — the shape of PERF.md's round-3 table, derived statically."""
        agg: Dict[str, OpCost] = {}
        counts: Dict[str, int] = {}
        for t, c in zip(self.op_types, self.op_costs):
            if c is None:
                continue
            agg[t] = agg.get(t, OpCost()) + c
            counts[t] = counts.get(t, 0) + 1
        rows = []
        for t, c in agg.items():
            rows.append({
                "op": t, "count": counts[t], "flops": c.flops,
                "bytes": c.bytes, "intensity": round(c.intensity, 2),
                "bound": ("compute" if c.intensity >= (
                    V5E_PEAK_FLOPS / V5E_HBM_BW) else "HBM"),
                "est_ms": round(c.step_seconds() * 1e3, 3)})
        rows.sort(key=lambda r: -r["est_ms"])
        return rows

    @property
    def collective_bytes(self) -> float:
        return self.collectives.total_bytes if self.collectives else 0.0

    def format_report(self, top_n: int = 10) -> str:
        scope_note = ""
        if self.mesh_axes:
            axes = "x".join(f"{a}={s}" for a, s in self.mesh_axes.items())
            scope_note = f" PER DEVICE over mesh [{axes}]"
        lines = [
            f"peak HBM watermark: {_fmt_bytes(self.peak_bytes)}{scope_note}"
            f" at op #{self.peak_op_index} {self.peak_op_type!r} "
            f"(batch={self.batch_size})",
            f"  resident (params/state/feeds): "
            f"{_fmt_bytes(self.resident_bytes)}",
            f"  transient at peak: "
            f"{_fmt_bytes(self.peak_bytes - self.resident_bytes)}",
            f"top {top_n} live tensors at the peak:",
        ]
        lines += ["  " + t.format() for t in self.top(top_n)]
        lines += [
            f"roofline: {self.total_flops / 1e9:.1f} GFLOP, "
            f"{_fmt_bytes(self.total_hbm_bytes)} HBM, intensity "
            f"{self.intensity:.1f} F/B vs ridge "
            f"{V5E_PEAK_FLOPS / V5E_HBM_BW:.0f} -> est "
            f"{self.estimated_step_seconds() * 1e3:.2f} ms/step (v5e)",
        ]
        if self.uncosted_ops:
            lines.append(
                f"  (no cost model for: "
                f"{sorted(set(self.uncosted_ops))[:8]})")
        if self.collectives is not None:
            lines.append(self.collectives.format_report())
        return "\n".join(lines)


# --------------------------------------------------------------------------
def _concrete(sds, batch_size: int):
    """Replace the batch sentinel with the given batch in a ShapeDtype
    tree (shapes from infer_program carry BATCH_DIM_SENTINEL). The
    sentinel is PRIME, so dims the program derived by flattening or
    concatenating the batch axis (``reshape([-1, V])`` -> tokens =
    sentinel * T) are recovered by divisibility: any multiple of the
    sentinel rescales by batch/sentinel."""
    def dim(d):
        d = int(d)
        if d == BATCH_DIM_SENTINEL:
            return batch_size
        if d and d % BATCH_DIM_SENTINEL == 0:
            return (d // BATCH_DIM_SENTINEL) * batch_size
        return d

    def leaf(s):
        if not hasattr(s, "shape"):
            return s
        return jax.ShapeDtypeStruct(tuple(dim(d) for d in s.shape),
                                    s.dtype)

    return jax.tree_util.tree_map(leaf, sds)


def _lookup_var(block: Block, name: str):
    b = block
    while b is not None:
        if name in b.vars:
            return b.vars[name]
        b = b.parent
    return None


def _segment_residual_bytes(op: Operator, env: Dict[str, object]) -> float:
    """Bytes the checkpoint policy keeps live across a recompute segment
    (seg_fwd -> grad_seg): outputs of SEGMENT_SAVE_OPS plus ndim<=1
    stats — backward.segment_forward's save-only-named-residuals set."""
    from ..core.backward import SEGMENT_SAVE_OPS

    local: Dict[str, object] = {}
    for name in op.attrs["ext_in"]:
        if name in env:
            local[name] = env[name]
    saved = 0.0
    for sop in op.attrs["seg_ops"]:
        try:
            ins = {slot: [local[n] for n in names]
                   for slot, names in sop["ins"].items() if names}
            outs = infer_outputs(sop["type"], sop["attrs"], ins)
        except Exception:
            continue
        save_all = sop["type"] in SEGMENT_SAVE_OPS
        for slot, names in sop["outs"].items():
            for n, sds in zip(names, (outs or {}).get(slot, [])):
                local[n] = sds
                nd = len(getattr(sds, "shape", ()))
                if save_all or nd <= 1:
                    saved += costmodel._nbytes(sds)
    return saved


def _paired_grad_index(block: Block, i: int, op: Operator) -> Optional[int]:
    """Index of the grad op that consumes op's forward residuals: for
    seg_fwd the grad_seg sharing its vjp_key; for plain ops the first
    later grad/grad_custom with fwd_type == op.type reading one of op's
    outputs (or their @PRE snapshots)."""
    if op.type == "seg_fwd":
        key = op.attrs.get("vjp_key")
        for j in range(i + 1, len(block.ops)):
            o = block.ops[j]
            if o.type == "grad_seg" and o.attrs.get("vjp_key") == key:
                return j
        return None
    out_names = set(op.output_names())
    for j in range(i + 1, len(block.ops)):
        o = block.ops[j]
        if o.type not in ("grad", "grad_custom"):
            continue
        if o.attrs.get("fwd_type") != op.type:
            continue
        reads = set(o.input_names())
        if out_names & reads:
            return j
    return None


# --------------------------------------------------------------------------
# elementwise-class ops that keep their input's last-dim sharding (the
# mini GSPMD propagation below); contractions and everything else stop
# the chain — conservative, in the cost model's ~20% honesty class
_TP_INHERIT_OPS = frozenset((
    "gelu", "relu", "sigmoid", "tanh", "elementwise_add",
    "elementwise_mul", "elementwise_sub", "dropout", "scale",
    "layer_norm", "softmax", "addto", "cast"))


def _tp_activation_divisors(block, plan, axis_sizes, data_axis):
    """Megatron's column-parallel activations, statically: an op
    contracting a weight sharded on its LAST (output) dim produces an
    activation sharded the same way, and elementwise consumers inherit
    — until the next contraction combines the partials. Returns
    name -> tp divisor for those activations (1 implied elsewhere)."""
    from ..parallel.plan import spec_axes
    from .sharding import _contract_like

    div: Dict[str, int] = {}
    for op in block.ops:
        d = 1
        if _contract_like(op):
            for name in op.input_names():
                v = _lookup_var(block, name)
                if v is None or not v.persistable:
                    continue
                spec = getattr(v, "sharding", None)
                if spec is None and v.shape is not None:
                    spec = plan.spec_for_state(name, len(v.shape),
                                               shape=v.shape)
                if spec is None or not tuple(spec):
                    continue
                last = tuple(spec)[-1]
                axes = last if isinstance(last, tuple) else (last,)
                for ax in axes:
                    if ax is not None and ax != data_axis:
                        d *= axis_sizes.get(ax, 1)
        elif op.type in _TP_INHERIT_OPS:
            d = max((div.get(n, 1) for n in op.input_names()), default=1)
        if d > 1:
            for out in op.output_names():
                div[out] = d
    return div


def _make_shard_divisor(plan, block, types, feeds, batch_size):
    """name -> how many ways that tensor's bytes split per device under
    the plan: state/feeds by their resolved PartitionSpec (ShardProgram
    annotations win), transient activations by the ``dp`` axis when
    their leading dim is batch-derived (the sharding GSPMD propagates);
    1 without a plan."""
    if plan is None:
        return lambda name: 1
    from ..parallel.plan import spec_axes

    axis_sizes = plan.mesh_axes()
    n_dp = axis_sizes.get(plan.data_axis, 1) if plan.data_axis else 1
    tp_div = _tp_activation_divisors(block, plan, axis_sizes,
                                     plan.data_axis)
    cache: Dict[str, int] = {}

    def leaf_shape(name):
        sds = types.get(name)
        leaves = costmodel._leaves(sds) if sds is not None else []
        return tuple(leaves[0].shape) if leaves else ()

    def div(name: str) -> int:
        if name in cache:
            return cache[name]
        base = name
        if GRAD_SUFFIX in name:
            # a weight's gradient shards exactly like the weight (GSPMD
            # propagates the spec through the cotangent)
            cand = name.split(GRAD_SUFFIX, 1)[0]
            cv = _lookup_var(block, cand)
            if cv is not None and cv.persistable:
                base = cand
        v = _lookup_var(block, base)
        ann = getattr(v, "sharding", None) if v is not None else None
        shape = leaf_shape(base if base != name else name)
        spec = None
        if ann is not None:
            spec = ann
        elif base in feeds:
            spec = plan.spec_for_feed(base, len(shape))
        elif v is not None and (v.persistable or v.is_data):
            spec = plan.spec_for_state(base, len(shape), shape=shape)
        if spec is None:
            d = n_dp if (n_dp > 1 and shape
                         and (shape[0] == batch_size
                              or (batch_size > 1
                                  and shape[0] % batch_size == 0))) else 1
            # column-parallel tp sharding composes with the dp split; an
            # activation's GRADIENT mirrors the forward activation
            act = name.split(GRAD_SUFFIX, 1)[0] \
                if GRAD_SUFFIX in name else name
            d *= tp_div.get(act, 1)
        else:
            d = 1
            for ax in spec_axes(spec):
                d *= axis_sizes.get(ax, 1)
        cache[name] = max(int(d), 1)
        return cache[name]

    return div


def analyze_memory(program: Program, feed_names: Sequence[str] = (),
                   fetch_names: Sequence[str] = (),
                   scope: Optional[Scope] = None,
                   batch_size: int = 1,
                   include_costs: bool = True,
                   plan=None) -> MemoryAnalysis:
    """Compute per-op live-byte sets, the peak-HBM watermark, and (with
    ``include_costs``) the per-op roofline costs for the global block.

    ``batch_size`` concretises every ``-1`` batch dim. Shapes come from
    :func:`~paddle_tpu.analysis.checker.infer_program`, so anything that
    fails whole-program inference raises the same located
    ``ProgramCheckError`` this plane is built on.

    ``plan`` (a :class:`paddle_tpu.parallel.ShardingPlan`; defaults to a
    ShardProgram-annotated program's own plan) switches the analysis to
    PER-DEVICE accounting: state/feed tensors divide by the mesh-axis
    product of their plan-resolved spec, batch-led activations divide by
    the ``dp`` axis (the sharding GSPMD propagates), and
    ``mem.collectives`` prices the plan's psum/all-to-all wire bytes.
    """
    costmodel.ensure_registered()
    if plan is None:
        plan = getattr(program, "sharding_plan", None)
    analysis = infer_program(program, feed_names, fetch_names, scope=scope,
                             annotate=False)
    block = program.global_block
    ops = list(block.ops)
    mem = MemoryAnalysis(batch_size)

    types: Dict[str, object] = {
        name: _concrete(sds, batch_size)
        for name, sds in analysis.types.items()}

    shard_div = _make_shard_divisor(plan, block, types, set(feed_names),
                                    batch_size)
    if plan is not None:
        mem.mesh_axes = plan.mesh_axes()

    def bytes_of(name: str) -> float:
        sds = types.get(name)
        if sds is None:
            return 0.0
        return costmodel._nbytes(sds) / shard_div(name)

    # ---- residency classification ------------------------------------
    feeds = set(feed_names)
    fetches = set(fetch_names)
    resident: Set[str] = set()
    scope_names: Set[str] = set()
    if scope is not None:
        s = scope
        while s is not None:
            scope_names.update(s.keys())
            s = s.parent
    for name in types:
        v = _lookup_var(block, name)
        if (name in feeds or name in scope_names
                or (v is not None and (v.persistable or v.is_data))):
            resident.add(name)
    mem.resident_bytes = sum(bytes_of(n) for n in resident)

    # ---- last-use map -------------------------------------------------
    last_use: Dict[str, int] = {}
    producer: Dict[str, Tuple[int, Operator]] = {}
    for i, op in enumerate(ops):
        for name in op.input_names():
            last_use[name] = i
        for name in op.output_names():
            producer[name] = (i, op)
    horizon = len(ops)
    for name in fetches:
        last_use[name] = horizon  # fetches survive the block

    # ---- residual intervals (fwd->bwd footprints liveness can't see) --
    # [(start, end, bytes, label)]
    residuals: List[Tuple[int, int, float, str]] = []

    # ---- the walk -----------------------------------------------------
    live: Dict[str, float] = {}
    peak = mem.resident_bytes
    peak_i: Optional[int] = None
    peak_live_names: List[str] = []
    peak_residuals: List[Tuple[float, str, int]] = []
    active_residuals: List[Tuple[int, float, str, int]] = []  # (end, b, lbl, i)

    dp_div = 1
    if plan is not None and plan.data_axis:
        dp_div = plan.mesh_axes().get(plan.data_axis, 1)

    for i, op in enumerate(ops):
        cost = None
        if include_costs and has_op(op.type):
            opdef = get_op(op.type)
            if opdef.cost_fn is not None:
                ins = {slot: [types[n] for n in names if n in types]
                       for slot, names in op.inputs.items() if names}
                outs = {slot: [types[n] for n in names if n in types]
                        for slot, names in op.outputs.items() if names}
                cost = op_cost(op.type, op.attrs, ins, outs)
                if cost is not None and plan is not None:
                    # per-device roofline: this op computes 1/d of the
                    # global work (its output's shard count)
                    out_names = op.output_names()
                    d = shard_div(out_names[0]) if out_names else 1
                    if d > 1:
                        cost = OpCost(cost.flops / d, cost.bytes / d,
                                      cost.residual_bytes / d)
            elif not opdef.cost_exempt:
                mem.uncosted_ops.append(op.type)
        mem.op_costs.append(cost)
        mem.op_types.append(op.type)
        if cost is not None:
            mem.total_cost = mem.total_cost + cost

        # residual footprint: seg_fwd's checkpoint saves, or the cost
        # handler's declared residual (stacked-scan activation planes;
        # batch-carried, so per-device they divide by dp)
        res_bytes = 0.0
        if op.type == "seg_fwd":
            res_bytes = _segment_residual_bytes(op, types) / dp_div
        elif cost is not None and cost.residual_bytes:
            res_bytes = cost.residual_bytes
            if plan is not None and shard_div(op.output_names()[0]
                                              if op.output_names()
                                              else "") <= 1:
                res_bytes /= dp_div
        if res_bytes:
            j = _paired_grad_index(block, i, op)
            if j is not None:
                residuals.append((i, j, res_bytes, op.type))
                active_residuals.append((j, res_bytes, op.type, i))

        # allocate outputs (name-keyed: an in-place rewrite of a live
        # name — donated state, aliased BN stats — replaces, not adds)
        for name in op.output_names():
            if name in resident:
                continue
            live[name] = bytes_of(name)
        running_transient = sum(live.values())
        res_active = sum(b for (end, b, _, _) in active_residuals
                         if end >= i)
        now = mem.resident_bytes + running_transient + res_active
        mem.live_at_op.append(now)
        if now > peak:
            peak = now
            peak_i = i
            peak_live_names = list(live)
            peak_residuals = [(b, lbl, src) for (end, b, lbl, src)
                              in active_residuals if end >= i]

        # free transients whose last consumer this was
        for name in list(live):
            if last_use.get(name, -1) <= i and name not in fetches:
                del live[name]
        active_residuals = [(end, b, lbl, src)
                            for (end, b, lbl, src) in active_residuals
                            if end > i]

    mem.peak_bytes = peak
    mem.peak_op_index = peak_i
    mem.peak_op_type = ops[peak_i].type if peak_i is not None else None

    # ---- the peak's named live set ------------------------------------
    peak_set: List[LiveTensor] = []
    for name in resident:
        sds = types.get(name)
        if sds is None:
            continue
        leaves = costmodel._leaves(sds)
        shape = tuple(leaves[0].shape) if leaves else ()
        dt = str(leaves[0].dtype) if leaves else "?"
        pi, pop = producer.get(name, (None, None))
        peak_set.append(LiveTensor(
            name=name, bytes=bytes_of(name), shape=shape, dtype=dt,
            kind="resident", producer_index=pi,
            producer_type=pop.type if pop is not None else None,
            callsite=(pop.attrs.get("_callsite")
                      if pop is not None else None)))
    for name in peak_live_names:
        sds = types.get(name)
        if sds is None:
            continue
        leaves = costmodel._leaves(sds)
        shape = tuple(leaves[0].shape) if leaves else ()
        dt = str(leaves[0].dtype) if leaves else "?"
        pi, pop = producer.get(name, (None, None))
        peak_set.append(LiveTensor(
            name=name, bytes=bytes_of(name), shape=shape, dtype=dt,
            kind="activation", producer_index=pi,
            producer_type=pop.type if pop is not None else None,
            callsite=(pop.attrs.get("_callsite")
                      if pop is not None else None)))
    for b, lbl, src in peak_residuals:
        sop = ops[src]
        peak_set.append(LiveTensor(
            name=f"<{lbl} residuals #{src}>", bytes=b, shape=(),
            dtype="-", kind="residual", producer_index=src,
            producer_type=lbl, callsite=sop.attrs.get("_callsite")))
    mem.peak_live = peak_set

    # ---- the plan's collective wire bytes (psum/all-reduce/all-to-all) -
    if plan is not None and include_costs:
        from .sharding import estimate_collectives

        try:
            mem.collectives = estimate_collectives(
                program, feed_names, fetch_names, plan=plan, scope=scope,
                batch_size=batch_size, types=types)
        except Exception:  # noqa: BLE001 - pricing must never break lint
            mem.collectives = None
    return mem


# --------------------------------------------------------------------------
# Remat advisor
# --------------------------------------------------------------------------
def advise_recompute(program: Program, mem: MemoryAnalysis,
                     min_ops: int = 3,
                     top_n: int = 5) -> List[RematAdvice]:
    """Rank candidate ``recompute_guard`` spans in the forward region by
    peak bytes they would free vs the traffic/FLOPs their barriered
    backward recompute would add. Only meaningful for training programs
    (a program with no grad ops holds no fwd->bwd activations — advice
    is empty there, remat cannot help inference)."""
    from ..core.backward import NON_DIFFERENTIABLE, SEGMENT_SAVE_OPS

    block = program.global_block
    ops = list(block.ops)
    first_grad = next(
        (i for i, op in enumerate(ops)
         if op.type in ("grad", "grad_custom", "grad_seg")), None)
    if first_grad is None:
        return []

    # names the backward actually reads (these are the pinned activations)
    bwd_reads: Set[str] = set()
    for op in ops[first_grad:]:
        bwd_reads.update(op.input_names())

    def eligible(op: Operator) -> bool:
        if not has_op(op.type) or op.type in NON_DIFFERENTIABLE:
            return False
        opdef = get_op(op.type)
        return not (opdef.special or opdef.needs_rng is True
                    or op.attrs.get("__recompute_seg__") is not None)

    types_cache: Dict[str, object] = {}

    def _bytes(name: str) -> float:
        # sizes via the recorded peak set / live curve are name-free;
        # re-derive from the op costs' slot shapes is overkill — use the
        # analysis peak tensors where available, else 0
        if not types_cache:
            for t in mem.peak_live:
                types_cache[t.name] = t.bytes
        return types_cache.get(name, 0.0)

    advice: List[RematAdvice] = []
    i = 0
    while i < first_grad:
        if not eligible(ops[i]):
            i += 1
            continue
        j = i
        while j + 1 < first_grad and eligible(ops[j + 1]):
            j += 1
        if j - i + 1 >= min_ops:
            saved = 0.0
            traffic = 0.0
            flops = 0.0
            op_types: List[str] = []
            site = None
            for k in range(i, j + 1):
                op = ops[k]
                op_types.append(op.type)
                if site is None:
                    site = op.attrs.get("_callsite")
                c = mem.op_costs[k] if k < len(mem.op_costs) else None
                if c is not None:
                    traffic += c.bytes
                    flops += c.flops
                save_all = op.type in SEGMENT_SAVE_OPS
                for name in op.output_names():
                    if save_all:
                        continue  # checkpoint policy keeps these anyway
                    if name in bwd_reads:
                        saved += _bytes(name)
            if saved > 0:
                advice.append(RematAdvice(
                    start=i, end=j, op_types=op_types, bytes_saved=saved,
                    extra_traffic_bytes=traffic, extra_flops=flops,
                    callsite=site))
        i = j + 1
    advice.sort(key=lambda a: -a.bytes_saved)
    return advice[:top_n]


# --------------------------------------------------------------------------
# Budget gating
# --------------------------------------------------------------------------
def check_memory_budget(program: Program, feed_names: Sequence[str],
                        fetch_names: Sequence[str], budget_bytes: float,
                        scope: Optional[Scope] = None,
                        batch_size: int = 1,
                        what: str = "program",
                        plan=None) -> MemoryAnalysis:
    """Raise :class:`MemoryBudgetError` when the static peak-HBM
    watermark exceeds ``budget_bytes``; returns the analysis otherwise.
    With a plan (argument or ShardProgram-annotated program) the budget
    gates the PER-DEVICE watermark — sharding state IS the remedy the
    advisor can't suggest, so it is priced in before the gate fires."""
    mem = analyze_memory(program, feed_names, fetch_names, scope=scope,
                         batch_size=batch_size, plan=plan)
    if mem.peak_bytes <= budget_bytes:
        return mem
    top = mem.top(8)
    advice = advise_recompute(program, mem)
    lines = [
        f"{what}: static peak-HBM estimate "
        f"{_fmt_bytes(mem.peak_bytes)} exceeds mem_budget "
        f"{_fmt_bytes(budget_bytes)} (batch={batch_size}; "
        f"resident {_fmt_bytes(mem.resident_bytes)} + transient "
        f"{_fmt_bytes(mem.peak_bytes - mem.resident_bytes)} at op "
        f"#{mem.peak_op_index} {mem.peak_op_type!r})",
        "top live tensors at the peak:",
    ]
    lines += ["  " + t.format() for t in top]
    if advice:
        lines.append("remat advisor (bytes-saved vs bytes-re-streamed):")
        lines += ["  " + a.format() for a in advice]
        lines.append(
            "  (remat trades HBM traffic for peak memory — PERF.md "
            "round 3: a memory lever, not a bandwidth lever)")
    else:
        lines.append("no recompute_guard candidates found (inference "
                     "program or segments already in place) — reduce "
                     "batch, shard state, or raise the budget")
    raise MemoryBudgetError("\n".join(lines), peak_bytes=mem.peak_bytes,
                            budget_bytes=budget_bytes, top=top,
                            advice=advice)

"""Per-op analytical cost model: FLOPs, HBM bytes, arithmetic intensity.

The static half of PERF.md's roofline methodology: every registered
kernel gets a cost handler — registered on the OpDef exactly like
``infer_outputs`` derives shapes from the kernel — that maps the op's
abstract input/output ``ShapeDtypeStruct``s to an :class:`OpCost`
(FLOPs + HBM bytes touched). ``registry conformance`` (audit_op) makes
the coverage a contract: a newly registered op without a handler or an
explicit ``cost_exempt`` marker fails ``tests/test_registry_conformance``
at registration quality.

Two deliberate modeling choices, both calibrated against PERF.md's
measured ResNet-50 bs256 step (78.4 GB by ``cost_analysis``):

- **fusion discount**: XLA fuses elementwise chains into their
  producers, so a unary elementwise op charges only its output write
  (the read rides the producer's epilogue), binaries charge one operand
  stream + the write, and assign/reshape-class aliases are free (XLA
  elides the copies — the @PRE snapshots and @GRAD canonical aliases).
  Counting full in+out bytes per op over-estimates a conv/BN/ReLU stack
  by ~2x.
- **backward stream accounting**: a generic ``grad`` op emits one XLA
  kernel per LARGE gradient (dX and dW), each re-streaming the incoming
  cotangent (the round-3 profile: backward dots carry ~4x the forward's
  bytes), plus its gradient writes and one saved-primal re-read.

Handlers are approximations with ~20% honesty, not instruction counts;
the ``bench_memplan`` bench records estimated-vs-``cost_analysis`` drift
per release so the model cannot rot silently.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core import registry
from ..core.registry import get_op, has_op

# v5e-class chip constants (PERF.md "Roofline position"): bf16 peak and
# HBM stream bandwidth; the ridge point is their ratio (~240 FLOP/byte).
V5E_PEAK_FLOPS = 197e12
V5E_HBM_BW = 819e9


@dataclasses.dataclass
class OpCost:
    """One op's analytic cost: FLOPs executed and HBM bytes touched
    (reads + writes, post fusion discount). ``residual_bytes`` is the
    forward->backward residual footprint kernels keep *internally*
    (scan-over-layers activation stacks) — invisible to name-level
    liveness, added by the memory analyzer from fwd op to paired grad."""

    flops: float = 0.0
    bytes: float = 0.0
    residual_bytes: float = 0.0

    @property
    def intensity(self) -> float:
        """Arithmetic intensity in FLOP/byte (inf for zero-byte ops)."""
        if self.bytes <= 0:
            return float("inf") if self.flops > 0 else 0.0
        return self.flops / self.bytes

    def step_seconds(self, peak_flops: float = V5E_PEAK_FLOPS,
                     hbm_bw: float = V5E_HBM_BW) -> float:
        """Roofline time: bound by compute or bandwidth, whichever binds."""
        return max(self.flops / peak_flops, self.bytes / hbm_bw)

    def __add__(self, other: "OpCost") -> "OpCost":
        return OpCost(self.flops + other.flops, self.bytes + other.bytes,
                      self.residual_bytes + other.residual_bytes)


# --------------------------------------------------------------------------
# Registration plane (mirrors infer_outputs: handlers live on the OpDef)
# --------------------------------------------------------------------------
def register_cost(op_type: str, fn: Callable = None):
    """Attach a cost handler ``fn(attrs, ins, outs) -> OpCost`` to a
    registered op (``ins``/``outs`` map slot -> [ShapeDtypeStruct] with
    batch dims already concrete). Decorator or direct call."""

    def _do(f):
        opdef = get_op(op_type)
        if opdef.cost_fn is not None:
            raise ValueError(f"op {op_type!r} already has a cost handler")
        opdef.cost_fn = f
        opdef.cost_exempt = False
        return f

    if fn is None:
        return _do
    return _do(fn)


def cost_exempt(*op_types: str) -> None:
    """Mark ops as deliberately outside the cost model (structural ops
    the executor interprets, unbounded decode loops). The conformance
    audit accepts the marker in place of a handler."""
    for t in op_types:
        get_op(t).cost_exempt = True


def has_cost(op_type: str) -> bool:
    ensure_registered()
    return has_op(op_type) and get_op(op_type).cost_fn is not None


def is_cost_exempt(op_type: str) -> bool:
    ensure_registered()
    return has_op(op_type) and get_op(op_type).cost_exempt


def op_cost(op_type: str, attrs, ins, outs) -> Optional[OpCost]:
    """Evaluate the registered handler; None for exempt/uncovered ops.
    A handler crash degrades to None — the cost plane must never turn a
    lintable program into an exception."""
    if not has_cost(op_type):
        return None
    try:
        return get_op(op_type).cost_fn(attrs or {}, ins, outs)
    except Exception:
        return None


# --------------------------------------------------------------------------
# Shape helpers
# --------------------------------------------------------------------------
def _nbytes(sds) -> float:
    leaves = _leaves(sds)
    return sum(float(np.prod(s.shape)) * np.dtype(s.dtype).itemsize
               for s in leaves)


def _leaves(sds) -> List:
    """ShapeDtypeStruct leaves of a possibly-pytree value (SelectedRows)."""
    import jax

    return [l for l in jax.tree_util.tree_leaves(sds)
            if hasattr(l, "shape") and hasattr(l, "dtype")]


def _elems(sds) -> float:
    return sum(float(np.prod(s.shape)) for s in _leaves(sds))


def _slot_bytes(d: Dict[str, list]) -> float:
    return sum(_nbytes(s) for arrs in (d or {}).values() for s in arrs)


def _slot_elems(d: Dict[str, list]) -> float:
    return sum(_elems(s) for arrs in (d or {}).values() for s in arrs)


def _first(d: Dict[str, list], slot: str):
    arrs = (d or {}).get(slot) or []
    return arrs[0] if arrs else None


def _io_cost(flops: float, ins, outs) -> OpCost:
    return OpCost(flops=flops, bytes=_slot_bytes(ins) + _slot_bytes(outs))


# --------------------------------------------------------------------------
# Generic handler families
# --------------------------------------------------------------------------
def _elementwise(k: float = 1.0, fused_reads: bool = True):
    """k FLOPs per output element. With ``fused_reads`` (the default),
    charge the output write plus ONE operand stream — the XLA-fusion
    model: the other operands ride the producing kernels' epilogues."""

    def h(attrs, ins, outs):
        ob = _slot_bytes(outs)
        if fused_reads:
            # unary chains fuse into their producer: the read rides the
            # producer's epilogue and only the (replacing) write counts
            return OpCost(flops=k * _slot_elems(outs), bytes=ob)
        biggest = max((_nbytes(s) for arrs in (ins or {}).values()
                       for s in arrs), default=0.0)
        return OpCost(flops=k * _slot_elems(outs), bytes=biggest + ob)

    return h


def _alias(attrs, ins, outs):
    """assign/reshape-class ops are pure aliases: XLA elides the copy
    (the @PRE snapshots and @GRAD canonical aliases cost nothing)."""
    return OpCost(flops=0.0, bytes=0.0)


def _movement(attrs, ins, outs):
    """Pure data movement (reshape/transpose/concat/...): zero FLOPs,
    one read + one write stream."""
    return OpCost(flops=0.0, bytes=_slot_bytes(ins) + _slot_bytes(outs))


def _fill(attrs, ins, outs):
    """Generators (fill/random): write-only."""
    return OpCost(flops=_slot_elems(outs), bytes=_slot_bytes(outs))


def _reduction(k: float = 1.0):
    """k FLOPs per INPUT element (reductions stream the operand once)."""

    def h(attrs, ins, outs):
        return OpCost(flops=k * _slot_elems(ins),
                      bytes=_slot_bytes(ins) + _slot_bytes(outs))

    return h


def _memory_bound(attrs, ins, outs):
    """The honest default for the long tail (metrics, decode utilities):
    a few FLOPs per element, full operand streams."""
    return _io_cost(_slot_elems(ins) + _slot_elems(outs), ins, outs)


def _optimizer(attrs, ins, outs):
    """Parameter updates: ~4 FLOPs/element, every state read + written
    (no fusion discount — accumulators genuinely stream)."""
    return _io_cost(4.0 * _slot_elems(outs), ins, outs)


# --------------------------------------------------------------------------
# Compute-op handlers
# --------------------------------------------------------------------------
def _mul_cost(attrs, ins, outs):
    x = _first(ins, "X")
    y = _first(ins, "Y")
    o = _first(outs, "Out")
    if x is None or y is None or o is None:
        return _memory_bound(attrs, ins, outs)
    yd = attrs.get("y_num_col_dims", 1)
    k = float(np.prod(y.shape[:yd]))  # contracted dim
    return _io_cost(2.0 * _elems(o) * k, ins, outs)


def _matmul_cost(attrs, ins, outs):
    x = _first(ins, "X")
    o = _first(outs, "Out")
    if x is None or o is None:
        return _memory_bound(attrs, ins, outs)
    k = float(x.shape[-2] if attrs.get("transpose_X", False)
              else x.shape[-1]) if len(x.shape) else 1.0
    return _io_cost(2.0 * _elems(o) * k, ins, outs)


def _conv_cost(attrs, ins, outs):
    w = _first(ins, "Filter")
    o = _first(outs, "Output") or _first(outs, "Out")
    if w is None or o is None:
        return _memory_bound(attrs, ins, outs)
    fmt = attrs.get("data_format", "NCHW")
    wsh = tuple(w.shape)
    if fmt == "NHWC":  # HWIO (2-D) / DHWIO (3-D)
        k_spatial = float(np.prod(wsh[:-2]))
        cin_per_group = float(wsh[-2])
    else:  # OIHW / OIDHW
        k_spatial = float(np.prod(wsh[2:]))
        cin_per_group = float(wsh[1])
    flops = 2.0 * _elems(o) * k_spatial * cin_per_group
    return _io_cost(flops, ins, outs)


def _pool_cost(attrs, ins, outs):
    ksize = attrs.get("ksize") or attrs.get("pool_size") or [2, 2]
    try:
        window = float(np.prod([int(k) for k in np.atleast_1d(ksize)]))
    except Exception:
        window = 4.0
    return _io_cost(window * _slot_elems(outs), ins, outs)


def _norm_cost(attrs, ins, outs):
    # normalize + stats: ~8 FLOPs per element; activation streamed in+out,
    # stats/affine params are noise
    x = _first(ins, "X") or _first(ins, "Input")
    xb = _nbytes(x) if x is not None else _slot_bytes(ins)
    main_out = max((_nbytes(s) for arrs in (outs or {}).values()
                    for s in arrs), default=0.0)
    return OpCost(flops=8.0 * (_elems(x) if x is not None else 0.0),
                  bytes=xb + main_out)


def _sdpa_cost(attrs, ins, outs):
    q = _first(ins, "Q") or _first(ins, "X")
    o = _first(outs, "Out")
    if q is None or o is None:
        return _memory_bound(attrs, ins, outs)
    # q: [..., T, dh] (possibly [b, h, T, dh]); two T x T contractions.
    t = float(q.shape[-2])
    flops = 4.0 * _elems(q) * t
    if attrs.get("causal", False):
        flops *= 0.5
    # flash form: the [T, T] score plane never reaches HBM
    return _io_cost(flops, ins, outs)


def _fused_head_ce_cost(attrs, ins, outs):
    x = _first(ins, "X")
    w = _first(ins, "W")
    if x is None or w is None:
        return _memory_bound(attrs, ins, outs)
    n = float(np.prod(x.shape[:-1]))
    d = float(x.shape[-1])
    v = float(w.shape[-1])
    # chunked online-logsumexp scan: logits NEVER materialize — bytes are
    # the activation + weight streams only (PERF.md "chunked fused head")
    return OpCost(flops=2.0 * n * d * v,
                  bytes=_nbytes(x) + _nbytes(w) + _slot_bytes(outs))


def _embedding_cost(attrs, ins, outs):
    # O(batch) random gathers: touched table rows = output bytes. The
    # [V, D] table is NOT a stream operand — a V=1e6 lookup costs its
    # rows-touched bytes (id stream + row reads + output write), which
    # is what the chip actually DMAs.
    ids = _first(ins, "Ids")
    return OpCost(flops=0.0,
                  bytes=(_nbytes(ids) if ids is not None else 0.0)
                  + 2.0 * _slot_bytes(outs))


def _sparse_optimizer(attrs, ins, outs):
    """Row-granular scatter-apply updates (sparse_sgd/sparse_adagrad):
    price by ROWS TOUCHED — the SelectedRows grad's (ids + row values)
    stream plus a read+write of the touched rows per dense state tensor
    — never the [V, D] table (rows-touched bytes are what the update
    DMAs; the table only pays for rows it owns in the batch)."""
    g = _first(ins, "Grad")
    if g is None:
        return _optimizer(attrs, ins, outs)
    grad_bytes = _nbytes(g)  # id stream + row grads (dense on fan-in)
    row_bytes = max((_nbytes(l) for l in _leaves(g)), default=0.0)
    n_state = sum(1 for slot in ("Param", "Moment") if (ins or {}).get(slot))
    return OpCost(flops=6.0 * _elems(g),  # dedup sort + update arithmetic
                  bytes=grad_bytes + 2.0 * n_state * row_bytes)


def _rnn_cost(attrs, ins, outs):
    # per-step gate matmuls: hidden x hidden contractions dominate.
    # Input carries [b, T, G*H] pre-projected gates; recurrent weight is
    # [H, G*H] -> 2*b*T*H*(G*H) FLOPs == 2 * in_elems * H.
    w = _first(ins, "Weight") or _first(ins, "W")
    h = float(w.shape[0]) if w is not None and len(w.shape) else 1.0
    return _io_cost(2.0 * _slot_elems(ins) * h, ins, outs)


def _conv1x1_bn_act_cost(attrs, ins, outs):
    x = _first(ins, "Input")
    w = _first(ins, "Filter")
    o = _first(outs, "Output")
    if x is None or w is None or o is None:
        return _memory_bound(attrs, ins, outs)
    flops = 2.0 * _elems(o) * float(w.shape[-2])
    # the fused epilogue's point: the raw conv output never streams — one
    # input read, one weight read, one fused output write
    return OpCost(flops=flops,
                  bytes=_nbytes(x) + _nbytes(w) + _nbytes(o))


# --------------------------------------------------------------------------
# Gradient ops: derive from the forward op's cost
# --------------------------------------------------------------------------
def _rebuilt_fwd_ins(attrs, ins):
    return {slot: ins["I:" + slot] for slot in attrs.get("in_slots", {})
            if "I:" + slot in ins}


def _grad_cost(attrs, ins, outs):
    fwd_type = attrs.get("fwd_type")
    fwd_ins = _rebuilt_fwd_ins(attrs, ins)
    fwd = None
    if fwd_type and fwd_ins and has_cost(fwd_type):
        try:
            fwd_outs = registry.infer_outputs(fwd_type,
                                              attrs.get("fwd_attrs"),
                                              fwd_ins)
            fwd = op_cost(fwd_type, attrs.get("fwd_attrs"), fwd_ins,
                          fwd_outs)
        except Exception:
            fwd = None
    og_bytes = sum(_nbytes(s) for slot, arrs in (ins or {}).items()
                   if slot.startswith("OG:") for s in arrs)
    ig_bytes = _slot_bytes(outs)
    if fwd is None:
        return OpCost(flops=2.0 * _slot_elems(ins),
                      bytes=_slot_bytes(ins) + ig_bytes)
    # Explicit stream accounting (round-3 profile): each LARGE gradient
    # (ndim>=2 — dX, dW; vector grads ride along) is its own kernel that
    # re-streams the cotangent once, writes its output, and re-reads the
    # largest saved primal once (dW reads X; recomputed subexpressions
    # are CSE'd with the forward, not re-streamed).
    n_big = max(1, sum(
        1 for arrs in (outs or {}).values() for s in arrs
        if len(getattr(s, "shape", ())) >= 2))
    primal = max((_nbytes(s) for slot, arrs in (ins or {}).items()
                  if slot.startswith("I:") for s in arrs), default=0.0)
    return OpCost(flops=2.0 * fwd.flops,
                  bytes=ig_bytes + n_big * og_bytes + primal)


def _seg_ops_cost(seg_ops, resolve) -> OpCost:
    """Walk a recompute segment's serialized interior ops, accumulating
    their costs with a local shape environment (checker's seg handler)."""
    total = OpCost()
    local: Dict[str, object] = {}

    def get(name):
        return local[name] if name in local else resolve(name)

    for sop in seg_ops:
        op_ins = {slot: [get(n) for n in names]
                  for slot, names in sop["ins"].items() if names}
        op_outs = registry.infer_outputs(sop["type"], sop["attrs"], op_ins)
        c = op_cost(sop["type"], sop["attrs"], op_ins, op_outs)
        if c is not None:
            total = total + c
        for slot, names in sop["outs"].items():
            for n, sds in zip(names, (op_outs or {}).get(slot, [])):
                local[n] = sds
    return total


def _seg_fwd_cost(attrs, ins, outs):
    env = dict(zip(attrs["ext_in"], ins.get("I", [])))
    inner = _seg_ops_cost(attrs["seg_ops"], env.__getitem__)
    return OpCost(flops=inner.flops, bytes=inner.bytes)


def _grad_seg_cost(attrs, ins, outs):
    # the round-3 lesson as analysis: the barriered backward re-RUNS the
    # segment (recompute FLOPs) and re-streams its interior as separate
    # kernels — roughly the forward's traffic twice, plus the grads
    og_bytes = _slot_bytes({"OG": ins.get("OG", [])})
    ig_bytes = _slot_bytes(outs)
    return OpCost(flops=2.0 * _slot_elems(ins),
                  bytes=2.0 * og_bytes + ig_bytes + _slot_bytes(ins))


def _stack_cost(attrs, ins, outs):
    """pipelined_transformer_stack: scan-over-layers. FLOPs from the
    stacked [L, in, out] weights (each is one token-plane contraction per
    layer); residual_bytes models what the scan keeps resident forward->
    backward under the remat policy — the [L, ...] activation planes
    PERF.md's stacked-scan A/Bs are about."""
    x = _first(ins, "X")
    if x is None:
        return _memory_bound(attrs, ins, outs)
    b_t = float(np.prod(x.shape[:-1]))  # tokens
    d = float(x.shape[-1])
    itemsize = np.dtype(x.dtype).itemsize
    flops = 0.0
    weight_bytes = 0.0
    L = 1.0
    for slot, arrs in (ins or {}).items():
        for w in arrs:
            weight_bytes += _nbytes(w)
            if len(w.shape) == 3:  # [L, in, out] matmul plane
                L = float(w.shape[0])
                flops += 2.0 * b_t * float(w.shape[1]) * float(w.shape[2])
    t = float(x.shape[-2]) if len(x.shape) >= 2 else 1.0
    flops += L * 2.0 * b_t * t * d  # attention score+context contractions
    remat = attrs.get("remat", False)
    # saved per token per layer, in units of d (see ops/pipeline_ops.py):
    # full save ~14d (every interior), "dots" ~9d (GEMM outputs), remat
    # all-or-nothing saves only the layer input carry (1d).
    per_tok_d = 1.0 if remat is True else (9.0 if remat == "dots" else 14.0)
    residual = L * b_t * per_tok_d * d * itemsize
    return OpCost(flops=flops,
                  bytes=_nbytes(x) + weight_bytes + _slot_bytes(outs),
                  residual_bytes=residual)


def _slot_cache_cost(attrs, ins, outs):
    """transformer_stack_slot_prefill/decode: stacked-weight pass over the
    slot KV cache; decode is pure HBM streaming of the cache planes."""
    x = (_first(ins, "Prompt") or _first(ins, "Tok")
         or _first(ins, "X") or _first(ins, "Ids"))
    toks = float(np.prod(x.shape)) if x is not None else 1.0
    flops = 0.0
    for slot, arrs in (ins or {}).items():
        for w in arrs:
            if len(w.shape) == 3:  # [L, in, out]
                flops += 2.0 * toks * float(w.shape[1]) * float(w.shape[2])
    return _io_cost(flops, ins, outs)


def _encdec_cost(attrs, ins, outs):
    """transformer_encdec_* family (seq2seq): stacked encoder/decoder
    passes — FLOPs from every [L, in, out] weight plane applied to the
    op's token count (source tokens for encode, source + target for the
    teacher, slot rows for the cross decode), bytes from the full I/O
    stream, which prices the cross-KV planes ``[L, S+1, Hkv, Ts, dh]``
    as read state — the memplan gate sees the encoder-decoder config's
    extra resident bytes."""
    toks = 0.0
    for slot in ("SrcIds", "TgtIn", "Chunk", "Tok"):
        x = _first(ins, slot)
        if x is not None:
            toks += float(np.prod(x.shape))
    flops = 0.0
    for arrs in (ins or {}).values():
        for w in arrs:
            if len(w.shape) == 3:
                flops += 2.0 * toks * float(w.shape[1]) * float(w.shape[2])
    return _io_cost(flops, ins, outs)


def _paged_cache_cost(attrs, ins, outs):
    """transformer_stack_paged_prefill/decode: the slot-cache cost plus
    the per-row gathered context — every row streams its table-width
    [Hkv, P*ps, dh] K/V block per layer (x2 for K and V), which is the
    decode plane's dominant HBM term and what the dense path reads as
    contiguous slot rows."""
    base = _slot_cache_cost(attrs, ins, outs)
    table = _first(ins, "BlockTable")
    pool = _first(ins, "CacheK")
    gathered = 0.0
    if table is not None and pool is not None and len(pool.shape) == 5:
        L, _, hkv, ps, dh = pool.shape
        rows, P = table.shape
        itemsize = np.dtype(pool.dtype).itemsize
        gathered = 2.0 * float(L) * float(rows) * float(hkv) \
            * float(P) * float(ps) * float(dh) * itemsize
    return OpCost(flops=base.flops, bytes=base.bytes + gathered)


# --------------------------------------------------------------------------
# Coverage: every registered op gets a handler or an exempt marker.
# (tests/test_registry_conformance.py pins the audit clean — a new op
# registered without either fails there, naming the op.)
# --------------------------------------------------------------------------
_ELEMENTWISE_1 = (
    "abs", "brelu", "ceil", "clip", "cos", "equal", "exp",
    "floor", "greater_equal", "greater_than", "hard_shrink",
    "hard_sigmoid", "increment", "leaky_relu", "less_equal", "less_than",
    "log", "logical_and", "logical_not", "logical_or", "logical_xor",
    "not_equal", "prelu", "reciprocal", "relu", "relu6", "round",
    "rsqrt", "scale", "sin", "sqrt",
    "square", "fill_zeros_like", "cast", "scale_shift",
    "slope_intercept", "l1_decay_sign", "interpolation", "linear_comb",
    "scaling", "multiplex", "sequence_mask", "power", "pow",
)
_ELEMENTWISE_4 = (
    "elu", "gelu", "logsigmoid", "sigmoid", "soft_relu", "softplus",
    "softshrink", "softsign", "stanh", "swish", "tanh", "tanh_shrink",
    "thresholded_relu", "dropout", "clip_by_norm",
    "clip_by_global_norm", "lrn", "rotary_embed", "maxout",
    "sum_to_one_norm", "row_l2_norm", "static_prune_mask",
)
_ELEMENTWISE_BIN = (
    "elementwise_add", "elementwise_div", "elementwise_max",
    "elementwise_min", "elementwise_mul", "elementwise_pow",
    "elementwise_sub", "addto", "sum",
)
_MOVEMENT = (
    "transpose", "concat", "split", "slice", "pad", "squeeze",
    "unsqueeze", "stack", "expand", "repeat", "gather", "scatter",
    "crop", "resize", "rotate", "switch_order", "sequence_concat",
    "sequence_expand", "sequence_reshape", "sequence_reverse",
    "sequence_slice", "sequence_enumerate", "sub_nested_seq", "sub_seq",
    "array_read", "array_write", "assign_value", "one_hot",
    "im2sequence", "unpool", "scale_sub_region", 
)
_ALIAS = (
    "assign", "reshape", "squeeze", "unsqueeze", "lod_reset",
)
_FILL = (
    "fill_constant", "fill_constant_batch_size_like", "gaussian_random",
    "gaussian_random_batch_size_like", "uniform_random",
    "truncated_gaussian_random", "sampling_id",
)
_REDUCTION = (
    "mean", "reduce_max", "reduce_mean", "reduce_min", "reduce_prod",
    "reduce_sum", "l1_norm", "squared_l2_norm", "norm", "l2_distance",
    "squared_l2_distance", "cos_sim", "dot_prod", "sequence_pool",
    "kmax_seq_score",
)
_SOFTMAXISH = (
    "softmax", "log_softmax", "sequence_softmax",
    "softmax_with_cross_entropy", "cross_entropy",
    "cross_entropy_with_selfnorm", "bce_loss",
    "sigmoid_cross_entropy_with_logits", "log_loss", "huber_loss",
    "modified_huber_loss", "smooth_l1_loss", "square_error_cost",
    "hinge_loss", "margin_rank_loss", "rank_loss", "lambda_cost",
)
_OPTIMIZER = (
    "sgd", "momentum", "adam", "adamax", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "ftrl", "proximal_gd", "proximal_adagrad",
    "model_average_update", "lr_schedule", "lr_warmup",
)
_MATMUL_LIKE = {
    "mul": _mul_cost, "matmul": _matmul_cost,
}
_CONV = (
    "conv2d", "conv2d_cudnn", "conv2d_transpose",
    "conv2d_transpose_cudnn", "conv3d", "conv3d_cudnn",
    "conv3d_transpose", "conv3d_transpose_cudnn", "depthwise_conv2d",
    "sequence_conv", "row_conv", "conv_shift", "context_project",
)
_POOL = (
    "pool2d", "pool2d_cudnn", "pool3d", "pool3d_cudnn",
    "max_pool2d_with_index", "max_pool3d_with_index", "spp", "roi_pool",
)
_NORM = ("batch_norm", "layer_norm", "rms_norm")
_RNN = ("lstm", "gru", "gru_unit", "lstm_unit", "simple_rnn",
        "gated_unit")
# metrics / decode / detection utilities: memory-bound default
_MEMORY_BOUND = (
    "accuracy", "auc", "auc_histogram", "precision_recall",
    "confusion_counts", "pnpair_counts", "positive_negative_pair",
    "rank_auc", "detection_map_counts", "chunk_eval", "edit_distance",
    "top_k", "argmax", "iou_similarity", "prior_box", "box_coder",
    "detection_output", "multibox_loss", "linear_chain_crf",
    "crf_decoding", "warpctc", "ctc_greedy_decode", "beam_search",
    "is_empty", "nce", "hsigmoid", "bilinear_interp",
    "bilinear_tensor_product", "tensor_product", "out_prod", "dot",
    "factorization_machine", "switch_moe",
)
# structural / executor-interpreted / unbounded-loop ops: exempt
_EXEMPT = (
    "feed", "fetch", "while", "cond", "static_rnn", "beam_search_decoder",
    "transformer_stack_generate", "transformer_stack_beam_search",
    "transformer_stack_speculative_generate",
)


def _register_all() -> None:
    def reg(names, handler):
        for n in names:
            if has_op(n) and not has_cost(n) and not is_cost_exempt(n):
                register_cost(n, handler)

    reg(_ALIAS, _alias)
    reg(_ELEMENTWISE_1, _elementwise(1.0))
    reg(_ELEMENTWISE_4, _elementwise(4.0))
    reg(_ELEMENTWISE_BIN, _elementwise(1.0, fused_reads=False))
    reg(_MOVEMENT, _movement)
    reg(_FILL, _fill)
    reg(_REDUCTION, _reduction(1.0))
    reg(_SOFTMAXISH, _reduction(6.0))
    reg(_OPTIMIZER, _optimizer)
    reg(_CONV, _conv_cost)
    reg(_POOL, _pool_cost)
    reg(_NORM, _norm_cost)
    reg(_RNN, _rnn_cost)
    reg(_MEMORY_BOUND, _memory_bound)
    for name, h in _MATMUL_LIKE.items():
        reg((name,), h)
    reg(("conv1x1_bn_act",), _conv1x1_bn_act_cost)
    reg(("scaled_dot_product_attention",), _sdpa_cost)
    reg(("fused_head_cross_entropy",), _fused_head_ce_cost)
    reg(("lookup_table",), _embedding_cost)
    reg(("sparse_sgd", "sparse_adagrad"), _sparse_optimizer)
    reg(("grad", "grad_custom"), _grad_cost)
    reg(("seg_fwd",), _seg_fwd_cost)
    reg(("grad_seg",), _grad_seg_cost)
    reg(("pipelined_transformer_stack",), _stack_cost)
    reg(("transformer_stack_slot_prefill", "transformer_stack_slot_decode"),
        _slot_cache_cost)
    reg(("transformer_stack_paged_prefill", "transformer_stack_paged_decode"),
        _paged_cache_cost)
    reg(("transformer_encdec_encode", "transformer_encdec_teacher",
         "transformer_stack_cross_prefill",
         "transformer_stack_cross_decode"), _encdec_cost)
    reg(("kv_cache_page_copy",), _movement)
    cost_exempt(*[n for n in _EXEMPT if has_op(n)])


_registered = False


def ensure_registered() -> None:
    """Idempotently attach the standard handler set. Registration is
    lazy because paddle_tpu/__init__ imports the analysis package BEFORE
    the ops modules — at that point the registry is still empty; the
    first cost query after the ops plane loads does the real work."""
    global _registered
    if _registered or not has_op("relu"):
        return
    _registered = True
    _register_all()


ensure_registered()
